"""Benchmarks for the driver (BASELINE.md configs).

Primary metric (BASELINE config 1, the north star): ResNet-50 training
throughput in images/sec/chip, with accounting that makes the number
defensible.

FLOP accounting — resolving the round-2 "MFU > 100%" contradiction
------------------------------------------------------------------
Round 2 reported `mfu: 1.07, mfu_plausible: false` because its analytic
anchor (4.1 GFLOP/img forward) was a *MAC* count: the canonical
"ResNet-50 = 3.8-4.1 GFLOPs" figures count one multiply-accumulate as
one FLOP. XLA's `cost_analysis()` (and the MFU literature) counts
mul+add = 2 FLOPs. Counting every conv/dot in this repo's actual
forward graph at 2 FLOPs/MAC gives 7.72 GFLOP/img at 224² — and the
compiled-HLO number then agrees with the analytic number within ~5%
(verified op-by-op from the jaxpr; see `_count_math_flops`). Both are
reported: `mfu_analytic` is authoritative (model FLOPs, the standard
MFU definition — excludes rematerialization and non-MXU elementwise
work), `mfu_hlo` is the diagnostic against the full compiled program.

Peak accounting: nominal bf16 peak comes from the device_kind lookup
(public TPU specs). Because the driver tunnels the chip ("axon"
platform) and the device_kind label may not describe the silicon that
actually executes, a speed-of-light probe (`bench_matmul_peak`: a
scan-chained 4096³ bf16 matmul, ~99% MXU work) empirically measures
sustained matmul TFLOP/s. `mfu_*` is reported against the nominal
peak; `effective_peak_tflops = max(nominal, measured probe)` and
`mfu_vs_effective_peak` cover the case where the label undersells the
part. `mfu_plausible` checks MFU against the *effective* peak — a
number can only be flagged implausible if it beats what the silicon
demonstrably sustains on pure matmul.

`vs_baseline` anchor: 360 img/s ≈ published tf_cnn_benchmarks ResNet-50
fp32 results for the reference's cuDNN era — 2,840 img/s on an 8xV100
DGX-1 (355/GPU, TensorFlow benchmarks page, 2017/18) — the strongest
widely-cited per-V100 fp32 training number for the stack the reference
targeted. Provenance is recorded in the JSON (`baseline_source`).

Dispatch accounting: the axon tunnel between this host and the chip
adds tens of ms of latency per dispatch (`device_diagnostics.
dispatch_readback_ms` measures it). Every timed path therefore runs as
ONE fused dispatch per timed window — ResNet-50, LeNet and the LSTM all
drain their steps through the user-facing `fit(steps_per_execution=k)`
scan machinery, and the matmul probe chains 128 matmuls inside one jit
call. A dispatch-per-step loop measures the tunnel, not the TPU
(observed 40x under-measurement on ResNet-50).

Secondary metrics in `extras`: LeNet-MNIST (config 0), GravesLSTM
char-RNN (config 2), Word2Vec skip-gram words/sec (config 3, steady
state after a compile warmup pass), and multi-device data-parallel
scaling on an 8-virtual-device CPU mesh (config 4; subprocess so the
accelerator process stays clean).

Scaling accounting (config 4): virtual CPU devices share one host
threadpool, so "scaling" there can only honestly measure partitioning
overhead, not hardware speedup. Both weak-scaling (fixed per-device
batch) and strong-scaling (fixed global batch) efficiencies are
computed against the *fastest* single-device configuration (plain jit
fit or the same trainer at n=1, whichever is higher) so the denominator
can't be a pathologically slow baseline; `host_cores` is reported and
efficiencies on a shared-core host are a lower bound on real-hardware
scaling.

Synthetic data everywhere (the reference's own benchmark pattern:
`datasets/iterator/impl/BenchmarkDataSetIterator.java`) so ETL is
excluded, matching how `PerformanceListener.java:87-88` isolates
compute.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np

REF_BASELINE = 360.0  # img/s — see module docstring (tf_cnn_benchmarks V100 fp32)
BASELINE_SOURCE = ("tf_cnn_benchmarks ResNet-50 fp32, 8xV100 DGX-1: "
                   "2840 img/s => ~355/GPU (TensorFlow benchmarks, 2017/18); "
                   "rounded to 360")

# bf16 peak TFLOP/s by device-kind substring (public TPU specs).
_PEAK_TFLOPS = [
    ("v6", 918.0), ("trillium", 918.0), ("v5p", 459.0), ("v5e", 197.0),
    ("v5 lite", 197.0), ("v4", 275.0), ("v3", 123.0), ("v2", 45.0),
]
_DEFAULT_TPU_PEAK = 197.0  # unknown TPU-class part: assume v5e


def _device_info():
    import jax
    d = jax.devices()[0]
    plat = getattr(d, "platform", "cpu")
    kind = str(getattr(d, "device_kind", plat)).lower()
    accel = plat != "cpu"
    peak = None
    if accel:
        peak = _DEFAULT_TPU_PEAK
        for key, val in _PEAK_TFLOPS:
            if key in kind:
                peak = val
                break
    return plat, kind, accel, peak


def _device_diagnostics():
    """What is actually on the other side of the tunnel."""
    import jax
    d = jax.devices()[0]
    out = {"n_devices": jax.device_count(),
           "platform": getattr(d, "platform", "?"),
           "device_kind": str(getattr(d, "device_kind", "?"))}
    try:
        ms = d.memory_stats()
        if ms:
            out["hbm_bytes_limit"] = int(ms.get("bytes_limit", 0))
    except Exception:
        pass
    for attr in ("num_cores", "core_on_chip"):
        try:
            out[attr] = int(getattr(d, attr))
        except Exception:
            pass
    try:
        # per-dispatch round-trip latency (dispatch + scalar readback of
        # a trivial jitted op). Over the axon tunnel this is tens of ms
        # — the reason every timed path above uses fused dispatches.
        import jax.numpy as jnp
        f = jax.jit(lambda v: v + 1.0)
        z = jnp.zeros((8,))
        float(f(z)[0])
        ts = []
        for _ in range(5):
            t0 = time.perf_counter()
            float(f(z)[0])
            ts.append(time.perf_counter() - t0)
        out["dispatch_readback_ms"] = round(sorted(ts)[len(ts) // 2] * 1e3, 2)
    except Exception:
        pass
    return out


# ------------------------------------------------- analytic FLOP counting
def _count_math_flops(jaxpr) -> float:
    """Sum 2*MAC FLOPs over every conv_general_dilated / dot_general in a
    jaxpr (recursing into sub-jaxprs: pjit, scan, cond, ...). This is the
    'model FLOPs' count used for MFU — elementwise ops excluded (they are
    not MXU work and are <2% of a conv net's FLOPs)."""
    total = 0.0
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if name == "conv_general_dilated":
            out = eqn.outvars[0].aval.shape
            rhs = eqn.invars[1].aval.shape
            dn = eqn.params["dimension_numbers"]
            kspatial = 1
            for d in dn.rhs_spec[2:]:
                kspatial *= rhs[d]
            # rhs I-dim is already cin/groups for grouped convs, so the
            # formula needs no feature_group_count adjustment
            cin = rhs[dn.rhs_spec[1]]
            nout = 1
            for s in out:
                nout *= s
            total += 2.0 * nout * kspatial * cin
        elif name == "dot_general":
            a = eqn.invars[0].aval.shape
            b = eqn.invars[1].aval.shape
            (lc, rc), (lb, rb) = eqn.params["dimension_numbers"]
            m = 1
            for i, s in enumerate(a):
                if i not in lc and i not in lb:
                    m *= s
            n = 1
            for i, s in enumerate(b):
                if i not in rc and i not in rb:
                    n *= s
            k = 1
            for i in lc:
                k *= a[i]
            bsz = 1
            for i in lb:
                bsz *= a[i]
            total += 2.0 * bsz * m * n * k
        for p in eqn.params.values():
            for sub in (p if isinstance(p, (list, tuple)) else (p,)):
                inner = getattr(sub, "jaxpr", None)
                if inner is not None and hasattr(inner, "eqns"):
                    total += _count_math_flops(inner)
                elif hasattr(sub, "eqns"):
                    total += _count_math_flops(sub)
    return total


# ------------------------------------------------- speed-of-light probe
def bench_matmul_peak():
    """Empirical sustained bf16 matmul TFLOP/s on the attached device —
    a scan of dependent 4096³ matmuls is ~pure MXU work, so this is the
    chip's demonstrable ceiling (and a lie detector for device_kind).

    ONE dispatch with a long chain (not many small calls): the axon
    tunnel adds tens of ms of per-dispatch latency, so a multi-call
    probe measures the tunnel, not the MXU (observed: 28 TF/s from 8
    chained calls vs the same silicon sustaining far more in one call).
    The timed window is a single dispatch + one scalar readback; chain
    length is sized so compute (~0.4 s at nominal peak) dominates the
    ~10 ms amortized per-window overhead (measured: chain 128 → 151.7
    TF/s, chain 512 → 163.5 TF/s on the same silicon; the longer chain
    recovers the ~85%-of-nominal sustained rate a real v5e shows)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    n, chain = 4096, 512

    @jax.jit
    def run(x, w):
        def body(c, _):
            return (c @ w) * (1.0 / 64.0), None
        c, _ = lax.scan(body, x, None, length=chain)
        return jnp.sum(c)

    key = jax.random.PRNGKey(0)
    x = jax.random.normal(key, (n, n), jnp.bfloat16)
    w = jax.random.normal(jax.random.fold_in(key, 1), (n, n), jnp.bfloat16)
    float(run(x, w))               # compile + warmup
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        float(run(x, w))           # value readback ends the window
        best = min(best, time.perf_counter() - t0)
    tflops = 2.0 * n * n * n * chain / best / 1e12
    return round(tflops, 2)


# --------------------------------------------------------------- ResNet-50
def bench_resnet50(accel, batch=None, size=None, steps=None,
                   with_etl=True):
    import jax
    import jax.numpy as jnp
    from deeplearning4j_tpu.zoo.resnet50 import ResNet50

    batch = batch or (128 if accel else 8)   # v5e HBM holds it easily; bigger
    size = size or (224 if accel else 64)    # batches keep the MXU fed
    steps = steps or (20 if accel else 3)

    model = ResNet50(num_classes=1000, height=size, width=size, channels=3)
    conf = model.conf()
    # bench-only lr override: the zoo recipe (Nesterov lr=0.1) is tuned
    # for real epochs over distinct batches; re-fitting the benchmark's
    # single repeated batch at that lr diverges within a few steps. A
    # smaller lr changes none of the measured compute (update math is
    # O(params), noise next to the conv FLOPs) but keeps the
    # train-signal check meaningful.
    from deeplearning4j_tpu.common.updaters import Nesterovs
    for node in conf.nodes.values():
        if node.layer is not None and getattr(node.layer, "updater", None) is not None:
            node.layer.updater = Nesterovs(0.005, 0.9)
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    if accel:
        # fp32 params, bf16 compute — convs hit the MXU at full rate
        from deeplearning4j_tpu.nd.dtype import bf16_policy
        net = ComputationGraph(conf, dtype_policy=bf16_policy()).init(model.seed)
    else:
        net = ComputationGraph(conf).init(model.seed)

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((batch, size, size, 3)),
                    jnp.bfloat16 if accel else jnp.float32)
    y = jnp.asarray(np.eye(1000, dtype=np.float32)[rng.integers(0, 1000, batch)])

    step = net._make_train_step()

    # analytic model FLOPs: count every conv/dot (fwd + autodiff bwd) in
    # the train-step jaxpr at 2 FLOPs per MAC. This is the number the
    # MFU definition wants — and it now agrees with the compiled-HLO
    # count (round 2's 1.83x gap was MACs-vs-FLOPs; module docstring).
    analytic_flops = None
    try:
        jp = jax.make_jaxpr(step)(net.params, net.updater_state, net.net_state,
                                  jnp.asarray(0, jnp.int32), [x], [y],
                                  jax.random.PRNGKey(0), None, None)
        analytic_flops = _count_math_flops(jp.jaxpr)
    except Exception:
        pass

    # Timed path = the fused steps_per_execution drain (ONE dispatch for
    # all `steps` minibatch steps, one loss readback) — the same
    # user-facing `fit(steps_per_execution=k)` machinery the LeNet/LSTM
    # benches use. Per-step dispatch over the axon tunnel costs tens of
    # ms of RTT each, which round 3 measured as a 40x throughput hit on
    # this config (228 img/s dispatch-per-step vs fused); the tunnel is
    # not TPU silicon, so the headline number must not measure it.
    # Input stacks are materialized ON device (broadcast of an already
    # device-resident array), so the timed window moves no host data.
    xs_stack = jnp.broadcast_to(x[None], (steps,) + x.shape)
    ys_stack = jnp.broadcast_to(y[None], (steps,) + y.shape)

    # AOT-compile the fused program ONCE and use the same executable for
    # cost_analysis AND the warmup/timed calls — a jit __call__ would
    # not share the AOT lowering's cache and would recompile the
    # identical minutes-long ResNet program a second time. The lowering
    # seam is the container's own (`lower_train_step` — what
    # benchtools/hlo_cost.py AOT-analyzes device-free), so the analyzed
    # program and the timed program can never drift apart.
    # Created eagerly OUTSIDE the try: the except-fallback below calls
    # net._jit_multi_step directly, and a tracing failure inside the
    # try must surface as itself, not as a None-call.
    if net._jit_multi_step is None:
        net._jit_multi_step = net._make_multi_step()
    # same rng derivation _run_multi_step uses, so the bench exercises
    # the library's numerics exactly
    rng_root = jax.random.PRNGKey(net.conf.seed + 1)

    def make_rngs(it0):
        return jax.block_until_ready(
            jax.vmap(lambda i: jax.random.fold_in(rng_root, i))(
                jnp.arange(it0, it0 + steps)))

    st = (net.params, net.updater_state, net.net_state)
    hlo_flops = None
    try:
        compiled_multi = net.lower_train_step(x, y, steps=steps).compile()
        cost = compiled_multi.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0]
        f = float(cost.get("flops", 0.0))
        # XLA's cost model counts a scan/while body ONCE (it does not
        # multiply by trip count), so the fused-k executable's flops
        # already approximate one step — verified: raw/analytic lands
        # at the same ~0.85-0.9 ratio the per-step executable showed
        hlo_flops = f if f > 0 else None

        def run(st, it0, rngs):
            out = compiled_multi(*st, it0, (xs_stack,), (ys_stack,), rngs)
            return (out[0], out[1], out[2]), out[3]

        def run_x(st, it0, xs, ys, rngs):
            out = compiled_multi(*st, it0, (xs,), (ys,), rngs)
            return (out[0], out[1], out[2]), out[3]
    except Exception:
        def run(st, it0, rngs):
            out = net._jit_multi_step(*st, it0, (xs_stack,), (ys_stack,),
                                      rngs)
            return (out[0], out[1], out[2]), out[3]

        def run_x(st, it0, xs, ys, rngs):
            out = net._jit_multi_step(*st, it0, (xs,), (ys,), rngs)
            return (out[0], out[1], out[2]), out[3]

    st, losses = run(st, 0, make_rngs(0))  # warmup (no recompile: AOT above)
    warm = np.asarray(losses)
    # train signal is judged on the warmup window, where the (bench-
    # overridden, see above) lr demonstrably reduces loss over the
    # first k steps of the repeated batch
    loss_first, loss_warm_end = float(warm[0]), float(warm[-1])
    loss_last = loss_warm_end
    dt = float("inf")
    for r in range(1, 3):
        rngs = make_rngs(r * steps)    # rng derivation outside the window
        t0 = time.perf_counter()
        st, losses = run(st, r * steps, rngs)
        # np.asarray forces VALUE readback inside the timed window —
        # block_until_ready over the tunneled backend was observed to
        # under-measure; one k-scalar transfer cannot lie
        loss_last = float(np.asarray(losses)[-1])
        dt = min(dt, time.perf_counter() - t0)
    losses = [loss_first, loss_warm_end]
    ips = batch * steps / dt
    plat, kind, _, nominal_peak = _device_info()
    measured_peak = None
    if accel:
        try:
            measured_peak = bench_matmul_peak()
        except Exception:
            measured_peak = None
    effective_peak = None
    if nominal_peak:
        effective_peak = max(nominal_peak, measured_peak or 0.0)

    def _mfu(flops):
        if flops is None or not effective_peak:
            return None, None
        ach = flops * steps / dt / 1e12
        return ach, ach / nominal_peak

    # ETL-inclusive window (reference PerformanceListener tracks ETL ms
    # per iteration, `PerformanceListener.java:87-88`; AsyncDataSetIterator
    # overlaps host feed with compute): distinct HOST-resident batches
    # are stacked + device_put by a producer thread while the device
    # crunches the previous fused window — the SAME executable as the
    # headline, so the delta is purely the input pipeline.
    if with_etl:
        try:
            etl = _resnet_etl_window(run_x, st, make_rngs, x, y, batch,
                                     steps, compute_ips=ips)
            st = etl.pop("_st")
        except Exception as e:
            etl = {"error": f"{type(e).__name__}: {e}"[:300]}
    else:
        etl = {"skipped": "sweep config — ETL window on headline only"}

    ach_analytic, mfu_analytic = _mfu(analytic_flops)
    ach_hlo, mfu_hlo = _mfu(hlo_flops)
    mfu_vs_eff = (ach_analytic / effective_peak
                  if ach_analytic is not None and effective_peak else None)
    try:
        # exposed-vs-overlapped comm bytes of the (default) bucketed
        # gradient exchange for this exact net — host math over the
        # bucket plan (benchtools/hlo_cost.comm_overlap_block), so the
        # BENCH ledger tracks the overlap win alongside MFU
        from benchtools import hlo_cost as _hc
        _co = _hc.comm_overlap_block(
            net,
            backward_flops_per_step=(analytic_flops or 0.0) * 2.0 / 3.0,
            peak_tflops=(measured_peak or nominal_peak or 100.0),
            device_kind=str(kind), bucket_table=False)
        comm_overlap = {k: _co[k] for k in (
            "total_bytes", "exposed_bytes", "overlapped_bytes",
            "exposed_fraction", "ici_gbps", "ici_source", "n_workers",
            "buckets")}
    except Exception as e:  # noqa: BLE001 — accounting never kills bench
        comm_overlap = {"error": f"{type(e).__name__}: {e}"[:200]}
    try:
        # dtype-policy provenance + real wire dtype: a run that fell
        # back to fp32 (policy resolution, env override) must be
        # visible in the ledger, and the gate's wire_reduction entry
        # catches a stale fp32 record masquerading as a bf16 win
        from deeplearning4j_tpu.parallel import gradient_sharing as _gs
        _wire = _gs.exchange_wire_bytes(
            net.params, "dense", grad_dtype=net.dtype.compute_dtype)
        _wire_fp32 = _gs.exchange_wire_bytes(net.params, "dense")
        precision = {
            "policy": net.dtype.name,
            "param_dtype": str(np.dtype(net.dtype.param_dtype)),
            "compute_dtype": jnp.dtype(net.dtype.compute_dtype).name,
            "wire_bytes_dense": _wire,
            "wire_bytes_dense_fp32": _wire_fp32,
            "wire_reduction": round(_wire_fp32 / max(_wire, 1.0), 3),
        }
    except Exception as e:  # noqa: BLE001 — accounting never kills bench
        precision = {"error": f"{type(e).__name__}: {e}"[:200]}
    return {
        "metric": "resnet50_train_images_per_sec_per_chip",
        "value": round(ips, 2),
        "unit": "images/sec",
        "vs_baseline": round(ips / REF_BASELINE, 3),
        "baseline_source": BASELINE_SOURCE,
        "platform": plat,
        "device_kind": kind,
        "device_diagnostics": _device_diagnostics(),
        "batch": batch, "image_size": size, "steps": steps,
        "seconds": round(dt, 4),
        "flops_per_step_analytic": round(analytic_flops) if analytic_flops else None,
        "flops_per_step_hlo": hlo_flops,
        "hlo_over_analytic": (round(hlo_flops / analytic_flops, 3)
                              if hlo_flops and analytic_flops else None),
        "achieved_tflops": round(ach_analytic, 2) if ach_analytic else None,
        "peak_bf16_tflops_nominal": nominal_peak,
        "measured_matmul_tflops": measured_peak,
        "effective_peak_tflops": effective_peak,
        "mfu": round(mfu_analytic, 4) if mfu_analytic is not None else None,
        "mfu_hlo": round(mfu_hlo, 4) if mfu_hlo is not None else None,
        "mfu_vs_effective_peak": (round(mfu_vs_eff, 4)
                                  if mfu_vs_eff is not None else None),
        "mfu_plausible": (mfu_vs_eff is None or mfu_vs_eff <= 1.0),
        # achieved > what the silicon sustains on PURE matmul is
        # physically impossible -> the step-loop timing under-measured
        # (tunnel asynchrony), not a FLOP-count error; the timed window
        # already ends with a value readback, so a remaining anomaly is
        # platform-side and is flagged rather than hidden
        "timing_anomaly_suspected": bool(
            measured_peak
            and next((a for a in (ach_analytic, ach_hlo)
                      if a is not None), 0.0) > 1.1 * measured_peak),
        "mfu_note": ("mfu = analytic model FLOPs (2/MAC, conv+dot only, "
                     "counted from the train-step jaxpr) / nominal peak; "
                     "plausibility judged against effective peak = "
                     "max(nominal, measured matmul probe) because the "
                     "tunneled device_kind label may not match the "
                     "executing silicon"),
        "with_etl": etl,
        "comm_overlap": comm_overlap,
        "precision": precision,
        "loss_first": losses[0], "loss_last": losses[-1],
        "loss_after_timed_windows": loss_last,
        "train_signal_ok": losses[-1] < losses[0],
        "train_signal_note": ("judged over the warmup window; updaters "
                              "were bench-overridden to Nesterovs(0.005, "
                              "0.9) because the zoo lr=0.1 recipe diverges "
                              "when one batch is re-fit dozens of times "
                              "(identical FLOPs, stable signal)"),
    }


def _resnet_etl_window(run_x, st, make_rngs, x, y, batch, steps, *,
                       compute_ips, rounds=3, pool_size=None):
    """Sustained throughput WITH the input pipeline: a producer thread
    stacks `steps` distinct host batches and starts their (async)
    device transfer while the device runs the previous fused window.

    The wire payload is what a real image pipeline delivers — uint8
    pixels and int32 labels — normalized / one-hot'd ON DEVICE by a
    tiny jitted prolog, then fed to the SAME AOT train executable as
    the compute-only number. Over the axon tunnel the host→device
    link is ~15-20 MB/s (a real TPU host does GB/s over PCIe), so the
    achievable rate is wire-limited far below compute; the overlap
    verdict is therefore judged against min(compute, measured wire
    bound), not compute alone — that is what the pipeline can control.

    `host_producer_wait_ms` is consumer time blocked on the HOST side
    of the producer (stacking; device_put is async, so wire stalls are
    NOT in this field — they surface in the window wall time and thus
    in images_per_sec_with_etl). The reference's per-iteration ETL time
    (PerformanceListener.java:87-88) corresponds to this wait plus the
    non-overlapped share of the transfer, which is exactly the gap
    between images_per_sec_with_etl and the feasible bound."""
    import concurrent.futures
    import jax
    import jax.numpy as jnp

    dtype = np.asarray(jax.device_get(x[:1])).dtype  # match exec avals
    n_classes = y.shape[-1]
    pool_size = pool_size or steps
    rng = np.random.default_rng(7)
    # distinct HOST batches in pipeline-native form (the headline's
    # broadcast stack never moves host data; this pool is what a real
    # decode stage would feed)
    pool_x = [rng.integers(0, 256, x.shape, dtype=np.uint8)
              for _ in range(pool_size)]
    labels_host = np.argmax(np.asarray(jax.device_get(y)), -1).astype(np.int32)

    @jax.jit
    def prolog(xs_u8, labels):
        xs = (xs_u8.astype(jnp.dtype(dtype)) - 127.5) * (1.0 / 127.5)
        ys = jax.nn.one_hot(labels, n_classes, dtype=jnp.float32)
        return xs, ys

    def produce(r):
        idx = [(r * steps + i) % pool_size for i in range(steps)]
        xs = np.stack([pool_x[i] for i in idx])
        ls = np.broadcast_to(labels_host[None], (steps,) + labels_host.shape)
        return jax.device_put(xs), jax.device_put(np.ascontiguousarray(ls))

    wire_bytes_per_window = steps * (
        int(np.prod(x.shape)) + batch * 4)      # uint8 pixels + int32 labels
    ex = concurrent.futures.ThreadPoolExecutor(1)
    try:
        # round 0 is WARMUP: its produce has nothing to overlap with, so
        # timing it would charge the steady-state pipeline for a cold
        # start (round 1's produce is submitted before round 0's compute,
        # so the timed rounds measure genuine overlap). It also warms the
        # transfer path so the wire probe below measures steady-state
        # bandwidth, not first-transfer setup.
        fut = ex.submit(produce, 0)
        xs_u8, ls_d = (jax.block_until_ready(a) for a in fut.result())
        # wire probe on the WARM path with host stacking done up front,
        # so the timed region is purely device_put + transfer (a cold or
        # stack-inclusive probe understates the wire and skews the
        # overlap verdict's feasibility bound)
        probe_xs = np.stack([pool_x[i % pool_size] for i in range(steps)])
        probe_ls = np.ascontiguousarray(
            np.broadcast_to(labels_host[None], (steps,) + labels_host.shape))
        wire_probe_s = float("inf")     # best-of-2: one transient tunnel
        for _ in range(2):              # stall must not skew the bound
            tp = time.perf_counter()
            _pb = [jax.device_put(probe_xs), jax.device_put(probe_ls)]
            jax.block_until_ready(_pb)
            wire_probe_s = min(wire_probe_s, time.perf_counter() - tp)
            del _pb
        del probe_xs, probe_ls
        wire_mb_s = wire_bytes_per_window / wire_probe_s / 1e6
        fut = ex.submit(produce, 1)
        xs_d, ys_d = prolog(xs_u8, ls_d)        # compiles the prolog
        st, losses = run_x(st, 10 * steps, xs_d, ys_d, make_rngs(10 * steps))
        np.asarray(losses)
        etl_wait = 0.0
        t0 = time.perf_counter()
        for r in range(1, rounds + 1):
            tw = time.perf_counter()
            xs_u8, ls_d = fut.result()
            etl_wait += time.perf_counter() - tw
            if r < rounds:
                fut = ex.submit(produce, r + 1)
            xs_d, ys_d = prolog(xs_u8, ls_d)
            st, losses = run_x(st, (10 + r) * steps, xs_d, ys_d,
                               make_rngs((10 + r) * steps))
            np.asarray(losses)  # value readback ends each window
        total = time.perf_counter() - t0
    finally:
        ex.shutdown(wait=False)
    ips_etl = batch * steps * rounds / total
    bytes_per_image = wire_bytes_per_window / (batch * steps)
    wire_bound_ips = wire_mb_s * 1e6 / bytes_per_image
    feasible_ips = (min(compute_ips, wire_bound_ips)
                    if compute_ips else wire_bound_ips)
    return {
        "_st": st,
        "images_per_sec_with_etl": round(ips_etl, 2),
        "host_producer_wait_ms_per_window": round(etl_wait * 1000 / rounds, 2),
        "rounds": rounds, "distinct_host_batches": pool_size,
        "wire_payload": "uint8 pixels + int32 labels (normalize/one-hot on device)",
        "wire_mb_per_sec_probe": round(wire_mb_s, 2),
        "wire_mb_per_sec_achieved": round(
            wire_bytes_per_window * rounds / total / 1e6, 2),
        "wire_bound_images_per_sec": round(wire_bound_ips, 2),
        "vs_compute_only": (round(ips_etl / compute_ips, 4)
                            if compute_ips else None),
        "etl_wire_limited": bool(compute_ips
                                 and wire_bound_ips < 0.9 * compute_ips),
        "etl_overlap_ok": bool(ips_etl >= 0.8 * feasible_ips),
        "note": ("producer thread stacks+transfers the next fused "
                 "window while the device runs the current one "
                 "(AsyncDataSetIterator role); same AOT train executable "
                 "as the compute-only number behind a jitted on-device "
                 "uint8-normalize/one-hot prolog; overlap judged against "
                 "min(compute, wire bound) because a tunneled link "
                 "(~MB/s, vs GB/s PCIe on a real TPU host) caps any "
                 "possible pipeline"),
    }


def _time_fused_steps(net, x, y, steps, repeats=2):
    """Time `steps` train steps executed as ONE fused scan dispatch
    (steps_per_execution drain) — measures the device, not Python."""
    import jax
    import jax.numpy as jnp

    xs = jnp.broadcast_to(x[None], (steps,) + x.shape)
    ys = jnp.broadcast_to(y[None], (steps,) + y.shape)
    losses = net._run_multi_step(xs, ys, 0)     # compile + warmup
    jax.block_until_ready(losses)
    best = float("inf")
    for r in range(repeats):
        t0 = time.perf_counter()
        losses = net._run_multi_step(xs, ys, (r + 1) * steps)
        np.asarray(losses)          # value readback ends the window
        best = min(best, time.perf_counter() - t0)
    return best


# ------------------------------------------------------- LeNet (config 0)
def bench_lenet(accel):
    import jax.numpy as jnp
    from deeplearning4j_tpu.zoo.lenet import LeNet

    batch = 128 if accel else 64
    steps = 100 if accel else 5
    if accel:
        # bf16 compute on the MXU (fp32 params) — the TPU-first config;
        # the reference's CPU path is fp32-only
        from deeplearning4j_tpu.nd.dtype import bf16_policy
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        net = MultiLayerNetwork(LeNet(num_classes=10).conf(),
                                dtype_policy=bf16_policy()).init(123)
    else:
        net = LeNet(num_classes=10).init()
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.standard_normal((batch, 28, 28, 1)), jnp.float32)
    y = jnp.asarray(np.eye(10, dtype=np.float32)[rng.integers(0, 10, batch)])
    dt = _time_fused_steps(net, x, y, steps)
    ips = batch * steps / dt
    return {
        "metric": "lenet_mnist_images_per_sec", "value": round(ips, 2),
        "unit": "images/sec", "batch": batch, "steps": steps,
        "fused_dispatch": True,
        "epoch_seconds_60k": round(60000.0 / ips, 3),
    }


# --------------------------------------------- LSTM char-RNN (config 2)
def bench_lstm_charnn(accel):
    import jax.numpy as jnp
    from deeplearning4j_tpu.zoo.textgenlstm import TextGenerationLSTM

    vocab, T = 77, 100
    batch = 64 if accel else 8
    steps = 50 if accel else 3
    if accel:
        from deeplearning4j_tpu.nd.dtype import bf16_policy
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        net = MultiLayerNetwork(TextGenerationLSTM(vocab_size=vocab).conf(),
                                dtype_policy=bf16_policy()).init(123)
    else:
        net = TextGenerationLSTM(vocab_size=vocab).init()
    rng = np.random.default_rng(2)
    ids = rng.integers(0, vocab, (batch, T))
    x = jnp.asarray(np.eye(vocab, dtype=np.float32)[ids])
    y = jnp.asarray(np.eye(vocab, dtype=np.float32)[np.roll(ids, -1, axis=1)])
    dt = _time_fused_steps(net, x, y, steps)
    return {
        "metric": "lstm_charnn_chars_per_sec",
        "value": round(batch * T * steps / dt, 1), "unit": "chars/sec",
        "batch": batch, "seq_len": T, "steps": steps,
        "fused_dispatch": True,
    }


# ------------------------------------------- Transformer LM (beyond-ref)
def bench_transformer_lm(accel, B=None, T=None, d_model=None,
                         n_layers=None, n_heads=None, steps=None, V=512,
                         with_long_context=False, remat=False):
    """Causal transformer LM training throughput (tokens/sec) — the
    beyond-reference long-context flagship (the 2017 zoo tops out at
    LSTMs). On TPU the encoder blocks ride the Pallas flash-attention
    kernel (`kernels/flash_attention.py`); fused multi-step dispatch
    like the other configs."""
    import jax
    import jax.numpy as jnp
    from deeplearning4j_tpu.zoo.transformer import TransformerLM

    B = B or (16 if accel else 4)
    T = T or (256 if accel else 32)
    steps = steps or (30 if accel else 3)
    d_model = d_model or (256 if accel else 32)
    n_layers = n_layers or (4 if accel else 2)
    n_heads = n_heads or (8 if accel else 4)
    lm = TransformerLM(vocab_size=V, d_model=d_model, n_layers=n_layers,
                       n_heads=n_heads, max_len=T, remat=remat)
    if accel:
        from deeplearning4j_tpu.nd.dtype import bf16_policy
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        net = MultiLayerNetwork(lm.conf(), dtype_policy=bf16_policy()).init(123)
    else:
        net = lm.init()
    rng = np.random.default_rng(5)
    ids = rng.integers(0, V, (B, T))
    x = jnp.asarray(ids, jnp.float32)
    y = jnp.asarray(np.eye(V, dtype=np.float32)[np.roll(ids, -1, axis=1)])
    dt = _time_fused_steps(net, x, y, steps)
    out = {
        "metric": "transformer_lm_tokens_per_sec",
        "value": round(B * T * steps / dt, 1), "unit": "tokens/sec",
        "batch": B, "seq_len": T, "d_model": d_model,
        "n_layers": n_layers, "n_heads": n_heads,
        "flash_attention": jax.default_backend() == "tpu",
        "fused_dispatch": True,
    }
    # autoregressive decode throughput: the fused on-device sampling
    # loop (zoo.transformer.generate — KV caches as rnnTimeStep-style
    # carries, lax.scan over steps, rng carried). Headline driver only.
    if with_long_context and accel:
        try:
            from deeplearning4j_tpu.zoo.transformer import generate
            dec_B, dec_N = 8, 224      # prompt 16 + 224 fits max_len=T
            prompt = np.random.default_rng(11).integers(0, V, (dec_B, 16))
            generate(net, prompt, dec_N, temperature=0.8)   # compile
            t0 = time.perf_counter()
            generate(net, prompt, dec_N, temperature=0.8)
            d_dt = time.perf_counter() - t0
            out["decode"] = {
                "metric": "transformer_decode_tokens_per_sec",
                "value": round(dec_B * dec_N / d_dt, 1),
                "unit": "tokens/sec", "batch": dec_B,
                "new_tokens": dec_N, "ms_per_step": round(
                    d_dt / dec_N * 1e3, 3),
                "fused_scan_sampling": True,
            }
        except Exception as e:
            out["decode"] = {"error": f"{type(e).__name__}: {e}"[:200]}

    # long-context config (GPT-2-small-ish blocks at T=2048): at this
    # length training rides the Pallas flash BACKWARD too (the
    # size-routed fast path, kernels/flash_attention.py) — the
    # beyond-reference long-context flagship number. Opt-in (the
    # headline driver asks for it once; sweeps must not re-pay the
    # most expensive config per sweep point)
    if with_long_context and accel and T < 2048:
        try:
            out["long_context"] = bench_transformer_lm(
                accel, B=8, T=2048, d_model=512, n_layers=8, n_heads=8,
                steps=10)
            out["long_context"]["metric"] = (
                "transformer_lm_long_context_tokens_per_sec")
        except Exception as e:
            out["long_context"] = {"error": f"{type(e).__name__}: {e}"[:200]}
        # T=8192 silicon point: flash fwd+bwd + remat — the config the
        # CPU tests only exercise at toy scale. Memory stats recorded
        # when the backend exposes them (bytes_in_use peak)
        try:
            out["long_context_8k"] = bench_transformer_lm(
                accel, B=2, T=8192, d_model=512, n_layers=8, n_heads=8,
                steps=4, remat=True)
            out["long_context_8k"]["metric"] = (
                "transformer_lm_T8192_tokens_per_sec")
            out["long_context_8k"]["remat"] = True
            try:
                ms = jax.devices()[0].memory_stats() or {}
                out["long_context_8k"]["device_peak_bytes_in_use"] = int(
                    ms.get("peak_bytes_in_use", 0))
            except Exception:
                pass
        except Exception as e:
            out["long_context_8k"] = {
                "error": f"{type(e).__name__}: {e}"[:200]}
    return out


# --------------------------------------------------- Word2Vec (config 3)
def bench_word2vec(accel):
    from deeplearning4j_tpu.nlp.word2vec import Word2Vec

    rng = np.random.default_rng(3)
    vocab, n_sent, sent_len = 5000, (400 if accel else 40), 250
    # zipf-ish corpus so the vocab/negative-table paths do real work
    probs = 1.0 / np.arange(1, vocab + 1)
    probs /= probs.sum()
    seqs = [[f"w{t}" for t in rng.choice(vocab, sent_len, p=probs)]
            for _ in range(n_sent)]
    total_words = n_sent * sent_len

    # bigger fused groups on the accelerator: the tunnel adds tens of
    # ms per dispatch, so fewer/larger scans win; the async producer
    # packs the next group while the device drains the current one
    w2v = Word2Vec(layer_size=128, window_size=5, negative_sample=5,
                   min_word_frequency=1, epochs=1, batch_size=4096)
    if accel:
        w2v.conf.steps_per_flush = 32
    w2v.build_vocab(seqs)
    # warmup pass compiles every jitted step shape (fused groups + the
    # per-B and ragged-tail drains); the timed pass then measures
    # steady-state throughput — the reference's words/sec is likewise a
    # steady-state number (its native op has no compile step to pay)
    w2v.fit(seqs)
    w2v._init_tables()              # fresh tables: timed run trains from scratch
    t0 = time.perf_counter()
    w2v.fit(seqs)
    dt = time.perf_counter() - t0
    out = {
        "metric": "word2vec_skipgram_words_per_sec",
        "value": round(total_words / dt, 1), "unit": "words/sec",
        "corpus_words": total_words, "vector_length": 128,
        "steady_state": True,
        # AsyncSequencer overlap accounting (consumer_wait ≈ device
        # starved for host packing; producer_wait ≈ healthy backpressure)
        "etl": dict(w2v.etl_stats or {}),
    }
    if accel:
        try:
            out["large_vocab"] = _bench_word2vec_large()
        except Exception as e:   # keep the headline config's number
            out["large_vocab"] = {"error": f"{type(e).__name__}: {e}"[:200]}
    return out


def _bench_word2vec_large():
    """100k-word vocab config — exercises the sparse scatter update at a
    realistic table size (dense [V,D] autodiff grads would be ~50MB per
    step here; the sparse path touches only B·(K+2) rows)."""
    from deeplearning4j_tpu.nlp.word2vec import Word2Vec

    rng = np.random.default_rng(7)
    vocab, n_sent, sent_len = 100_000, 800, 500
    # zipf-ish sampling via inverse-CDF (rng.choice with p is O(V)/draw)
    probs = 1.0 / np.arange(1, vocab + 1)
    cdf = np.cumsum(probs / probs.sum())
    seqs = [np.searchsorted(cdf, rng.random(sent_len)).tolist()
            for _ in range(n_sent)]
    seqs = [[f"w{t}" for t in s] for s in seqs]
    total_words = n_sent * sent_len

    w2v = Word2Vec(layer_size=128, window_size=5, negative_sample=5,
                   min_word_frequency=1, epochs=1, batch_size=8192)
    w2v.conf.steps_per_flush = 16
    w2v.build_vocab(seqs)
    w2v.fit(seqs)                   # warmup: compile all step shapes
    w2v._init_tables()
    t0 = time.perf_counter()
    w2v.fit(seqs)
    dt = time.perf_counter() - t0
    return {"metric": "word2vec_100k_vocab_words_per_sec",
            "value": round(total_words / dt, 1), "unit": "words/sec",
            "corpus_words": total_words, "vocab_size": vocab,
            "steady_state": True, "etl": dict(w2v.etl_stats or {})}


# --------------------------------- multi-device scaling (config 4)
def bench_scaling_subprocess():
    """Scaling shape on an 8-virtual-device CPU mesh, in a subprocess so
    this process's accelerator backend is untouched."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + " --xla_force_host_platform_device_count=8").strip()
    # -m so the child resolves the package from site-packages or the
    # repo root alike (bench.py now lives inside the package)
    pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in (pkg_root, env.get("PYTHONPATH", "")) if p)
    proc = subprocess.run([sys.executable, "-m", "deeplearning4j_tpu.bench",
                           "--scaling-child"],
                          capture_output=True, text=True, timeout=1200,
                          env=env)
    if proc.returncode != 0:
        return {"error": (proc.stderr or proc.stdout)[-500:]}
    return json.loads(proc.stdout.strip().splitlines()[-1])


def _scaling_child():
    import jax

    # force the CPU backend INSIDE the process: the axon TPU plugin's
    # sitecustomize overrides JAX_PLATFORMS env vars, and with the
    # tunnel down any accidental axon init hangs forever
    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    from jax.sharding import Mesh

    from deeplearning4j_tpu.common.updaters import Adam
    from deeplearning4j_tpu.common.weights import WeightInit
    from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import (
        ConvolutionLayer, DenseLayer, OutputLayer, SubsamplingLayer,
    )
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.parallel.trainer import ParallelTrainer

    def build():
        conf = (NeuralNetConfiguration.builder()
                .seed(7).updater(Adam(1e-3)).weight_init(WeightInit.XAVIER)
                .list()
                .layer(ConvolutionLayer(n_out=32, kernel_size=(3, 3),
                                        activation="relu"))
                .layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
                .layer(ConvolutionLayer(n_out=64, kernel_size=(3, 3),
                                        activation="relu"))
                .layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
                .layer(DenseLayer(n_out=128, activation="relu"))
                .layer(OutputLayer(n_out=10, activation="softmax",
                                   loss="mcxent"))
                .set_input_type(InputType.convolutional(28, 28, 1))
                .build())
        return MultiLayerNetwork(conf).init()

    rng = np.random.default_rng(0)
    host_cores = os.cpu_count() or 1
    # size the workload to the host: the efficiency math needs compute-
    # dominated steps (dispatch-dominated steps made round 2's ratios
    # meaningless), but a 1-core sandbox can't chew 1024-image conv
    # batches in the bench budget
    per_dev = 128 if host_cores >= 8 else (64 if host_cores >= 4 else 16)
    steps = 5 if host_cores >= 4 else 3

    def timed_fit(trainer_fit, x, y, B, warmup_epochs=1):
        # `steps` batches tiled into ONE epoch drained through the fused
        # steps_per_execution scan — the timed window is one dispatch,
        # so the ratios measure partitioning, not Python dispatch.
        # Warmup exercises every jitted path the window hits (incl. the
        # averaging collective), or the window pays compiles.
        xt = np.tile(x, (steps,) + (1,) * (x.ndim - 1))
        yt = np.tile(y, (steps,) + (1,) * (y.ndim - 1))
        for _ in range(warmup_epochs):
            trainer_fit(xt, yt, epochs=1, batch_size=B,
                        steps_per_execution=steps)
        best = float("inf")
        for _ in range(2):           # best-of-2: the sandbox host is shared
            t0 = time.perf_counter()
            trainer_fit(xt, yt, epochs=1, batch_size=B,
                        steps_per_execution=steps)
            best = min(best, time.perf_counter() - t0)
        return best

    def make_data(B):
        x = rng.standard_normal((B, 28, 28, 1)).astype(np.float32)
        y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, B)]
        return x, y

    # plain single-device baseline (no mesh machinery) — the honest
    # denominator: efficiency must never be computed against a baseline
    # slower than the framework's own best 1-device path.
    plain = build()
    x1, y1 = make_data(per_dev)
    dt = timed_fit(lambda x, y, **kw: plain.fit(x, y, shuffle=False, **kw),
                   x1, y1, per_dev)
    thr_plain = per_dev * steps / dt

    out = {"host_cores": host_cores, "per_device_batch": per_dev,
           "plain_1dev_images_per_sec": round(thr_plain, 1),
           "note": ("virtual CPU devices share one host threadpool: "
                    "efficiency measures partitioning overhead, and is a "
                    "lower bound on real multi-chip scaling when "
                    "host_cores < devices")}
    for mode in ("sync", "averaging"):
        ips_by_n = {}
        for n in (1, 2, 4, 8):
            devs = np.array(jax.devices()[:n])
            mesh = Mesh(devs, ("data",))
            model = build()
            B = per_dev * n
            x, y = make_data(B)
            # averaging_frequency=2 with a 2-epoch warmup: the pmean
            # round compiles during warmup and then fires inside the
            # timed window (steps>=2), so the mode measures what it says
            tr = ParallelTrainer(model, mesh, mode=mode,
                                 averaging_frequency=2)
            dt = timed_fit(tr.fit, x, y, B,
                           warmup_epochs=2 if mode == "averaging" else 1)
            ips_by_n[str(n)] = round(B * steps / dt, 1)
        base = max(thr_plain, ips_by_n["1"])
        eff = {str(n): round(ips_by_n[str(n)] / (n * base), 3)
               for n in (2, 4, 8)}
        out[mode] = {"images_per_sec_by_devices": ips_by_n,
                     "weak_scaling_efficiency": eff,
                     "baseline_images_per_sec": round(base, 1)}

    # strong scaling: fixed global batch, sync mode
    G = per_dev * 8 if host_cores >= 4 else per_dev * 4
    xg, yg = make_data(G)
    plain2 = build()
    dt1_plain = timed_fit(
        lambda x, y, **kw: plain2.fit(x, y, shuffle=False, **kw), xg, yg, G)
    # the efficiency denominator is the FASTEST 1-device configuration
    # (plain jit fit or the trainer at n=1) so a slow baseline can't
    # manufacture superlinear "efficiency"
    tr1 = ParallelTrainer(build(), Mesh(np.array(jax.devices()[:1]),
                                        ("data",)), mode="sync")
    dt1 = min(dt1_plain, timed_fit(tr1.fit, xg, yg, G))
    strong = {"global_batch": G,
              "plain_1dev_seconds": round(dt1_plain, 3),
              "best_1dev_seconds": round(dt1, 3)}
    secs = {1: dt1}
    for n in (2, 4, 8):
        mesh = Mesh(np.array(jax.devices()[:n]), ("data",))
        tr = ParallelTrainer(build(), mesh, mode="sync")
        secs[n] = timed_fit(tr.fit, xg, yg, G)
    # Efficiency denominator: the best observed device-seconds product
    # across ALL configs (incl. n=1). Round 2 published efficiencies
    # >1 because the unpartitioned 1-device XLA-CPU program is ~2x
    # slower than the same work partitioned 2-ways on the same core
    # (conv kernel / blocking selection at the larger per-call batch) —
    # a slow baseline manufactures superlinear "scaling". Normalizing
    # by the best config makes every efficiency <=1.0 by construction
    # and measures what partitioning actually costs.
    best_dev_seconds = min(s * n for n, s in secs.items())
    strong["efficiency_denominator"] = (
        "best observed device-seconds across all configs "
        f"({round(best_dev_seconds, 3)}s x 1dev-equivalent); raw seconds "
        "reported so any other ratio can be recomputed")
    for n in (2, 4, 8):
        strong[str(n)] = {
            "seconds": round(secs[n], 3),
            "speedup_vs_best_1dev": round(dt1 / secs[n], 3),
            "strong_scaling_efficiency": round(
                best_dev_seconds / (secs[n] * n), 3),
        }
    out["strong_sync"] = strong
    print(json.dumps({"metric": "dataparallel_scaling_cpu8", **out}))


# ------------------------------------------- last-known-good fallback
# The driver's scoreboard is the LAST JSON line this script prints. A
# tunnel flap at capture time must not zero the round's perf record
# while a committed chip measurement exists (round 4 lost its official
# number exactly this way) — so every successful on-chip run persists
# its parsed result to LASTGOOD_BENCH.json (committed to git), and
# every failure path emits that artifact with explicit staleness
# provenance instead of zeros.

def _lastgood_path():
    p = os.environ.get("DL4J_BENCH_LASTGOOD")
    if p:
        return p
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.join(root, "LASTGOOD_BENCH.json")


def _load_lastgood():
    try:
        with open(_lastgood_path()) as f:
            d = json.load(f)
        if isinstance(d, dict) and float(d.get("value", 0.0)) > 0.0:
            return d
    except Exception:
        pass
    return None


def _save_lastgood(result):
    """Persist a fresh parsed bench block as the fallback artifact.

    Only real accelerator measurements qualify — a CPU-sandbox run must
    never overwrite chip numbers."""
    try:
        if str(result.get("platform", "")) == "cpu":
            return
        if float(result.get("value", 0.0)) <= 0.0:
            return
        snap = dict(result)
        snap.pop("stale", None)
        snap.pop("stale_error", None)
        # the gate verdict compares against the PREVIOUS artifact — it
        # must not be frozen into the artifact it superseded
        snap.pop("regression_check", None)
        snap["measured_at"] = time.strftime(
            "%Y-%m-%dT%H:%M:%SZ", time.gmtime())
        # write-then-rename: a failed dump (unserializable value) must
        # not truncate the existing good artifact it is replacing
        path = _lastgood_path()
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(snap, f, indent=1, default=str)
            f.write("\n")
        os.replace(tmp, path)
    except Exception:
        pass


# connectivity-class failure classification for the LASTGOOD echo: the
# stale fallback exists to survive TUNNEL flaps (the chip is fine, we
# just can't reach it), not to launder in-bench crashes — a genuine
# regression that throws must surface as the explicit error/zero shape,
# never as 2425 img/s with a `stale` flag (ADVICE r5).
_CONNECTIVITY_MARKERS = (
    "tunnel", "unavailable", "deadline", "connection", "connect",
    "grpc", "socket", "transport", "timed out", "timeout",
    "unreachable", "backend did not initialize",
)


def _is_connectivity_error(err) -> bool:
    """Heuristic: does this exception/message describe losing the
    accelerator, rather than the bench code failing?"""
    if isinstance(err, (ConnectionError, TimeoutError)):
        return True
    msg = (f"{type(err).__name__}: {err}" if isinstance(err, BaseException)
           else str(err)).lower()
    return any(m in msg for m in _CONNECTIVITY_MARKERS)


def _emit_failure(err, attempts, connectivity=True):
    """Failure emission. Connectivity-class failures (tunnel probe /
    backend init / mid-run transport loss) echo last-known-good with
    staleness provenance — the committed measurement is still the best
    estimate of the silicon. Anything else (an in-bench exception) is a
    code/regression signal and emits the explicit error/zero shape so
    the gate can catch it; zeros also when no good measurement was ever
    recorded."""
    lastgood = _load_lastgood() if connectivity else None
    if lastgood is not None:
        out = dict(lastgood)
        out["stale"] = True
        out["stale_error"] = err
        out["probe_attempts"] = attempts
        print(json.dumps(out))
        return
    print(json.dumps({
        "metric": "resnet50_train_images_per_sec_per_chip",
        "value": 0.0, "unit": "images/sec", "vs_baseline": 0.0,
        "error": err, "probe_attempts": attempts,
    }))


# ------------------------------------------------- bench regression gate
# Structural comparison of a fresh BENCH record against the committed
# last-known-good artifact, with per-metric tolerances. The point is to
# distinguish three very different events that all look like "the
# number went down": a stale fallback echo (tunnel died — the record IS
# the baseline, annotated), a CPU-sandbox run (not comparable to chip
# numbers), and a genuine throughput regression (exit nonzero — see
# benchtools/regression_gate.py for the CLI).

GATE_DEFAULT_TOLERANCE = 0.10  # relative drop that flags a regression
# noisier secondary metrics get wider bands (word2vec rides the host
# ETL path; the matmul probe is best-of-3 on shared silicon)
GATE_TOLERANCES = {
    "transformer_long_context_tokens_per_sec": 0.20,
    "word2vec_words_per_sec": 0.20,
    "matmul_peak_tflops": 0.15,
    "resnet50_mfu": 0.12,
    # precision metrics are STRUCTURAL (wire-byte ratios from static
    # shape/dtype math, not timings): near-zero tolerance, so a record
    # whose run silently fell back to fp32 (wire_reduction 1.0 against
    # a bf16 baseline's 2.0) gates as a regression instead of
    # masquerading as a bf16 win
    "resnet50_bf16_wire_reduction": 0.02,
    # serving-side numbers ride host thread scheduling (the loadtest's
    # event-driven clients still contend with the scheduler thread) —
    # wider bands than the pure-device metrics
    "serving_tokens_per_sec": 0.25,
    "serving_speedup_vs_sequential": 0.25,
    "serving_quantized_tokens_per_sec": 0.25,
    # STRUCTURAL (weight-tree shape/dtype math, not a timing): a run
    # that silently fell back to fp weights reports ~1.0 against an
    # int8 baseline's ~3.6 and gates as a regression instead of
    # masquerading as a quantized win (the bf16 wire-reduction pattern)
    "serving_quantized_weight_bytes_reduction": 0.02,
    # TTFT under mixed-length bucketed admission (lower is better —
    # see GATE_LOWER_IS_BETTER); p50 of a host-scheduled latency
    "serving_mixed_p50_ttft_ms": 0.5,
    # fleet phase: sustained concurrency is STRUCTURAL (how many
    # streams were simultaneously open across the fleet — a scheduler
    # or drain regression that drops/serializes streams collapses it),
    # swap-window TTFT is the no-compile-cliff evidence (successor
    # warmed before drain; lower is better, host-scheduled band)
    "fleet_streams_sustained": 0.05,
    "fleet_swap_p99_ttft_ms": 0.5,
    "fleet_tokens_per_sec": 0.25,
    # speculative decode on the acceptance-friendly workload: a
    # host-timing number (wide band), but a silently-disabled drafting
    # path halves it far past the band
    "serving_speculative_tokens_per_sec": 0.25,
    # STRUCTURAL (prompt-token accounting, not a timing): shared-prefix
    # CoW silently falling back to private-block prefills reports ~1.0
    # against a shared baseline's >2 and gates as a regression instead
    # of masquerading as a sharing win (the int8/bf16 pattern)
    "serving_prefix_prefill_reduction": 0.02,
    # STRUCTURAL (token-position accounting from the goodput ledger,
    # not a timing): a silently-broken accounting path reports ~0
    # (ledger never fed) or ~1.0 (padding never counted) against a
    # real baseline's mid-range fraction and gates as a regression
    # instead of masquerading as an efficiency change (the
    # prefix-reduction pattern)
    "serving_goodput_fraction": 0.05,
    # rejection-sampled speculation on sampled traffic: host-timing
    # number (wide band) — a silently-greedy-only drafting path drops
    # the sampled arm back to one dispatch per token, far past it
    "serving_sampled_spec_tokens_per_sec": 0.25,
    # truncated-layer drafter acceptance on the n-gram-adversarial
    # workload: deterministic-seeded but acceptance-EWMA-coupled, so a
    # mid band — a drafter that stops agreeing with the full model
    # collapses it orders past 50%
    "serving_truncated_draft_truncated_accept_rate": 0.5,
    # STRUCTURAL (prompt-token accounting): radix auto-dedup silently
    # disabled reports ~1.0 against a shared baseline's >=2 and gates
    # instead of masquerading as a cache win (the registered-prefix
    # pattern)
    "serving_radix_prefill_reduction": 0.02,
    # horizontal serving: the 1->2 replica aggregate scale rides the
    # emulated device-step floor (see run_replicated's sandbox_model),
    # so it's near-structural — a routing plane that serializes the
    # fleet collapses it from ~1.9 toward 1.0, far past the band; the
    # loadtest itself hard-fails below 1.7 regardless of baseline
    "serving_replica_scale_x": 0.08,
    "serving_replicated_tokens_per_sec": 0.25,
    # multi-tenant fleet (scripts/tenant_loadtest.py): throughput is a
    # host-timing number (wide band); the other three are STRUCTURAL —
    # shared_base_copies counts distinct in-memory base-weight copies
    # (1 by construction; a tenant silently deep-copying the base
    # doubles it, far past the band; lower is better),
    # adapter_zip_fraction is adapter-artifact bytes over the full
    # model zip (a publish path that silently ships base weights jumps
    # from ~0.03 toward 1.0; lower is better), and the fair-share
    # floor margin is light-tenant admitted share over its floor under
    # 10:1 skew (an admission plane that stops protecting the floor
    # collapses it below 1.0)
    "tenant_tokens_per_sec": 0.25,
    "tenant_shared_base_copies": 0.02,
    "tenant_adapter_zip_fraction": 0.5,
    "tenant_light_share_floor_margin": 0.10,
}
# metrics where a RISE past tolerance is the regression (latencies);
# compare_bench inverts the ratio so the shared gate math applies
GATE_LOWER_IS_BETTER = {"serving_mixed_p50_ttft_ms",
                        "fleet_swap_p99_ttft_ms",
                        "tenant_shared_base_copies",
                        "tenant_adapter_zip_fraction"}
_GATE_HEADLINE = "resnet50_images_per_sec"


def _gate_metrics(rec):
    """Flatten the gated metrics out of one BENCH record."""
    out = {}

    def take(name, *path):
        cur = rec
        for p in path:
            if not isinstance(cur, dict):
                return
            cur = cur.get(p)
        if isinstance(cur, (int, float)) and cur > 0:
            out[name] = float(cur)

    take("resnet50_images_per_sec", "value")
    take("resnet50_mfu", "mfu")
    take("resnet50_bf16_wire_reduction", "precision", "wire_reduction")
    take("matmul_peak_tflops", "measured_matmul_tflops")
    take("lenet_images_per_sec", "extras", "lenet_mnist", "value")
    take("lstm_chars_per_sec", "extras", "lstm_char_rnn", "value")
    take("transformer_tokens_per_sec", "extras", "transformer_lm", "value")
    take("transformer_long_context_tokens_per_sec",
         "extras", "transformer_lm", "long_context", "value")
    take("word2vec_words_per_sec", "extras", "word2vec", "value")
    # serving ledger (scripts/serve_loadtest.py writes these): the
    # continuous-batching throughput and its margin over sequential
    # whole-batch generate() round-trips gate like training metrics
    take("serving_tokens_per_sec", "extras", "serving", "tokens_per_sec")
    take("serving_speedup_vs_sequential",
         "extras", "serving", "speedup_vs_sequential")
    # the mixed-length + int8-quantized loadtest phase: throughput,
    # the structural weight-byte reduction of the decode program, and
    # bucketed-admission TTFT (lower-is-better)
    take("serving_quantized_tokens_per_sec",
         "extras", "serving_mixed_quantized", "tokens_per_sec")
    take("serving_quantized_weight_bytes_reduction",
         "extras", "serving_mixed_quantized", "weight_bytes_reduction")
    take("serving_mixed_p50_ttft_ms",
         "extras", "serving_mixed_quantized", "p50_ttft_ms")
    # the multi-model fleet phase (>10k streams, 2 models, mid-run
    # hot-swap): peak simultaneously-open streams across the fleet and
    # the p99 TTFT of admissions landing in the swap window
    take("fleet_streams_sustained",
         "extras", "serving_fleet", "streams_sustained")
    take("fleet_swap_p99_ttft_ms",
         "extras", "serving_fleet", "swap_p99_ttft_ms")
    take("fleet_tokens_per_sec",
         "extras", "serving_fleet", "tokens_per_sec")
    # speculative decoding + shared-prefix CoW (loadtest phases 5+6)
    take("serving_speculative_tokens_per_sec",
         "extras", "serving_speculative", "tokens_per_sec")
    take("serving_prefix_prefill_reduction",
         "extras", "serving_prefix", "prefill_reduction")
    # goodput ledger (loadtest "goodput" block): the useful fraction of
    # dispatched token-positions — structural accounting, tight band
    take("serving_goodput_fraction",
         "extras", "goodput", "goodput_fraction")
    # sampled speculation + truncated drafter + radix prefix cache
    # (loadtest phases 7-9)
    take("serving_sampled_spec_tokens_per_sec",
         "extras", "serving_sampled_spec", "tokens_per_sec")
    take("serving_truncated_draft_truncated_accept_rate",
         "extras", "serving_truncated_draft", "truncated_accept_rate")
    take("serving_radix_prefill_reduction",
         "extras", "serving_radix", "prefill_reduction")
    # horizontal serving (loadtest phase 10): the 1->2 replica
    # aggregate-throughput scale and the two-replica absolute rate
    take("serving_replica_scale_x",
         "extras", "serving_replicated", "replica_scale_x")
    take("serving_replicated_tokens_per_sec",
         "extras", "serving_replicated", "tokens_per_sec_2r")
    # multi-tenant fleet (scripts/tenant_loadtest.py): shared-base
    # memory claim, adapter-delta artifact size, fair-share floor
    take("tenant_tokens_per_sec",
         "extras", "serving_tenancy", "tokens_per_sec")
    take("tenant_shared_base_copies",
         "extras", "serving_tenancy", "shared_base_copies")
    take("tenant_adapter_zip_fraction",
         "extras", "serving_tenancy", "adapter_zip_fraction")
    take("tenant_light_share_floor_margin",
         "extras", "serving_tenancy", "fair_share", "floor_margin")
    return out


def compare_bench(fresh, baseline, default_tolerance=GATE_DEFAULT_TOLERANCE,
                  tolerances=None):
    """Gate verdict for a fresh BENCH record vs a baseline record.

    Returns a dict whose ``status`` is one of:

    - ``no_baseline``     — nothing to compare against (first run)
    - ``stale_fallback``  — fresh is the tunnel-failure echo of the
      baseline itself (``stale: true``), not a measurement: explained,
      never a regression
    - ``incomparable_platform`` — CPU-sandbox record vs chip baseline
    - ``no_measurement``  — fresh carries an error and no usable value
    - ``regression``      — at least one metric dropped past tolerance
      (or the headline metric vanished)
    - ``pass``            — every shared metric within tolerance
    """
    tol = dict(GATE_TOLERANCES)
    tol.update(tolerances or {})
    if not isinstance(baseline, dict) or not _gate_metrics(baseline):
        return {"status": "no_baseline",
                "note": "no usable baseline metrics — nothing gated"}
    if not isinstance(fresh, dict):
        return {"status": "no_measurement", "note": "fresh record unreadable"}
    if fresh.get("stale"):
        return {"status": "stale_fallback",
                "stale_error": fresh.get("stale_error"),
                "note": ("fresh record is the last-known-good echo emitted "
                         "on a tunnel failure — an explained outage, not a "
                         "throughput regression")}
    fplat = str(fresh.get("platform", ""))
    bplat = str(baseline.get("platform", ""))
    if fplat and bplat and fplat != bplat:
        return {"status": "incomparable_platform",
                "fresh_platform": fplat, "baseline_platform": bplat,
                "note": "sandbox/chip records are not comparable"}
    fm, bm = _gate_metrics(fresh), _gate_metrics(baseline)
    if not fm:
        if fresh.get("error"):
            return {"status": "no_measurement",
                    "error": fresh.get("error"),
                    "note": "fresh record carries an explicit error and no "
                            "usable value"}
        return {"status": "regression", "regressions": [],
                "missing": sorted(bm),
                "note": "fresh record has no gated metrics and no error"}
    regressions, improvements, missing, checked = [], [], [], []
    for name, base in sorted(bm.items()):
        t = tol.get(name, default_tolerance)
        val = fm.get(name)
        if val is None:
            missing.append(name)
            continue
        checked.append(name)
        # lower-is-better metrics (latencies) invert the ratio so the
        # same "delta < -t is a regression" arithmetic applies: a TTFT
        # that ROSE past tolerance yields a negative delta here
        if name in GATE_LOWER_IS_BETTER:
            delta = base / val - 1.0
        else:
            delta = val / base - 1.0
        entry = {"metric": name, "baseline": base, "fresh": val,
                 "delta_pct": round(100.0 * delta, 2),
                 "tolerance_pct": round(100.0 * t, 1)}
        if delta < -t:
            regressions.append(entry)
        elif delta > t:
            improvements.append(entry)
    status = "pass"
    if regressions or _GATE_HEADLINE in missing:
        status = "regression"
    return {"status": status, "checked": checked,
            "regressions": regressions, "improvements": improvements,
            "missing": missing,
            "tolerance_default_pct": round(100.0 * default_tolerance, 1)}


def _probe_tunnel_subprocess(timeout_s=None) -> bool:
    """One tunnel-health probe in a FRESH interpreter. A retry must use
    a subprocess: once this process's backend init hangs on a dead
    tunnel, every later jax call in the same process waits on the same
    stuck init — only a new interpreter can re-attempt."""
    if timeout_s is None:
        try:
            timeout_s = float(
                os.environ.get("DL4J_BENCH_PROBE_TIMEOUT_S", "120"))
        except ValueError:
            timeout_s = 120.0
    try:
        proc = subprocess.run(
            [sys.executable, "-u", "-c", "import jax; jax.devices()"],
            capture_output=True, timeout=timeout_s)
        return proc.returncode == 0
    except subprocess.TimeoutExpired:
        return False
    except Exception:
        return False


def _probe_backend(timeout_s=180):
    """Initialize the JAX backend with a watchdog and RETRY window. The
    axon plugin's device init HANGS indefinitely when the TPU tunnel is
    down (observed in round 3, which lost its end-of-round number to a
    single blip) — so: (1) subprocess probes retry with backoff across
    DL4J_BENCH_RETRY_WINDOW_S (default 600s) until one succeeds; (2)
    only then does THIS process initialize, still under a watchdog
    thread; (3) failure emits a structured error JSON, never a hang."""
    import threading

    try:
        window_s = float(os.environ.get("DL4J_BENCH_RETRY_WINDOW_S", "600"))
    except ValueError:
        window_s = 600.0
    # CPU-forced runs (tests / sandbox drives set jax_platforms=cpu
    # in-process, which a subprocess would NOT inherit) skip the tunnel
    # probe — there is no tunnel to wait for
    try:
        import jax
        if "cpu" == str(getattr(jax.config, "jax_platforms", "") or ""):
            window_s = 0.0
    except Exception:
        pass
    deadline = time.monotonic() + window_s
    attempts = 0
    while window_s > 0:
        attempts += 1
        if _probe_tunnel_subprocess():
            break
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            _emit_failure(f"accelerator tunnel unreachable after "
                          f"{attempts} probes over {window_s:.0f}s",
                          attempts)
            return None
        time.sleep(min(45.0, remaining))

    box = {}

    def probe():
        try:
            box["info"] = _device_info()
        except Exception as e:
            box["err"] = f"{type(e).__name__}: {e}"

    t = threading.Thread(target=probe, daemon=True)
    t.start()
    t.join(timeout_s)
    if "info" in box:
        return box["info"]
    err = box.get("err", f"backend did not initialize within {timeout_s}s "
                         "(accelerator tunnel down?)")
    _emit_failure(err, attempts)
    return None


def _compile_tracker():
    """Cumulative XLA compile tracking via the telemetry core's
    jit-compile collector (monitor/collectors.py) on a private registry.
    Returns a snap() closure yielding (compile_count, compile_seconds) —
    what lets each bench block report warmup (compile) vs steady-state
    time instead of one undifferentiated wall clock."""
    try:
        from deeplearning4j_tpu.monitor import (JitCompileCollector,
                                                MetricsRegistry)
        coll = JitCompileCollector(MetricsRegistry()).install()
        return lambda: (coll.compile_count(), coll.compile_seconds())
    except Exception:  # collector must never kill a bench run
        return lambda: (0.0, 0.0)


def _with_compile_split(snap, fn, *args, **kwargs):
    """Run one bench block and attach its compile-vs-steady-state split
    to the result dict (no-op for non-dict results/errors)."""
    c0, s0 = snap()
    t0 = time.perf_counter()
    out = fn(*args, **kwargs)
    wall = time.perf_counter() - t0
    c1, s1 = snap()
    if isinstance(out, dict):
        out["compile"] = {
            "xla_compiles": int(c1 - c0),
            "compile_seconds": round(s1 - s0, 3),
            "wall_seconds": round(wall, 3),
            "steady_state_wall_seconds": round(max(0.0, wall - (s1 - s0)), 3),
        }
    return out


def main():
    info = _probe_backend()
    if info is None:
        return
    plat, kind, accel, _ = info
    try:
        # persistent XLA cache: repeat bench runs skip the minutes-long
        # ResNet compile (timed windows never include compiles anyway —
        # the warmup dispatch absorbs them)
        from deeplearning4j_tpu.nd import enable_compilation_cache
        enable_compilation_cache()
    except Exception:
        pass
    snap = _compile_tracker()
    try:
        primary = _with_compile_split(snap, bench_resnet50, accel)
    except Exception as e:
        # a mid-run tunnel drop must not zero the scoreboard — but ONLY
        # a connectivity-class failure may echo LASTGOOD; an in-bench
        # crash is a regression signal and emits the explicit error
        # shape (a genuine regression must never surface as stale-good)
        _emit_failure(f"primary bench failed: {type(e).__name__}: "
                      f"{e}"[:400], attempts=0,
                      connectivity=_is_connectivity_error(e))
        return

    extras = {}
    for name, fn in (("lenet_mnist", bench_lenet),
                     ("lstm_char_rnn", bench_lstm_charnn),
                     ("transformer_lm",
                      lambda a: bench_transformer_lm(
                          a, with_long_context=True)),
                     ("word2vec", bench_word2vec)):
        try:
            extras[name] = _with_compile_split(snap, fn, accel)
        except Exception as e:  # secondary metric must not kill the run
            extras[name] = {"error": f"{type(e).__name__}: {e}"[:300]}
    try:
        extras["scaling_cpu8"] = bench_scaling_subprocess()
    except Exception as e:
        extras["scaling_cpu8"] = {"error": f"{type(e).__name__}: {e}"[:300]}

    primary["extras"] = extras
    # gate verdict vs the PREVIOUS last-known-good — computed before
    # _save_lastgood replaces it, and embedded in the printed record so
    # benchtools/regression_gate.py can exit on it even after the
    # artifact has been refreshed (comparing afterwards would be
    # fresh-vs-fresh and always pass)
    try:
        prior = _load_lastgood()
        if prior is not None:
            primary["regression_check"] = compare_bench(primary, prior)
    except Exception:  # noqa: BLE001 — the gate must never kill a run
        pass
    _save_lastgood(primary)
    print(json.dumps(primary))


if __name__ == "__main__":
    if "--scaling-child" in sys.argv:
        _scaling_child()
    else:
        main()
