"""Watermarked normalizer statistics for unbounded streams.

`NormalizerStandardize` fits once over a finite corpus; an unbounded
firehose has no "once". `WindowedStandardize` keeps the one-pass
sum/sum-of-squares moments (`datasets/normalizers.py` math, float64)
PER BATCH in a sliding window of the last `window` dispatched batches,
so the statistics track the live distribution instead of averaging a
drifting stream into mush.

Versioned snapshot-per-publish: `snapshot()` freezes the current
window statistics into an ordinary `NormalizerStandardize` (tagged
with a monotonically increasing version + the records watermark) that
rides the published model zip (`ModelRegistry.publish(normalizer=)` →
`ModelSerializer.add_normalizer_to_model`) — a served release carries
exactly the stats its training batches were transformed under, and
`restore_normalizer_from_file` on the zip reproduces them bit-for-bit.

The LIVE window state is itself checkpointable through the ordinary
normalizer persistence contract (`state()` / `normalizer_from_meta`),
so `CheckpointListener(normalizer=...)` snapshots it and a
resume-from-offset run rebuilds the identical window — which is what
keeps the resumed trajectory bit-equal to the uninterrupted one (the
transform is trajectory-bearing).
"""

from __future__ import annotations

from collections import deque
from typing import Optional

import numpy as np

from deeplearning4j_tpu.datasets.normalizers import (
    NormalizerStandardize,
    _float_dtype,
    _mask_weights,
    _reduce_axes,
    register_normalizer,
)


@register_normalizer
class StandardizeSnapshot(NormalizerStandardize):
    """A frozen, versioned standardizer — what `WindowedStandardize.
    snapshot()` returns and a published model zip carries. Transform /
    revert are the parent's; the meta additionally records which
    window version and records watermark produced the stats."""

    kind = "standardize_snapshot"

    def __init__(self, version: int = 0, records_seen: int = 0):
        super().__init__()
        self.version = int(version)
        self.records_seen = int(records_seen)

    def state(self):
        return ({"kind": self.kind, "version": self.version,
                 "records_seen": self.records_seen},
                {"mean": self.mean, "std": self.std})

    @classmethod
    def _from_state(cls, meta, arrays):
        out = cls(meta.get("version", 0), meta.get("records_seen", 0))
        out.mean = arrays["mean"]
        out.std = arrays["std"]
        return out


@register_normalizer
class WindowedStandardize:
    """Sliding-window zero-mean/unit-variance statistics.

    `observe(features)` folds one dispatched batch's moments into the
    window (evicting the oldest past `window` batches); `transform`
    applies the CURRENT window stats. Implements the normalizer
    persistence contract (`state()`/`_from_state`) over the full
    window contents, so checkpoints restore the exact window — not
    just its aggregate."""

    kind = "windowed_standardize"

    def __init__(self, window: int = 64):
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
        self.window = int(window)
        self._moments: deque = deque()   # (count, sum[F], sumsq[F])
        self.records_seen = 0            # rows ever observed (watermark)
        self.snapshot_version = 0        # bumped per snapshot()
        self._mean: Optional[np.ndarray] = None
        self._std: Optional[np.ndarray] = None
        self._dirty = True

    # ---------------------------------------------------------- updating
    def observe(self, features, mask=None) -> "WindowedStandardize":
        x = np.asarray(features, np.float64)
        axes = _reduce_axes(x)
        w = _mask_weights(x, mask)
        if w is not None:
            cnt = float(w.sum())
            s = (x * w).sum(axis=axes)
            sq = (x * x * w).sum(axis=axes)
        else:
            cnt = float(np.prod([x.shape[a] for a in axes])) if axes else 1.0
            s = x.sum(axis=axes)
            sq = (x * x).sum(axis=axes)
        self._moments.append((cnt, s, sq))
        while len(self._moments) > self.window:
            self._moments.popleft()
        self.records_seen += int(x.shape[0]) if x.ndim else 1
        self._dirty = True
        return self

    def fit(self, data) -> "WindowedStandardize":
        """Normalizer-protocol fit: observe every batch of a DataSet /
        iterable (the finite-corpus warm-start before streaming)."""
        from deeplearning4j_tpu.datasets.dataset import DataSet
        batches = [data] if isinstance(data, DataSet) else data
        n = 0
        for ds in batches:
            mask = getattr(ds, "features_mask", None)
            self.observe(np.asarray(ds.features),
                         None if mask is None else np.asarray(mask))
            n += 1
        if n == 0:
            raise ValueError("fit() saw no data")
        if hasattr(data, "reset"):
            data.reset()
        return self

    def _refresh(self):
        if not self._dirty:
            return
        if not self._moments:
            raise ValueError(
                "WindowedStandardize has observed no data yet — "
                "transform() before the first batch has no statistics")
        n = sum(c for c, _, _ in self._moments)
        s = sum((m[1] for m in self._moments), 0.0)
        sq = sum((m[2] for m in self._moments), 0.0)
        self._mean = s / n
        var = sq / n - self._mean ** 2
        self._std = np.sqrt(np.clip(var, 1e-12, None))
        self._dirty = False

    # -------------------------------------------------------- transforms
    @property
    def mean(self) -> np.ndarray:
        self._refresh()
        return self._mean

    @property
    def std(self) -> np.ndarray:
        self._refresh()
        return self._std

    def transform(self, features):
        self._refresh()
        x = np.asarray(features)
        return ((x - self._mean) / self._std).astype(_float_dtype(x))

    def revert(self, features):
        self._refresh()
        x = np.asarray(features)
        return (x * self._std + self._mean).astype(_float_dtype(x))

    def pre_process(self, ds):
        ds.features = self.transform(ds.features)
        return ds

    # ---------------------------------------------------------- snapshot
    def snapshot(self) -> StandardizeSnapshot:
        """Freeze the current window statistics as an independent,
        versioned standardizer (later `observe` calls do not touch
        it) — the normalizer a publish attaches to its model zip."""
        self._refresh()
        self.snapshot_version += 1
        snap = StandardizeSnapshot(self.snapshot_version,
                                   self.records_seen)
        snap.mean = np.array(self._mean, np.float64)
        snap.std = np.array(self._std, np.float64)
        return snap

    # ------------------------------------------------------- persistence
    def state(self):
        counts = np.asarray([m[0] for m in self._moments], np.float64)
        sums = (np.stack([m[1] for m in self._moments])
                if self._moments else np.zeros((0,), np.float64))
        sumsqs = (np.stack([m[2] for m in self._moments])
                  if self._moments else np.zeros((0,), np.float64))
        return ({"kind": self.kind, "window": self.window,
                 "records_seen": self.records_seen,
                 "snapshot_version": self.snapshot_version},
                {"counts": counts, "sums": sums, "sumsqs": sumsqs})

    @classmethod
    def _from_state(cls, meta, arrays):
        out = cls(meta.get("window", 64))
        out.records_seen = int(meta.get("records_seen", 0))
        out.snapshot_version = int(meta.get("snapshot_version", 0))
        counts = np.asarray(arrays.get("counts", ()))
        sums = np.asarray(arrays.get("sums", ()))
        sumsqs = np.asarray(arrays.get("sumsqs", ()))
        for i in range(counts.shape[0]):
            out._moments.append((float(counts[i]), sums[i], sumsqs[i]))
        out._dirty = True
        return out
