"""OnlineTrainer — continuous training that publishes into the fleet.

The sending end of the train→serve loop PR 12's registry/hot-swap
machinery opened: an unbounded `StreamingDataSetIterator` drives the
ORDINARY `MultiLayerNetwork.fit` loop (one epoch that never ends until
the stream quiesces or `stop()`/`max_steps` fires), so every existing
fit-loop contract holds without new step code — `step_boundary`
markers gate the checkpoint/publish listeners, the in-graph
diagnostics cadence (`monitor.diagnostics.process_if_due`) runs
unchanged, and the fault runtime checkpoints the full state including
the stream cursor and the live normalizer window.

Drift-aware early stopping (`DriftGate`): an `EvaluativeListener` tap
on a HELD-OUT stream feeds the `evaluative_score{tag=,metric=}`
gauges; when the held-out score degrades past a configurable band
below the best score seen, the gate trips — which pauses PUBLISHING
(the registry listener skips its cadence without advancing its clock)
but never training, and publishing resumes at the first boundary after
the score recovers into the band. `online_publish_paused` /
`online_drift_trips_total` are the alarm surface.
"""

from __future__ import annotations

import logging
from typing import Callable, List, Optional

from deeplearning4j_tpu.optimize.listeners import (
    EvaluativeListener,
    TrainingListener,
)

log = logging.getLogger("deeplearning4j_tpu.online")


class DriftGate(EvaluativeListener):
    """Held-out-score regression gate over the EvaluativeListener tap.

    Evaluates the held-out iterator every `frequency` iterations
    (iteration_end invocation — an unbounded run has no epoch ends);
    tracks the best score seen and trips when the current score falls
    below ``best - band``. `allow_publish()` is the gate callable the
    registry publish listener consults; `paused` flips back to False
    the moment the score recovers into the band. Training itself is
    never touched.

    ``metric="loss"`` is the LOSS-BAND mode: the held-out LOSS (mean
    per-example `model.score`, masked-example aware through the loss
    fn) replaces the classification score, best is the MINIMUM seen,
    and the gate trips when loss RISES past ``best + band`` — which is
    what regression and LM-perplexity online loops gate on, where
    accuracy/f1 mean nothing."""

    def __init__(self, heldout, *, frequency: int = 50,
                 band: float = 0.1, metric: str = "accuracy",
                 min_evals_before_gating: int = 1, tag: str = "heldout",
                 printer: Optional[Callable[[str], None]] = None):
        super().__init__(heldout, frequency=frequency,
                         invocation="iteration_end", tag=tag,
                         printer=printer or (lambda s: log.info(s)))
        if band <= 0:
            raise ValueError(f"band must be > 0, got {band}")
        if metric not in ("accuracy", "f1", "loss"):
            raise ValueError(
                f"metric must be 'accuracy', 'f1' or 'loss'; "
                f"got {metric!r}")
        self.metric = metric
        self.band = float(band)
        self.min_evals_before_gating = int(min_evals_before_gating)
        self.best_score: Optional[float] = None
        self.last_score: Optional[float] = None
        self.paused = False
        self.trips = 0
        self.history: List[tuple] = []    # (iteration, score, paused)
        self._evals = 0
        self._metrics_cache = None

    # ----------------------------------------------------------- scoring
    def _current_score(self, evaluation) -> float:
        if self.metric == "f1":
            return float(evaluation.f1())
        return float(evaluation.accuracy())

    def _gate_metrics(self):
        from deeplearning4j_tpu import monitor
        return monitor.resolve_cached_metrics(
            self, "_metrics_cache", lambda reg: {
                "paused": reg.gauge(
                    "online_publish_paused",
                    "1 while the drift gate is refusing publishes",
                    tag=self.tag),
                "trips": reg.counter(
                    "online_drift_trips_total",
                    "held-out regressions that tripped the publish "
                    "gate", tag=self.tag),
            })

    def _heldout_loss(self, model) -> float:
        """Example-weighted mean loss over the held-out iterator (or a
        single DataSet) through `model.score` — the exact training
        objective, so the band compares like against like."""
        import numpy as np

        it = self.iterator
        if hasattr(it, "features"):            # a bare DataSet
            batches = [it]
        else:
            if hasattr(it, "reset"):
                it.reset()
            batches = it
        total, n = 0.0, 0
        for ds in batches:
            b = int(np.asarray(ds.features).shape[0])
            total += float(model.score(ds)) * b
            n += b
        if n == 0:
            raise ValueError("held-out iterator yielded no examples")
        return total / n

    def _evaluate(self, model, when):
        loss_mode = self.metric == "loss"
        if loss_mode:
            score = self._heldout_loss(model)
            self.printer(f"[{when}] heldout loss={score:.4f}")
            from deeplearning4j_tpu import monitor
            if monitor.is_enabled():
                reg = monitor.registry()
                reg.gauge("evaluative_score",
                          help="held-out evaluation score from "
                               "EvaluativeListener",
                          tag=self.tag, metric="loss").set(float(score))
                reg.gauge("evaluative_last_iteration",
                          help="iteration of the last held-out "
                               "evaluation",
                          tag=self.tag).set(float(self._last_iteration))
        else:
            super()._evaluate(model, when)
            score = self._current_score(self.evaluations[-1])
        self.last_score = score
        self._evals += 1
        better = (score < self.best_score if loss_mode
                  else score > self.best_score) \
            if self.best_score is not None else True
        if better:
            self.best_score = score
        degraded = (score > self.best_score + self.band if loss_mode
                    else score < self.best_score - self.band)
        if (degraded and not self.paused
                and self._evals >= self.min_evals_before_gating):
            self.paused = True
            self.trips += 1
            from deeplearning4j_tpu.monitor.flightrec import (
                GLOBAL_FLIGHT_RECORDER,
            )
            GLOBAL_FLIGHT_RECORDER.record(
                "drift_trip", tag=self.tag, metric=self.metric,
                score=float(score), best=float(self.best_score),
                band=float(self.band),
                iteration=int(self._last_iteration))
            log.warning(
                "drift gate TRIPPED at %s: held-out %s %.4f moved more "
                "than %.3f past best %.4f — publishing paused "
                "(training continues)", when, self.metric, score,
                self.band, self.best_score)
            m = self._gate_metrics()
            if m is not None:
                m["trips"].inc()
        elif self.paused and not degraded:
            self.paused = False
            log.info(
                "drift gate recovered at %s: held-out %s %.4f back "
                "inside the band — publishing resumes", when,
                self.metric, score)
        self.history.append((self._last_iteration, score, self.paused))
        m = self._gate_metrics()
        if m is not None:
            m["paused"].set(1.0 if self.paused else 0.0)

    # -------------------------------------------------------------- gate
    def allow_publish(self) -> bool:
        return not self.paused


class _StopAfterListener(TrainingListener):
    """Ends the unbounded stream after `max_steps` completed
    iterations by asking the ITERATOR to stop — the fit loop then
    finishes the epoch naturally (flushing any pending fused group and
    firing on_epoch_end/on_fit_end), so the publish/checkpoint
    listeners see an ordinary end-of-fit at an arbitrary step."""

    def __init__(self, iterator, max_steps: int):
        self.iterator = iterator
        self.max_steps = int(max_steps)

    def iteration_done(self, model, iteration, epoch, score, **info):
        if iteration + 1 >= self.max_steps:
            stop = getattr(self.iterator, "stop", None)
            if stop is not None:
                stop()


class OnlineTrainer:
    """Continuous fine-tuning from a streaming iterator, publishing
    snapshots into a `ModelRegistry` and checkpointing through the
    fault runtime.

    ``trainer.run()`` blocks until the stream quiesces (watermark
    timeout), `stop()` is called, or `max_steps` completes; it returns
    a summary dict. Resume an interrupted run with
    `OnlineTrainer.resume(directory, ...)` — the checkpoint cursor
    seeks the (replayable) transport back to the exact record after
    the last trained batch, and the restored counters pin the rng
    stream, so the resumed trajectory is bit-equal to an uninterrupted
    run over the same record sequence."""

    def __init__(self, net, iterator, *, registry=None,
                 model_name: Optional[str] = None,
                 publish_frequency: int = 100,
                 publish_at_fit_end: bool = True,
                 save_updater: bool = False,
                 checkpoint_dir=None, checkpoint_frequency: int = 50,
                 checkpoint_at_fit_end: bool = True,
                 normalizer=None, drift_gate: Optional[DriftGate] = None,
                 steps_per_execution: int = 1,
                 listeners=()):
        if (registry is None) != (model_name is None):
            raise ValueError(
                "registry and model_name come together (both or "
                "neither)")
        self.net = net
        self.iterator = iterator
        self.registry = registry
        self.model_name = model_name
        self.publish_frequency = int(publish_frequency)
        self.publish_at_fit_end = publish_at_fit_end
        self.save_updater = save_updater
        self.checkpoint_dir = checkpoint_dir
        self.checkpoint_frequency = int(checkpoint_frequency)
        self.checkpoint_at_fit_end = checkpoint_at_fit_end
        self.normalizer = normalizer
        self.drift_gate = drift_gate
        self.steps_per_execution = int(steps_per_execution)
        self.extra_listeners = list(listeners)
        self.publish_listener = None
        self.checkpoint_listener = None
        # streaming iterators transform through the SAME normalizer the
        # checkpoints persist — wire it if the iterator has the slot
        # and nothing is set yet (explicit wiring wins)
        if (normalizer is not None
                and getattr(iterator, "normalizer", "absent") is None):
            iterator.normalizer = normalizer

    # ---------------------------------------------------------- assembly
    def _build_listeners(self, max_steps: Optional[int]):
        ls: List[TrainingListener] = []
        if self.registry is not None:
            gate = (self.drift_gate.allow_publish
                    if self.drift_gate is not None else None)
            normalizer_provider = None
            if self.normalizer is not None:
                snap = getattr(self.normalizer, "snapshot", None)
                normalizer_provider = snap if snap is not None \
                    else (lambda: self.normalizer)
            self.publish_listener = self.registry.publish_listener(
                self.model_name, frequency=self.publish_frequency,
                save_updater=self.save_updater,
                publish_at_fit_end=self.publish_at_fit_end,
                gate=gate, normalizer_provider=normalizer_provider)
            ls.append(self.publish_listener)
        if self.checkpoint_dir is not None:
            from deeplearning4j_tpu.fault.listener import (
                CheckpointListener)
            self.checkpoint_listener = CheckpointListener(
                self.checkpoint_dir,
                frequency=self.checkpoint_frequency,
                iterator=self.iterator, normalizer=self.normalizer,
                save_at_fit_end=self.checkpoint_at_fit_end)
            ls.append(self.checkpoint_listener)
        if self.drift_gate is not None:
            ls.append(self.drift_gate)
        if max_steps is not None:
            completed = int(self.net.iteration_count)
            ls.append(_StopAfterListener(self.iterator,
                                         completed + int(max_steps)))
        ls.extend(self.extra_listeners)
        return ls

    # --------------------------------------------------------------- run
    def run(self, max_steps: Optional[int] = None) -> dict:
        """Train until the stream ends. `max_steps` bounds the number
        of FURTHER iterations (on top of any already-restored
        counters); None streams until quiescence/stop()."""
        run_listeners = self._build_listeners(max_steps)
        added = []
        for l in run_listeners:
            self.net.add_listener(l)
            added.append(l)
        start_it = int(self.net.iteration_count)
        try:
            self.net.fit(self.iterator, epochs=1,
                         steps_per_execution=self.steps_per_execution)
        finally:
            for l in added:
                try:
                    self.net.listeners.remove(l)
                except ValueError:
                    pass
        return self.summary(start_iteration=start_it)

    def stop(self):
        """Ask the stream to end at the next batch boundary; `run()`
        returns after the fit loop drains (final checkpoint + final
        publish included)."""
        stop = getattr(self.iterator, "stop", None)
        if stop is not None:
            stop()

    def summary(self, *, start_iteration: int = 0) -> dict:
        out = {
            "iterations": int(self.net.iteration_count) - start_iteration,
            "iteration_count": int(self.net.iteration_count),
            "score": float(getattr(self.net, "score_value", float("nan"))),
        }
        if self.publish_listener is not None:
            out["published_versions"] = list(
                self.publish_listener.published_versions)
            out["published_steps"] = list(
                self.publish_listener.published_steps)
            out["publishes_gated"] = self.publish_listener.gated_skips
        if self.drift_gate is not None:
            out["drift_trips"] = self.drift_gate.trips
            out["publish_paused"] = self.drift_gate.paused
            out["heldout_best"] = self.drift_gate.best_score
            out["heldout_last"] = self.drift_gate.last_score
        cur = getattr(self.iterator, "cursor", lambda: None)()
        if cur is not None:
            out["cursor"] = cur
        return out

    # ------------------------------------------------------------ resume
    @classmethod
    def resume(cls, directory, iterator, *, net=None, **kw
               ) -> "OnlineTrainer":
        """Rebuild an OnlineTrainer from the newest valid checkpoint
        under `directory`: the model (rebuilt from the stored
        configuration unless `net` is passed), counters (which pin the
        per-step rng stream), the live normalizer WINDOW, and the
        stream position — `iterator` is seeked to the checkpoint
        cursor, so over a replayable transport the next batch read is
        the exact record sequence the interrupted run would have
        trained next. Keyword args are the OnlineTrainer constructor's
        (checkpoint_dir defaults to `directory` so the resumed run
        keeps checkpointing in place)."""
        from deeplearning4j_tpu.fault.resume import load_latest_valid
        from deeplearning4j_tpu.fault.state import (
            build_model,
            restore_normalizer,
            restore_training_state,
        )
        state, step = load_latest_valid(directory)
        model = net if net is not None else build_model(state["meta"])
        restore_training_state(model, state, iterator=iterator)
        normalizer = kw.pop("normalizer", None)
        restored_norm = restore_normalizer(state)
        if restored_norm is not None:
            normalizer = restored_norm
        # the monitor restore counters mirror fault.resume's
        from deeplearning4j_tpu import monitor
        if monitor.is_enabled():
            monitor.registry().counter(
                "restore_total",
                help="successful training-state restores").inc()
            monitor.registry().gauge(
                "restore_last_step",
                help="step of the last restored checkpoint").set(step)
        if (normalizer is not None
                and getattr(iterator, "normalizer", "absent") is None):
            iterator.normalizer = normalizer
        kw.setdefault("checkpoint_dir", directory)
        log.info("online trainer resumed at step %d from %s", step,
                 directory)
        return cls(model, iterator, normalizer=normalizer, **kw)
