"""StreamingDataSetIterator — the unbounded-iterator contract.

Every fit loop in this repo consumes a `DataSetIterator`; this adapter
turns a `streaming/` transport topic (LocalQueue/LocalLog in-tree,
Kafka gated) into one whose pass never terminates on an empty queue —
it *blocks* awaiting the producer, up to a watermark timeout — so
`MultiLayerNetwork.fit(stream, epochs=1)` becomes a long-lived
training service fed by an input pipeline (the parameter-server
framing of arXiv:1605.08695) rather than a batch job over a dataset.

Contracts:

- **Fixed-shape batches, ragged-tail hold-back.** Records are decoded
  (`record_to_example`), accumulated, and dispatched ONLY in full
  `batch_size` groups with identical shapes — every batch hits the
  already-compiled train-step program; a partial tail is held back
  until the firehose completes it (never emitted, never dropped:
  held-back records are not "consumed" and replay after a resume).
- **cursor() is the transport offset.** The fault-runtime position
  contract (`datasets/iterator.py`): ``{"batch": batches consumed,
  "offset": records consumed, "batch_size": B}``, counted BEFORE
  yield (a cursor taken while the consumer holds a batch includes it).
  `seek(cursor)` = replay-from-offset: over an offset-addressable
  transport (`LocalLogTransport.read`, Kafka seek) the iterator simply
  starts reading at ``batch * batch_size``; over a destructive queue
  it silently *skips* that many records, which reproduces the stream
  iff the producer replays from the epoch start (documented in
  docs/STREAMING_TRAINING.md).
- **Watermark semantics.** ``watermark_timeout_s`` bounds how long a
  pass waits for the next record before declaring the stream quiesced
  and ending (None = wait forever); the wait polls in ``poll_s``
  slices so `stop()` (graceful end at the next batch boundary) and
  `abandon()` (a consumer breaking out — the AsyncDataSetIterator
  early-abandon hook) take effect promptly instead of blocking a
  thread inside `Transport.receive`.
- **Telemetry.** `streaming_records_consumed_total`,
  `streaming_batches_total`, lazy `streaming_watermark_age_seconds`
  (seconds since the last record arrived — the staleness alarm), and
  lazy `streaming_lag_records` (producer offset − consumed offset,
  when the transport exposes `producer_offset`) on the monitor
  registry; docs/OBSERVABILITY.md "Streaming / online training".
"""

from __future__ import annotations

import queue as _queue
import threading
import time
from typing import Callable, Optional, Tuple

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterator import DataSetIterator
from deeplearning4j_tpu.streaming.ndarray import deserialize_ndarray


def _default_example(record: np.ndarray) -> Tuple[np.ndarray, Optional[np.ndarray]]:
    return record, None


def lm_example(record: np.ndarray, *, vocab_size: int):
    """Record convention for the language-model firehose: a ``[2, T]``
    int array — row 0 the input token ids, row 1 the target ids —
    decoded into the `(x float ids [T], y one-hot [T, V])` pair the
    TransformerLM fit contract consumes."""
    ids = np.asarray(record[0], np.int64)
    tgt = np.asarray(record[1], np.int64)
    x = ids.astype(np.float32)
    # scatter, not np.eye(V)[tgt]: the identity-matrix gather is
    # O(V^2) per record — quadratic in vocab on the ingest hot path
    y = np.zeros((tgt.shape[0], vocab_size), np.float32)
    y[np.arange(tgt.shape[0]), tgt] = 1.0
    return x, y


class StreamingDataSetIterator(DataSetIterator):
    """Unbounded `DataSetIterator` over a streaming transport topic.

    `normalizer`: an object with ``observe(features)`` and
    ``transform(features)`` (e.g. `online.WindowedStandardize`) — each
    dispatched batch first updates the sliding-window statistics, then
    is transformed with the *current* stats, so the stats a published
    snapshot carries are exactly the ones its training batches saw."""

    def __init__(self, transport, topic: str, *, batch_size: int,
                 record_to_example: Optional[Callable] = None,
                 normalizer=None,
                 watermark_timeout_s: Optional[float] = 10.0,
                 poll_s: float = 0.05,
                 deserialize: Callable = deserialize_ndarray):
        if batch_size < 1:
            raise ValueError(f"batch_size must be >= 1, got {batch_size}")
        self.transport = transport
        self.topic = topic
        self._batch = int(batch_size)
        self.record_to_example = record_to_example or _default_example
        self.normalizer = normalizer
        self.watermark_timeout_s = watermark_timeout_s
        self.poll_s = float(poll_s)
        self.deserialize = deserialize
        # offset-addressable fast path: the transport retains messages
        # and serves them by position (LocalLogTransport / Kafka seek)
        self._addressable = hasattr(transport, "read")
        self._next_offset = 0          # next record to READ
        self._consumed_records = 0     # records in batches handed out
        self._consumed_batches = 0
        self._skip_records = 0         # destructive-transport seek debt
        self._held: list = []          # ragged tail awaiting a full batch
        self._stopped = threading.Event()    # per-pass, stop()
        self._abandoned = threading.Event()  # per-pass, abandon()
        self._last_record_ts: Optional[float] = None
        self._metrics_cache = None
        self._lazy_gauge_registry = None   # which registry holds them

    # ------------------------------------------------------------ metrics
    def _metrics(self):
        from deeplearning4j_tpu import monitor
        m = monitor.resolve_cached_metrics(
            self, "_metrics_cache", lambda reg: {
                "records": reg.counter(
                    "streaming_records_consumed_total",
                    "records consumed into dispatched training batches",
                    topic=self.topic),
                "batches": reg.counter(
                    "streaming_batches_total",
                    "fixed-shape batches dispatched to the fit loop",
                    topic=self.topic),
            })
        # the lazy gauges re-bind when enable(registry=) swaps the
        # active registry (identity check, the cached-families pattern)
        if m is not None and self._lazy_gauge_registry \
                is not monitor.registry():
            reg = monitor.registry()
            reg.gauge(
                "streaming_watermark_age_seconds",
                help="seconds since the last record arrived from the "
                     "transport (staleness alarm)",
                topic=self.topic).set_function(self._watermark_age)
            reg.gauge(
                "streaming_lag_records",
                help="producer offset minus consumed offset (NaN when "
                     "the transport has no producer_offset)",
                topic=self.topic).set_function(self._lag)
            self._lazy_gauge_registry = reg
        return m

    def _watermark_age(self) -> float:
        ts = self._last_record_ts
        return float("nan") if ts is None else time.time() - ts

    def _lag(self) -> float:
        fn = getattr(self.transport, "producer_offset", None)
        if fn is None:
            return float("nan")
        try:
            head = int(fn(self.topic))
        except Exception:  # noqa: BLE001 — a broker hiccup must not kill exposition
            return float("nan")
        return float(head - self._consumed_records - len(self._held))

    # ----------------------------------------------------------- control
    def stop(self):
        """End the CURRENT pass gracefully at the next batch boundary
        (records already held back stay held and replay on a later
        pass/resume). Like `abandon()`, the flag is per-pass: a later
        `__iter__` starts a fresh pass — which is what lets one
        OnlineTrainer `run(max_steps=N)` several times over the same
        iterator."""
        self._stopped.set()

    def abandon(self):
        """Abort the CURRENT pass promptly (within one poll slice) —
        the early-abandon hook `AsyncDataSetIterator`'s consumer
        teardown calls so its prefetch thread never stays blocked in a
        watermark wait after the consumer broke out. Re-iterating
        afterwards starts a fresh pass."""
        self._abandoned.set()

    # ------------------------------------------------------------ reading
    def _read_record(self) -> Optional[np.ndarray]:
        """Next raw record, or None when the stream ended (stop /
        abandon / watermark timeout). Blocks in poll_s slices."""
        waited = 0.0
        while True:
            if self._stopped.is_set() or self._abandoned.is_set():
                return None
            try:
                if self._addressable:
                    payload = self.transport.read(
                        self.topic, self._next_offset, timeout=self.poll_s)
                else:
                    payload = self.transport.receive(
                        self.topic, timeout=self.poll_s)
            except (TimeoutError, _queue.Empty):
                waited += self.poll_s
                if (self.watermark_timeout_s is not None
                        and waited >= self.watermark_timeout_s):
                    return None          # stream quiesced
                continue
            self._next_offset += 1
            self._last_record_ts = time.time()
            if self._skip_records > 0:
                # destructive-transport seek: these records were
                # consumed by the interrupted run — drop silently
                self._skip_records -= 1
                continue
            return self.deserialize(payload)

    def _build_batch(self) -> DataSet:
        feats = np.stack([f for f, _ in self._held])
        labels = None
        if self._held[0][1] is not None:
            labels = np.stack([l for _, l in self._held])
        self._held.clear()
        if self.normalizer is not None:
            # window first, transform second: the batch trains under
            # statistics that INCLUDE it (and a snapshot taken after
            # this step carries exactly what training saw)
            self.normalizer.observe(feats)
            feats = self.normalizer.transform(feats)
        return DataSet(feats, labels)

    def __iter__(self):
        self._abandoned.clear()
        self._stopped.clear()
        while True:
            if self._stopped.is_set():
                return
            record = self._read_record()
            if record is None:
                return
            example = self.record_to_example(record)
            if not isinstance(example, tuple):
                example = (example, None)
            if self._held and (
                    np.shape(example[0]) != np.shape(self._held[0][0])
                    or (example[1] is None)
                    != (self._held[0][1] is None)
                    or (example[1] is not None and np.shape(example[1])
                        != np.shape(self._held[0][1]))):
                # a shape change mid-stream can never share a batch
                # with the held tail — fail loudly, a silently dropped
                # tail would break the replay contract
                raise ValueError(
                    f"record shapes (features {np.shape(example[0])}, "
                    f"labels {None if example[1] is None else np.shape(example[1])}) "
                    f"do not match the held batch tail; the "
                    f"unbounded-iterator contract dispatches "
                    f"fixed-shape batches only")
            self._held.append(example)
            if len(self._held) < self._batch:
                continue
            ds = self._build_batch()
            # count BEFORE yielding (fault-runtime cursor contract:
            # code after a yield runs only at the NEXT pull)
            self._consumed_records += self._batch
            self._consumed_batches += 1
            m = self._metrics()
            if m is not None:
                m["records"].inc(self._batch)
                m["batches"].inc()
            yield ds

    # ---------------------------------------------------------- contract
    def cursor(self) -> dict:
        """Position = transport offset. ``batch`` is authoritative
        (the `AsyncDataSetIterator` wrapper rewrites it to its own
        counts-CONSUMED value); ``offset`` is derived from it at
        seek()."""
        return {"kind": "stream", "topic": self.topic,
                "batch": int(self._consumed_batches),
                "batch_size": int(self._batch),
                "offset": int(self._consumed_records)}

    def seek(self, cursor: dict):
        """Replay-from-offset: position the next read at the first
        record after the last CONSUMED batch. Held-back tail records
        and prefetched-but-unconsumed batches replay by construction —
        they never reached the training loop."""
        bs = int(cursor.get("batch_size", self._batch))
        if bs != self._batch:
            raise ValueError(
                f"checkpoint cursor was taken at batch_size {bs}, this "
                f"iterator batches {self._batch} — replay offsets would "
                f"not line up")
        batches = int(cursor["batch"])
        offset = batches * self._batch
        self._consumed_batches = batches
        self._consumed_records = offset
        self._held.clear()
        if self._addressable:
            self._next_offset = offset
            self._skip_records = 0
        else:
            # destructive transport: the log cannot be re-read — skip
            # the consumed prefix of whatever the producer replays
            self._next_offset = 0
            self._skip_records = offset

    def batch_size(self):
        return self._batch
