"""Online-learning runtime: unbounded-stream training that publishes
into the serving fleet.

- `online.iterator`   — `StreamingDataSetIterator`: the unbounded
  `DataSetIterator` contract over the `streaming/` transports
  (cursor = transport offset, watermark-bounded blocking reads,
  fixed-shape batches with ragged-tail hold-back)
- `online.normalizer` — `WindowedStandardize`: sliding-window
  standardize statistics with versioned `snapshot()`-per-publish
- `online.trainer`    — `OnlineTrainer` (continuous fit → registry
  publish → fault checkpoint, resume-from-offset bit-parity) and
  `DriftGate` (held-out regression band that pauses publishing,
  never training)

See docs/STREAMING_TRAINING.md for the iterator contract, the
watermark semantics, and the publish/drift-gate state machine; the
end-to-end train→publish→hot-swap harness is scripts/online_loop.py.
"""

from deeplearning4j_tpu.online.iterator import (
    StreamingDataSetIterator,
    lm_example,
)
from deeplearning4j_tpu.online.normalizer import (
    StandardizeSnapshot,
    WindowedStandardize,
)
from deeplearning4j_tpu.online.trainer import DriftGate, OnlineTrainer

__all__ = [
    "StreamingDataSetIterator", "lm_example",
    "WindowedStandardize", "StandardizeSnapshot",
    "OnlineTrainer", "DriftGate",
]
