"""Sentence / document iterators.

Reference: `text/sentenceiterator/*` (BasicLineIterator,
CollectionSentenceIterator, FileSentenceIterator, SentencePreProcessor)
and `text/documentiterator/*` (LabelledDocument, LabelAwareIterator,
LabelsSource) — the corpus-side protocol every embedding model
consumes.
"""

from __future__ import annotations

import os
from pathlib import Path
from typing import Callable, Iterable, List, Optional


class SentencePreProcessor:
    def pre_process(self, sentence: str) -> str:
        raise NotImplementedError


class SentenceIterator:
    """Reference `SentenceIterator.java`: nextSentence/hasNext/reset +
    optional preprocessor."""

    def __init__(self):
        self.preprocessor: Optional[SentencePreProcessor] = None

    def set_pre_processor(self, pre: SentencePreProcessor):
        self.preprocessor = pre
        return self

    def _apply(self, s: str) -> str:
        return self.preprocessor.pre_process(s) if self.preprocessor else s

    def __iter__(self):
        self.reset()
        while self.has_next():
            yield self.next_sentence()

    def has_next(self) -> bool:
        raise NotImplementedError

    def next_sentence(self) -> str:
        raise NotImplementedError

    def reset(self) -> None:
        raise NotImplementedError


class CollectionSentenceIterator(SentenceIterator):
    def __init__(self, sentences: Iterable[str]):
        super().__init__()
        self._sentences = list(sentences)
        self._idx = 0

    def has_next(self):
        return self._idx < len(self._sentences)

    def next_sentence(self):
        s = self._sentences[self._idx]
        self._idx += 1
        return self._apply(s)

    def reset(self):
        self._idx = 0


class BasicLineIterator(SentenceIterator):
    """One sentence per line from a file (reference
    `BasicLineIterator.java`)."""

    def __init__(self, path):
        super().__init__()
        self.path = Path(path)
        self._fh = None

    def reset(self):
        if self._fh:
            self._fh.close()
        self._fh = open(self.path, "r", encoding="utf-8")
        self._peek = None

    def has_next(self):
        if self._fh is None:
            self.reset()
        if self._peek is None:
            line = self._fh.readline()
            self._peek = line if line else False
        return self._peek is not False

    def next_sentence(self):
        if not self.has_next():
            raise StopIteration
        s = self._peek.rstrip("\n")
        self._peek = None
        return self._apply(s)


class FileSentenceIterator(SentenceIterator):
    """Every file under a directory, line by line (reference
    `FileSentenceIterator.java`)."""

    def __init__(self, root):
        super().__init__()
        self.root = Path(root)
        self.reset()

    def reset(self):
        self._files = sorted(p for p in self.root.rglob("*") if p.is_file())
        self._lines: List[str] = []
        self._fidx = 0

    def has_next(self):
        while not self._lines and self._fidx < len(self._files):
            self._lines = self._files[self._fidx].read_text(
                encoding="utf-8", errors="replace").splitlines()
            self._fidx += 1
        return bool(self._lines)

    def next_sentence(self):
        if not self.has_next():
            raise StopIteration
        return self._apply(self._lines.pop(0))


# ---------------------------------------------------------------- documents
class LabelledDocument:
    """Reference `documentiterator/LabelledDocument.java`."""

    def __init__(self, content: str, labels: Optional[List[str]] = None):
        self.content = content
        self.labels = labels or []


class LabelAwareIterator:
    """Reference `documentiterator/LabelAwareIterator.java`."""

    def __iter__(self):
        self.reset()
        while self.has_next():
            yield self.next_document()

    def has_next(self) -> bool:
        raise NotImplementedError

    def next_document(self) -> LabelledDocument:
        raise NotImplementedError

    def reset(self) -> None:
        raise NotImplementedError


class SimpleLabelAwareIterator(LabelAwareIterator):
    def __init__(self, documents: Iterable[LabelledDocument]):
        self._docs = list(documents)
        self._idx = 0

    def has_next(self):
        return self._idx < len(self._docs)

    def next_document(self):
        d = self._docs[self._idx]
        self._idx += 1
        return d

    def reset(self):
        self._idx = 0
