"""ParagraphVectors (doc2vec): DM and DBOW.

Reference: `models/paragraphvectors/ParagraphVectors.java` (1,461 LoC)
with sequence learning algorithms `DM.java` / `DBOW.java` and
`inferVector` for unseen documents.

TPU realisation reuses the SequenceVectors engine with the embedding
table EXTENDED by one row per document label (label rows live at
indices >= vocab size). DBOW pairs the label row with every word of the
document (label predicts words, reference DBOW semantics); DM adds the
label row into the CBOW context mean. `infer_vector` freezes all
word/label rows (`trainable_from`) and gradient-trains only the new
document's row — the same frozen-tables inference the reference does.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Union

import numpy as np

from deeplearning4j_tpu.nlp.sentenceiterator import (
    LabelAwareIterator,
    LabelledDocument,
    SimpleLabelAwareIterator,
)
from deeplearning4j_tpu.nlp.sequencevectors import (
    SequenceVectors,
    SequenceVectorsConfig,
)
from deeplearning4j_tpu.nlp.tokenization import DefaultTokenizerFactory


class ParagraphVectors(SequenceVectors):
    def __init__(self,
                 documents: Union[LabelAwareIterator, Iterable[LabelledDocument], None] = None,
                 tokenizer_factory=None,
                 layer_size: int = 100,
                 window_size: int = 5,
                 min_word_frequency: int = 1,
                 negative_sample: int = 5,
                 learning_rate: float = 0.025,
                 min_learning_rate: float = 1e-4,
                 epochs: int = 1,
                 batch_size: int = 2048,
                 seed: int = 42,
                 dm: bool = False):
        super().__init__(SequenceVectorsConfig(
            vector_length=layer_size, window=window_size,
            min_word_frequency=min_word_frequency, negative=negative_sample,
            learning_rate=learning_rate, min_learning_rate=min_learning_rate,
            epochs=epochs, batch_size=batch_size, seed=seed, cbow=dm))
        if documents is not None and not isinstance(documents, LabelAwareIterator):
            documents = SimpleLabelAwareIterator(documents)
        self.documents = documents
        self.tokenizer_factory = tokenizer_factory or DefaultTokenizerFactory()
        self.dm = dm
        self.labels: List[str] = []
        self._label_index: Dict[str, int] = {}
        self._doc_tokens: List[List[str]] = []

    # ---------------------------------------------------------------- corpus
    def _prepare(self):
        if self._doc_tokens:
            return
        self._doc_label_idx: List[int] = []  # sequence → label row (labels may repeat)
        for doc in self.documents:
            toks = self.tokenizer_factory.create(doc.content).get_tokens()
            label = doc.labels[0] if doc.labels else f"DOC_{len(self.labels)}"
            if label not in self._label_index:
                self._label_index[label] = len(self.labels)
                self.labels.append(label)
            self._doc_label_idx.append(self._label_index[label])
            self._doc_tokens.append(toks)

    def _label_row(self, label_idx: int) -> int:
        return self.vocab.num_words() + label_idx

    # ------------------------------------------------------------------ fit
    def fit(self, *a, **kw):
        self._prepare()
        self.build_vocab(self._doc_tokens)

        def pair_hook(sv, seq_idx, tokens):
            row = self._label_row(self._doc_label_idx[seq_idx])
            if self.dm:
                # DM: label row joins every CBOW context window
                pairs = sv._sequence_to_pairs(tokens)
                return [(center, center, ctx + [row]) for center, _, ctx in pairs]
            # DBOW: label row predicts each word (reference DBOW.java)
            idxs = [self.vocab.index_of(t) for t in tokens]
            return [(row, i, []) for i in idxs if i >= 0]

        return super().fit(self._doc_tokens, extra_rows=len(self.labels),
                           pair_hook=pair_hook)

    # ------------------------------------------------------------- queries
    def get_doc_vector(self, label: str):
        i = self._label_index.get(label)
        return None if i is None else np.asarray(self.syn0[self._label_row(i)])

    def similarity_doc(self, l1: str, l2: str) -> float:
        v1, v2 = self.get_doc_vector(l1), self.get_doc_vector(l2)
        if v1 is None or v2 is None:
            return float("nan")
        denom = np.linalg.norm(v1) * np.linalg.norm(v2)
        return float(np.dot(v1, v2) / denom) if denom > 0 else 0.0

    def infer_vector(self, text: str, steps: int = 10,
                     learning_rate: float = 0.01):
        """Train ONE new row against frozen tables (reference
        `inferVector`)."""
        tokens = self.tokenizer_factory.create(text).get_tokens()
        V = self.vocab.num_words()
        new_row = self.syn0.shape[0]
        D = self.conf.vector_length
        init = ((self._rng.random((1, D)) - 0.5) / D).astype(np.float32)
        self.syn0 = np.concatenate([np.asarray(self.syn0), init], axis=0)

        def pair_hook(sv, seq_idx, toks):
            idxs = [self.vocab.index_of(t) for t in toks]
            return [(new_row, i, []) for i in idxs if i >= 0]

        saved_conf = self.conf
        import dataclasses as _dc
        self.conf = _dc.replace(saved_conf, epochs=steps,
                                learning_rate=learning_rate, cbow=False)
        try:
            super().fit([tokens], pair_hook=pair_hook, trainable_from=new_row)
        finally:
            self.conf = saved_conf
        vec = np.asarray(self.syn0[new_row]).copy()
        self.syn0 = np.asarray(self.syn0[:new_row])  # pop the scratch row
        return vec
