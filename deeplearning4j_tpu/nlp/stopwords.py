"""Stop-word handling.

Reference: `deeplearning4j-nlp/.../text/stopwords/StopWords.java` (loads
a bundled `stopwords` resource list) and its use as a token filter in
the text pipelines. Here the default English list ships inline, the
class supports custom lists/files, and `StopWordsRemover` plugs into
the tokenizer-factory pre-processor seam (`TokenPreProcess`) so any
tokenizer drops stop words in-stream.
"""

from __future__ import annotations

from typing import Iterable, List, Optional

from deeplearning4j_tpu.nlp.tokenization import TokenPreProcess

# The classic English stop-word list (the reference bundles an
# equivalent resource file).
_DEFAULT_STOPWORDS = """
a about above after again against all am an and any are aren't as at be
because been before being below between both but by can't cannot could
couldn't did didn't do does doesn't doing don't down during each few for
from further had hadn't has hasn't have haven't having he he'd he'll
he's her here here's hers herself him himself his how how's i i'd i'll
i'm i've if in into is isn't it it's its itself let's me more most
mustn't my myself no nor not of off on once only or other ought our
ours ourselves out over own same shan't she she'd she'll she's should
shouldn't so some such than that that's the their theirs them themselves
then there there's these they they'd they'll they're they've this those
through to too under until up very was wasn't we we'd we'll we're we've
were weren't what what's when when's where where's which while who who's
whom why why's with won't would wouldn't you you'd you'll you're you've
your yours yourself yourselves
""".split()


class StopWords:
    """Holds a stop-word set (reference `StopWords.getStopWords()`)."""

    _default: Optional["StopWords"] = None

    def __init__(self, words: Optional[Iterable[str]] = None,
                 case_sensitive: bool = False):
        self.case_sensitive = case_sensitive
        src = _DEFAULT_STOPWORDS if words is None else words
        self.words = set(w if case_sensitive else w.lower() for w in src)

    @classmethod
    def get_stop_words(cls) -> List[str]:
        return sorted(cls.default().words)

    @classmethod
    def default(cls) -> "StopWords":
        if cls._default is None:
            cls._default = cls()
        return cls._default

    @classmethod
    def from_file(cls, path: str, **kw) -> "StopWords":
        with open(path) as f:
            return cls([line.strip() for line in f if line.strip()], **kw)

    def is_stop_word(self, token: str) -> bool:
        t = token if self.case_sensitive else token.lower()
        return t in self.words

    def filter(self, tokens: Iterable[str]) -> List[str]:
        return [t for t in tokens if not self.is_stop_word(t)]

    def __contains__(self, token: str) -> bool:
        return self.is_stop_word(token)

    def __len__(self):
        return len(self.words)


class StopWordsRemover(TokenPreProcess):
    """TokenPreProcess that maps stop words to "" (tokenizers drop empty
    tokens) — the filter seam the reference wires through
    `TokenizerFactory.setTokenPreProcessor`."""

    def __init__(self, stop_words: Optional[StopWords] = None,
                 inner: Optional[TokenPreProcess] = None):
        self.stop_words = stop_words or StopWords.default()
        self.inner = inner

    def pre_process(self, token: str) -> str:
        if self.inner is not None:
            token = self.inner.pre_process(token)
        return "" if self.stop_words.is_stop_word(token) else token
