"""Word2Vec.

Reference: `models/word2vec/Word2Vec.java:82` (Builder) — a thin,
configured front-end over SequenceVectors with a tokenizer + sentence
iterator pipeline. Same here: `fit()` tokenises the corpus once into
token sequences and drives the TPU-batched SequenceVectors engine.
"""

from __future__ import annotations

from typing import Iterable, List, Optional, Union

from deeplearning4j_tpu.nlp.sentenceiterator import (
    CollectionSentenceIterator,
    SentenceIterator,
)
from deeplearning4j_tpu.nlp.sequencevectors import (
    SequenceVectors,
    SequenceVectorsConfig,
)
from deeplearning4j_tpu.nlp.tokenization import (
    DefaultTokenizerFactory,
    TokenizerFactory,
)


class Word2Vec(SequenceVectors):
    """skip-gram / CBOW word embeddings (reference Word2Vec builder
    options map 1:1 onto the constructor kwargs: layerSize→
    vector_length, windowSize→window, minWordFrequency, negativeSample→
    negative, useHierarchicSoftmax, sampling→subsampling, workers→
    (absorbed by device batching), batchSize→batch_size)."""

    def __init__(self,
                 sentence_iterator: Union[SentenceIterator, Iterable[str], None] = None,
                 tokenizer_factory: Optional[TokenizerFactory] = None,
                 layer_size: int = 100,
                 window_size: int = 5,
                 min_word_frequency: int = 1,
                 negative_sample: int = 5,
                 use_hierarchic_softmax: bool = False,
                 sampling: float = 0.0,
                 learning_rate: float = 0.025,
                 min_learning_rate: float = 1e-4,
                 epochs: int = 1,
                 iterations: int = 1,
                 batch_size: int = 2048,
                 seed: int = 42,
                 cbow: bool = False):
        super().__init__(SequenceVectorsConfig(
            vector_length=layer_size, window=window_size,
            min_word_frequency=min_word_frequency, negative=negative_sample,
            use_hierarchic_softmax=use_hierarchic_softmax,
            subsampling=sampling, learning_rate=learning_rate,
            min_learning_rate=min_learning_rate, epochs=epochs,
            iterations=iterations, batch_size=batch_size, seed=seed, cbow=cbow))
        if sentence_iterator is not None and not isinstance(sentence_iterator,
                                                            SentenceIterator):
            sentence_iterator = CollectionSentenceIterator(sentence_iterator)
        self.sentence_iterator = sentence_iterator
        self.tokenizer_factory = tokenizer_factory or DefaultTokenizerFactory()
        self._sequences: Optional[List[List[str]]] = None

    def _tokenize_corpus(self) -> List[List[str]]:
        if self._sequences is None:
            if self.sentence_iterator is None:
                raise ValueError("Word2Vec needs a sentence iterator / corpus")
            self._sequences = [
                self.tokenizer_factory.create(s).get_tokens()
                for s in self.sentence_iterator
            ]
        return self._sequences

    def fit(self, sequences=None, **kw):
        if sequences is None:
            sequences = self._tokenize_corpus()
        return super().fit(sequences, **kw)


def load_packaged_word2vec():
    """Load the packaged doc-trained skip-gram vectors
    (`zoo/weights/word2vec_docs.bin`, Google binary format) through the
    full verification path: manifest lookup → sha256 check →
    `WordVectorSerializer.read_binary`. The pretrained-word-vectors
    story the reference served with hosted GoogleNews-style .bin files
    (`WordVectorSerializer.java` readers), shipped as a package asset
    so it works offline. Raises if the artifact is missing or fails
    its checksum (never silently loads an unverifiable file — same
    contract as `zoo.base.packaged_weight`)."""
    import hashlib
    from pathlib import Path

    from deeplearning4j_tpu.nlp.serializer import WordVectorSerializer
    from deeplearning4j_tpu.zoo import base as zoo_base

    name = "word2vec_docs.bin"
    # packaged_weight owns the manifest policy (missing entry or missing
    # sha256 → not packaged); the path is the weights dir it resolves
    uri, expected = zoo_base.packaged_weight(name)
    if uri is None:
        raise FileNotFoundError(
            f"{name} is not a packaged artifact (no manifest entry); "
            "regenerate with tests/make_word2vec_pretrained.py")
    path = Path(zoo_base.__file__).parent / "weights" / name
    sha = hashlib.sha256(path.read_bytes()).hexdigest()
    if sha != expected:
        raise ValueError(
            f"{name} checksum mismatch (got {sha[:12]}…, manifest "
            f"{expected[:12]}…) — refusing to load")
    return WordVectorSerializer.read_binary(path)
