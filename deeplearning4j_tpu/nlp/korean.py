"""Korean tokenization through the TokenizerFactory seam.

Reference role: `deeplearning4j-nlp-korean` (`KoreanTokenizer.java:34`,
83 LoC) wraps the twitter-korean-text processor, whose load-bearing
behavior for embedding pipelines is MORPHEME separation: Korean spaces
delimit eojeol (word + attached particles/endings), so a whitespace
tokenizer conflates 고양이가/고양이는/고양이를 into distinct "words".
This module reproduces that capability at seed scale: whitespace
pre-split, then longest-suffix separation of josa (case particles) and
common eomi (verb/adjective endings) from the stem, with hangul-final
(batchim) agreement checks for the particle alternations (이/가, 은/는,
을/를, 과/와, 으로/로).

Like the reference (twitter-korean-text emits particles as their own
tokens), stems and particles both surface as tokens; `pos_keep`
filters to content morphemes for embedding corpora (same knob as
`nlp/japanese.py`).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from deeplearning4j_tpu.nlp.tokenization import (
    TokenPreProcess,
    Tokenizer,
    TokenizerFactory,
)

# (suffix, needs_batchim) — None: either; True: only after a final
# consonant; False: only after a vowel. Longest match wins.
_JOSA = [
    ("에게서", None), ("으로부터", True), ("로부터", False), ("에서", None),
    ("에게", None), ("부터", None), ("까지", None), ("처럼", None),
    ("보다", None), ("하고", None), ("이나", True), ("마다", None),
    ("으로", True), ("로", False), ("와", False), ("과", True),
    ("은", True), ("는", False), ("이", True), ("가", False),
    ("을", True), ("를", False), ("의", None), ("에", None),
    ("도", None), ("만", None), ("나", False), ("요", None),
]

_EOMI = [
    ("했습니다", None), ("했다", None), ("해요", None),   # 하다 light verb
    ("습니다", True), ("ㅂ니다", False), ("었습니다", None), ("았습니다", None),
    ("어요", None), ("아요", None), ("예요", False), ("이에요", True),
    ("었다", None), ("았다", None), ("는다", None), ("ㄴ다", None),
    ("지만", None), ("면서", None), ("려고", None), ("어서", None),
    ("아서", None), ("고", None), ("면", None), ("다", None),
]


def _is_hangul(ch: str) -> bool:
    return 0xAC00 <= ord(ch) <= 0xD7A3


def _has_batchim(ch: str) -> bool:
    """Does the syllable end in a final consonant? (jongseong != 0 in
    the Unicode hangul-syllable decomposition)."""
    return _is_hangul(ch) and (ord(ch) - 0xAC00) % 28 != 0


def _split_suffix(word: str, table, min_stem: int = 1):
    """Longest matching suffix whose batchim constraint agrees with the
    stem's last syllable; None if nothing splits."""
    for suffix, needs in sorted(table, key=lambda e: -len(e[0])):
        if not word.endswith(suffix):
            continue
        stem = word[: len(word) - len(suffix)]
        if len(stem) < min_stem or not all(_is_hangul(c) for c in stem):
            continue
        if needs is not None and _has_batchim(stem[-1]) != needs:
            continue
        return stem, suffix
    return None


class KoreanSegmenter:
    """Eojeol → morphemes: (surface, pos) with pos in
    {noun-ish "stem", "josa", "eomi", "other"}."""

    def tokenize_with_pos(self, text: str) -> List[Tuple[str, str]]:
        out: List[Tuple[str, str]] = []
        for word in text.split():
            word = word.strip(".,!?;:()[]{}\"'…「」")
            if not word:
                continue
            if not all(_is_hangul(c) for c in word):
                out.append((word, "other"))
                continue
            hit = _split_suffix(word, _JOSA)
            if hit:
                out.append((hit[0], "stem"))
                out.append((hit[1], "josa"))
                continue
            hit = _split_suffix(word, _EOMI, min_stem=1)
            if hit:
                out.append((hit[0], "stem"))
                out.append((hit[1], "eomi"))
                continue
            out.append((word, "stem"))
        return out

    def segment(self, text: str) -> List[str]:
        return [s for s, _ in self.tokenize_with_pos(text)]


#: content morphemes for embedding corpora (drop particles/endings)
CONTENT_POS = frozenset({"stem", "other"})


class KoreanTokenizer(Tokenizer):
    def __init__(self, sentence: str, segmenter: KoreanSegmenter,
                 preprocessor: Optional[TokenPreProcess] = None,
                 pos_keep: Optional[frozenset] = None):
        toks = (segmenter.segment(sentence) if pos_keep is None else
                [s for s, pos in segmenter.tokenize_with_pos(sentence)
                 if pos in pos_keep])
        super().__init__(toks, preprocessor)


class KoreanTokenizerFactory(TokenizerFactory):
    """Reference `KoreanTokenizerFactory.java` seam."""

    def __init__(self, segmenter: Optional[KoreanSegmenter] = None,
                 preprocessor: Optional[TokenPreProcess] = None,
                 pos_keep: Optional[frozenset] = None):
        self.segmenter = segmenter or KoreanSegmenter()
        self.preprocessor = preprocessor
        self.pos_keep = pos_keep

    def create(self, sentence: str) -> Tokenizer:
        return KoreanTokenizer(sentence, self.segmenter,
                               self.preprocessor, self.pos_keep)

    def set_token_pre_processor(self, pre: TokenPreProcess):
        self.preprocessor = pre
        return self
