"""Bag-of-words vectorizers: counts and TF-IDF.

Reference: `bagofwords/vectorizer/BagOfWordsVectorizer.java` and
`TfidfVectorizer.java` — fit a vocabulary over a corpus, then transform
documents to dense vocab-sized vectors.
"""

from __future__ import annotations

import math
from typing import Iterable, List, Optional

import numpy as np

from deeplearning4j_tpu.nlp.tokenization import DefaultTokenizerFactory
from deeplearning4j_tpu.nlp.vocab import VocabCache, VocabConstructor


class CountVectorizer:
    """Term-count vectors (reference BagOfWordsVectorizer)."""

    def __init__(self, tokenizer_factory=None, min_word_frequency: int = 1):
        self.tokenizer_factory = tokenizer_factory or DefaultTokenizerFactory()
        self.min_word_frequency = min_word_frequency
        self.vocab: Optional[VocabCache] = None

    def _tokens(self, text: str) -> List[str]:
        return self.tokenizer_factory.create(text).get_tokens()

    def _fit_tokens(self, seqs: List[List[str]]):
        self.vocab = VocabConstructor(
            min_word_frequency=self.min_word_frequency,
            build_huffman_tree=False).build(seqs)

    def fit(self, corpus: Iterable[str]):
        self._fit_tokens([self._tokens(t) for t in corpus])
        return self

    def _vector_from_tokens(self, tokens: List[str]) -> np.ndarray:
        vec = np.zeros((self.vocab.num_words(),), np.float32)
        for tok in tokens:
            i = self.vocab.index_of(tok)
            if i >= 0:
                vec[i] += 1.0
        return vec

    def transform(self, text: str) -> np.ndarray:
        return self._vector_from_tokens(self._tokens(text))

    def fit_transform(self, corpus: Iterable[str]) -> np.ndarray:
        seqs = [self._tokens(t) for t in corpus]  # tokenize ONCE
        self._fit_tokens(seqs)
        return np.stack([self._vector_from_tokens(s) for s in seqs])


class TfidfVectorizer(CountVectorizer):
    """TF-IDF weighting (reference TfidfVectorizer: idf = log(N/df))."""

    def _fit_tokens(self, seqs: List[List[str]]):
        super()._fit_tokens(seqs)
        V = self.vocab.num_words()
        df = np.zeros((V,), np.float64)
        for tokens in seqs:
            seen = {self.vocab.index_of(t) for t in tokens}
            for i in seen:
                if i >= 0:
                    df[i] += 1
        n_docs = max(len(seqs), 1)
        self.idf = np.log(n_docs / np.clip(df, 1.0, None)).astype(np.float32)

    def _vector_from_tokens(self, tokens: List[str]) -> np.ndarray:
        counts = super()._vector_from_tokens(tokens)
        total = counts.sum()
        tf = counts / total if total > 0 else counts
        return tf * self.idf
