"""Bag-of-words vectorizers: counts and TF-IDF.

Reference: `bagofwords/vectorizer/BagOfWordsVectorizer.java` and
`TfidfVectorizer.java` — fit a vocabulary over a corpus, then transform
documents to dense vocab-sized vectors.
"""

from __future__ import annotations

import math
from typing import Iterable, List, Optional

import numpy as np

from deeplearning4j_tpu.nlp.tokenization import DefaultTokenizerFactory
from deeplearning4j_tpu.nlp.vocab import VocabCache, VocabConstructor


class CountVectorizer:
    """Term-count vectors (reference BagOfWordsVectorizer)."""

    def __init__(self, tokenizer_factory=None, min_word_frequency: int = 1):
        self.tokenizer_factory = tokenizer_factory or DefaultTokenizerFactory()
        self.min_word_frequency = min_word_frequency
        self.vocab: Optional[VocabCache] = None

    def _tokens(self, text: str) -> List[str]:
        return self.tokenizer_factory.create(text).get_tokens()

    def fit(self, corpus: Iterable[str]):
        seqs = [self._tokens(t) for t in corpus]
        self.vocab = VocabConstructor(
            min_word_frequency=self.min_word_frequency,
            build_huffman_tree=False).build(seqs)
        return self

    def transform(self, text: str) -> np.ndarray:
        vec = np.zeros((self.vocab.num_words(),), np.float32)
        for tok in self._tokens(text):
            i = self.vocab.index_of(tok)
            if i >= 0:
                vec[i] += 1.0
        return vec

    def fit_transform(self, corpus: Iterable[str]) -> np.ndarray:
        corpus = list(corpus)
        self.fit(corpus)
        return np.stack([self.transform(t) for t in corpus])


class TfidfVectorizer(CountVectorizer):
    """TF-IDF weighting (reference TfidfVectorizer: idf = log(N/df))."""

    def fit(self, corpus: Iterable[str]):
        corpus = list(corpus)
        super().fit(corpus)
        V = self.vocab.num_words()
        df = np.zeros((V,), np.float64)
        for text in corpus:
            seen = {self.vocab.index_of(t) for t in self._tokens(text)}
            for i in seen:
                if i >= 0:
                    df[i] += 1
        n_docs = max(len(corpus), 1)
        self.idf = np.log(n_docs / np.clip(df, 1.0, None)).astype(np.float32)
        return self

    def transform(self, text: str) -> np.ndarray:
        counts = super().transform(text)
        total = counts.sum()
        tf = counts / total if total > 0 else counts
        return tf * self.idf
