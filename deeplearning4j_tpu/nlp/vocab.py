"""Vocabulary: VocabWord, VocabCache, VocabConstructor, Huffman coding.

Reference: `models/word2vec/VocabWord.java` (a SequenceElement with
frequency + Huffman codes/points), `wordstore/inmemory/AbstractCache`
(word↔index maps, frequency), `models/word2vec/wordstore/
VocabConstructor.java` (corpus scan, min-frequency pruning) and
`graph/huffman/` (code assignment for hierarchical softmax).
"""

from __future__ import annotations

import heapq
from typing import Dict, Iterable, List, Optional


class VocabWord:
    """One vocabulary element (reference `VocabWord.java`)."""

    __slots__ = ("word", "frequency", "index", "codes", "points")

    def __init__(self, word: str, frequency: float = 1.0):
        self.word = word
        self.frequency = frequency
        self.index = -1
        self.codes: List[int] = []    # Huffman bits, root→leaf
        self.points: List[int] = []   # inner-node indices, root→leaf

    def increment(self, by: float = 1.0):
        self.frequency += by

    def __repr__(self):
        return f"VocabWord({self.word!r}, f={self.frequency})"


class VocabCache:
    """word↔index↔VocabWord store (reference `AbstractCache.java`)."""

    def __init__(self):
        self._words: Dict[str, VocabWord] = {}
        self._by_index: List[VocabWord] = []
        self.total_word_count = 0.0

    def add_token(self, vw: VocabWord):
        if vw.word in self._words:
            self._words[vw.word].increment(vw.frequency)
        else:
            self._words[vw.word] = vw

    def contains_word(self, word: str) -> bool:
        return word in self._words

    def word_frequency(self, word: str) -> float:
        vw = self._words.get(word)
        return vw.frequency if vw else 0.0

    def word_for(self, word: str) -> Optional[VocabWord]:
        return self._words.get(word)

    def index_of(self, word: str) -> int:
        vw = self._words.get(word)
        return vw.index if vw else -1

    def word_at_index(self, idx: int) -> str:
        return self._by_index[idx].word

    def element_at_index(self, idx: int) -> VocabWord:
        return self._by_index[idx]

    def num_words(self) -> int:
        return len(self._by_index)

    def words(self) -> List[str]:
        return [vw.word for vw in self._by_index]

    def finalize_vocab(self):
        """Assign indices by descending frequency (reference sorts by
        frequency so negative-sampling tables are cache-friendly)."""
        self._by_index = sorted(self._words.values(),
                                key=lambda v: (-v.frequency, v.word))
        for i, vw in enumerate(self._by_index):
            vw.index = i
        self.total_word_count = sum(v.frequency for v in self._by_index)


def build_huffman(cache: VocabCache) -> int:
    """Assign Huffman codes/points to every word (reference
    `graph/huffman/GraphHuffman.java` / word2vec Huffman). Returns the
    number of inner nodes (= hierarchical-softmax table rows needed)."""
    n = cache.num_words()
    if n == 0:
        return 0
    heap = [(vw.frequency, i, ("leaf", i)) for i, vw in
            enumerate(cache._by_index)]
    heapq.heapify(heap)
    next_inner = 0
    children: Dict[int, tuple] = {}
    while len(heap) > 1:
        f1, _, n1 = heapq.heappop(heap)
        f2, _, n2 = heapq.heappop(heap)
        inner = next_inner
        next_inner += 1
        children[inner] = (n1, n2)
        heapq.heappush(heap, (f1 + f2, n + inner, ("inner", inner)))
    # walk the tree assigning codes
    _, _, root = heap[0]
    stack = [(root, [], [])]
    while stack:
        node, codes, points = stack.pop()
        kind, idx = node
        if kind == "leaf":
            vw = cache._by_index[idx]
            vw.codes = codes
            vw.points = points
        else:
            left, right = children[idx]
            stack.append((left, codes + [0], points + [idx]))
            stack.append((right, codes + [1], points + [idx]))
    return next_inner


class VocabConstructor:
    """Builds a VocabCache from token sequences (reference
    `VocabConstructor.java:buildJointVocabulary`)."""

    def __init__(self, min_word_frequency: int = 1, build_huffman_tree: bool = True):
        self.min_word_frequency = min_word_frequency
        self.build_huffman_tree = build_huffman_tree

    def build(self, sequences: Iterable[List[str]]) -> VocabCache:
        cache = VocabCache()
        for tokens in sequences:
            for tok in tokens:
                cache.add_token(VocabWord(tok))
        if self.min_word_frequency > 1:
            cache._words = {w: vw for w, vw in cache._words.items()
                            if vw.frequency >= self.min_word_frequency}
        cache.finalize_vocab()
        if self.build_huffman_tree:
            build_huffman(cache)
        return cache
