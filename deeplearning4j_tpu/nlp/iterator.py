"""CnnSentenceDataSetIterator — sentences → padded word-vector tensors
for CNN sentence classification.

Reference: `iterator/CnnSentenceDataSetIterator.java` (516 LoC): each
sentence becomes a [1, maxLength, vectorSize] image-like tensor of
stacked word vectors, zero-padded + masked to the batch max length.
Output here is NHWC [B, maxLen, D, 1] (TPU layout) with a [B, maxLen]
feature mask.
"""

from __future__ import annotations

from typing import Iterable, List, Sequence, Tuple

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.nlp.sequencevectors import SequenceVectors
from deeplearning4j_tpu.nlp.tokenization import DefaultTokenizerFactory


class CnnSentenceDataSetIterator:
    def __init__(self, sentences: Sequence[str], labels: Sequence[int],
                 word_vectors: SequenceVectors, num_classes: int,
                 batch_size: int = 32, max_length: int = 64,
                 tokenizer_factory=None):
        assert len(sentences) == len(labels)
        self.sentences = list(sentences)
        self.labels = list(labels)
        self.wv = word_vectors
        self.num_classes = num_classes
        self.batch_size = batch_size
        self.max_length = max_length
        self.tokenizer_factory = tokenizer_factory or DefaultTokenizerFactory()
        self._pos = 0

    def reset(self):
        self._pos = 0

    def has_next(self) -> bool:
        return self._pos < len(self.sentences)

    def __iter__(self):
        self.reset()
        while self.has_next():
            yield self.next()

    def next(self) -> DataSet:
        lo = self._pos
        hi = min(lo + self.batch_size, len(self.sentences))
        self._pos = hi
        D = self.wv.conf.vector_length
        batch_tokens = []
        for s in self.sentences[lo:hi]:
            toks = [t for t in self.tokenizer_factory.create(s).get_tokens()
                    if self.wv.has_word(t)][:self.max_length]
            batch_tokens.append(toks)
        L = max((len(t) for t in batch_tokens), default=1) or 1
        B = hi - lo
        feats = np.zeros((B, L, D, 1), np.float32)
        fmask = np.zeros((B, L), np.float32)
        labels = np.zeros((B, self.num_classes), np.float32)
        for bi, toks in enumerate(batch_tokens):
            for ti, tok in enumerate(toks):
                feats[bi, ti, :, 0] = self.wv.get_word_vector(tok)
            fmask[bi, :len(toks)] = 1.0
            labels[bi, self.labels[lo + bi]] = 1.0
        return DataSet(feats, labels, features_mask=fmask)
