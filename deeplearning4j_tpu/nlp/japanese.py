"""Japanese morphological segmentation through the TokenizerFactory
seam (Kuromoji role).

Reference role: `deeplearning4j-nlp-japanese` bundles the Kuromoji
tokenizer (~6.8k LoC under `com/atilika/kuromoji/`) behind a
TokenizerFactory so Japanese corpora — written without spaces — drive
Word2Vec/SequenceVectors unchanged. This module reproduces the
*capability* with the same algorithmic shape Kuromoji uses, at seed-
dictionary scale:

- a **morpheme lattice**: every dictionary entry (surface, POS, cost)
  matching at position i adds an edge i → i+len(surface);
- **unknown-word invocation by character class** (kanji / hiragana /
  katakana / latin / digit runs get class-specific candidate edges and
  costs — the kuromoji `unk.def` idea), so OOV text still segments;
- **joint Viterbi over (position, POS)** minimizing word cost +
  POS-bigram connection cost — the same min-sum recurrence as
  `util/viterbi.py` (`Viterbi.java` role), specialized to the
  variable-length-edge DAG a word lattice is.

`JapaneseTokenizerFactory` plugs the segmenter into the text pipeline;
`tokenize_with_pos` exposes the POS tags for downstream filtering
(kuromoji's Token.getPartOfSpeech surface).
"""

from __future__ import annotations

import math
import os
from typing import Dict, Iterable, List, Optional, Tuple

from deeplearning4j_tpu.nlp.tokenization import (
    TokenPreProcess,
    Tokenizer,
    TokenizerFactory,
)

_DATA = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "data", "ja_dict.tsv")

POS_TAGS = ("noun", "verb", "adj", "particle", "aux", "adverb",
            "prefix", "suffix", "unk", "punct")

# POS-bigram connection costs (the kuromoji matrix.def role, seed
# scale): favored transitions — particle after content word, content
# word after particle, aux after verb/adj — are cheap; doubled
# particles or aux after particle are penalized.
_CONN_DEFAULT = 1000.0
_CONN = {
    ("noun", "particle"): 0.0, ("verb", "particle"): 100.0,
    ("adj", "particle"): 200.0, ("particle", "noun"): 0.0,
    ("particle", "verb"): 100.0, ("particle", "adj"): 200.0,
    ("particle", "adverb"): 300.0, ("particle", "particle"): 1800.0,
    ("verb", "aux"): 0.0, ("adj", "aux"): 100.0, ("noun", "aux"): 400.0,
    ("aux", "particle"): 600.0, ("adverb", "verb"): 100.0,
    ("adverb", "adj"): 100.0, ("noun", "noun"): 900.0,
    ("noun", "suffix"): 0.0, ("prefix", "noun"): 0.0,
    ("BOS", "noun"): 0.0, ("BOS", "verb"): 400.0, ("BOS", "adverb"): 300.0,
    ("BOS", "adj"): 400.0, ("BOS", "prefix"): 300.0,
    ("BOS", "particle"): 1500.0,
}

_PUNCT = set("、。！？…・「」『』（）【】；：,.!?;:()[]{}\"' \t\n\r　")


def _char_class(ch: str) -> str:
    o = ord(ch)
    if ch in _PUNCT:
        return "punct"
    if 0x4E00 <= o <= 0x9FFF or ch in "々〆ヶ":
        return "kanji"
    if 0x3040 <= o <= 0x309F:
        return "hiragana"
    if 0x30A0 <= o <= 0x30FF or ch == "ー":
        return "katakana"
    if ch.isdigit() or 0xFF10 <= o <= 0xFF19:
        return "digit"
    if (ch.isascii() and ch.isalpha()) or 0xFF21 <= o <= 0xFF3A \
            or 0xFF41 <= o <= 0xFF5A:   # fullwidth A-Z / a-z only —
        return "latin"                  # the gap (FF3B-FF40) is punct
    return "other"


# unknown-word candidate policy per character class (unk.def role):
# (group whole same-class run?, cost per candidate)
_UNK = {
    "kanji": (False, 9000.0),      # kanji: single-char candidates
    "hiragana": (False, 11000.0),  # hiragana is mostly function words —
                                   # heavily penalized so dictionary
                                   # entries win
    "katakana": (True, 6000.0),    # katakana runs are usually one
                                   # loanword — group the run
    "latin": (True, 5000.0),
    "digit": (True, 5000.0),
    "other": (False, 12000.0),
}


def load_seed_dictionary(path: Optional[str] = None) -> Dict[str, List[Tuple[str, float]]]:
    """surface → [(pos, cost), ...] from the committed TSV."""
    entries: Dict[str, List[Tuple[str, float]]] = {}
    with open(path or _DATA, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            surface, pos, cost = line.split("\t")
            entries.setdefault(surface, []).append((pos, float(cost)))
    return entries


class JapaneseSegmenter:
    """Lattice + Viterbi morphological segmenter (Kuromoji role)."""

    def __init__(self, entries: Optional[Dict] = None,
                 user_entries: Optional[Iterable[Tuple[str, str, float]]] = None,
                 conn: Optional[Dict] = None):
        base = load_seed_dictionary() if entries is None else entries
        # copy the value lists too — appending user entries must not
        # mutate a caller-shared dictionary
        self.entries = {s: list(v) for s, v in base.items()}
        for surface, pos, cost in (user_entries or ()):
            self.entries.setdefault(surface, []).append((pos, float(cost)))
        self.max_len = max((len(s) for s in self.entries), default=1)
        self.conn = _CONN if conn is None else conn

    def _conn_cost(self, prev_pos: str, pos: str) -> float:
        return self.conn.get((prev_pos, pos), _CONN_DEFAULT)

    def tokenize_with_pos(self, text: str) -> List[Tuple[str, str]]:
        out: List[Tuple[str, str]] = []
        run: List[str] = []
        for ch in text:
            if ch in _PUNCT:
                if run:
                    out.extend(self._viterbi("".join(run)))
                    run = []
            else:
                run.append(ch)
        if run:
            out.extend(self._viterbi("".join(run)))
        return out

    def segment(self, text: str) -> List[str]:
        return [s for s, _ in self.tokenize_with_pos(text)]

    # ------------------------------------------------------------- lattice
    def _candidates(self, text: str, i: int):
        """Edges starting at position i: dictionary matches + class-
        driven unknown words. Yields (surface, pos, cost)."""
        n = len(text)
        found_dict = False
        for L in range(1, min(self.max_len, n - i) + 1):
            surface = text[i:i + L]
            for pos, cost in self.entries.get(surface, ()):
                found_dict = True
                yield surface, pos, cost
        cls = _char_class(text[i])
        group, unk_cost = _UNK.get(cls, _UNK["other"])
        if group:
            j = i + 1
            while j < n and _char_class(text[j]) == cls:
                j += 1
            yield text[i:j], "unk", unk_cost
        if not found_dict or not group:
            # single-char fallback keeps the lattice connected even
            # when no dictionary edge covers position i
            yield text[i], "unk", unk_cost

    def _viterbi(self, text: str) -> List[Tuple[str, str]]:
        """Min-cost path through the (position, POS) lattice — the
        `util/viterbi.py` min-sum recurrence on a variable-edge DAG."""
        n = len(text)
        # best[(i, pos)] = (cost, back-pointer (j, prev_pos, surface))
        INF = math.inf
        best: Dict[Tuple[int, str], Tuple[float, Optional[Tuple]]] = {
            (0, "BOS"): (0.0, None)}
        frontier: Dict[int, List[str]] = {0: ["BOS"]}
        for i in range(n):
            states = frontier.pop(i, [])
            if not states:
                continue
            for surface, pos, wcost in self._candidates(text, i):
                j = i + len(surface)
                for prev_pos in states:
                    base = best[(i, prev_pos)][0]
                    cost = base + wcost + self._conn_cost(prev_pos, pos)
                    key = (j, pos)
                    if cost < best.get(key, (INF, None))[0]:
                        best[key] = (cost,
                                     (i, prev_pos, surface))
                        if pos not in frontier.setdefault(j, []):
                            frontier[j].append(pos)
        # pick the cheapest end state and walk back
        end = min(((c, pos) for (j, pos), (c, _) in best.items() if j == n),
                  default=None)
        if end is None:    # unreachable text (shouldn't happen)
            return [(text, "unk")]
        pos = end[1]
        i = n
        toks: List[Tuple[str, str]] = []
        while i > 0:
            _, bp = best[(i, pos)]
            j, prev_pos, surface = bp
            toks.append((surface, pos))
            i, pos = j, prev_pos
        toks.reverse()
        return toks


class JapaneseTokenizer(Tokenizer):
    def __init__(self, sentence: str, segmenter: JapaneseSegmenter,
                 preprocessor: Optional[TokenPreProcess] = None,
                 pos_keep: Optional[frozenset] = None):
        toks = (segmenter.segment(sentence) if pos_keep is None else
                [s for s, pos in segmenter.tokenize_with_pos(sentence)
                 if pos in pos_keep])
        super().__init__(toks, preprocessor)


#: content-word POS set for embedding training — the standard Kuromoji
#: usage pattern (filter particles/auxiliaries by POS before word2vec)
CONTENT_POS = frozenset({"noun", "verb", "adj", "adverb", "prefix",
                         "suffix", "unk"})


class JapaneseTokenizerFactory(TokenizerFactory):
    """Reference role: kuromoji's `JapaneseTokenizerFactory`
    (deeplearning4j-nlp-japanese) — a drop-in TokenizerFactory whose
    `create()` runs morphological analysis instead of whitespace
    splitting. `pos_keep` optionally filters tokens by POS (e.g.
    `CONTENT_POS` drops particles/aux — the usual preprocessing for
    embedding corpora, where function words are noise)."""

    def __init__(self, segmenter: Optional[JapaneseSegmenter] = None,
                 preprocessor: Optional[TokenPreProcess] = None,
                 pos_keep: Optional[frozenset] = None):
        self.segmenter = segmenter or JapaneseSegmenter()
        self.preprocessor = preprocessor
        self.pos_keep = pos_keep

    def create(self, sentence: str) -> Tokenizer:
        return JapaneseTokenizer(sentence, self.segmenter,
                                 self.preprocessor, self.pos_keep)

    def set_token_pre_processor(self, pre: TokenPreProcess):
        self.preprocessor = pre
        return self
