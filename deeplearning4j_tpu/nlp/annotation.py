"""UIMA-style annotation pipeline.

Reference role: `deeplearning4j-nlp-uima` (3,212 LoC) wires Apache
UIMA AnalysisEngines — SentenceAnnotator, TokenizerAnnotator,
PoStagger, StemmerAnnotator — into the text pipeline via
`UimaSentenceIterator` / `UimaTokenizerFactory`: documents flow
through a CAS (typed annotation store), and downstream iterators read
the annotated spans. UIMA itself is a framework, not an algorithm —
what this module reproduces is that architecture:

- `AnnotatedDocument` (CAS role): immutable text + typed, offset-keyed
  `Annotation` spans with a feature dict;
- `Annotator` protocol (AnalysisEngine role) + `AnnotationPipeline`
  (aggregate engine role): each annotator reads existing annotations
  and adds its own;
- built-in annotators: sentence segmentation, tokenization (pluggable
  `TokenizerFactory` — the CJK/Japanese/Korean segmenters drop in),
  POS tagging (lexicon + suffix-rule English tagger by default,
  pluggable), and a suffix stemmer (SnowballProgram role);
- pipeline-fed iterators: `AnnotationSentenceIterator`
  (`UimaSentenceIterator` role) and `AnnotationTokenizerFactory`
  (`UimaTokenizerFactory` role) so Word2Vec/ParagraphVectors consume
  annotated corpora unchanged.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Callable, Dict, Iterable, List, Optional

from deeplearning4j_tpu.nlp.sentenceiterator import SentenceIterator
from deeplearning4j_tpu.nlp.tokenization import (
    TokenPreProcess,
    Tokenizer,
    TokenizerFactory,
)


@dataclasses.dataclass
class Annotation:
    """One typed span over the document text (UIMA `AnnotationFS`)."""

    type: str
    begin: int
    end: int
    features: Dict[str, object] = dataclasses.field(default_factory=dict)


class AnnotatedDocument:
    """The CAS: one immutable text + accumulated annotations."""

    def __init__(self, text: str):
        self.text = text
        self.annotations: List[Annotation] = []

    def add(self, type: str, begin: int, end: int, **features) -> Annotation:
        a = Annotation(type, begin, end, dict(features))
        self.annotations.append(a)
        return a

    def select(self, type: str) -> List[Annotation]:
        """Spans of one type in document order (UIMA `select`)."""
        return sorted((a for a in self.annotations if a.type == type),
                      key=lambda a: (a.begin, a.end))

    def covered_text(self, a: Annotation) -> str:
        return self.text[a.begin:a.end]

    def covered(self, type: str, within: Annotation) -> List[Annotation]:
        """Spans of `type` inside `within` (UIMA `selectCovered`)."""
        return [a for a in self.select(type)
                if a.begin >= within.begin and a.end <= within.end]


class Annotator:
    """AnalysisEngine role: reads the CAS, adds annotations."""

    def process(self, doc: AnnotatedDocument) -> None:
        raise NotImplementedError


class AnnotationPipeline(Annotator):
    """Aggregate engine: run annotators in order (UIMA
    `AggregateBuilder`)."""

    def __init__(self, annotators: Iterable[Annotator]):
        self.annotators = list(annotators)

    def process(self, doc: AnnotatedDocument) -> None:
        for a in self.annotators:
            a.process(doc)

    def annotate(self, text: str) -> AnnotatedDocument:
        doc = AnnotatedDocument(text)
        self.process(doc)
        return doc


# ------------------------------------------------------------ annotators
_ABBREV = {"mr", "mrs", "ms", "dr", "prof", "st", "vs", "e.g", "i.e",
           "etc", "jr", "sr", "inc", "fig"}


class SentenceAnnotator(Annotator):
    """Rule-based sentence segmentation (the UIMA SentenceAnnotator
    slot): split on ./!/? followed by whitespace + an uppercase or
    non-latin start, with an abbreviation guard."""

    _BOUNDARY = re.compile(r"[.!?。！？]+[\s]+")

    def process(self, doc: AnnotatedDocument) -> None:
        text = doc.text
        start = 0
        for m in self._BOUNDARY.finditer(text):
            prev = text[start:m.start()].rstrip()
            last_word = prev.rsplit(None, 1)[-1].lower() if prev else ""
            if last_word.rstrip(".") in _ABBREV:
                continue
            end = m.start() + len(m.group().rstrip())
            if end > start:
                doc.add("sentence", start, end)
            start = m.end()
        tail = text[start:].strip()
        if tail:
            doc.add("sentence", start + text[start:].index(tail[0]),
                    start + text[start:].index(tail[0]) + len(tail))


class TokenAnnotator(Annotator):
    """Tokenize each sentence span; any `TokenizerFactory` plugs in
    (whitespace/punct default; CJK/Japanese/Korean factories work
    unchanged). Token offsets are recovered by left-to-right search
    within the sentence."""

    def __init__(self, factory: Optional[TokenizerFactory] = None):
        self.factory = factory

    _DEFAULT = re.compile(r"\w+(?:['’]\w+)?", re.UNICODE)

    def process(self, doc: AnnotatedDocument) -> None:
        sentences = doc.select("sentence") or [
            doc.add("sentence", 0, len(doc.text))]
        for s in sentences:
            stext = doc.covered_text(s)
            if self.factory is None:
                for m in self._DEFAULT.finditer(stext):
                    doc.add("token", s.begin + m.start(),
                            s.begin + m.end())
                continue
            cursor = 0
            for tok in self.factory.create(stext).get_tokens():
                at = stext.find(tok, cursor)
                if at < 0:    # preprocessor rewrote the surface: fall
                    at = cursor   # back to cursor-anchored placement
                doc.add("token", s.begin + at, s.begin + at + len(tok),
                        surface=tok)
                cursor = at + len(tok)


# tiny English POS lexicon + suffix rules (the PoStagger slot — same
# architecture as the UIMA HMM tagger wrapper: lexicon first, then
# morphology, then default-noun)
_POS_LEXICON = {
    "the": "DT", "a": "DT", "an": "DT", "this": "DT", "that": "DT",
    "is": "VB", "are": "VB", "was": "VB", "were": "VB", "be": "VB",
    "has": "VB", "have": "VB", "had": "VB", "do": "VB", "does": "VB",
    "and": "CC", "or": "CC", "but": "CC",
    "in": "IN", "on": "IN", "at": "IN", "of": "IN", "for": "IN",
    "to": "IN", "with": "IN", "from": "IN", "by": "IN",
    "he": "PRP", "she": "PRP", "it": "PRP", "they": "PRP", "we": "PRP",
    "i": "PRP", "you": "PRP",
    "not": "RB", "very": "RB", "quickly": "RB",
}
_POS_SUFFIX = [("ing", "VBG"), ("ed", "VBD"), ("ly", "RB"), ("s", "NNS"),
               ("tion", "NN"), ("ness", "NN"), ("ful", "JJ"),
               ("ous", "JJ"), ("ive", "JJ"), ("able", "JJ")]


def default_pos_tagger(token: str) -> str:
    low = token.lower()
    if low in _POS_LEXICON:
        return _POS_LEXICON[low]
    if low[:1].isdigit():
        return "CD"
    for suf, tag in _POS_SUFFIX:
        if len(low) > len(suf) + 2 and low.endswith(suf):
            return tag
    if token[:1].isupper():
        return "NNP"
    return "NN"


class POSAnnotator(Annotator):
    """Tag every token span with a `pos` feature."""

    def __init__(self, tagger: Optional[Callable[[str], str]] = None):
        self.tagger = tagger or default_pos_tagger

    def process(self, doc: AnnotatedDocument) -> None:
        for t in doc.select("token"):
            t.features["pos"] = self.tagger(
                t.features.get("surface", doc.covered_text(t)))


class StemAnnotator(Annotator):
    """Suffix stemmer (`StemmerAnnotator`/Snowball role): adds a
    `stem` feature used by stem-normalized vocabularies."""

    _RULES = [("ational", "ate"), ("ization", "ize"), ("fulness", "ful"),
              ("iveness", "ive"), ("ousness", "ous"), ("ies", "y"),
              ("sses", "ss"), ("ing", ""), ("edly", ""), ("ed", ""),
              ("ly", ""), ("s", "")]

    def process(self, doc: AnnotatedDocument) -> None:
        for t in doc.select("token"):
            w = t.features.get("surface", doc.covered_text(t)).lower()
            for suf, rep in self._RULES:
                if len(w) > len(suf) + 2 and w.endswith(suf):
                    w = w[: len(w) - len(suf)] + rep
                    break
            t.features["stem"] = w


def default_pipeline(tokenizer_factory=None, pos=True, stem=False):
    anns: List[Annotator] = [SentenceAnnotator(),
                             TokenAnnotator(tokenizer_factory)]
    if pos:
        anns.append(POSAnnotator())
    if stem:
        anns.append(StemAnnotator())
    return AnnotationPipeline(anns)


# ---------------------------------------------------- pipeline-fed seams
class AnnotationSentenceIterator(SentenceIterator):
    """`UimaSentenceIterator` role: documents → pipeline → one sentence
    per `next_sentence()`."""

    def __init__(self, documents: Iterable[str],
                 pipeline: Optional[AnnotationPipeline] = None):
        self.documents = list(documents)
        self.pipeline = pipeline or AnnotationPipeline(
            [SentenceAnnotator()])
        self.reset()

    def reset(self) -> None:
        self._sentences: List[str] = []
        for d in self.documents:
            doc = self.pipeline.annotate(d)
            self._sentences.extend(
                doc.covered_text(s) for s in doc.select("sentence"))
        self._idx = 0

    def has_next(self) -> bool:
        return self._idx < len(self._sentences)

    def next_sentence(self) -> str:
        s = self._sentences[self._idx]
        self._idx += 1
        return s


class AnnotationTokenizerFactory(TokenizerFactory):
    """`UimaTokenizerFactory` role: create() runs the pipeline over the
    sentence; `pos_keep` filters tokens by POS tag, `use_stems=True`
    emits stem features instead of surfaces."""

    def __init__(self, pipeline: Optional[AnnotationPipeline] = None,
                 preprocessor: Optional[TokenPreProcess] = None,
                 pos_keep: Optional[frozenset] = None,
                 use_stems: bool = False):
        self.pipeline = pipeline or default_pipeline(
            pos=True, stem=use_stems)
        self.preprocessor = preprocessor
        self.pos_keep = pos_keep
        self.use_stems = use_stems

    def create(self, sentence: str) -> Tokenizer:
        doc = self.pipeline.annotate(sentence)
        toks = []
        for t in doc.select("token"):
            if self.pos_keep is not None and \
                    t.features.get("pos") not in self.pos_keep:
                continue
            if self.use_stems and "stem" in t.features:
                toks.append(t.features["stem"])
            else:
                toks.append(t.features.get("surface",
                                           doc.covered_text(t)))
        return Tokenizer(toks, self.preprocessor)

    def set_token_pre_processor(self, pre: TokenPreProcess):
        self.preprocessor = pre
        return self
