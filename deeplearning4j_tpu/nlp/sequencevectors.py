"""SequenceVectors — the generic embedding-training engine, TPU-first.

Reference: `models/sequencevectors/SequenceVectors.java:192` (`fit()`):
vocab scan → AsyncSequencer prefetch thread → N Hogwild
`VectorCalculationsThread`s doing per-pair scalar updates through the
fused native `AggregateSkipGram` op (`SkipGram.java:224`,
`iterateSample`).

TPU redesign (same capability, device-friendly schedule): the host side
streams sequences, applies frequent-word subsampling and the
reduced-window trick, and packs (center, context, negatives) into
fixed-shape batches; the device side runs ONE jitted step per batch —
embedding gathers, a [B,K] dot-product block (MXU), log-sigmoid loss,
and autodiff scatter-add updates. Batched minibatch SGD replaces
Hogwild (which does not map to SPMD hardware); gradients are averaged
over the batch (minibatch SGD), trading the reference's per-pair
sequential updates for device-sized steps. Both learning regimes are kept: negative sampling and
hierarchical softmax over Huffman codes (padded [B, C] with masks so
shapes stay static for XLA).

Skip-gram and CBOW both supported (`elements_learning_algorithm`);
ParagraphVectors reuses this engine by extending the embedding table
with label rows (see paragraphvectors.py).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Iterable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nlp.vocab import VocabCache, VocabConstructor


from deeplearning4j_tpu.nd.donation import donate_argnums as _donate
from deeplearning4j_tpu.nd.donation import jit_donated as _jit_donated


@dataclasses.dataclass
class SequenceVectorsConfig:
    vector_length: int = 100
    window: int = 5
    min_word_frequency: int = 1
    negative: int = 5           # K negative samples; 0 → hierarchical softmax
    use_hierarchic_softmax: bool = False
    learning_rate: float = 0.025
    min_learning_rate: float = 1e-4
    epochs: int = 1
    iterations: int = 1         # passes per batch (reference `iterations`)
    batch_size: int = 2048      # pairs per device step
    steps_per_flush: int = 8    # skip-gram batches fused into one scan dispatch
    subsampling: float = 0.0    # frequent-word discard threshold (e.g. 1e-3)
    seed: int = 42
    cbow: bool = False          # elements learning algorithm: CBOW vs SkipGram
    unigram_power: float = 0.75  # negative-table exponent (word2vec standard)
    # AsyncSequencer role (`SequenceVectors.java:288`): pack pair
    # arrays on a producer thread while the device runs the previous
    # fused scan — the jax dispatch is async, so the two overlap.
    # Applies to the fast path (skip-gram/neg, iterations=1, no
    # pair_hook); the trainer records host/device wait ms either way.
    async_producer: bool = True
    producer_queue_depth: int = 2


# ------------------------------------------------------------ jitted steps
def _row_counts(n_rows, *index_sets):
    """How many times each table row is touched in the batch. Each
    entry is an index array, or (indices, weights) for masked refs."""
    c = jnp.zeros((n_rows,), jnp.float32)
    for s in index_sets:
        if isinstance(s, tuple):
            idx, w = s
            c = c.at[idx.reshape(-1)].add(w.reshape(-1).astype(jnp.float32))
        else:
            c = c.at[s.reshape(-1)].add(1.0)
    return jnp.clip(c, 1.0, None)[:, None]


# Batched treatment of word2vec's sequential per-pair updates: the
# scatter-added (sum) row gradient is divided by the row's occurrence
# count, so every touched row moves ~one per-pair step per flush
# regardless of batch size. A plain batch mean shrinks steps by 1/B and
# stalls small corpora; a plain sum diverges for frequent rows.


def _sg_neg_math(syn0, syn1neg, centers, contexts, negs, lr, trainable_from,
                 valid=None):
    """Skip-gram negative-sampling update math (shared by the single-step
    jit and the fused scan). trainable_from: row index from which syn0
    rows are trainable (0 = all; used by inferVector).

    Sparse closed-form update: the gradient of the SGNS loss only
    touches the B center rows and B·(K+1) output rows, so the update is
    computed per pair ([B,D]/[B,K,D] intermediates) and scatter-added —
    never materializing the [V,D] dense gradient autodiff would produce.
    At real vocabulary sizes (10⁵–10⁶ rows) the dense route is
    memory-bound garbage; this is the Pallas-guide "sparse-update"
    shape, expressed with XLA scatters (`.at[].add`). Row sums are
    divided by per-row occurrence counts (see note above) — identical
    math to the autodiff version, verified by test.

    `valid` (optional [B] 0/1 mask) lets ragged epoch-end tails run
    padded to the full compiled batch shape: masked entries contribute
    nothing to loss, counts, or updates — bitwise the same result as a
    ragged-shape flush, without paying an XLA compile per distinct tail
    length."""
    f32 = jnp.float32
    v = jnp.take(syn0, centers, axis=0)                        # [B,D]
    u_pos = jnp.take(syn1neg, contexts, axis=0)                # [B,D]
    u_neg = jnp.take(syn1neg, negs, axis=0)                    # [B,K,D]
    s_pos = jnp.sum(v * u_pos, axis=-1)                        # [B]
    s_neg = jnp.einsum("bd,bkd->bk", v, u_neg)                 # [B,K]
    lp, ln = jax.nn.log_sigmoid(s_pos), jax.nn.log_sigmoid(-s_neg)
    # dL/ds: σ(s)-1 for the positive, σ(s) for negatives
    c_pos = -jax.nn.sigmoid(-s_pos)                            # [B]
    c_neg = jax.nn.sigmoid(s_neg)                              # [B,K]
    if valid is None:
        n_eff = centers.shape[0]
        loss = -(jnp.sum(lp) + jnp.sum(ln))
        one = None
    else:
        n_eff = jnp.clip(jnp.sum(valid), 1.0, None)
        loss = -(jnp.sum(lp * valid) + jnp.sum(ln * valid[:, None]))
        c_pos = c_pos * valid
        c_neg = c_neg * valid[:, None]
        one = valid
    dv = c_pos[:, None] * u_pos + jnp.einsum("bk,bkd->bd", c_neg, u_neg)
    du_pos = c_pos[:, None] * v                                # [B,D]
    du_neg = c_neg[..., None] * v[:, None, :]                  # [B,K,D]

    w1 = 1.0 if one is None else one
    wk = 1.0 if one is None else jnp.broadcast_to(one[:, None], negs.shape)
    counts0 = jnp.zeros((syn0.shape[0],), f32).at[centers].add(w1)
    counts0 = jnp.clip(counts0, 1.0, None)
    counts1 = (jnp.zeros((syn1neg.shape[0],), f32)
               .at[contexts].add(w1)
               .at[negs.reshape(-1)].add(
                   wk.reshape(-1) if one is not None else 1.0))
    counts1 = jnp.clip(counts1, 1.0, None)

    scale0 = (lr / counts0[centers])[:, None]                  # [B,1]
    if trainable_from > 0:
        # inference mode: only rows >= trainable_from learn; the output
        # table is frozen entirely (reference inferVector semantics)
        scale0 = scale0 * (centers >= trainable_from)[:, None]
        new_syn1neg = syn1neg
    else:
        s_ctx = (lr / counts1[contexts])[:, None]
        s_negs = (lr / counts1[negs])[..., None]               # [B,K,1]
        new_syn1neg = (syn1neg
                       .at[contexts].add(-(du_pos * s_ctx)
                                         .astype(syn1neg.dtype))
                       .at[negs.reshape(-1)].add(
                           -(du_neg * s_negs)
                           .reshape(-1, syn1neg.shape[1])
                           .astype(syn1neg.dtype)))
    new_syn0 = syn0.at[centers].add(-(dv * scale0).astype(syn0.dtype))
    return new_syn0, new_syn1neg, loss / n_eff


@_jit_donated(donate=(0, 1), static_argnums=(6,))
def _sg_neg_step(syn0, syn1neg, centers, contexts, negs, lr, trainable_from):
    return _sg_neg_math(syn0, syn1neg, centers, contexts, negs, lr,
                        trainable_from)


@_jit_donated(donate=(0, 1), static_argnums=(6,))
def _sg_neg_step_masked(syn0, syn1neg, centers, contexts, negs, lr,
                        trainable_from, valid):
    """Tail flush: ragged batch padded to the compiled [B] shape with a
    validity mask — one compile serves every tail length."""
    return _sg_neg_math(syn0, syn1neg, centers, contexts, negs, lr,
                        trainable_from, valid)


def _sg_neg_scan(syn0, syn1neg, centers, contexts, negs, lrs, trainable_from):
    """k fused skip-gram batches in ONE dispatch (`lax.scan` over the
    per-batch update). The reference amortizes its per-pair update cost
    across Hogwild threads (`SequenceVectors.java:294`); on TPU the
    equivalent lever is fewer, bigger dispatches — the host packs k
    [B]-shaped batches while the device drains the previous group
    (async dispatch, no host sync in between).

    centers/contexts: [k,B]; negs: [k,B,K]; lrs: [k]. This is the one
    copy of the fused math; it gets jitted twice — plain and
    mesh-sharded (`_mesh_steps`)."""

    def body(carry, inp):
        s0, s1 = carry
        c, x, n, lr = inp
        s0, s1, loss = _sg_neg_math(s0, s1, c, x, n, lr, trainable_from)
        return (s0, s1), loss

    (syn0, syn1neg), losses = jax.lax.scan(
        body, (syn0, syn1neg), (centers, contexts, negs, lrs))
    return syn0, syn1neg, losses[-1]


_sg_neg_multi = _jit_donated(_sg_neg_scan, donate=(0, 1),
                            static_argnums=(6,))


def _cbow_neg_math(syn0, syn1neg, ctx, ctx_mask, centers, negs, lr,
                   trainable_from, valid=None):
    """CBOW negative-sampling step (sparse closed form, same reasoning
    as `_sg_neg_math`). ctx: [B, 2W] indices, ctx_mask 0/1. `valid` as
    in `_sg_neg_math` — padded tail rows (ctx_mask all zero) contribute
    nothing to loss, counts, or either table."""
    f32 = jnp.float32
    vecs = jnp.take(syn0, ctx, axis=0)                         # [B,W2,D]
    m = ctx_mask[..., None]
    M = jnp.clip(jnp.sum(ctx_mask, axis=1, keepdims=True), 1.0, None)
    h = jnp.sum(vecs * m, axis=1) / M                          # [B,D]
    u_pos = jnp.take(syn1neg, centers, axis=0)
    u_neg = jnp.take(syn1neg, negs, axis=0)                    # [B,K,D]
    s_pos = jnp.sum(h * u_pos, axis=-1)
    s_neg = jnp.einsum("bd,bkd->bk", h, u_neg)
    lp, ln = jax.nn.log_sigmoid(s_pos), jax.nn.log_sigmoid(-s_neg)
    c_pos = -jax.nn.sigmoid(-s_pos)                            # [B]
    c_neg = jax.nn.sigmoid(s_neg)                              # [B,K]
    if valid is None:
        n_eff = centers.shape[0]
        loss = -(jnp.sum(lp) + jnp.sum(ln))
        w1, wk = 1.0, 1.0
    else:
        n_eff = jnp.clip(jnp.sum(valid), 1.0, None)
        loss = -(jnp.sum(lp * valid) + jnp.sum(ln * valid[:, None]))
        c_pos = c_pos * valid
        c_neg = c_neg * valid[:, None]
        w1 = valid
        wk = jnp.broadcast_to(valid[:, None], negs.shape)
    dh = c_pos[:, None] * u_pos + jnp.einsum("bk,bkd->bd", c_neg, u_neg)
    # dL/dv_slot = (mask/M) * dh, per context slot
    dctx = (m / M[..., None]) * dh[:, None, :]                 # [B,W2,D]
    du_pos = c_pos[:, None] * h
    du_neg = c_neg[..., None] * h[:, None, :]

    counts0 = (jnp.zeros((syn0.shape[0],), f32)
               .at[ctx.reshape(-1)].add(ctx_mask.reshape(-1)))
    counts0 = jnp.clip(counts0, 1.0, None)
    counts1 = (jnp.zeros((syn1neg.shape[0],), f32)
               .at[centers].add(w1)
               .at[negs.reshape(-1)].add(
                   wk.reshape(-1) if valid is not None else 1.0))
    counts1 = jnp.clip(counts1, 1.0, None)

    scale0 = (lr / counts0[ctx])[..., None] * m                # [B,W2,1]
    if trainable_from > 0:
        scale0 = scale0 * (ctx >= trainable_from)[..., None]
        new_syn1neg = syn1neg
    else:
        s_ctr = (lr / counts1[centers])[:, None]
        s_negs = (lr / counts1[negs])[..., None]
        new_syn1neg = (syn1neg
                       .at[centers].add(-(du_pos * s_ctr)
                                        .astype(syn1neg.dtype))
                       .at[negs.reshape(-1)].add(
                           -(du_neg * s_negs)
                           .reshape(-1, syn1neg.shape[1])
                           .astype(syn1neg.dtype)))
    new_syn0 = syn0.at[ctx.reshape(-1)].add(
        -(dctx * scale0).reshape(-1, syn0.shape[1]).astype(syn0.dtype))
    return new_syn0, new_syn1neg, loss / n_eff


@_jit_donated(donate=(0, 1), static_argnums=(7,))
def _cbow_neg_step(syn0, syn1neg, ctx, ctx_mask, centers, negs, lr,
                   trainable_from):
    return _cbow_neg_math(syn0, syn1neg, ctx, ctx_mask, centers, negs,
                          lr, trainable_from)


@_jit_donated(donate=(0, 1), static_argnums=(7,))
def _cbow_neg_step_masked(syn0, syn1neg, ctx, ctx_mask, centers, negs, lr,
                          trainable_from, valid):
    return _cbow_neg_math(syn0, syn1neg, ctx, ctx_mask, centers, negs,
                          lr, trainable_from, valid)


def _hs_path_grads(h, syn1, points, codes, code_mask):
    """Shared HS math: dL/dh and the per-path-node output deltas for a
    batch of hidden vectors classified down Huffman paths."""
    u = jnp.take(syn1, points, axis=0)                         # [B,C,D]
    sign = 1.0 - 2.0 * codes
    logits = jnp.einsum("bd,bcd->bc", h, u) * sign
    loss = -jnp.sum(jax.nn.log_sigmoid(logits) * code_mask)
    dlogit = -jax.nn.sigmoid(-logits) * code_mask              # [B,C]
    coef = dlogit * sign
    dh = jnp.einsum("bc,bcd->bd", coef, u)
    du = coef[..., None] * h[:, None, :]                       # [B,C,D]
    return loss, dh, du


def _cbow_hs_math(syn0, syn1, ctx, ctx_mask, centers, points, codes,
                  code_mask, lr, valid=None):
    """CBOW + hierarchical softmax: context mean classified down the
    center word's Huffman path (reference `CBOW.java` HS branch).
    Sparse closed form like the NS steps. `valid` as in `_sg_hs_math`
    (padded rows' path mask is neutralized here; their ctx_mask rows
    are already all-zero)."""
    f32 = jnp.float32
    if valid is not None:
        code_mask = code_mask * valid[:, None]
    n_eff = (centers.shape[0] if valid is None
             else jnp.clip(jnp.sum(valid), 1.0, None))
    vecs = jnp.take(syn0, ctx, axis=0)
    m = ctx_mask[..., None]
    M = jnp.clip(jnp.sum(ctx_mask, axis=1, keepdims=True), 1.0, None)
    h = jnp.sum(vecs * m, axis=1) / M
    loss, dh, du = _hs_path_grads(h, syn1, points, codes, code_mask)
    dctx = (m / M[..., None]) * dh[:, None, :]

    counts0 = (jnp.zeros((syn0.shape[0],), f32)
               .at[ctx.reshape(-1)].add(ctx_mask.reshape(-1)))
    counts0 = jnp.clip(counts0, 1.0, None)
    counts1 = (jnp.zeros((syn1.shape[0],), f32)
               .at[points.reshape(-1)].add(code_mask.reshape(-1)))
    counts1 = jnp.clip(counts1, 1.0, None)

    scale0 = (lr / counts0[ctx])[..., None] * m
    scale1 = (lr / counts1[points])[..., None]
    new_syn0 = syn0.at[ctx.reshape(-1)].add(
        -(dctx * scale0).reshape(-1, syn0.shape[1]).astype(syn0.dtype))
    new_syn1 = syn1.at[points.reshape(-1)].add(
        -(du * scale1).reshape(-1, syn1.shape[1]).astype(syn1.dtype))
    return new_syn0, new_syn1, loss / n_eff


@_jit_donated(donate=(0, 1))
def _cbow_hs_step(syn0, syn1, ctx, ctx_mask, centers, points, codes,
                  code_mask, lr):
    return _cbow_hs_math(syn0, syn1, ctx, ctx_mask, centers, points,
                         codes, code_mask, lr)


@_jit_donated(donate=(0, 1))
def _cbow_hs_step_masked(syn0, syn1, ctx, ctx_mask, centers, points, codes,
                         code_mask, lr, valid):
    return _cbow_hs_math(syn0, syn1, ctx, ctx_mask, centers, points,
                         codes, code_mask, lr, valid)


def _sg_hs_math(syn0, syn1, centers, points, codes, code_mask, lr,
                valid=None):
    """Skip-gram hierarchical-softmax step over Huffman paths
    (reference `SkipGram.iterateSample` HS branch, `SkipGram.java:224`).
    Sparse closed form like the NS steps. `valid` as in `_sg_neg_math`:
    padded tail entries are masked out of the path mask here, so callers
    only need to pad index arrays with zeros."""
    f32 = jnp.float32
    if valid is not None:
        # padded rows index word 0's Huffman path — neutralize it fully
        code_mask = code_mask * valid[:, None]
    v = jnp.take(syn0, centers, axis=0)                        # [B,D]
    loss, dv, du = _hs_path_grads(v, syn1, points, codes, code_mask)

    w1 = 1.0 if valid is None else valid
    n_eff = (centers.shape[0] if valid is None
             else jnp.clip(jnp.sum(valid), 1.0, None))
    counts0 = jnp.clip(jnp.zeros((syn0.shape[0],), f32)
                       .at[centers].add(w1), 1.0, None)
    counts1 = (jnp.zeros((syn1.shape[0],), f32)
               .at[points.reshape(-1)].add(code_mask.reshape(-1)))
    counts1 = jnp.clip(counts1, 1.0, None)

    scale0 = (lr / counts0[centers])[:, None]
    scale1 = (lr / counts1[points])[..., None]
    new_syn0 = syn0.at[centers].add(-(dv * scale0).astype(syn0.dtype))
    new_syn1 = syn1.at[points.reshape(-1)].add(
        -(du * scale1).reshape(-1, syn1.shape[1]).astype(syn1.dtype))
    return new_syn0, new_syn1, loss / n_eff


@_jit_donated(donate=(0, 1))
def _sg_hs_step(syn0, syn1, centers, points, codes, code_mask, lr):
    return _sg_hs_math(syn0, syn1, centers, points, codes, code_mask, lr)


@_jit_donated(donate=(0, 1))
def _sg_hs_step_masked(syn0, syn1, centers, points, codes, code_mask, lr,
                       valid):
    return _sg_hs_math(syn0, syn1, centers, points, codes, code_mask, lr,
                       valid)


class SequenceVectors:
    """Trains an embedding table over token sequences."""

    def __init__(self, config: Optional[SequenceVectorsConfig] = None, *,
                 mesh=None, data_axis: str = "data", **kw):
        if config is None:
            config = SequenceVectorsConfig(**kw)
        self.conf = config
        self.vocab: Optional[VocabCache] = None
        self.syn0 = None       # np.ndarray [V(+labels), D]
        self.syn1 = None       # HS inner-node table
        self.syn1neg = None    # negative-sampling output table
        self._neg_table = None
        self._rng = np.random.default_rng(config.seed)
        self._negs_rng = None   # flush-side stream (see _sample_negatives)
        self.etl_stats = None   # producer/consumer wait accounting
        # mesh-sharded training (the dl4j-spark-nlp distributed Word2Vec
        # capability, `spark/models/embeddings/word2vec/Word2Vec.java`):
        # the pair batch shards over `data_axis`, tables stay replicated,
        # and XLA inserts the grad all-reduce. Global-view jit semantics
        # make the result bitwise-equivalent (up to reduction order) to
        # single-device training. Covers the skip-gram paths; CBOW/HS
        # fall back to unsharded steps.
        self.mesh = mesh
        self.data_axis = data_axis
        self._sharded_step = None
        self._sharded_multi = None
        self._warmed_key = None

    # ------------------------------------------------------------- vocab
    def build_vocab(self, sequences: Iterable[List[str]]):
        self.vocab = VocabConstructor(
            min_word_frequency=self.conf.min_word_frequency).build(sequences)
        return self

    def _init_tables(self, extra_rows: int = 0):
        V = self.vocab.num_words()
        D = self.conf.vector_length
        # word2vec init: U(-0.5, 0.5)/D for syn0, zeros for output tables
        self.syn0 = ((self._rng.random((V + extra_rows, D)) - 0.5) / D
                     ).astype(np.float32)
        self.syn1neg = np.zeros((V, D), np.float32)
        max_inner = max(V, 2)
        self.syn1 = np.zeros((max_inner, D), np.float32)
        self._init_aux_tables()

    def _init_aux_tables(self):
        """Sampler + Huffman lookup state derived from the vocab. Split
        from `_init_tables` so a model warm-started from
        `WordVectorSerializer` (which restores vocab + syn0 and zeroed
        output tables, but none of this derived state) can resume
        `fit()` without resetting its trained embeddings."""
        V = self.vocab.num_words()
        D = self.syn0.shape[1]
        # guards for manually-assembled models (syn0/vocab set directly)
        if self.syn1neg is None:
            self.syn1neg = np.zeros((V, D), np.float32)
        if self.syn1 is None:
            self.syn1 = np.zeros((max(V, 2), D), np.float32)
        # deserialized vocabs carry no Huffman codes — without this, HS
        # warm-start training would be fully masked out (a silent no-op)
        if V > 1 and all(not self.vocab.element_at_index(i).codes
                         for i in range(V)):
            from deeplearning4j_tpu.nlp.vocab import build_huffman
            build_huffman(self.vocab)
        # unigram^0.75 negative-sampling distribution (word2vec standard)
        self._freqs = np.array([self.vocab.element_at_index(i).frequency
                                for i in range(V)])
        probs = self._freqs ** self.conf.unigram_power
        self._neg_cdf = np.cumsum(probs / probs.sum())
        self._neg_cdf[-1] = 1.0
        # quantized unigram table: one searchsorted at build time, O(1)
        # integer draws afterwards (the reference's negative table idea;
        # per-draw CDF searchsorted measured at 40% of steady-state fit)
        tsize = max(1 << 20, 16 * V)
        self._neg_table = np.searchsorted(
            self._neg_cdf, (np.arange(tsize) + 0.5) / tsize).astype(np.int32)
        # Huffman paths as dense [V, C] tables → batch assembly is pure
        # fancy indexing (fixed pad width keeps XLA shapes static)
        C = max((len(self.vocab.element_at_index(i).codes)
                 for i in range(V)), default=1) or 1
        self._max_code = C
        self._hs_points = np.zeros((V, C), np.int32)
        self._hs_codes = np.zeros((V, C), np.float32)
        self._hs_mask = np.zeros((V, C), np.float32)
        for i in range(V):
            vw = self.vocab.element_at_index(i)
            L = len(vw.codes)
            if L:
                self._hs_points[i, :L] = vw.points
                self._hs_codes[i, :L] = vw.codes
                self._hs_mask[i, :L] = 1.0

    # ------------------------------------------------------- pair batching
    def _tokens_to_indices(self, tokens: Sequence[str]) -> np.ndarray:
        """Vocab lookup + frequent-word subsampling, vectorised."""
        conf = self.conf
        idx_of = self.vocab.index_of
        idxs = np.fromiter((idx_of(t) for t in tokens), np.int64, len(tokens))
        idxs = idxs[idxs >= 0]
        if conf.subsampling > 0 and self.vocab.total_word_count > 0 and len(idxs):
            f = self._freqs[idxs] / self.vocab.total_word_count
            keep_p = (np.sqrt(f / conf.subsampling) + 1) * conf.subsampling / f
            idxs = idxs[self._rng.random(len(idxs)) < keep_p]
        return idxs

    def _sequence_to_pair_arrays(self, tokens: Sequence[str]):
        """Skip-gram (center, context) arrays with the reduced-window
        trick, fully vectorised (no per-position Python loop)."""
        conf = self.conf
        idxs = self._tokens_to_indices(tokens)
        n = len(idxs)
        if n < 2:
            return None
        b = self._rng.integers(1, conf.window + 1, n)
        pos = np.arange(n)
        cs, xs = [], []
        for off in range(1, conf.window + 1):
            ok = b >= off
            left = np.nonzero(ok & (pos >= off))[0]
            cs.append(idxs[left]); xs.append(idxs[left - off])
            right = np.nonzero(ok & (pos + off < n))[0]
            cs.append(idxs[right]); xs.append(idxs[right + off])
        return (np.concatenate(cs).astype(np.int32),
                np.concatenate(xs).astype(np.int32))

    def _sequence_to_pairs(self, tokens: Sequence[str]):
        """CBOW pair lists: (center, center, ctx_indices)."""
        conf = self.conf
        idxs = self._tokens_to_indices(tokens).tolist()
        pairs = []
        n = len(idxs)
        for p, center in enumerate(idxs):
            bb = int(self._rng.integers(1, conf.window + 1))
            ctx = idxs[max(0, p - bb):p] + idxs[p + 1:p + bb + 1]
            if ctx:
                pairs.append((center, center, ctx))
        return pairs

    def _sample_negatives(self, B: int) -> np.ndarray:
        # own stream, not self._rng: negatives are drawn at FLUSH time
        # (consumer side) while the pair packer may be running on the
        # producer thread — one shared generator would race and break
        # sync/async determinism parity
        if self._negs_rng is None:
            self._negs_rng = np.random.default_rng(self.conf.seed + 0x5EED)
        K = max(self.conf.negative, 1)
        idx = self._negs_rng.integers(0, len(self._neg_table), (B, K))
        return self._neg_table[idx]

    def _mesh_steps(self):
        """Sharded jit variants of the skip-gram/neg steps (built lazily:
        batch dims shard over `data_axis`, tables replicate)."""
        if self._sharded_step is None:
            from jax.sharding import NamedSharding, PartitionSpec as P
            mesh = self.mesh
            repl = NamedSharding(mesh, P())
            b1 = NamedSharding(mesh, P(self.data_axis))
            b2 = NamedSharding(mesh, P(None, self.data_axis))
            bk = NamedSharding(mesh, P(self.data_axis, None))
            b3 = NamedSharding(mesh, P(None, self.data_axis, None))
            self._sharded_step = jax.jit(
                _sg_neg_math, static_argnums=(6,), donate_argnums=_donate(0, 1),
                in_shardings=(repl, repl, b1, b1, bk, None),
                out_shardings=(repl, repl, None))
            self._sharded_multi = jax.jit(
                _sg_neg_scan, static_argnums=(6,), donate_argnums=_donate(0, 1),
                in_shardings=(repl, repl, b2, b2, b3, None),
                out_shardings=(repl, repl, None))
        return self._sharded_step, self._sharded_multi

    def _flush_sg_neg(self, centers, contexts, lr):
        step = _sg_neg_step
        if self.mesh is not None and len(centers) % self.mesh.size == 0:
            # ragged tails (not divisible by the mesh) run unsharded —
            # replicated tables make that transparently correct
            step, _ = self._mesh_steps()
        self.syn0, self.syn1neg, loss = step(
            self.syn0, self.syn1neg, centers, contexts,
            self._sample_negatives(len(centers)),
            np.float32(lr), self._trainable_from)
        return loss

    def _flush_sg_neg_multi(self, centers, contexts, lrs):
        """centers/contexts: [k,B]; lrs: [k]. One fused dispatch, no
        host sync — the loss comes back as a device array."""
        multi = _sg_neg_multi
        if self.mesh is not None and centers.shape[1] % self.mesh.size == 0:
            _, multi = self._mesh_steps()
        k, B = centers.shape
        negs = self._sample_negatives(k * B).reshape(k, B, -1)
        self.syn0, self.syn1neg, loss = multi(
            self.syn0, self.syn1neg, centers, contexts, negs,
            lrs.astype(np.float32), self._trainable_from)
        return loss

    def _pack_cbow(self, pairs):
        # +1 slot so a DM label row fits even at the max reduced window
        W2 = 2 * self.conf.window + 1
        B = len(pairs)
        ctx = np.zeros((B, W2), np.int32)
        mask = np.zeros((B, W2), np.float32)
        centers = np.zeros((B,), np.int32)
        for i, (center, _, cs) in enumerate(pairs):
            centers[i] = center
            cs = cs[:W2]
            ctx[i, :len(cs)] = cs
            mask[i, :len(cs)] = 1.0
        return ctx, mask, centers

    def _flush_cbow_neg(self, pairs, lr):
        ctx, mask, centers = self._pack_cbow(pairs)
        self.syn0, self.syn1neg, loss = _cbow_neg_step(
            self.syn0, self.syn1neg, ctx, mask, centers,
            self._sample_negatives(len(pairs)),
            np.float32(lr), self._trainable_from)
        return loss

    def _flush_cbow_hs(self, pairs, lr):
        ctx, mask, centers = self._pack_cbow(pairs)
        self.syn0, self.syn1, loss = _cbow_hs_step(
            self.syn0, self.syn1, ctx, mask, centers,
            self._hs_points[centers], self._hs_codes[centers],
            self._hs_mask[centers], np.float32(lr))
        return loss

    def _flush_sg_hs(self, centers, contexts, lr):
        # Huffman paths precomputed as [V, C] tables → pure array indexing
        self.syn0, self.syn1, loss = _sg_hs_step(
            self.syn0, self.syn1, centers,
            self._hs_points[contexts], self._hs_codes[contexts],
            self._hs_mask[contexts], np.float32(lr))
        return loss

    def _flush_cbow_neg_tail(self, pairs, lr):
        B = self.conf.batch_size
        n = len(pairs)
        if n == B:
            return self._flush_cbow_neg(pairs, lr)
        padded = pairs + [(0, 0, ())] * (B - n)   # empty ctx -> zero mask
        ctx, mask, centers = self._pack_cbow(padded)
        valid = self._valid_mask(B, n)
        negs = np.zeros((B, max(self.conf.negative, 1)), np.int32)
        negs[:n] = self._sample_negatives(n)      # rng stream == ragged path
        self.syn0, self.syn1neg, loss = _cbow_neg_step_masked(
            self.syn0, self.syn1neg, ctx, mask, centers, negs,
            np.float32(lr), self._trainable_from, valid)
        return loss

    def _flush_cbow_hs_tail(self, pairs, lr):
        B = self.conf.batch_size
        n = len(pairs)
        if n == B:
            return self._flush_cbow_hs(pairs, lr)
        padded = pairs + [(0, 0, ())] * (B - n)
        ctx, mask, centers = self._pack_cbow(padded)
        valid = self._valid_mask(B, n)
        self.syn0, self.syn1, loss = _cbow_hs_step_masked(
            self.syn0, self.syn1, ctx, mask, centers,
            self._hs_points[centers], self._hs_codes[centers],
            self._hs_mask[centers], np.float32(lr), valid)
        return loss

    # Ragged epoch-end tails run PADDED to the compiled [B] shape with a
    # validity mask (exact math, see `_sg_neg_math`): without this,
    # every distinct tail length costs a fresh XLA compile — measured at
    # ~0.6 s per fit on the word2vec bench, since the reduced-window rng
    # makes each epoch's tail length unique.
    @staticmethod
    def _valid_mask(B, n):
        valid = np.zeros(B, np.float32)
        valid[:n] = 1.0
        return valid

    def _pad_tail(self, centers, contexts):
        B = self.conf.batch_size
        n = len(centers)
        pc = np.zeros(B, np.int32); pc[:n] = centers
        px = np.zeros(B, np.int32); px[:n] = contexts
        return pc, px, self._valid_mask(B, n)

    def _warm_drain_executables(self, use_hs, array_path):
        """Pre-compile every drain executable a fit can reach. Which
        shapes a given fit hits depends on the subsampling rng — a >=B
        epoch tail drains per-batch [B], a ragged tail hits the masked
        step — so without this a late tail can stall mid-fit on a fresh
        XLA compile (seconds over a TPU tunnel), landing inside a
        user's or the bench's steady-state window. Zero-lr, zero-index
        calls at the exact production avals; outputs are assigned back
        (lr=0 makes the update an exact no-op on finite tables) because
        the steps donate the table buffers. No host rng is consumed, so
        seeded training streams are unchanged. Mesh-sharded fits skip
        this: their drain set depends on divisibility and is exercised
        on virtual devices where compiles are cheap. Inference-mode fits
        (trainable_from > 0, i.e. infer_vector over one document) skip
        it too: their pair count is a document, not a corpus, so they
        only ever touch the masked tail step — pre-compiling the full-
        batch executables they cannot reach would ADD a compile stall."""
        if self.mesh is not None or self._trainable_from > 0:
            return
        B = self.conf.batch_size
        key = (self.syn0.shape, B, bool(use_hs), bool(array_path),
               self._trainable_from)
        # the skip additionally requires device-resident tables: jit
        # caches on argument sharding, so host-resident tables (fresh
        # _init_tables — the normal start of every fit) must be warmed
        # through to device arrays again or the first real flush of a
        # refit compiles a second, host-input cache entry
        if self._warmed_key == key and not isinstance(self.syn0, np.ndarray):
            return
        lr0 = np.float32(0.0)
        zc = np.zeros(B, np.int32)
        zvalid = self._valid_mask(B, 0)
        zn = np.zeros((B, max(self.conf.negative, 1)), np.int32)
        if array_path:
            if use_hs:
                pts, cds, msk = (self._hs_points[zc], self._hs_codes[zc],
                                 self._hs_mask[zc])
                self.syn0, self.syn1, _ = _sg_hs_step(
                    self.syn0, self.syn1, zc, pts, cds, msk, lr0)
                self.syn0, self.syn1, _ = _sg_hs_step_masked(
                    self.syn0, self.syn1, zc, pts, cds, msk, lr0, zvalid)
            else:
                self.syn0, self.syn1neg, _ = _sg_neg_step(
                    self.syn0, self.syn1neg, zc, zc, zn, lr0,
                    self._trainable_from)
                self.syn0, self.syn1neg, _ = _sg_neg_step_masked(
                    self.syn0, self.syn1neg, zc, zc, zn, lr0,
                    self._trainable_from, zvalid)
        else:
            W2 = 2 * self.conf.window + 1
            zctx = np.zeros((B, W2), np.int32)
            zmask = np.zeros((B, W2), np.float32)
            if use_hs:
                pts, cds, msk = (self._hs_points[zc], self._hs_codes[zc],
                                 self._hs_mask[zc])
                self.syn0, self.syn1, _ = _cbow_hs_step(
                    self.syn0, self.syn1, zctx, zmask, zc, pts, cds, msk,
                    lr0)
                self.syn0, self.syn1, _ = _cbow_hs_step_masked(
                    self.syn0, self.syn1, zctx, zmask, zc, pts, cds, msk,
                    lr0, zvalid)
            else:
                self.syn0, self.syn1neg, _ = _cbow_neg_step(
                    self.syn0, self.syn1neg, zctx, zmask, zc, zn, lr0,
                    self._trainable_from)
                self.syn0, self.syn1neg, _ = _cbow_neg_step_masked(
                    self.syn0, self.syn1neg, zctx, zmask, zc, zn, lr0,
                    self._trainable_from, zvalid)
        self._warmed_key = key

    def _flush_sg_neg_tail(self, centers, contexts, lr):
        if len(centers) == self.conf.batch_size:
            return self._flush_sg_neg(centers, contexts, lr)
        pc, px, valid = self._pad_tail(centers, contexts)
        # negatives drawn for the REAL entries only: the host rng stream
        # stays identical to a ragged-shape flush, so results match the
        # unpadded path exactly (padded rows are masked out anyway)
        negs = np.zeros((len(pc), max(self.conf.negative, 1)), np.int32)
        negs[:len(centers)] = self._sample_negatives(len(centers))
        self.syn0, self.syn1neg, loss = _sg_neg_step_masked(
            self.syn0, self.syn1neg, pc, px, negs, np.float32(lr),
            self._trainable_from, valid)
        return loss

    def _flush_sg_hs_tail(self, centers, contexts, lr):
        if len(centers) == self.conf.batch_size:
            return self._flush_sg_hs(centers, contexts, lr)
        pc, px, valid = self._pad_tail(centers, contexts)
        self.syn0, self.syn1, loss = _sg_hs_step_masked(
            self.syn0, self.syn1, pc, self._hs_points[px],
            self._hs_codes[px], self._hs_mask[px],
            np.float32(lr), valid)
        return loss

    # ----------------------------------------------------------------- fit
    def fit(self, sequences, extra_rows: int = 0, trainable_from: int = 0,
            pair_hook=None, total_words: Optional[int] = None):
        """Train. `sequences`: iterable (re-iterable across epochs) of
        token lists. Returns self."""
        conf = self.conf
        if self.vocab is None:
            self.build_vocab(sequences)
        warm_start = self.syn0 is not None and self._neg_table is None
        if self.syn0 is None or (not warm_start and extra_rows and
                                 self.syn0.shape[0] == self.vocab.num_words()):
            self._init_tables(extra_rows)
        elif warm_start:
            # warm start (deserialized model): vocab + syn0 exist but the
            # sampler/Huffman state was never built. Keep the trained
            # embeddings; label rows (ParagraphVectors) are appended, not
            # re-randomized with the rest of the table.
            if extra_rows and self.syn0.shape[0] == self.vocab.num_words():
                D = self.syn0.shape[1]
                new_rows = ((self._rng.random((extra_rows, D)) - 0.5) / D
                            ).astype(np.float32)
                self.syn0 = np.concatenate([np.asarray(self.syn0), new_rows])
            self._init_aux_tables()
        self._trainable_from = trainable_from

        use_hs = conf.use_hierarchic_softmax or conf.negative <= 0
        array_path = not conf.cbow  # skip-gram variants carry index arrays
        sg_flush = self._flush_sg_hs if use_hs else self._flush_sg_neg
        sg_flush_tail = (self._flush_sg_hs_tail if use_hs
                         else self._flush_sg_neg_tail)
        cbow_flush = self._flush_cbow_hs if use_hs else self._flush_cbow_neg
        cbow_flush_tail = (self._flush_cbow_hs_tail if use_hs
                           else self._flush_cbow_neg_tail)

        # lr decays linearly over the full corpus; when the training
        # corpus differs from the vocab-construction corpus (graph
        # walks vs degree sequences), the caller passes the real size.
        # For in-memory corpora the exact size is one cheap pass — this
        # also keeps warm-started models (whose deserialized vocab has
        # no real counts) from collapsing the lr schedule immediately.
        if total_words is None and isinstance(sequences, (list, tuple)):
            total_words = sum(len(s) for s in sequences)
        if total_words is None:
            total_words = self.vocab.total_word_count
        corpus_words = total_words
        total_words = max(total_words * conf.epochs, 1)
        # warm only when a full-batch flush is reachable: an epoch emits
        # at most 2*window pairs per center word (1 for CBOW), so a
        # corpus whose pair upper bound is below B can only ever hit the
        # masked tail step — pre-compiling [B] executables for it would
        # ADD the compile stall this exists to remove. pair_hook makes
        # the count uncallerable, so it always warms.
        pairs_per_word = 1 if conf.cbow else 2 * conf.window
        if (pair_hook is not None
                or corpus_words * pairs_per_word >= conf.batch_size):
            self._warm_drain_executables(use_hs, array_path)
        self.last_loss = 0.0
        self.etl_stats = None   # per-fit accounting — never stale
        loss_dev = None      # device-side last loss — read ONCE after fit
        B = conf.batch_size
        # fused flush group: skip-gram/neg drains k batches per dispatch;
        # HS and iterations>1 keep per-batch flushes
        k_group = (max(1, conf.steps_per_flush)
                   if (array_path and not use_hs and conf.iterations == 1)
                   else 1)
        if array_path:
            items = self._pair_work_items(sequences, pair_hook, total_words,
                                          k_group)
            # AsyncSequencer role: pair packing on a producer thread,
            # overlapped with the (async) device dispatches. pair_hook
            # runs arbitrary user code against self — keep it on the
            # caller's thread.
            use_async = conf.async_producer and pair_hook is None
            if use_async:
                items = self._produce_async(items)
            loss_dev = self._drain_items(items, sg_flush, sg_flush_tail,
                                         conf.iterations)
        else:
            loss_dev = self._fit_cbow_list_path(
                sequences, pair_hook, total_words, cbow_flush,
                cbow_flush_tail)
        self.syn0 = np.asarray(self.syn0)
        self.syn1 = np.asarray(self.syn1)
        self.syn1neg = np.asarray(self.syn1neg)
        if loss_dev is not None:
            self.last_loss = float(loss_dev)
        return self

    def _pair_work_items(self, sequences, pair_hook, total_words, k_group):
        """Generator of flush work items for the skip-gram array path:
        ("group", c[k,B], x[k,B], lrs[k]) fused groups, ("single",
        c[B], x[B], lr) compiled-shape batches, ("tail", c[<B], x[<B],
        lr) one ragged flush per epoch."""
        conf = self.conf
        B = conf.batch_size
        words_seen = 0
        lr_prev = conf.learning_rate
        for epoch in range(conf.epochs):
            abuf_c, abuf_x, abuf_n = [], [], 0
            for si, tokens in enumerate(sequences):
                frac = words_seen / total_words
                lr = max(conf.learning_rate * (1.0 - frac),
                         conf.min_learning_rate)
                words_seen += len(tokens)
                if pair_hook is not None:
                    new = pair_hook(self, si, tokens)
                    if isinstance(new, list):
                        if not new:
                            continue
                        new = (np.fromiter((p[0] for p in new), np.int32,
                                           len(new)),
                               np.fromiter((p[1] for p in new), np.int32,
                                           len(new)))
                else:
                    new = self._sequence_to_pair_arrays(tokens)
                if new is None:
                    continue
                abuf_c.append(new[0])
                abuf_x.append(new[1])
                abuf_n += len(new[0])
                while abuf_n >= k_group * B:
                    cs = np.concatenate(abuf_c)
                    xs = np.concatenate(abuf_x)
                    take = k_group * B
                    batch_c, rest_c = cs[:take], cs[take:]
                    batch_x, rest_x = xs[:take], xs[take:]
                    abuf_c, abuf_x, abuf_n = [rest_c], [rest_x], len(rest_c)
                    if k_group > 1:
                        # lr interpolated across the group — same decay
                        # granularity the per-batch path would apply
                        lrs = np.linspace(lr_prev, lr, k_group,
                                          dtype=np.float32)
                        yield ("group", batch_c.reshape(k_group, B),
                               batch_x.reshape(k_group, B), lrs)
                    else:
                        yield ("single", batch_c, batch_x, lr)
                    lr_prev = lr
            tail_lr = max(conf.learning_rate * (1 - words_seen / total_words),
                          conf.min_learning_rate)
            if abuf_n:
                cs = np.concatenate(abuf_c)
                xs = np.concatenate(abuf_x)
                # drain full-B batches at the compiled shape, then one
                # ragged tail flush
                while len(cs) >= B:
                    yield ("single", cs[:B], xs[:B], tail_lr)
                    cs, xs = cs[B:], xs[B:]
                if len(cs):
                    yield ("tail", cs, xs, tail_lr)

    def _produce_async(self, items):
        """Run the work-item generator on a producer thread through a
        bounded queue (AsyncSequencer, `SequenceVectors.java:288`).
        Wait accounting lands in `self.etl_stats`: consumer_wait_ms is
        time the device-feeding side starved for host packing (the
        number to drive to ~0), producer_wait_ms is host time absorbed
        by the queue bound while the device was busy (healthy)."""
        import queue as _queue
        import threading

        q = _queue.Queue(maxsize=max(1, self.conf.producer_queue_depth))
        stats = {"producer_wait_ms": 0.0, "consumer_wait_ms": 0.0,
                 "mode": "async"}
        self.etl_stats = stats
        DONE = object()
        stop = threading.Event()   # consumer abandoned (flush raised)

        def produce():
            try:
                for item in items:
                    t0 = time.perf_counter()
                    while not stop.is_set():
                        try:
                            q.put(item, timeout=0.25)
                            break
                        except _queue.Full:
                            continue
                    if stop.is_set():
                        return
                    stats["producer_wait_ms"] += (
                        (time.perf_counter() - t0) * 1e3)
                q.put(DONE)
            except BaseException as e:   # surface in the consumer
                q.put(("__error__", e))

        t = threading.Thread(target=produce, daemon=True,
                             name="sequencevectors-producer")
        t.start()
        try:
            while True:
                t0 = time.perf_counter()
                item = q.get()
                stats["consumer_wait_ms"] += (time.perf_counter() - t0) * 1e3
                if item is DONE:
                    break
                if isinstance(item, tuple) and item[0] == "__error__":
                    raise item[1]
                yield item
        finally:
            # a raising flush closes this generator mid-iteration: wake
            # the producer out of its bounded put so the thread (and
            # its queued batches) cannot leak
            stop.set()
            t.join()

    def _drain_items(self, items, sg_flush, sg_flush_tail, iterations):
        loss_dev = None
        if self.etl_stats is None:
            self.etl_stats = {"mode": "sync"}
        for kind, c, x, lr in items:
            if kind == "group":
                loss_dev = self._flush_sg_neg_multi(c, x, lr)
            elif kind == "single":
                for _ in range(iterations):
                    loss_dev = sg_flush(c, x, lr)
            else:
                for _ in range(iterations):
                    loss_dev = sg_flush_tail(c, x, lr)
        return loss_dev

    def _fit_cbow_list_path(self, sequences, pair_hook, total_words,
                            cbow_flush, cbow_flush_tail):
        conf = self.conf
        B = conf.batch_size
        words_seen = 0
        loss_dev = None
        for epoch in range(conf.epochs):
            lbuf = []
            for si, tokens in enumerate(sequences):
                frac = words_seen / total_words
                lr = max(conf.learning_rate * (1.0 - frac),
                         conf.min_learning_rate)
                words_seen += len(tokens)
                if pair_hook is not None:
                    new = pair_hook(self, si, tokens)
                else:
                    new = self._sequence_to_pairs(tokens)
                lbuf.extend(new)
                while len(lbuf) >= B:
                    batch, lbuf = lbuf[:B], lbuf[B:]
                    for _ in range(conf.iterations):
                        loss_dev = cbow_flush(batch, lr)
            tail_lr = max(conf.learning_rate * (1 - words_seen / total_words),
                          conf.min_learning_rate)
            if lbuf:
                for _ in range(conf.iterations):
                    loss_dev = cbow_flush_tail(lbuf, tail_lr)
        return loss_dev

    # ------------------------------------------------------------- queries
    def get_word_vector(self, word: str):
        i = self.vocab.index_of(word)
        return None if i < 0 else np.asarray(self.syn0[i])

    def has_word(self, word: str) -> bool:
        return self.vocab is not None and self.vocab.contains_word(word)

    def _unit_table(self):
        t = np.asarray(self.syn0[:self.vocab.num_words()])
        norms = np.linalg.norm(t, axis=1, keepdims=True)
        return t / np.clip(norms, 1e-9, None)

    def similarity(self, w1: str, w2: str) -> float:
        v1, v2 = self.get_word_vector(w1), self.get_word_vector(w2)
        if v1 is None or v2 is None:
            return float("nan")
        denom = np.linalg.norm(v1) * np.linalg.norm(v2)
        return float(np.dot(v1, v2) / denom) if denom > 0 else 0.0

    def words_nearest(self, word_or_vec, top_n: int = 10) -> List[str]:
        if isinstance(word_or_vec, str):
            vec = self.get_word_vector(word_or_vec)
            exclude = {word_or_vec}
        else:
            vec, exclude = np.asarray(word_or_vec), set()
        if vec is None:
            return []
        unit = self._unit_table()
        q = vec / max(np.linalg.norm(vec), 1e-9)
        sims = unit @ q
        order = np.argsort(-sims)
        out = []
        for i in order:
            w = self.vocab.word_at_index(int(i))
            if w not in exclude:
                out.append(w)
            if len(out) >= top_n:
                break
        return out
