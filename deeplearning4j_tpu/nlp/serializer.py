"""Word-vector serialization.

Reference: `models/embeddings/loader/WordVectorSerializer.java`
(2,824 LoC) — Google word2vec binary + text formats and DL4J's own
formats. The two interchange formats implemented here are the ones
other tools read/write:

- Google BINARY: header "V D\\n", then per word: "word " + D float32 LE
  + "\\n" (`writeWordVectors`/`readBinaryModel` semantics)
- TEXT: one "word v1 v2 ... vD" line per word (`loadTxtVectors`)
"""

from __future__ import annotations

import struct
from pathlib import Path

import numpy as np

from deeplearning4j_tpu.nlp.sequencevectors import SequenceVectors, SequenceVectorsConfig
from deeplearning4j_tpu.nlp.vocab import VocabCache, VocabWord


def _check_word(word: str) -> str:
    """Both interchange formats delimit words by whitespace; tokens with
    spaces/newlines (e.g. n-grams) cannot round-trip — fail at write
    time rather than corrupt the file."""
    if any(c.isspace() for c in word):
        raise ValueError(
            f"word {word!r} contains whitespace — not representable in the "
            "word2vec text/binary formats (join n-grams with '_' first)")
    return word


class WordVectorSerializer:
    # ----------------------------------------------------------- binary
    @staticmethod
    def write_binary(vectors: SequenceVectors, path):
        path = Path(path)
        V = vectors.vocab.num_words()
        D = vectors.conf.vector_length
        with open(path, "wb") as f:
            f.write(f"{V} {D}\n".encode())
            for i in range(V):
                word = _check_word(vectors.vocab.word_at_index(i))
                f.write(word.encode("utf-8") + b" ")
                f.write(np.asarray(vectors.syn0[i], np.float32).tobytes())
                f.write(b"\n")

    @staticmethod
    def read_binary(path) -> SequenceVectors:
        path = Path(path)
        with open(path, "rb") as f:
            header = b""
            while not header.endswith(b"\n"):
                header += f.read(1)
            V, D = (int(x) for x in header.split())
            words, rows = [], []
            for _ in range(V):
                word = b""
                while True:
                    ch = f.read(1)
                    if ch in (b" ", b""):
                        break
                    word += ch
                words.append(word.decode("utf-8"))
                rows.append(np.frombuffer(f.read(4 * D), np.float32))
                nl = f.read(1)
                if nl not in (b"\n", b""):  # some writers omit the newline
                    f.seek(-1, 1)
        cache = VocabCache()
        for w in words:
            cache.add_token(VocabWord(w))
        cache.finalize_vocab()
        table = np.zeros((V, D), np.float32)
        for w, r in zip(words, rows):
            table[cache.index_of(w)] = r
        return WordVectorSerializer._assemble(cache, table, path)

    # ------------------------------------------------------------- text
    @staticmethod
    def write_text(vectors: SequenceVectors, path):
        with open(path, "w", encoding="utf-8") as f:
            for i in range(vectors.vocab.num_words()):
                vec = " ".join(f"{v:.6f}" for v in np.asarray(vectors.syn0[i]))
                f.write(f"{_check_word(vectors.vocab.word_at_index(i))} {vec}\n")

    @staticmethod
    def read_text(path) -> SequenceVectors:
        words, rows = [], []
        first = True
        with open(path, "r", encoding="utf-8") as f:
            for line in f:
                parts = line.rstrip("\n").split(" ")
                if not line.strip():
                    continue
                if (first and len(parts) == 2
                        and parts[0].isdigit() and parts[1].isdigit()):
                    first = False
                    continue  # optional "V D" header
                first = False
                words.append(parts[0])
                rows.append(np.array([float(x) for x in parts[1:]], np.float32))
        if not rows:
            raise ValueError(f"{path}: no vectors found")
        cache = VocabCache()
        for w in words:
            cache.add_token(VocabWord(w))
        cache.finalize_vocab()
        table = np.zeros((len(words), len(rows[0])), np.float32)
        for w, r in zip(words, rows):
            table[cache.index_of(w)] = r
        return WordVectorSerializer._assemble(cache, table, path)

    @staticmethod
    def _assemble(cache: VocabCache, table: np.ndarray, path) -> SequenceVectors:
        # finalize_vocab may reorder by frequency (all 1.0 → ties by word);
        # reindex table rows to the cache order
        sv = SequenceVectors(SequenceVectorsConfig(vector_length=table.shape[1]))
        sv.vocab = cache
        sv.syn0 = table
        sv.syn1neg = np.zeros_like(table)
        sv.syn1 = np.zeros_like(table)
        return sv
