"""Tokenizers, token preprocessors, factories.

Reference: `text/tokenization/tokenizer/*` +
`tokenizerfactory/DefaultTokenizerFactory.java` — Tokenizer iterates
tokens of one string; TokenPreProcess normalises each token; factories
stamp out configured tokenizers per sentence. (UIMA/Kuromoji/ansj
language plug-ins are third-party segmenters in the reference; the
factory protocol here is the plug-in point for equivalents.)
"""

from __future__ import annotations

import re
from typing import Callable, List, Optional


class TokenPreProcess:
    def pre_process(self, token: str) -> str:
        raise NotImplementedError


class CommonPreprocessor(TokenPreProcess):
    """Lowercase + strip punctuation/specials (reference
    `preprocessor/CommonPreprocessor.java`)."""

    _PUNCT = re.compile(r"[\d\.:,\"'\(\)\[\]|/?!;]+")

    def pre_process(self, token: str) -> str:
        return self._PUNCT.sub("", token.lower())


class EndingPreProcessor(TokenPreProcess):
    """Crude stemmer for plurals/edges (reference
    `preprocessor/EndingPreProcessor.java`)."""

    def pre_process(self, token: str) -> str:
        if token.endswith("s") and not token.endswith("ss"):
            token = token[:-1]
        if token.endswith("."):
            token = token[:-1]
        if token.endswith("ly"):
            token = token[:-2]
        if token.endswith("ing"):
            token = token[:-3]
        return token


class Tokenizer:
    """Token stream over one sentence (reference `tokenizer/Tokenizer.java`)."""

    def __init__(self, tokens: List[str], preprocessor: Optional[TokenPreProcess] = None):
        self._tokens = tokens
        self._pre = preprocessor
        self._idx = 0

    def has_more_tokens(self) -> bool:
        return self._idx < len(self._tokens)

    def count_tokens(self) -> int:
        return len(self._tokens)

    def next_token(self) -> str:
        tok = self._tokens[self._idx]
        self._idx += 1
        return self._pre.pre_process(tok) if self._pre else tok

    def get_tokens(self) -> List[str]:
        out = []
        while self.has_more_tokens():
            t = self.next_token()
            if t:
                out.append(t)
        return out


class DefaultTokenizer(Tokenizer):
    """Whitespace tokenizer (reference `DefaultTokenizer.java` uses
    StringTokenizer)."""

    def __init__(self, sentence: str, preprocessor=None):
        super().__init__(sentence.split(), preprocessor)


class NGramTokenizer(Tokenizer):
    """Sliding n-gram tokens (reference `NGramTokenizer.java`)."""

    def __init__(self, sentence: str, min_n: int, max_n: int, preprocessor=None):
        base = DefaultTokenizer(sentence, preprocessor).get_tokens()
        tokens = list(base) if min_n == 1 else []
        for n in range(max(2, min_n), max_n + 1):
            for i in range(len(base) - n + 1):
                tokens.append(" ".join(base[i:i + n]))
        super().__init__(tokens, None)


class TokenizerFactory:
    def create(self, sentence: str) -> Tokenizer:
        raise NotImplementedError

    def set_token_pre_processor(self, pre: TokenPreProcess) -> "TokenizerFactory":
        self._pre = pre
        return self


class DefaultTokenizerFactory(TokenizerFactory):
    def __init__(self, preprocessor: Optional[TokenPreProcess] = None):
        self._pre = preprocessor

    def create(self, sentence: str) -> Tokenizer:
        return DefaultTokenizer(sentence, self._pre)


class NGramTokenizerFactory(TokenizerFactory):
    def __init__(self, min_n: int = 1, max_n: int = 2,
                 preprocessor: Optional[TokenPreProcess] = None):
        self.min_n, self.max_n = min_n, max_n
        self._pre = preprocessor

    def create(self, sentence: str) -> Tokenizer:
        return NGramTokenizer(sentence, self.min_n, self.max_n, self._pre)
