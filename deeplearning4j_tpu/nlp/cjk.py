"""Dictionary-driven CJK segmentation through the TokenizerFactory seam.

Reference role: `deeplearning4j-nlp-chinese` (bundles the ansj
segmenter, ~9.5k LoC) and `deeplearning4j-nlp-japanese` (bundles
kuromoji, `com/atilika/kuromoji/`, ~6.8k LoC) ship TokenizerFactory
implementations whose `create()` runs a real segmenter instead of
whitespace splitting. Those engines are third-party dictionaries+code;
what this module reproduces is the *capability*: a working
non-whitespace segmenter driving the same seam, so CJK corpora train
through Word2Vec/SequenceVectors unchanged.

Algorithm: unigram-frequency DP over the word lattice (the same shape
ansj/jieba use): every dictionary word starting at position i adds an
edge i→i+len(w) with cost -log p(w); unknown single characters get a
floor probability; the min-cost path is the segmentation. Viterbi over
a DAG — O(n · max_word_len).
"""

from __future__ import annotations

import math
from typing import Dict, Iterable, List, Optional, Tuple

from deeplearning4j_tpu.nlp.tokenization import (
    TokenPreProcess,
    Tokenizer,
    TokenizerFactory,
)

# Characters that never join words: CJK + ASCII punctuation, whitespace.
_PUNCT = set("，。！？；：、「」『』（）《》·…—,.!?;:()[]{}\"' \t\n\r")


class DictionarySegmenter:
    """Unigram-DP word segmenter over a frequency dictionary."""

    def __init__(self, freqs: Dict[str, float],
                 unknown_log_prob: float = -13.0):
        if not freqs:
            raise ValueError("empty dictionary")
        self.max_len = max(len(w) for w in freqs)
        total = float(sum(freqs.values()))
        self._logp = {w: math.log(f / total) for w, f in freqs.items()}
        self.unknown_log_prob = unknown_log_prob

    @classmethod
    def from_word_list(cls, words: Iterable[str], **kw):
        """Uniform frequencies; longer words still win via fewer edges."""
        return cls({w: 1.0 for w in words}, **kw)

    def segment(self, text: str) -> List[str]:
        out: List[str] = []
        for run in self._runs(text):
            if len(run) == 1 or self._is_foreign(run):
                out.append(run)
            else:
                out.extend(self._dp(run))
        return out

    # ---------------------------------------------------------------- impl
    @staticmethod
    def _is_foreign(run: str) -> bool:
        # whitespace-delimited latin/number runs pass through whole
        return all(ord(c) < 0x2E80 for c in run)

    @staticmethod
    def _runs(text: str):
        """Split into maximal runs of non-punctuation, also breaking at
        script boundaries so embedded latin/number tokens ("GPU和TPU")
        pass through whole instead of entering the CJK lattice."""
        cur: List[str] = []
        cur_foreign = False
        for c in text:
            if c in _PUNCT:
                if cur:
                    yield "".join(cur)
                    cur = []
                continue
            foreign = ord(c) < 0x2E80
            if cur and foreign != cur_foreign:
                yield "".join(cur)
                cur = []
            cur.append(c)
            cur_foreign = foreign
        if cur:
            yield "".join(cur)

    def _dp(self, run: str) -> List[str]:
        n = len(run)
        # best[i] = (cost to segment run[:i], start of last word)
        INF = float("inf")
        best_cost = [INF] * (n + 1)
        best_prev = [0] * (n + 1)
        best_cost[0] = 0.0
        for i in range(n):
            if best_cost[i] == INF:
                continue
            # unknown single char — floor edge keeps the DP connected
            c1 = best_cost[i] - self.unknown_log_prob
            if c1 < best_cost[i + 1]:
                best_cost[i + 1] = c1
                best_prev[i + 1] = i
            for L in range(1, min(self.max_len, n - i) + 1):
                w = run[i:i + L]
                lp = self._logp.get(w)
                if lp is None:
                    continue
                c = best_cost[i] - lp
                if c < best_cost[i + L]:
                    best_cost[i + L] = c
                    best_prev[i + L] = i
        words = []
        j = n
        while j > 0:
            i = best_prev[j]
            words.append(run[i:j])
            j = i
        words.reverse()
        return words


class CJKTokenizer(Tokenizer):
    def __init__(self, sentence: str, segmenter: DictionarySegmenter,
                 preprocessor: Optional[TokenPreProcess] = None):
        super().__init__(segmenter.segment(sentence), preprocessor)


class CJKTokenizerFactory(TokenizerFactory):
    """The nlp-chinese/-japanese TokenizerFactory role: constructed
    from a frequency dictionary (or plain word list), produces
    tokenizers that really segment."""

    def __init__(self, dictionary, preprocessor: Optional[TokenPreProcess] = None):
        if isinstance(dictionary, DictionarySegmenter):
            self.segmenter = dictionary
        elif isinstance(dictionary, dict):
            self.segmenter = DictionarySegmenter(dictionary)
        else:
            self.segmenter = DictionarySegmenter.from_word_list(dictionary)
        self.preprocessor = preprocessor

    def create(self, sentence: str) -> Tokenizer:
        return CJKTokenizer(sentence, self.segmenter, self.preprocessor)

    def set_token_pre_processor(self, pre: TokenPreProcess):
        self.preprocessor = pre
        return self
