"""GloVe embeddings.

Reference: `models/glove/Glove.java` + `AbstractCoOccurrences.java`
(646 LoC): windowed co-occurrence counting pass, then AdaGrad descent
on the weighted least-squares objective
f(X_ij)(w_i·w̃_j + b_i + b̃_j − log X_ij)².

TPU realisation: co-occurrence counting on host (sparse dict), then the
whole optimisation runs as jitted minibatch AdaGrad steps over the
non-zero entries — gathers + fused elementwise, scatter-add updates.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nlp.sequencevectors import SequenceVectors, SequenceVectorsConfig
from deeplearning4j_tpu.nlp.vocab import VocabConstructor


from deeplearning4j_tpu.nd.donation import jit_donated as _jit_donated


@_jit_donated(donate=(0, 1, 2, 3, 4, 5, 6, 7))
def _glove_step(w, wt, b, bt, gw, gwt, gb, gbt, rows, cols, logx, weight, lr):
    """One AdaGrad step on a batch of non-zero co-occurrence cells."""

    def loss_fn(w_, wt_, b_, bt_):
        wi = jnp.take(w_, rows, axis=0)
        wj = jnp.take(wt_, cols, axis=0)
        pred = jnp.sum(wi * wj, axis=-1) + jnp.take(b_, rows) + jnp.take(bt_, cols)
        return jnp.sum(weight * (pred - logx) ** 2)

    loss, grads = jax.value_and_grad(loss_fn, argnums=(0, 1, 2, 3))(w, wt, b, bt)
    outs = []
    for p, g, acc in ((w, grads[0], gw), (wt, grads[1], gwt),
                      (b, grads[2], gb), (bt, grads[3], gbt)):
        acc = acc + g * g
        p = p - lr * g / jnp.sqrt(acc + 1e-8)
        outs.extend([p, acc])
    w, gw, wt, gwt, b, gb, bt, gbt = outs
    return w, wt, b, bt, gw, gwt, gb, gbt, loss


class Glove(SequenceVectors):
    def __init__(self, layer_size: int = 100, window: int = 5,
                 min_word_frequency: int = 1, learning_rate: float = 0.05,
                 epochs: int = 5, x_max: float = 100.0, alpha: float = 0.75,
                 batch_size: int = 8192, symmetric: bool = True, seed: int = 42):
        super().__init__(SequenceVectorsConfig(
            vector_length=layer_size, window=window,
            min_word_frequency=min_word_frequency,
            learning_rate=learning_rate, epochs=epochs,
            batch_size=batch_size, seed=seed))
        self.x_max = x_max
        self.alpha = alpha
        self.symmetric = symmetric

    def _count_cooccurrences(self, sequences) -> Dict[Tuple[int, int], float]:
        """Windowed 1/d-weighted counts (reference
        `AbstractCoOccurrences.java`)."""
        counts: Dict[Tuple[int, int], float] = {}
        w = self.conf.window
        for tokens in sequences:
            idxs = [self.vocab.index_of(t) for t in tokens]
            idxs = [i for i in idxs if i >= 0]
            for pos, i in enumerate(idxs):
                for off in range(1, w + 1):
                    if pos + off >= len(idxs):
                        break
                    j = idxs[pos + off]
                    inc = 1.0 / off
                    counts[(i, j)] = counts.get((i, j), 0.0) + inc
                    if self.symmetric:
                        counts[(j, i)] = counts.get((j, i), 0.0) + inc
        return counts

    def fit(self, sequences, **_):
        sequences = list(sequences)
        self.build_vocab(sequences)
        V, D = self.vocab.num_words(), self.conf.vector_length
        rng = self._rng
        counts = self._count_cooccurrences(sequences)
        items = list(counts.items())
        rows = np.array([ij[0] for ij, _ in items], np.int32)
        cols = np.array([ij[1] for ij, _ in items], np.int32)
        xs = np.array([x for _, x in items], np.float32)
        logx = np.log(xs)
        weight = np.minimum((xs / self.x_max) ** self.alpha, 1.0).astype(np.float32)

        scale = 0.5 / D
        w = (rng.random((V, D), np.float32) - 0.5) * 2 * scale
        wt = (rng.random((V, D), np.float32) - 0.5) * 2 * scale
        b = np.zeros((V,), np.float32)
        bt = np.zeros((V,), np.float32)
        gw = np.ones_like(w); gwt = np.ones_like(wt)
        gb = np.ones_like(b); gbt = np.ones_like(bt)

        B = self.conf.batch_size
        n = len(items)
        self.last_loss = 0.0
        for _ in range(self.conf.epochs):
            order = rng.permutation(n)
            for s in range(0, n, B):
                sel = order[s:s + B]
                (w, wt, b, bt, gw, gwt, gb, gbt, loss) = _glove_step(
                    w, wt, b, bt, gw, gwt, gb, gbt,
                    rows[sel], cols[sel], logx[sel], weight[sel],
                    np.float32(self.conf.learning_rate))
                self.last_loss = float(loss) / max(len(sel), 1)
        # final embeddings = w + wt (GloVe paper / reference convention)
        self.syn0 = np.asarray(w) + np.asarray(wt)
        self.syn1neg = np.zeros_like(self.syn0)
        self.syn1 = np.zeros_like(self.syn0)
        return self
