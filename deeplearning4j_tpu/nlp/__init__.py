"""NLP stack (reference: deeplearning4j-nlp-parent, SURVEY.md §2.5).

SequenceVectors engine redesigned TPU-first: instead of the reference's
Hogwild `VectorCalculationsThread`s doing lock-free scalar updates
(`SequenceVectors.java:294-296`), training batches (center, context,
negatives) pairs on the host and runs ONE jitted device step per batch
— gathers + matmuls + scatter-adds that XLA fuses; same capability
(skip-gram/CBOW, hierarchical softmax + negative sampling, subsampling,
lr decay), a schedule that actually maps to the MXU.
"""

from deeplearning4j_tpu.nlp.tokenization import (
    Tokenizer,
    DefaultTokenizer,
    NGramTokenizer,
    TokenizerFactory,
    DefaultTokenizerFactory,
    NGramTokenizerFactory,
    CommonPreprocessor,
    EndingPreProcessor,
)
from deeplearning4j_tpu.nlp.sentenceiterator import (
    SentenceIterator,
    BasicLineIterator,
    CollectionSentenceIterator,
    FileSentenceIterator,
    LabelledDocument,
    LabelAwareIterator,
    SimpleLabelAwareIterator,
)
from deeplearning4j_tpu.nlp.vocab import VocabWord, VocabCache, VocabConstructor
from deeplearning4j_tpu.nlp.sequencevectors import SequenceVectors, SequenceVectorsConfig
from deeplearning4j_tpu.nlp.word2vec import Word2Vec
from deeplearning4j_tpu.nlp.paragraphvectors import ParagraphVectors
from deeplearning4j_tpu.nlp.glove import Glove
from deeplearning4j_tpu.nlp.serializer import WordVectorSerializer
from deeplearning4j_tpu.nlp.bagofwords import CountVectorizer, TfidfVectorizer
from deeplearning4j_tpu.nlp.iterator import CnnSentenceDataSetIterator
from deeplearning4j_tpu.nlp.stopwords import StopWords, StopWordsRemover
from deeplearning4j_tpu.nlp.invertedindex import InvertedIndex
from deeplearning4j_tpu.nlp.documentiterator import (
    CollectionDocumentIterator,
    DocumentIterator,
    FileDocumentIterator,
    FileLabelAwareIterator,
    FilenamesLabelAwareIterator,
)
