"""Inverted index over tokenized documents.

Reference: `text/invertedindex/InvertedIndex.java` (the Lucene-backed
`LuceneInvertedIndex` implementation): word → documents containing it,
document → word list, batch/mini-batch sampling for embedding trainers.
Here: plain in-memory postings (word index → sorted doc ids + term
frequencies) built on the same VocabCache vocabulary the embedding
engines use — no Lucene; the TPU pipeline consumes fixed-shape batches,
so the index's job is lookup + corpus statistics, not on-disk search.
"""

from __future__ import annotations

from collections import defaultdict
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from deeplearning4j_tpu.nlp.vocab import VocabCache


class InvertedIndex:
    def __init__(self, vocab: Optional[VocabCache] = None):
        self.vocab = vocab
        self._docs: List[List[str]] = []
        self._postings: Dict[str, Dict[int, int]] = defaultdict(dict)
        self._doc_labels: Dict[int, List[str]] = {}

    # ------------------------------------------------------------- building
    def add_word_to_doc(self, doc_id: int, word: str):
        """Reference `addWordToDoc`."""
        while len(self._docs) <= doc_id:
            self._docs.append([])
        self._docs[doc_id].append(word)
        self._postings[word][doc_id] = self._postings[word].get(doc_id, 0) + 1

    def add_doc(self, tokens: Sequence[str],
                labels: Optional[List[str]] = None) -> int:
        """Reference `addWordsToDoc`; returns the new doc id."""
        doc_id = len(self._docs)
        self._docs.append(list(tokens))
        for t in tokens:
            self._postings[t][doc_id] = self._postings[t].get(doc_id, 0) + 1
        if labels:
            self._doc_labels[doc_id] = list(labels)
        return doc_id

    def index(self, documents: Iterable[Sequence[str]]):
        for tokens in documents:
            self.add_doc(tokens)
        return self

    # -------------------------------------------------------------- queries
    def document(self, doc_id: int) -> List[str]:
        """Reference `document(index)` — the token list."""
        return list(self._docs[doc_id])

    def documents(self, word: str) -> List[int]:
        """Reference `documents(vocabWord)` — sorted doc ids containing
        the word."""
        return sorted(self._postings.get(word, {}))

    def doc_labels(self, doc_id: int) -> List[str]:
        return list(self._doc_labels.get(doc_id, []))

    def term_frequency(self, word: str, doc_id: int) -> int:
        return self._postings.get(word, {}).get(doc_id, 0)

    def document_frequency(self, word: str) -> int:
        return len(self._postings.get(word, {}))

    def total_words(self) -> int:
        """Reference `totalWords()`."""
        return sum(len(d) for d in self._docs)

    def num_documents(self) -> int:
        return len(self._docs)

    def words(self) -> List[str]:
        return sorted(self._postings)

    # ---------------------------------------------------- trainer interface
    def batch_doc_ids(self, batch_size: int) -> Iterable[List[int]]:
        """Mini-batch doc-id slices (the role of the reference's
        `batchIter`/miniBatchSize machinery feeding SequenceVectors)."""
        ids = list(range(len(self._docs)))
        for i in range(0, len(ids), batch_size):
            yield ids[i:i + batch_size]

    def eachDocWithLabels(self) -> Iterable[Tuple[List[str], List[str]]]:
        for i in range(len(self._docs)):
            yield self.document(i), self.doc_labels(i)

    def __iter__(self):
        return iter(self._docs)
