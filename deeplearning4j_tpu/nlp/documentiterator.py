"""Document iterators.

Reference: `text/documentiterator/` — `DocumentIterator.java` (stream
per document), `FileDocumentIterator.java` (one file = one document),
`FileLabelAwareIterator.java` (subdirectory name = label),
`FilenamesLabelAwareIterator.java` (filename = label). These feed
ParagraphVectors and the bag-of-words vectorizers; here they yield
plain strings / LabelledDocument so they plug into the same pipelines
as the sentence iterators.
"""

from __future__ import annotations

import os
from typing import Iterable, List, Optional

from deeplearning4j_tpu.nlp.sentenceiterator import (
    LabelAwareIterator,
    LabelledDocument,
)


class DocumentIterator:
    """One string per document (reference `DocumentIterator.java`)."""

    def has_next(self) -> bool:
        raise NotImplementedError

    def next_document(self) -> str:
        raise NotImplementedError

    def reset(self) -> None:
        raise NotImplementedError

    def __iter__(self):
        self.reset()
        while self.has_next():
            yield self.next_document()


class CollectionDocumentIterator(DocumentIterator):
    def __init__(self, documents: Iterable[str]):
        self._docs = list(documents)
        self._idx = 0

    def has_next(self):
        return self._idx < len(self._docs)

    def next_document(self):
        d = self._docs[self._idx]
        self._idx += 1
        return d

    def reset(self):
        self._idx = 0


class FileDocumentIterator(DocumentIterator):
    """Each file under `root` (recursively, sorted) is one document
    (reference `FileDocumentIterator.java`)."""

    def __init__(self, root: str, encoding: str = "utf-8"):
        self.root = root
        self.encoding = encoding
        self._paths: List[str] = []
        for dirpath, _, files in sorted(os.walk(root)):
            for f in sorted(files):
                self._paths.append(os.path.join(dirpath, f))
        self._idx = 0

    def has_next(self):
        return self._idx < len(self._paths)

    def next_document(self):
        p = self._paths[self._idx]
        self._idx += 1
        with open(p, encoding=self.encoding) as f:
            return f.read()

    def reset(self):
        self._idx = 0


class FileLabelAwareIterator(LabelAwareIterator):
    """`root/<label>/<file>` layout: the subdirectory name is the
    document label (reference `FileLabelAwareIterator.java`)."""

    def __init__(self, root: str, encoding: str = "utf-8"):
        self.root = root
        self.encoding = encoding
        self._entries: List[tuple] = []
        for label in sorted(os.listdir(root)):
            d = os.path.join(root, label)
            if not os.path.isdir(d):
                continue
            for f in sorted(os.listdir(d)):
                p = os.path.join(d, f)
                if os.path.isfile(p):
                    self._entries.append((p, label))
        self._idx = 0

    def has_next(self):
        return self._idx < len(self._entries)

    def next_document(self) -> LabelledDocument:
        p, label = self._entries[self._idx]
        self._idx += 1
        with open(p, encoding=self.encoding) as f:
            return LabelledDocument(f.read(), [label])

    def reset(self):
        self._idx = 0


class FilenamesLabelAwareIterator(LabelAwareIterator):
    """Each file is a document labelled by its own (base)name —
    reference `FilenamesLabelAwareIterator.java`."""

    def __init__(self, root: str, encoding: str = "utf-8",
                 strip_extension: bool = True):
        self.root = root
        self.encoding = encoding
        self.strip_extension = strip_extension
        self._paths = []
        for dirpath, _, files in sorted(os.walk(root)):
            for f in sorted(files):
                self._paths.append(os.path.join(dirpath, f))
        self._idx = 0

    def has_next(self):
        return self._idx < len(self._paths)

    def next_document(self) -> LabelledDocument:
        p = self._paths[self._idx]
        self._idx += 1
        name = os.path.basename(p)
        if self.strip_extension and "." in name:
            name = name.rsplit(".", 1)[0]
        with open(p, encoding=self.encoding) as f:
            return LabelledDocument(f.read(), [name])

    def reset(self):
        self._idx = 0
