"""Transformer encoder zoo models (beyond-reference: the 2017 zoo tops
out at InceptionResNet/LSTMs; this is the long-context flagship the TPU
rebuild adds, riding the Pallas flash-attention fast path and — over a
mesh — ring/Ulysses sequence parallelism).

Two configurations:
- `TransformerClassifier`: token ids → embedding + positions → N
  encoder blocks → masked global average pool → softmax.
- `TransformerLM`: causal blocks → per-position softmax over the
  vocabulary (RnnOutputLayer), the TextGenerationLSTM successor.
"""

from __future__ import annotations

from deeplearning4j_tpu.common.updaters import Adam
from deeplearning4j_tpu.common.weights import WeightInit
from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import (
    EmbeddingLayer,
    GlobalPoolingLayer,
    OutputLayer,
    PositionalEncodingLayer,
    RnnOutputLayer,
    TransformerEncoderBlock,
)
from deeplearning4j_tpu.nn.layers.pooling import PoolingType
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.zoo.base import ZooModel


class TransformerClassifier(ZooModel):
    def __init__(self, vocab_size: int, num_classes: int, *,
                 d_model: int = 128, n_layers: int = 2, n_heads: int = 8,
                 ff_multiplier: int = 4, max_len: int = 512,
                 dropout: float = None, pooling: PoolingType = PoolingType.AVG,
                 remat: bool = False, remat_policy: str = None,
                 sequence_parallel: str = None, seed: int = 123):
        super().__init__(num_classes=num_classes, seed=seed)
        self.vocab_size = vocab_size
        self.d_model = d_model
        self.n_layers = n_layers
        self.n_heads = n_heads
        self.ff_multiplier = ff_multiplier
        self.max_len = max_len
        self.dropout = dropout
        self.pooling = pooling
        self.remat = remat
        self.remat_policy = remat_policy
        self.sequence_parallel = sequence_parallel

    def conf(self):
        b = (NeuralNetConfiguration.builder()
             .seed(self.seed).updater(Adam(1e-3))
             .weight_init(WeightInit.XAVIER)
             .list()
             .layer(EmbeddingLayer(n_in=self.vocab_size, n_out=self.d_model))
             .layer(PositionalEncodingLayer(max_len=self.max_len)))
        # the block run scans by default (scan-over-layers — identical
        # blocks roll into one lax.scan; nn/scan_stack.py)
        for _ in range(self.n_layers):
            b.layer(TransformerEncoderBlock(
                n_heads=self.n_heads, ff_multiplier=self.ff_multiplier,
                dropout=self.dropout, remat=self.remat,
                remat_policy=self.remat_policy,
                sequence_parallel=self.sequence_parallel))
        b.layer(GlobalPoolingLayer(pooling_type=self.pooling))
        b.layer(OutputLayer(n_out=self.num_classes, activation="softmax",
                            loss="mcxent"))
        b.set_input_type(InputType.recurrent(self.vocab_size))
        return b.build()

    def init(self) -> MultiLayerNetwork:
        return MultiLayerNetwork(self.conf()).init(self.seed)


class TransformerLM(ZooModel):
    def __init__(self, vocab_size: int, *, d_model: int = 128,
                 n_layers: int = 2, n_heads: int = 8,
                 ff_multiplier: int = 4, max_len: int = 512,
                 remat: bool = False, remat_policy: str = None,
                 sequence_parallel: str = None, seed: int = 123):
        super().__init__(num_classes=vocab_size, seed=seed)
        self.vocab_size = vocab_size
        self.d_model = d_model
        self.n_layers = n_layers
        self.n_heads = n_heads
        self.ff_multiplier = ff_multiplier
        self.max_len = max_len
        self.remat = remat
        self.remat_policy = remat_policy
        self.sequence_parallel = sequence_parallel

    def conf(self):
        b = (NeuralNetConfiguration.builder()
             .seed(self.seed).updater(Adam(1e-3))
             .weight_init(WeightInit.XAVIER)
             .list()
             .layer(EmbeddingLayer(n_in=self.vocab_size, n_out=self.d_model))
             .layer(PositionalEncodingLayer(max_len=self.max_len)))
        # identical causal blocks — the containers roll this run into
        # one lax.scan by default (scan-over-layers, nn/scan_stack.py)
        for _ in range(self.n_layers):
            b.layer(TransformerEncoderBlock(
                n_heads=self.n_heads, ff_multiplier=self.ff_multiplier,
                causal=True, remat=self.remat,
                remat_policy=self.remat_policy, cache_len=self.max_len,
                sequence_parallel=self.sequence_parallel))
        b.layer(RnnOutputLayer(n_out=self.vocab_size, activation="softmax",
                               loss="mcxent"))
        b.set_input_type(InputType.recurrent(self.vocab_size))
        return b.build()

    def init(self) -> MultiLayerNetwork:
        return MultiLayerNetwork(self.conf()).init(self.seed)


def _check_cache_budget(net, prompt_len: int, n_tokens: int):
    """The fixed-size KV caches silently clamp writes past their length
    (dynamic_update_slice semantics), which would corrupt every token
    beyond the limit while still emitting valid-looking ids — so both
    decoders enforce the budget eagerly where the lengths are known."""
    from deeplearning4j_tpu.nn.layers.transformer import stream_budget
    budget = stream_budget(net.layers)
    total = prompt_len + n_tokens
    if budget is not None and total > budget:
        raise ValueError(
            f"prompt ({prompt_len}) + n_tokens ({n_tokens}) = {total} "
            f"exceeds the decode budget {budget} (min over KV cache "
            f"lengths and positional-encoding max_len); decode fewer "
            f"tokens or rebuild with a larger max_len")


def filter_logits(logits, top_k, top_p):
    """Shared vocabulary filters for sampled decoding — `generate()`'s
    fused scan AND the serving engine's per-slot sampler run THIS body
    (one copy; the chains must not drift). `top_k` is static
    (lax.top_k), `top_p` rides TRACED — a scalar (generate: sweeping p
    reuses one executable) or a per-row column (serving: per-slot p).
    Nucleus rule: keep tokens whose PRECEDING cumulative mass is < p
    (the most probable token always survives)."""
    import jax
    import jax.numpy as jnp

    if top_k is not None:
        kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits >= kth, logits, -jnp.inf)
    if top_p is not None:
        sorted_l = jnp.sort(logits, axis=-1)[..., ::-1]
        sp = jax.nn.softmax(sorted_l, axis=-1)
        keep_sorted = (jnp.cumsum(sp, axis=-1) - sp) < top_p
        cutoff = jnp.min(jnp.where(keep_sorted, sorted_l, jnp.inf),
                         axis=-1, keepdims=True)
        logits = jnp.where(logits >= cutoff, logits, -jnp.inf)
    return logits


def get_prefill(net: MultiLayerNetwork):
    """The cached prompt-prefill jit shared by `generate`, `beam_search`
    and the serving tier's admission path (serving/engine.py): one XLA
    program per (batch, prompt-length) shape that runs the full forward
    with KV-cache carries and returns ([B, V] next-token probs, the
    filled carries). Int8-quantized params trees (nd/quant.py) key
    their own trace of the same jit — the program then reads int8
    weights from HBM."""
    import jax

    jit_cache = net.__dict__.setdefault("_transformer_gen_jit", {})
    if "prefill" not in jit_cache:
        @jax.jit
        def prefill(params, state, x, carries):
            h, _, new_carries, _, _ = net._forward_core(
                params, state, x, train=False, rng=None, carries=carries)
            return h[:, -1], new_carries      # [B, V] next-token probs
        jit_cache["prefill"] = prefill
    return jit_cache["prefill"]


def get_prefill_bucketed(net: MultiLayerNetwork):
    """Mixed-length prefill (the serving tier's bucketed admission
    waves, serving/engine.py): `x` [B, Pb] holds prompts RIGHT-padded
    to a shared bucket length and `last_idx` [B] each row's final real
    position (`P_b - 1`). Returns that row's next-token probs plus the
    filled carries.

    Right padding is sound because the blocks are causal: position
    `P_b - 1`'s activations never see the padding tokens behind it,
    and the padding rows' K/V land at cache positions `>= P_b` which
    every later read masks by the slot's own position (the same
    0-weight-x-garbage invariant the paged pool rests on). The probs
    gather is the only difference from `get_prefill` — the forward is
    the same program family."""
    import jax
    import jax.numpy as jnp

    jit_cache = net.__dict__.setdefault("_transformer_gen_jit", {})
    if "prefill_bucketed" not in jit_cache:
        @jax.jit
        def prefill_bucketed(params, state, x, carries, last_idx):
            h, _, new_carries, _, _ = net._forward_core(
                params, state, x, train=False, rng=None, carries=carries)
            probs = h[jnp.arange(h.shape[0]), last_idx]   # [B, V]
            return probs, new_carries
        jit_cache["prefill_bucketed"] = prefill_bucketed
    return jit_cache["prefill_bucketed"]


def paged_score_forward(net, plan, params, state, kv, block_tables,
                        token_mat, pos, n_valid):
    """The K-POSITION score forward over the paged pool — one program
    scoring k proposed tokens per slot instead of k programs (the
    dataflow-batching argument applied to the decode loop): the target
    model's half of speculative decoding AND the suffix-extension
    prefill of copy-on-write shared-prefix admission
    (serving/engine.py; docs/SERVING.md).

    `token_mat` [S, K] holds K consecutive tokens per slot occupying
    positions `pos[s] .. pos[s]+K-1`; `n_valid` [S] bounds each slot's
    real lanes (0 = slot sits this dispatch out — its writes land in
    the garbage block, its output rows are discarded). `plan` is the
    engine's layer walk (("plain"|"pos", i) / ("block", i, pool_j)).
    Returns (kv', probs [S, K, V]) where probs[s, j] is the target's
    next-token distribution AFTER consuming token j — per-lane
    bit-equal to K sequential single-token decode dispatches, which is
    the acceptance oracle's whole foundation. Lives next to
    `get_prefill`/`get_prefill_bucketed` because it is the same program
    family: the engine jits it per (K, sampling-variant)."""
    import jax.numpy as jnp

    layers = net.layers
    K = token_mat.shape[1]
    positions = pos[:, None] + jnp.arange(K)[None, :]    # [S, K]
    h = token_mat                                        # [S, K] int ids
    kv = list(kv)
    for entry in plan:
        kind, i = entry[0], entry[1]
        layer = layers[i]
        lp = params.get(str(i), {})
        ls = state.get(str(i), {})
        if kind == "plain":
            h, _ = layer.forward(lp, ls, h, train=False, rng=None)
        elif kind == "pos":
            h, _ = layer.forward_at_positions(lp, ls, h, positions)
        else:
            j = entry[2]
            k_pool, v_pool = kv[j]
            h, k_pool, v_pool = layer.forward_paged_multi(
                lp, h, k_pool, v_pool, block_tables, pos, n_valid)
            kv[j] = (k_pool, v_pool)
    return tuple(kv), h                                  # [S, K, V]


def rejection_sample_drafts(probs, token_mat, n_valid, keys, emit_idx,
                            temp, top_p, top_k):
    """Speculative REJECTION SAMPLING over delta drafts — the sampled
    counterpart of the greedy acceptance oracle (arXiv:2211.17192
    specialized to point-mass draft distributions; serving/engine.py's
    `_spec_step` sampled path; docs/SERVING.md).

    Both proposers (n-gram suffix cache, truncated-layer drafter) emit
    CONCRETE tokens, i.e. the draft distribution is a delta at the
    proposed id `d`. The general rule — accept with prob
    `min(1, p_t(x)/p_d(x))`, on rejection resample from the normalized
    residual `max(0, p_t - p_d)` — then collapses to: accept draft `d`
    with prob `q_t(d)`, and the residual is `q_t` with `d` masked out,
    where `q_t` is the TARGET's filtered/tempered distribution (the
    exact `filter_logits(log(p)/T, top_k, top_p)` chain `_sample_ids`
    runs — one copy, no drift). Each emitted token is marginally
    distributed as a vanilla sample from `q_t` given its prefix (the
    chi-square harness in tests/test_serving_statistical.py holds this
    to a distributional contract), and the acceptance identity
    `E[#accepted at lane j] = sum_x min(q_t(x), p_d(x)) = q_t(d)`
    falls out of the delta specialization (unit-tested).

    Randomness keys off the SAME per-slot chain as vanilla decode —
    position t consumes `fold_in(key, emit_idx + t)` — with sub-folds
    (1 = acceptance uniform, 2 = resample/bonus categorical) so one
    position's accept test and its resample draw are independent.
    Fully deterministic under fixed keys.

    `probs` [S, K, V] from `paged_score_forward` (probs[s, j] is the
    target distribution AFTER consuming token_mat[s, j]); lanes
    `1..n_valid-1` of `token_mat` are drafts. Rows with `temp == 0`
    are computed under a guard temperature and their outputs ignored —
    the host keeps greedy slots on the bit-exact argmax oracle.
    Zero-support drafts (q_t(d) = 0, e.g. filtered out by top-k) are
    always rejected: `u ~ U[0,1) < 0` never fires. Returns
    `(n_acc [S], final [S])`: the count of leading accepted drafts and
    the resampled/bonus token at lane `n_acc` — the slot emits
    `n_acc + 1` tokens. Only these two small vectors cross d2h."""
    import jax
    import jax.numpy as jnp

    S, K, V = probs.shape
    safe_t = jnp.where(temp > 0, temp, 1.0)[:, None, None]
    logits = jnp.log(jnp.clip(probs, 1e-9)) / safe_t
    logits = filter_logits(
        logits, top_k, None if top_p is None else top_p[:, None, None])
    qt = jax.nn.softmax(logits, axis=-1)                   # [S, K, V]

    # per-(slot, lane) keys: the vanilla chain's fold_in(key, t)
    lanes = emit_idx[:, None] + jnp.arange(K)[None, :]     # [S, K]
    pos_keys = jax.vmap(jax.vmap(jax.random.fold_in, (None, 0)),
                        (0, 0))(keys, lanes)               # [S, K, 2]

    # accept draft at lane j+1 iff u < q_t[s, j](d) and the lane is real
    drafts = token_mat[:, 1:]                              # [S, K-1]
    p_acc = jnp.take_along_axis(
        qt[:, :-1, :], drafts[..., None], axis=-1)[..., 0]
    u = jax.vmap(jax.vmap(
        lambda k: jax.random.uniform(jax.random.fold_in(k, 1))))(
        pos_keys[:, :-1])                                  # [S, K-1]
    lane_ok = jnp.arange(K - 1)[None, :] < (n_valid[:, None] - 1)
    acc = (u < p_acc) & lane_ok
    n_acc = jnp.sum(jnp.cumprod(acc.astype(jnp.int32), axis=1), axis=1)

    # resample lane n_acc: residual masks the rejected draft (a
    # rejection implies q_t(d) < 1, so at least one other token
    # survives the filters); all-accepted rows sample the bonus token
    # from the last lane unmasked
    lane = jnp.clip(n_acc, 0, K - 1)
    final_logits = jnp.take_along_axis(
        logits, lane[:, None, None], axis=1)[:, 0, :]      # [S, V]
    rejected = n_acc < jnp.maximum(n_valid - 1, 0)
    rej_tok = jnp.take_along_axis(
        token_mat, jnp.clip(n_acc + 1, 0, K - 1)[:, None], axis=1)[:, 0]
    mask = (jax.nn.one_hot(rej_tok, V, dtype=bool)
            & rejected[:, None])
    final_logits = jnp.where(mask, -jnp.inf, final_logits)
    fin_keys = jax.vmap(lambda k: jax.random.fold_in(k, 2))(
        jnp.take_along_axis(
            pos_keys, lane[:, None, None], axis=1)[:, 0])  # [S, 2]
    final = jax.vmap(jax.random.categorical)(fin_keys, final_logits)
    return n_acc, final


def generate(net: MultiLayerNetwork, prompt_ids, n_tokens: int, *,
             temperature: float = 1.0, top_k: int = None,
             top_p: float = None, rng=None, quantize: str = None):
    """Autoregressive decoding with per-layer KV caches — the
    transformer counterpart of the reference's `rnnTimeStep` sampling
    loop (`MultiLayerNetwork.rnnTimeStep` :2605; the char-LM examples
    sample the same way). Static cache shapes mean exactly TWO XLA
    compiles (prompt prefill + the fused decode scan, keyed by the
    sampling config), and the decode loop runs entirely on-device —
    one dispatch, no per-token host round-trip.

    `prompt_ids` [B, T_prompt] int token ids; returns [B, n_tokens]
    sampled ids. `temperature=0` → greedy argmax; `top_k` keeps only
    the k most probable tokens; `top_p` nucleus sampling keeps the
    smallest set of tokens whose cumulative probability reaches p
    (both filters run on-device inside the fused scan).

    `quantize="int8"` serves the decode from per-output-channel int8
    matmul weights (nd/quant.py) — the prefill AND the fused decode
    scan read int8 from HBM and compute in the policy's compute dtype.
    The quantized tree is cached on the net; `net.params` (the
    training master) is untouched. Greedy decode agrees top-1 with the
    fp path over full generations (the serving parity contract,
    docs/SERVING.md)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from deeplearning4j_tpu.nd import quant

    from deeplearning4j_tpu.nn.layers.recurrent import BaseRecurrentLayer

    from jax import lax

    # ids stay INTEGER while carried standalone: a float32 round-trip
    # silently collapses ids at the 2^24 precision edge (16777217.0 ==
    # 16777216.0) — the embedding gather is the only consumer and it
    # indexes with int32 either way
    prompt = jnp.asarray(np.asarray(prompt_ids), jnp.int32)
    B = prompt.shape[0]
    _check_cache_budget(net, prompt.shape[1], n_tokens)
    carries = {str(i): layer.init_carry(B, net.dtype.compute_dtype)
               for i, layer in enumerate(net.layers)
               if isinstance(layer, BaseRecurrentLayer)}

    # jitted closures CACHED on the net (a fresh jax.jit per call would
    # re-trace every generate(), measured as ~4 s of fixed overhead per
    # call over the tunnel vs ~2 ms/token of actual decode compute)
    jit_cache = net.__dict__.setdefault("_transformer_gen_jit", {})
    prefill = get_prefill(net)

    # eager argument validation (same pattern as the cache budget above:
    # a bad value must fail HERE, not as a cryptic trace error — or
    # worse, top_p<=0 silently sampling token 0 forever)
    vocab = getattr(net.layers[-1], "n_out", None)
    if top_k is not None and not (1 <= int(top_k) <= (vocab or top_k)):
        raise ValueError(f"top_k must be in [1, vocab={vocab}]; "
                         f"got {top_k}")
    if top_p is not None and not (0.0 < float(top_p) <= 1.0):
        raise ValueError(f"top_p must be in (0, 1]; got {top_p}")
    # top_p rides as a TRACED scalar (only used in a comparison), so
    # sweeping it reuses one executable; top_k must stay static
    # (lax.top_k needs a static k) and keys the cache
    key = (float(temperature), int(n_tokens),
           None if top_k is None else int(top_k), top_p is not None)
    if key not in jit_cache:
        # the ENTIRE decode loop is one fused lax.scan dispatch —
        # sampling (categorical / argmax) happens on-device with the
        # rng carried, so no host round-trip per token (measured 66
        # tok/s host-looped over the tunnel vs silicon-speed fused)
        @jax.jit
        def decode(params, state, probs0, carries, rng0, top_p_val):
            def filt(logits):
                # static-shape vocabulary filters (masked, not
                # gathered) — the ONE filter body the serving engine
                # shares (filter_logits)
                return filter_logits(logits, top_k,
                                     top_p_val if top_p is not None
                                     else None)

            def body(carry, _):
                probs, carries, rng = carry
                if temperature == 0:
                    nxt = jnp.argmax(probs, axis=-1)
                else:
                    rng, k = jax.random.split(rng)
                    logits = jnp.log(
                        jnp.clip(probs, 1e-9, None)) / temperature
                    nxt = jax.random.categorical(k, filt(logits))
                h, _, new_carries, _, _ = net._forward_core(
                    params, state, nxt[:, None],
                    train=False, rng=None, carries=carries)
                return (h[:, -1], new_carries, rng), nxt
            _, toks = lax.scan(body, (probs0, carries, rng0), None,
                               length=n_tokens)
            return toks.T                      # [B, n_tokens]
        jit_cache[key] = decode
    decode = jit_cache[key]

    params = quant.serving_params(net, quantize)
    probs, carries = prefill(params, net.net_state, prompt, carries)
    rng = jax.random.PRNGKey(0) if rng is None else rng
    return np.asarray(decode(params, net.net_state, probs, carries,
                             rng, 1.0 if top_p is None else top_p))


def beam_search(net: MultiLayerNetwork, prompt_ids, n_tokens: int, *,
                beam_width: int = 4, eos_id: int = None,
                length_penalty: float = 0.0):
    """Beam-search decoding over the same per-layer KV caches as
    `generate` — the whole search runs as ONE fused `lax.scan` dispatch
    (beams ride the batch dimension; each step re-gathers every cache
    by the surviving beams' indices, all static shapes).

    `prompt_ids` [B, T_prompt] int ids → (ids [B, beam_width,
    n_tokens], log_probs [B, beam_width]) sorted best-first. With
    `eos_id`, finished beams extend with eos at no cost and keep their
    score. `length_penalty` α ranks the FINAL beams by
    score / ((5 + len) / 6)^α (the GNMT normalization; len counts
    tokens up to and incl. eos) — without it, sum-logprob ranking
    systematically favors short eos'd beams. The returned log_probs
    stay unnormalized sums (so they remain teacher-forceable);
    only the ordering changes. Deterministic (no rng)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import lax

    from deeplearning4j_tpu.nn.layers.recurrent import BaseRecurrentLayer

    prompt = jnp.asarray(np.asarray(prompt_ids), jnp.int32)
    B, Tp = prompt.shape
    W = int(beam_width)
    _check_cache_budget(net, Tp, n_tokens)
    # eager validation (generate's pattern): silent-garbage modes
    # otherwise — beam_width=0 returns empty arrays, an out-of-range
    # eos_id never matches any token so EOS handling no-ops
    if W < 1:
        raise ValueError(f"beam_width must be >= 1; got {beam_width}")
    vocab = getattr(net.layers[-1], "n_out", None)
    if eos_id is not None and vocab and not (0 <= int(eos_id) < vocab):
        raise ValueError(
            f"eos_id must be in [0, vocab={vocab}); got {eos_id}")

    jit_cache = net.__dict__.setdefault("_transformer_gen_jit", {})
    # length_penalty deliberately NOT in the key: the rerank happens
    # host-side after the scan, so sweeping alpha reuses one executable
    key = ("beam", int(n_tokens), W,
           None if eos_id is None else int(eos_id))
    if key not in jit_cache:
        @jax.jit
        def search(params, state, prompt, carries0):
            h, _, carries, _, _ = net._forward_core(
                params, state, prompt, train=False, rng=None,
                carries=carries0)
            logp0 = jnp.log(jnp.clip(h[:, -1], 1e-9, None))  # [B, V]
            V = logp0.shape[-1]
            # beams ride the batch dim: replicate the prompt's caches
            carries = jax.tree_util.tree_map(
                lambda a: (jnp.repeat(a[:, None], W, 1)
                           .reshape((B * W,) + a.shape[1:])
                           if a.ndim > 0 else a),
                carries)
            logp = jnp.repeat(logp0[:, None], W, 1)      # [B, W, V]
            # only beam 0 is live initially (all beams identical after
            # replication; -inf scores stop duplicate selections)
            scores = jnp.broadcast_to(
                jnp.where(jnp.arange(W) == 0, 0.0, -jnp.inf),
                (B, W))                                  # [B, W]
            seqs = jnp.zeros((B, W, n_tokens), jnp.int32)
            fin = jnp.zeros((B, W), bool)

            def body(carry, t):
                logp, scores, seqs, fin, carries = carry
                cand = scores[..., None] + logp          # [B, W, V]
                if eos_id is not None:
                    # finished beams may only extend with eos, cost 0
                    only_eos = jnp.full((V,), -jnp.inf
                                        ).at[eos_id].set(0.0)
                    cand = jnp.where(fin[..., None],
                                     scores[..., None] + only_eos, cand)
                flat = cand.reshape(B, W * V)
                top_s, top_i = lax.top_k(flat, W)        # [B, W]
                beam_idx = top_i // V
                token = top_i % V
                # re-gather histories and caches by surviving beams
                seqs = jnp.take_along_axis(
                    seqs, beam_idx[..., None], axis=1)
                seqs = lax.dynamic_update_slice_in_dim(
                    seqs, token[..., None], t, axis=2)
                fin = jnp.take_along_axis(fin, beam_idx, axis=1)
                if eos_id is not None:
                    fin = jnp.logical_or(fin, token == eos_id)
                gather = jax.vmap(lambda a, i: a[i])     # per batch row

                def regather(a):
                    if a.ndim == 0:
                        return a
                    aw = a.reshape((B, W) + a.shape[1:])
                    return gather(aw, beam_idx).reshape(a.shape)
                carries = jax.tree_util.tree_map(regather, carries)
                h, _, carries, _, _ = net._forward_core(
                    params, state, token.reshape(B * W, 1),
                    train=False, rng=None, carries=carries)
                logp = jnp.log(jnp.clip(h[:, -1], 1e-9, None)
                               ).reshape(B, W, V)
                return (logp, top_s, seqs, fin, carries), None

            (logp, scores, seqs, fin, carries), _ = lax.scan(
                body, (logp, scores, seqs, fin, carries),
                jnp.arange(n_tokens))
            return seqs, scores
        jit_cache[key] = search
    search = jit_cache[key]

    carries0 = {str(i): layer.init_carry(B, net.dtype.compute_dtype)
                for i, layer in enumerate(net.layers)
                if isinstance(layer, BaseRecurrentLayer)}
    seqs, scores = search(net.params, net.net_state, prompt, carries0)
    seqs, scores = np.asarray(seqs), np.asarray(scores)
    # GNMT length normalization — host-side rerank only (the returned
    # scores stay raw sums so they remain teacher-forceable)
    if eos_id is not None:
        hits = np.cumsum(seqs == eos_id, axis=2) > 0
        lengths = np.where(hits.any(2), hits.argmax(2) + 1, n_tokens)
    else:
        lengths = np.full(scores.shape, n_tokens)
    norm = ((5.0 + lengths.astype(np.float64)) / 6.0) ** length_penalty
    order = np.argsort(-scores / norm, axis=1, kind="stable")
    return (np.take_along_axis(seqs, order[..., None], axis=1),
            np.take_along_axis(scores, order, axis=1))
