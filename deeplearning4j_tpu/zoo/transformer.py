"""Transformer encoder zoo models (beyond-reference: the 2017 zoo tops
out at InceptionResNet/LSTMs; this is the long-context flagship the TPU
rebuild adds, riding the Pallas flash-attention fast path and — over a
mesh — ring/Ulysses sequence parallelism).

Two configurations:
- `TransformerClassifier`: token ids → embedding + positions → N
  encoder blocks → masked global average pool → softmax.
- `TransformerLM`: causal blocks → per-position softmax over the
  vocabulary (RnnOutputLayer), the TextGenerationLSTM successor.
"""

from __future__ import annotations

from deeplearning4j_tpu.common.updaters import Adam
from deeplearning4j_tpu.common.weights import WeightInit
from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import (
    EmbeddingLayer,
    GlobalPoolingLayer,
    OutputLayer,
    PositionalEncodingLayer,
    RnnOutputLayer,
    TransformerEncoderBlock,
)
from deeplearning4j_tpu.nn.layers.pooling import PoolingType
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.zoo.base import ZooModel


class TransformerClassifier(ZooModel):
    def __init__(self, vocab_size: int, num_classes: int, *,
                 d_model: int = 128, n_layers: int = 2, n_heads: int = 8,
                 ff_multiplier: int = 4, max_len: int = 512,
                 dropout: float = None, pooling: PoolingType = PoolingType.AVG,
                 remat: bool = False, sequence_parallel: str = None,
                 seed: int = 123):
        super().__init__(num_classes=num_classes, seed=seed)
        self.vocab_size = vocab_size
        self.d_model = d_model
        self.n_layers = n_layers
        self.n_heads = n_heads
        self.ff_multiplier = ff_multiplier
        self.max_len = max_len
        self.dropout = dropout
        self.pooling = pooling
        self.remat = remat
        self.sequence_parallel = sequence_parallel

    def conf(self):
        b = (NeuralNetConfiguration.builder()
             .seed(self.seed).updater(Adam(1e-3))
             .weight_init(WeightInit.XAVIER)
             .list()
             .layer(EmbeddingLayer(n_in=self.vocab_size, n_out=self.d_model))
             .layer(PositionalEncodingLayer(max_len=self.max_len)))
        for _ in range(self.n_layers):
            b.layer(TransformerEncoderBlock(
                n_heads=self.n_heads, ff_multiplier=self.ff_multiplier,
                dropout=self.dropout, remat=self.remat,
                sequence_parallel=self.sequence_parallel))
        b.layer(GlobalPoolingLayer(pooling_type=self.pooling))
        b.layer(OutputLayer(n_out=self.num_classes, activation="softmax",
                            loss="mcxent"))
        b.set_input_type(InputType.recurrent(self.vocab_size))
        return b.build()

    def init(self) -> MultiLayerNetwork:
        return MultiLayerNetwork(self.conf()).init(self.seed)


class TransformerLM(ZooModel):
    def __init__(self, vocab_size: int, *, d_model: int = 128,
                 n_layers: int = 2, n_heads: int = 8,
                 ff_multiplier: int = 4, max_len: int = 512,
                 remat: bool = False, sequence_parallel: str = None,
                 seed: int = 123):
        super().__init__(num_classes=vocab_size, seed=seed)
        self.vocab_size = vocab_size
        self.d_model = d_model
        self.n_layers = n_layers
        self.n_heads = n_heads
        self.ff_multiplier = ff_multiplier
        self.max_len = max_len
        self.remat = remat
        self.sequence_parallel = sequence_parallel

    def conf(self):
        b = (NeuralNetConfiguration.builder()
             .seed(self.seed).updater(Adam(1e-3))
             .weight_init(WeightInit.XAVIER)
             .list()
             .layer(EmbeddingLayer(n_in=self.vocab_size, n_out=self.d_model))
             .layer(PositionalEncodingLayer(max_len=self.max_len)))
        for _ in range(self.n_layers):
            b.layer(TransformerEncoderBlock(
                n_heads=self.n_heads, ff_multiplier=self.ff_multiplier,
                causal=True, remat=self.remat,
                sequence_parallel=self.sequence_parallel))
        b.layer(RnnOutputLayer(n_out=self.vocab_size, activation="softmax",
                               loss="mcxent"))
        b.set_input_type(InputType.recurrent(self.vocab_size))
        return b.build()

    def init(self) -> MultiLayerNetwork:
        return MultiLayerNetwork(self.conf()).init(self.seed)
