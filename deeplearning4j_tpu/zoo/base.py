"""ZooModel template.

Reference: `zoo/ZooModel.java:40-52`: `init()` builds the network from
its config; `initPretrained()` downloads checked-sum weights
(`:52-81`). Pretrained downloads require the reference's hosted DL4J
weight files (Java serialization) — not importable here; pretrained
loading is wired to our own `ModelSerializer` format plus the Keras
importer for h5 weights.
"""

from __future__ import annotations

import hashlib
from enum import Enum
from pathlib import Path
from typing import Optional

from deeplearning4j_tpu.datasets.fetchers import CACHE_DIR


class PretrainedType(str, Enum):
    IMAGENET = "imagenet"
    MNIST = "mnist"
    CIFAR10 = "cifar10"
    VGGFACE = "vggface"
    TEXT = "text"  # beyond reference: packaged char-LM weights


def packaged_weight(name: str):
    """(file URI, sha256) for an artifact shipped in zoo/weights/, or
    (None, None) when absent. MANIFEST.json maps filename → metadata.
    A weights file WITHOUT a manifest entry is treated as not packaged
    — returning its URI with no checksum would make init_pretrained
    silently skip integrity verification."""
    entry = packaged_weight_entry(name)
    if entry is None or not entry.get("sha256"):
        return None, None
    return (Path(__file__).parent / "weights" / name).as_uri(), entry["sha256"]


def packaged_weight_entry(name: str) -> Optional[dict]:
    """Manifest metadata for a packaged artifact (None when the file or
    its manifest entry is missing)."""
    import json

    wdir = Path(__file__).parent / "weights"
    f, mf = wdir / name, wdir / "MANIFEST.json"
    if not (f.exists() and mf.exists()):
        return None
    manifest = json.loads(mf.read_text())
    if "file" in manifest:  # round-4 single-entry layout
        manifest = {manifest["file"]: manifest}
    return manifest.get(name)


class ZooModel:
    """Subclasses implement `init()` → model and optionally provide
    pretrained checkpoint URLs."""

    def __init__(self, num_classes: int = 1000, seed: int = 123, **kwargs):
        self.num_classes = num_classes
        self.seed = seed
        self.kwargs = kwargs

    def init(self):
        raise NotImplementedError

    # {PretrainedType: filename in zoo/weights/} — subclasses shipping a
    # packaged artifact declare it here; external-URL models override
    # pretrained_url/pretrained_checksum instead
    packaged: dict = {}

    # {PretrainedType: packaged architecture-JSON filename} — for
    # weights-only keras-applications payloads whose architecture does
    # NOT match this zoo model's own builder (e.g. keras ResNet50's
    # explicit ZeroPadding + biased convs vs the zoo's SAME-padded
    # bias-free builder): the committed `model.to_json()` is the
    # ground-truth graph the weights belong to, and the import copies
    # by keras layer name through it
    keras_architecture: dict = {}

    def pretrained_url(self, ptype: PretrainedType) -> Optional[str]:
        name = self.packaged.get(ptype)
        return packaged_weight(name)[0] if name else None

    def pretrained_checksum(self, ptype: PretrainedType) -> Optional[str]:
        name = self.packaged.get(ptype)
        return packaged_weight(name)[1] if name else None

    def init_pretrained(self, ptype: PretrainedType = PretrainedType.IMAGENET):
        """Download + verify + load a pretrained checkpoint
        (reference `ZooModel.initPretrained` with checksum check :81).

        Supports two payloads: this framework's ModelSerializer zip, or
        a Keras .h5 weights file (the reference's "Keras modelimport and
        zoo models load unchanged" north star) — routed by file magic.
        Checksum algorithm is inferred from hex length (32 → md5, the
        hash format keras-applications publishes; 64 → sha256)."""
        url = self.pretrained_url(ptype)
        if url is None:
            raise ValueError(f"{type(self).__name__} has no pretrained weights for {ptype}")
        suffix = ".h5" if url.endswith(".h5") else ".zip"
        tag = hashlib.sha256(url.encode()).hexdigest()[:8]  # distinct URLs
        dest = CACHE_DIR / "zoo" / (
            f"{type(self).__name__}_{ptype.value}_{tag}{suffix}")
        if not dest.exists():
            import urllib.request
            dest.parent.mkdir(parents=True, exist_ok=True)
            urllib.request.urlretrieve(url, dest)  # noqa: S310
        expected = self.pretrained_checksum(ptype)
        if expected:
            algo = hashlib.md5 if len(expected) == 32 else hashlib.sha256
            h = algo(dest.read_bytes()).hexdigest()
            if h != expected:
                dest.unlink()
                raise IOError(f"Checksum mismatch for {dest}: {h} != {expected}")
        with open(dest, "rb") as f:
            magic = f.read(8)
        if magic[:4] == b"\x89HDF":
            from deeplearning4j_tpu.modelimport.hdf5 import Hdf5Archive
            from deeplearning4j_tpu.modelimport.keras import KerasModelImport
            with Hdf5Archive(str(dest)) as h5:
                full_model = h5.read_attr_string("model_config") is not None
            if full_model:
                return KerasModelImport.import_keras_model_and_weights(str(dest))
            arch_name = self.keras_architecture.get(ptype)
            if arch_name:
                # weights-only payload + committed keras architecture
                # JSON: build the ground-truth graph and copy by name
                arch_path = Path(__file__).parent / "weights" / arch_name
                return KerasModelImport.import_architecture_and_weights(
                    arch_path, str(dest))
            # weights-only file (keras-applications format): build this
            # zoo model's own architecture and order-match the weights
            net = self.init()
            return KerasModelImport.load_weights_into(net, str(dest))
        from deeplearning4j_tpu.util.serializer import ModelSerializer
        return ModelSerializer.restore_model(dest)
