"""InceptionResNetV1 — face-embedding backbone.

Reference: `zoo/model/InceptionResNetV1.java` (+ helper
`zoo/model/helper/InceptionResNetHelper.java`): stem convs, 5× block35
(Inception-ResNet-A), reduction-A, 10× block17 (B, with 1x7/7x1
factorised convs), reduction-B, 5× block8 (C, 1x3/3x1), global average
pool, dropout, 128-d bottleneck, L2 normalisation, center-loss softmax
output (the FaceNet training head).

Residual scaling uses ScaleVertex + ElementWiseVertex(add) exactly as
the reference composes them.
"""

from __future__ import annotations

from deeplearning4j_tpu.common.updaters import RmsProp
from deeplearning4j_tpu.common.weights import WeightInit
from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.graph import (
    ElementWiseVertex,
    L2NormalizeVertex,
    MergeVertex,
    ScaleVertex,
)
from deeplearning4j_tpu.nn.graph import ComputationGraph, ComputationGraphConfiguration
from deeplearning4j_tpu.nn.layers import (
    ActivationLayer,
    CenterLossOutputLayer,
    ConvolutionLayer,
    DenseLayer,
    GlobalPoolingLayer,
    SubsamplingLayer,
)
from deeplearning4j_tpu.nn.layers.convolution import ConvolutionMode
from deeplearning4j_tpu.nn.layers.pooling import PoolingType
from deeplearning4j_tpu.zoo.base import ZooModel


class InceptionResNetV1(ZooModel):
    def __init__(self, num_classes: int = 1000, seed: int = 123,
                 height: int = 160, width: int = 160, channels: int = 3,
                 embedding_size: int = 128,
                 blocks35: int = 5, blocks17: int = 10, blocks8: int = 5):
        super().__init__(num_classes=num_classes, seed=seed)
        self.height, self.width, self.channels = height, width, channels
        self.embedding_size = embedding_size
        self.blocks35, self.blocks17, self.blocks8 = blocks35, blocks17, blocks8

    def _conv(self, g, name, inp, filters, kernel, stride=(1, 1),
              mode=ConvolutionMode.SAME, act="relu"):
        g.add_layer(name, ConvolutionLayer(
            n_out=filters, kernel_size=kernel, stride=stride,
            convolution_mode=mode, activation=act), inp)
        return name

    def _residual(self, g, name, inp, branch_out, n_channels, scale):
        """merge branches → 1x1 linear expand → scale → add → relu
        (reference InceptionResNetHelper block pattern)."""
        up = self._conv(g, f"{name}_up", branch_out, n_channels, (1, 1), act="identity")
        g.add_vertex(f"{name}_scale", ScaleVertex(scale_factor=scale), up)
        g.add_vertex(f"{name}_add", ElementWiseVertex(op="add"), inp, f"{name}_scale")
        g.add_layer(f"{name}_relu", ActivationLayer(activation="relu"), f"{name}_add")
        return f"{name}_relu"

    def _block35(self, g, name, inp):
        b1 = self._conv(g, f"{name}_b1", inp, 32, (1, 1))
        b2 = self._conv(g, f"{name}_b2b", self._conv(g, f"{name}_b2a", inp, 32, (1, 1)),
                        32, (3, 3))
        b3a = self._conv(g, f"{name}_b3a", inp, 32, (1, 1))
        b3b = self._conv(g, f"{name}_b3b", b3a, 32, (3, 3))
        b3 = self._conv(g, f"{name}_b3c", b3b, 32, (3, 3))
        g.add_vertex(f"{name}_merge", MergeVertex(), b1, b2, b3)
        return self._residual(g, name, inp, f"{name}_merge", 256, 0.17)

    def _block17(self, g, name, inp):
        b1 = self._conv(g, f"{name}_b1", inp, 128, (1, 1))
        b2a = self._conv(g, f"{name}_b2a", inp, 128, (1, 1))
        b2b = self._conv(g, f"{name}_b2b", b2a, 128, (1, 7))
        b2 = self._conv(g, f"{name}_b2c", b2b, 128, (7, 1))
        g.add_vertex(f"{name}_merge", MergeVertex(), b1, b2)
        return self._residual(g, name, inp, f"{name}_merge", 896, 0.10)

    def _block8(self, g, name, inp):
        b1 = self._conv(g, f"{name}_b1", inp, 192, (1, 1))
        b2a = self._conv(g, f"{name}_b2a", inp, 192, (1, 1))
        b2b = self._conv(g, f"{name}_b2b", b2a, 192, (1, 3))
        b2 = self._conv(g, f"{name}_b2c", b2b, 192, (3, 1))
        g.add_vertex(f"{name}_merge", MergeVertex(), b1, b2)
        return self._residual(g, name, inp, f"{name}_merge", 1792, 0.20)

    def conf(self) -> ComputationGraphConfiguration:
        builder = NeuralNetConfiguration.builder() \
            .seed(self.seed) \
            .updater(RmsProp(0.1)) \
            .weight_init(WeightInit.RELU) \
            .l2(5e-5)
        g = ComputationGraphConfiguration.graph_builder(builder)
        g.add_inputs("input")
        g.set_input_types(InputType.convolutional(self.height, self.width, self.channels))

        # stem (reference `InceptionResNetV1.java` stem convs)
        x = self._conv(g, "stem1", "input", 32, (3, 3), (2, 2))
        x = self._conv(g, "stem2", x, 32, (3, 3))
        x = self._conv(g, "stem3", x, 64, (3, 3))
        g.add_layer("stem_pool", SubsamplingLayer(
            kernel_size=(3, 3), stride=(2, 2),
            convolution_mode=ConvolutionMode.SAME), x)
        x = self._conv(g, "stem4", "stem_pool", 80, (1, 1))
        x = self._conv(g, "stem5", x, 192, (3, 3))
        x = self._conv(g, "stem6", x, 256, (3, 3), (2, 2))

        for i in range(self.blocks35):
            x = self._block35(g, f"block35_{i}", x)

        # reduction-A
        g.add_layer("redA_pool", SubsamplingLayer(
            kernel_size=(3, 3), stride=(2, 2),
            convolution_mode=ConvolutionMode.SAME), x)
        ra1 = self._conv(g, "redA_b1", x, 384, (3, 3), (2, 2))
        ra2a = self._conv(g, "redA_b2a", x, 192, (1, 1))
        ra2b = self._conv(g, "redA_b2b", ra2a, 192, (3, 3))
        ra2 = self._conv(g, "redA_b2c", ra2b, 256, (3, 3), (2, 2))
        g.add_vertex("redA", MergeVertex(), "redA_pool", ra1, ra2)
        x = "redA"

        for i in range(self.blocks17):
            x = self._block17(g, f"block17_{i}", x)

        # reduction-B
        g.add_layer("redB_pool", SubsamplingLayer(
            kernel_size=(3, 3), stride=(2, 2),
            convolution_mode=ConvolutionMode.SAME), x)
        rb1 = self._conv(g, "redB_b1b", self._conv(g, "redB_b1a", x, 256, (1, 1)),
                         384, (3, 3), (2, 2))
        rb2 = self._conv(g, "redB_b2b", self._conv(g, "redB_b2a", x, 256, (1, 1)),
                         256, (3, 3), (2, 2))
        rb3a = self._conv(g, "redB_b3a", x, 256, (1, 1))
        rb3b = self._conv(g, "redB_b3b", rb3a, 256, (3, 3))
        rb3 = self._conv(g, "redB_b3c", rb3b, 256, (3, 3), (2, 2))
        g.add_vertex("redB", MergeVertex(), "redB_pool", rb1, rb2, rb3)
        x = "redB"

        for i in range(self.blocks8):
            x = self._block8(g, f"block8_{i}", x)

        g.add_layer("avgpool", GlobalPoolingLayer(pooling_type=PoolingType.AVG), x)
        g.add_layer("bottleneck", DenseLayer(
            n_out=self.embedding_size, activation="identity", dropout=0.8), "avgpool")
        g.add_vertex("embeddings", L2NormalizeVertex(), "bottleneck")
        g.add_layer("output", CenterLossOutputLayer(
            n_out=self.num_classes, activation="softmax", loss="mcxent",
            alpha=0.9, lambda_=2e-4), "embeddings")
        g.set_outputs("output")
        return g.build()

    def init(self) -> ComputationGraph:
        return ComputationGraph(self.conf()).init(self.seed)
