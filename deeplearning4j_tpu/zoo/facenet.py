"""FaceNet NN4-small2 — face-embedding model.

Reference: `zoo/model/FaceNetNN4Small2.java` (+ helper
`zoo/model/helper/FaceNetHelper.java`): GoogLeNet-style stem, inception
modules 3a/3b/3c (3c strided, no 1x1 branch), 4a/4e (strided), 5a/5b
(no 5x5 branch), global average pool, 128-d dense embedding,
L2NormalizeVertex, center-loss softmax head.

Pool-type mix in the reference alternates max and L2 (p-norm) pooling
branches; both map to `lax.reduce_window` here (SubsamplingLayer PNORM).
"""

from __future__ import annotations

from deeplearning4j_tpu.common.updaters import Adam
from deeplearning4j_tpu.common.weights import WeightInit
from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.graph import L2NormalizeVertex, MergeVertex
from deeplearning4j_tpu.nn.graph import ComputationGraph, ComputationGraphConfiguration
from deeplearning4j_tpu.nn.layers import (
    CenterLossOutputLayer,
    ConvolutionLayer,
    DenseLayer,
    GlobalPoolingLayer,
    LocalResponseNormalization,
    SubsamplingLayer,
)
from deeplearning4j_tpu.nn.layers.convolution import ConvolutionMode, PoolingMode
from deeplearning4j_tpu.nn.layers.pooling import PoolingType
from deeplearning4j_tpu.zoo.base import ZooModel


class FaceNetNN4Small2(ZooModel):
    def __init__(self, num_classes: int = 1000, seed: int = 123,
                 height: int = 96, width: int = 96, channels: int = 3,
                 embedding_size: int = 128):
        super().__init__(num_classes=num_classes, seed=seed)
        self.height, self.width, self.channels = height, width, channels
        self.embedding_size = embedding_size

    def _conv(self, g, name, inp, filters, kernel, stride=(1, 1)):
        g.add_layer(name, ConvolutionLayer(
            n_out=filters, kernel_size=kernel, stride=stride,
            convolution_mode=ConvolutionMode.SAME, activation="relu"), inp)
        return name

    def _inception(self, g, name, inp, n1, r3, n3, r5, n5, pool_mode, pp,
                   stride=(1, 1)):
        """FaceNetHelper.appendGraph-style module; n1/n5/pp of 0 drop the
        branch (reference 3c/4e/5x variants)."""
        branches = []
        if n1:
            branches.append(self._conv(g, f"{name}_1x1", inp, n1, (1, 1)))
        b3r = self._conv(g, f"{name}_3x3r", inp, r3, (1, 1))
        branches.append(self._conv(g, f"{name}_3x3", b3r, n3, (3, 3), stride))
        if n5:
            b5r = self._conv(g, f"{name}_5x5r", inp, r5, (1, 1))
            branches.append(self._conv(g, f"{name}_5x5", b5r, n5, (5, 5), stride))
        g.add_layer(f"{name}_pool", SubsamplingLayer(
            kernel_size=(3, 3), stride=stride, pooling_type=pool_mode,
            convolution_mode=ConvolutionMode.SAME), inp)
        if pp:
            branches.append(self._conv(g, f"{name}_poolproj", f"{name}_pool", pp, (1, 1)))
        else:
            branches.append(f"{name}_pool")
        g.add_vertex(f"{name}_merge", MergeVertex(), *branches)
        return f"{name}_merge"

    def conf(self) -> ComputationGraphConfiguration:
        builder = NeuralNetConfiguration.builder() \
            .seed(self.seed) \
            .updater(Adam(0.1)) \
            .weight_init(WeightInit.RELU) \
            .l2(5e-5)
        g = ComputationGraphConfiguration.graph_builder(builder)
        g.add_inputs("input")
        g.set_input_types(InputType.convolutional(self.height, self.width, self.channels))

        x = self._conv(g, "stem1", "input", 64, (7, 7), (2, 2))
        g.add_layer("stem_pool1", SubsamplingLayer(
            kernel_size=(3, 3), stride=(2, 2),
            convolution_mode=ConvolutionMode.SAME), x)
        g.add_layer("stem_lrn1", LocalResponseNormalization(), "stem_pool1")
        x = self._conv(g, "stem2", "stem_lrn1", 64, (1, 1))
        x = self._conv(g, "stem3", x, 192, (3, 3))
        g.add_layer("stem_lrn2", LocalResponseNormalization(), x)
        g.add_layer("stem_pool2", SubsamplingLayer(
            kernel_size=(3, 3), stride=(2, 2),
            convolution_mode=ConvolutionMode.SAME), "stem_lrn2")

        x = self._inception(g, "inc3a", "stem_pool2", 64, 96, 128, 16, 32,
                            PoolingMode.MAX, 32)
        x = self._inception(g, "inc3b", x, 64, 96, 128, 32, 64,
                            PoolingMode.PNORM, 64)
        x = self._inception(g, "inc3c", x, 0, 128, 256, 32, 64,
                            PoolingMode.MAX, 0, stride=(2, 2))

        x = self._inception(g, "inc4a", x, 256, 96, 192, 32, 64,
                            PoolingMode.PNORM, 128)
        x = self._inception(g, "inc4e", x, 0, 160, 256, 64, 128,
                            PoolingMode.MAX, 0, stride=(2, 2))

        x = self._inception(g, "inc5a", x, 256, 96, 384, 0, 0,
                            PoolingMode.PNORM, 96)
        x = self._inception(g, "inc5b", x, 256, 96, 384, 0, 0,
                            PoolingMode.MAX, 96)

        g.add_layer("avgpool", GlobalPoolingLayer(pooling_type=PoolingType.AVG), x)
        g.add_layer("bottleneck", DenseLayer(
            n_out=self.embedding_size, activation="identity"), "avgpool")
        g.add_vertex("embeddings", L2NormalizeVertex(), "bottleneck")
        g.add_layer("output", CenterLossOutputLayer(
            n_out=self.num_classes, activation="softmax", loss="mcxent",
            alpha=0.9, lambda_=2e-4), "embeddings")
        g.set_outputs("output")
        return g.build()

    def init(self) -> ComputationGraph:
        return ComputationGraph(self.conf()).init(self.seed)
