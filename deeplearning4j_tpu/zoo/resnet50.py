"""ResNet-50 — the north-star benchmark model.

Reference: `zoo/model/ResNet50.java:82` (init) / `:173` (graphBuilder):
7x7/2 stem conv + BN + relu + 3x3/2 maxpool, then bottleneck residual
stages [3, 4, 6, 3] (convBlock with projection shortcut at stage entry,
identityBlock otherwise), global average pool, softmax FC.

Built as a ComputationGraph with ElementWiseVertex(add) shortcuts —
the same graph shape the reference constructs, expressed over NHWC /
`lax.conv_general_dilated` so XLA maps every conv onto the MXU and
fuses BN+relu into the conv epilogue.
"""

from __future__ import annotations

from deeplearning4j_tpu.common.updaters import Nesterovs
from deeplearning4j_tpu.common.weights import WeightInit
from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.graph import ElementWiseVertex
from deeplearning4j_tpu.nn.graph import ComputationGraph, ComputationGraphConfiguration
from deeplearning4j_tpu.nn.layers import (
    ActivationLayer,
    BatchNormalization,
    ConvolutionLayer,
    GlobalPoolingLayer,
    OutputLayer,
    SubsamplingLayer,
    ZeroPaddingLayer,
)
from deeplearning4j_tpu.nn.layers.convolution import ConvolutionMode
from deeplearning4j_tpu.nn.layers.pooling import PoolingType
from deeplearning4j_tpu.zoo.base import PretrainedType, ZooModel


class ResNet50(ZooModel):
    STAGES = [(3, 64), (4, 128), (6, 256), (3, 512)]

    def __init__(self, num_classes: int = 1000, seed: int = 123,
                 height: int = 224, width: int = 224, channels: int = 3):
        super().__init__(num_classes=num_classes, seed=seed)
        self.height, self.width, self.channels = height, width, channels

    def _conv_bn(self, g, name, inp, filters, kernel, stride, mode=ConvolutionMode.SAME,
                 activation=True):
        g.add_layer(f"{name}_conv",
                    ConvolutionLayer(n_out=filters, kernel_size=kernel, stride=stride,
                                     convolution_mode=mode, has_bias=False,
                                     activation="identity"),
                    inp)
        g.add_layer(f"{name}_bn",
                    BatchNormalization(activation="relu" if activation else "identity"),
                    f"{name}_conv")
        return f"{name}_bn"

    def _bottleneck(self, g, name, inp, filters, stride, project):
        """Bottleneck residual block (reference `convBlock`/`identityBlock`
        ResNet50.java)."""
        x = self._conv_bn(g, f"{name}_a", inp, filters, (1, 1), (stride, stride))
        x = self._conv_bn(g, f"{name}_b", x, filters, (3, 3), (1, 1))
        x = self._conv_bn(g, f"{name}_c", x, 4 * filters, (1, 1), (1, 1), activation=False)
        if project:
            shortcut = self._conv_bn(g, f"{name}_proj", inp, 4 * filters, (1, 1),
                                     (stride, stride), activation=False)
        else:
            shortcut = inp
        g.add_vertex(f"{name}_add", ElementWiseVertex(op="add"), x, shortcut)
        g.add_layer(f"{name}_out", ActivationLayer(activation="relu"), f"{name}_add")
        return f"{name}_out"

    def conf(self) -> ComputationGraphConfiguration:
        builder = NeuralNetConfiguration.builder() \
            .seed(self.seed) \
            .updater(Nesterovs(1e-1, 0.9)) \
            .weight_init(WeightInit.RELU) \
            .l2(1e-4)
        g = ComputationGraphConfiguration.graph_builder(builder)
        g.add_inputs("input")
        g.set_input_types(InputType.convolutional(self.height, self.width, self.channels))
        x = self._conv_bn(g, "stem", "input", 64, (7, 7), (2, 2))
        g.add_layer("stem_pool",
                    SubsamplingLayer(kernel_size=(3, 3), stride=(2, 2),
                                     convolution_mode=ConvolutionMode.SAME), x)
        x = "stem_pool"
        for si, (blocks, filters) in enumerate(self.STAGES):
            for bi in range(blocks):
                stride = 2 if (si > 0 and bi == 0) else 1
                x = self._bottleneck(g, f"res{si}_{bi}", x, filters, stride,
                                     project=(bi == 0))
        g.add_layer("avgpool", GlobalPoolingLayer(pooling_type=PoolingType.AVG), x)
        g.add_layer("output",
                    OutputLayer(n_out=self.num_classes, activation="softmax",
                                loss="mcxent"), "avgpool")
        g.set_outputs("output")
        return g.build()

    def init(self) -> ComputationGraph:
        return ComputationGraph(self.conf()).init(self.seed)

    # Keras-applications hosted weights (reference `ZooModel.java:52-81`
    # pretrainedUrl + checksum pattern); md5 from keras-applications.
    # The payload is weights-only, and keras ResNet50 (explicit
    # ZeroPadding + biased convs) differs from this builder, so the
    # committed `model.to_json()` architecture routes the import.
    keras_architecture = {PretrainedType.IMAGENET:
                          "resnet50_keras_arch.json"}

    def pretrained_url(self, ptype):
        if ptype == PretrainedType.IMAGENET:
            return ("https://storage.googleapis.com/tensorflow/"
                    "keras-applications/resnet/"
                    "resnet50_weights_tf_dim_ordering_tf_kernels.h5")
        return None

    def pretrained_checksum(self, ptype):
        if ptype == PretrainedType.IMAGENET:
            return "2cb95161c43110f7111970584f804107"
        return None
