"""TextGenerationLSTM (reference `zoo/model/TextGenerationLSTM.java`):
two stacked GravesLSTM(256) + RnnOutputLayer over the character
vocabulary, TBPTT 50. BASELINE config 2 (char-RNN) model."""

from __future__ import annotations

from deeplearning4j_tpu.common.updaters import RmsProp
from deeplearning4j_tpu.common.weights import WeightInit
from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.builder import BackpropType
from deeplearning4j_tpu.nn.layers import GravesLSTM, RnnOutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.zoo.base import PretrainedType, ZooModel


class TextGenerationLSTM(ZooModel):
    def __init__(self, vocab_size: int = 77, hidden: int = 256, seed: int = 123,
                 tbptt_length: int = 50):
        super().__init__(num_classes=vocab_size, seed=seed)
        self.vocab_size = vocab_size
        self.hidden = hidden
        self.tbptt_length = tbptt_length

    def conf(self):
        return (NeuralNetConfiguration.builder()
                .seed(self.seed)
                .updater(RmsProp(1e-2))
                .weight_init(WeightInit.XAVIER)
                .list()
                .layer(GravesLSTM(n_in=self.vocab_size, n_out=self.hidden,
                                  activation="tanh"))
                .layer(GravesLSTM(n_in=self.hidden, n_out=self.hidden,
                                  activation="tanh"))
                .layer(RnnOutputLayer(n_in=self.hidden, n_out=self.vocab_size,
                                      activation="softmax", loss="mcxent"))
                .backprop_type(BackpropType.TRUNCATED_BPTT, self.tbptt_length)
                .build())

    def init(self) -> MultiLayerNetwork:
        return MultiLayerNetwork(self.conf()).init(self.seed)

    # Packaged pretrained checkpoint: char-LM trained on this repo's own
    # documentation (provenance + charset in zoo/weights/MANIFEST.json).
    packaged = {PretrainedType.TEXT: "textgen_docs.zip"}

    @staticmethod
    def pretrained_charset():
        """Charset the packaged TEXT checkpoint was trained with (index
        VOCAB-1 is the unknown slot); None when no packaged artifact."""
        from deeplearning4j_tpu.zoo.base import packaged_weight_entry

        entry = packaged_weight_entry("textgen_docs.zip")
        return None if entry is None else entry.get("charset")
