"""GoogLeNet (Inception v1).

Reference: `zoo/model/GoogLeNet.java` — stem (7x7/2 conv → maxpool →
LRN → 1x1 → 3x3 → LRN → maxpool), nine inception modules
(3a/3b, 4a–4e, 5a/5b) each merging four branches (1x1; 1x1→3x3;
1x1→5x5; maxpool→1x1), global average pool, 40% dropout, softmax FC.

NHWC / MXU-native convs; branch merge = channel-concat MergeVertex.
"""

from __future__ import annotations

from deeplearning4j_tpu.common.updaters import Nesterovs
from deeplearning4j_tpu.common.weights import WeightInit
from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.graph import MergeVertex
from deeplearning4j_tpu.nn.graph import ComputationGraph, ComputationGraphConfiguration
from deeplearning4j_tpu.nn.layers import (
    DenseLayer,
    ConvolutionLayer,
    GlobalPoolingLayer,
    LocalResponseNormalization,
    OutputLayer,
    SubsamplingLayer,
)
from deeplearning4j_tpu.nn.layers.convolution import ConvolutionMode
from deeplearning4j_tpu.nn.layers.pooling import PoolingType
from deeplearning4j_tpu.zoo.base import ZooModel

# (1x1, (3x3 reduce, 3x3), (5x5 reduce, 5x5), pool-proj) per module
_INCEPTION = {
    "3a": (64, (96, 128), (16, 32), 32),
    "3b": (128, (128, 192), (32, 96), 64),
    "4a": (192, (96, 208), (16, 48), 64),
    "4b": (160, (112, 224), (24, 64), 64),
    "4c": (128, (128, 256), (24, 64), 64),
    "4d": (112, (144, 288), (32, 64), 64),
    "4e": (256, (160, 320), (32, 128), 128),
    "5a": (256, (160, 320), (32, 128), 128),
    "5b": (384, (192, 384), (48, 128), 128),
}


class GoogLeNet(ZooModel):
    def __init__(self, num_classes: int = 1000, seed: int = 123,
                 height: int = 224, width: int = 224, channels: int = 3):
        super().__init__(num_classes=num_classes, seed=seed)
        self.height, self.width, self.channels = height, width, channels

    def _conv(self, g, name, inp, filters, kernel, stride=(1, 1)):
        g.add_layer(name, ConvolutionLayer(
            n_out=filters, kernel_size=kernel, stride=stride,
            convolution_mode=ConvolutionMode.SAME, activation="relu"), inp)
        return name

    def _inception(self, g, name, inp, spec):
        n1, (r3, n3), (r5, n5), pp = spec
        b1 = self._conv(g, f"{name}_1x1", inp, n1, (1, 1))
        b2r = self._conv(g, f"{name}_3x3r", inp, r3, (1, 1))
        b2 = self._conv(g, f"{name}_3x3", b2r, n3, (3, 3))
        b3r = self._conv(g, f"{name}_5x5r", inp, r5, (1, 1))
        b3 = self._conv(g, f"{name}_5x5", b3r, n5, (5, 5))
        g.add_layer(f"{name}_pool", SubsamplingLayer(
            kernel_size=(3, 3), stride=(1, 1),
            convolution_mode=ConvolutionMode.SAME), inp)
        b4 = self._conv(g, f"{name}_poolproj", f"{name}_pool", pp, (1, 1))
        g.add_vertex(f"{name}_merge", MergeVertex(), b1, b2, b3, b4)
        return f"{name}_merge"

    def conf(self) -> ComputationGraphConfiguration:
        builder = NeuralNetConfiguration.builder() \
            .seed(self.seed) \
            .updater(Nesterovs(1e-2, 0.9)) \
            .weight_init(WeightInit.RELU) \
            .l2(5e-4)
        g = ComputationGraphConfiguration.graph_builder(builder)
        g.add_inputs("input")
        g.set_input_types(InputType.convolutional(self.height, self.width, self.channels))

        x = self._conv(g, "stem_conv1", "input", 64, (7, 7), (2, 2))
        g.add_layer("stem_pool1", SubsamplingLayer(
            kernel_size=(3, 3), stride=(2, 2),
            convolution_mode=ConvolutionMode.SAME), x)
        g.add_layer("stem_lrn1", LocalResponseNormalization(), "stem_pool1")
        x = self._conv(g, "stem_conv2", "stem_lrn1", 64, (1, 1))
        x = self._conv(g, "stem_conv3", x, 192, (3, 3))
        g.add_layer("stem_lrn2", LocalResponseNormalization(), x)
        g.add_layer("stem_pool2", SubsamplingLayer(
            kernel_size=(3, 3), stride=(2, 2),
            convolution_mode=ConvolutionMode.SAME), "stem_lrn2")
        x = "stem_pool2"

        for name in ("3a", "3b"):
            x = self._inception(g, f"inc{name}", x, _INCEPTION[name])
        g.add_layer("pool3", SubsamplingLayer(
            kernel_size=(3, 3), stride=(2, 2),
            convolution_mode=ConvolutionMode.SAME), x)
        x = "pool3"
        for name in ("4a", "4b", "4c", "4d", "4e"):
            x = self._inception(g, f"inc{name}", x, _INCEPTION[name])
        g.add_layer("pool4", SubsamplingLayer(
            kernel_size=(3, 3), stride=(2, 2),
            convolution_mode=ConvolutionMode.SAME), x)
        x = "pool4"
        for name in ("5a", "5b"):
            x = self._inception(g, f"inc{name}", x, _INCEPTION[name])

        g.add_layer("avgpool", GlobalPoolingLayer(pooling_type=PoolingType.AVG), x)
        # reference GoogLeNet.java:172: fc1 1024-wide carrying dropOut(0.4)
        # — DL4J dropOut() is the RETAIN probability
        g.add_layer("fc1", DenseLayer(n_out=1024, activation="relu", dropout=0.4),
                    "avgpool")
        g.add_layer("output", OutputLayer(
            n_out=self.num_classes, activation="softmax", loss="mcxent"), "fc1")
        g.set_outputs("output")
        return g.build()

    def init(self) -> ComputationGraph:
        return ComputationGraph(self.conf()).init(self.seed)
