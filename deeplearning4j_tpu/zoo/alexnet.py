"""AlexNet (reference `zoo/model/AlexNet.java`: conv11x11s4(96) + LRN +
maxpool → conv5x5(256) + LRN + maxpool → conv3x3(384) ×2 → conv3x3(256)
+ maxpool → dense(4096)×2 with dropout → softmax)."""

from __future__ import annotations

from deeplearning4j_tpu.common.distributions import NormalDistribution
from deeplearning4j_tpu.common.updaters import Nesterovs
from deeplearning4j_tpu.common.weights import WeightInit
from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import (
    ConvolutionLayer,
    DenseLayer,
    LocalResponseNormalization,
    OutputLayer,
    SubsamplingLayer,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.zoo.base import ZooModel


class AlexNet(ZooModel):
    def __init__(self, num_classes: int = 1000, seed: int = 123,
                 height: int = 224, width: int = 224, channels: int = 3):
        super().__init__(num_classes=num_classes, seed=seed)
        self.height, self.width, self.channels = height, width, channels

    def conf(self):
        return (NeuralNetConfiguration.builder()
                .seed(self.seed)
                .updater(Nesterovs(1e-2, 0.9))
                .weight_init(WeightInit.DISTRIBUTION)
                .dist(NormalDistribution(0.0, 0.01))
                .l2(5e-4)
                .list()
                .layer(ConvolutionLayer(n_out=96, kernel_size=(11, 11), stride=(4, 4),
                                        activation="relu", name="cnn1"))
                .layer(LocalResponseNormalization(name="lrn1"))
                .layer(SubsamplingLayer(kernel_size=(3, 3), stride=(2, 2), name="maxpool1"))
                .layer(ConvolutionLayer(n_out=256, kernel_size=(5, 5), stride=(1, 1),
                                        padding=(2, 2), activation="relu", bias_init=1.0,
                                        name="cnn2"))
                .layer(LocalResponseNormalization(name="lrn2"))
                .layer(SubsamplingLayer(kernel_size=(3, 3), stride=(2, 2), name="maxpool2"))
                .layer(ConvolutionLayer(n_out=384, kernel_size=(3, 3), stride=(1, 1),
                                        padding=(1, 1), activation="relu", name="cnn3"))
                .layer(ConvolutionLayer(n_out=384, kernel_size=(3, 3), stride=(1, 1),
                                        padding=(1, 1), activation="relu", bias_init=1.0,
                                        name="cnn4"))
                .layer(ConvolutionLayer(n_out=256, kernel_size=(3, 3), stride=(1, 1),
                                        padding=(1, 1), activation="relu", bias_init=1.0,
                                        name="cnn5"))
                .layer(SubsamplingLayer(kernel_size=(3, 3), stride=(2, 2), name="maxpool3"))
                .layer(DenseLayer(n_out=4096, activation="relu", bias_init=1.0,
                                  dropout=0.5, name="ffn1"))
                .layer(DenseLayer(n_out=4096, activation="relu", bias_init=1.0,
                                  dropout=0.5, name="ffn2"))
                .layer(OutputLayer(n_out=self.num_classes, activation="softmax",
                                   loss="mcxent", name="output"))
                .set_input_type(InputType.convolutional(self.height, self.width, self.channels))
                .build())

    def init(self) -> MultiLayerNetwork:
        return MultiLayerNetwork(self.conf()).init(self.seed)
