"""VGG16 / VGG19 (reference `zoo/model/VGG16.java`, `VGG19.java`):
stacked 3x3 same-padded conv blocks with maxpool, then 4096-dense ×2 and
softmax."""

from __future__ import annotations

from deeplearning4j_tpu.common.updaters import Nesterovs
from deeplearning4j_tpu.common.weights import WeightInit
from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import (
    ConvolutionLayer,
    DenseLayer,
    OutputLayer,
    SubsamplingLayer,
)
from deeplearning4j_tpu.nn.layers.convolution import ConvolutionMode
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.zoo.base import PretrainedType, ZooModel


def _vgg_conf(block_sizes, num_classes, seed, height, width, channels):
    b = (NeuralNetConfiguration.builder()
         .seed(seed)
         .updater(Nesterovs(1e-2, 0.9))
         .weight_init(WeightInit.RELU)
         .list())
    i = 0
    for filters, reps in block_sizes:
        for _ in range(reps):
            b = b.layer(ConvolutionLayer(n_out=filters, kernel_size=(3, 3), stride=(1, 1),
                                         convolution_mode=ConvolutionMode.SAME,
                                         activation="relu", name=f"conv{i}"))
            i += 1
        b = b.layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2), name=f"pool{i}"))
    return (b.layer(DenseLayer(n_out=4096, activation="relu", dropout=0.5, name="fc1"))
             .layer(DenseLayer(n_out=4096, activation="relu", dropout=0.5, name="fc2"))
             .layer(OutputLayer(n_out=num_classes, activation="softmax", loss="mcxent",
                                name="output"))
             .set_input_type(InputType.convolutional(height, width, channels))
             .build())


class VGG16(ZooModel):
    BLOCKS = [(64, 2), (128, 2), (256, 3), (512, 3), (512, 3)]

    def __init__(self, num_classes: int = 1000, seed: int = 123,
                 height: int = 224, width: int = 224, channels: int = 3):
        super().__init__(num_classes=num_classes, seed=seed)
        self.height, self.width, self.channels = height, width, channels

    def conf(self):
        return _vgg_conf(self.BLOCKS, self.num_classes, self.seed,
                         self.height, self.width, self.channels)

    def init(self) -> MultiLayerNetwork:
        return MultiLayerNetwork(self.conf()).init(self.seed)

    # Keras-applications hosted weights (reference `ZooModel.java:52-81`
    # pretrainedUrl + checksum pattern; the h5 loads through the Keras
    # importer). Hash is the md5 keras-applications publishes.
    def pretrained_url(self, ptype):
        if ptype == PretrainedType.IMAGENET:
            return ("https://storage.googleapis.com/tensorflow/"
                    "keras-applications/vgg16/"
                    "vgg16_weights_tf_dim_ordering_tf_kernels.h5")
        return None

    def pretrained_checksum(self, ptype):
        if ptype == PretrainedType.IMAGENET:
            return "64373286793e3c8b2b4e3219cbf3544b"
        return None


class VGG19(VGG16):
    BLOCKS = [(64, 2), (128, 2), (256, 4), (512, 4), (512, 4)]

    def pretrained_url(self, ptype):
        if ptype == PretrainedType.IMAGENET:
            return ("https://storage.googleapis.com/tensorflow/"
                    "keras-applications/vgg19/"
                    "vgg19_weights_tf_dim_ordering_tf_kernels.h5")
        return None

    def pretrained_checksum(self, ptype):
        if ptype == PretrainedType.IMAGENET:
            return "cbe5617147190e668d6c5d5026f83318"
        return None
