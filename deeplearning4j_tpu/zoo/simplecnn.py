"""SimpleCNN (reference `zoo/model/SimpleCNN.java`): small conv net with
batchnorm used for quick experiments."""

from __future__ import annotations

from deeplearning4j_tpu.common.updaters import Adam
from deeplearning4j_tpu.common.weights import WeightInit
from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import (
    BatchNormalization,
    ConvolutionLayer,
    DenseLayer,
    OutputLayer,
    SubsamplingLayer,
)
from deeplearning4j_tpu.nn.layers.convolution import ConvolutionMode
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.zoo.base import ZooModel


class SimpleCNN(ZooModel):
    def __init__(self, num_classes: int = 10, seed: int = 123,
                 height: int = 48, width: int = 48, channels: int = 3):
        super().__init__(num_classes=num_classes, seed=seed)
        self.height, self.width, self.channels = height, width, channels

    def conf(self):
        return (NeuralNetConfiguration.builder()
                .seed(self.seed)
                .updater(Adam(1e-3))
                .weight_init(WeightInit.RELU)
                .list()
                .layer(ConvolutionLayer(n_out=16, kernel_size=(3, 3), stride=(1, 1),
                                        convolution_mode=ConvolutionMode.SAME,
                                        activation="relu"))
                .layer(BatchNormalization())
                .layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
                .layer(ConvolutionLayer(n_out=32, kernel_size=(3, 3),
                                        convolution_mode=ConvolutionMode.SAME,
                                        activation="relu"))
                .layer(BatchNormalization())
                .layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
                .layer(DenseLayer(n_out=128, activation="relu", dropout=0.5))
                .layer(OutputLayer(n_out=self.num_classes, activation="softmax",
                                   loss="mcxent"))
                .set_input_type(InputType.convolutional(self.height, self.width, self.channels))
                .build())

    def init(self) -> MultiLayerNetwork:
        return MultiLayerNetwork(self.conf()).init(self.seed)
