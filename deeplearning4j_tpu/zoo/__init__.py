"""Model zoo (reference: deeplearning4j-zoo `zoo/model/*`):
LeNet, AlexNet, VGG16/19, SimpleCNN, ResNet50, GoogLeNet,
InceptionResNetV1, FaceNetNN4Small2, TextGenerationLSTM — each a
config-builder producing a MultiLayerNetwork or ComputationGraph.
"""

from deeplearning4j_tpu.zoo.base import ZooModel, PretrainedType
from deeplearning4j_tpu.zoo.lenet import LeNet
from deeplearning4j_tpu.zoo.alexnet import AlexNet
from deeplearning4j_tpu.zoo.vgg import VGG16, VGG19
from deeplearning4j_tpu.zoo.simplecnn import SimpleCNN
from deeplearning4j_tpu.zoo.resnet50 import ResNet50
from deeplearning4j_tpu.zoo.textgenlstm import TextGenerationLSTM
from deeplearning4j_tpu.zoo.googlenet import GoogLeNet
from deeplearning4j_tpu.zoo.inceptionresnet import InceptionResNetV1
from deeplearning4j_tpu.zoo.facenet import FaceNetNN4Small2
from deeplearning4j_tpu.zoo.transformer import TransformerClassifier, TransformerLM
