"""LeNet (reference `zoo/model/LeNet.java:86-104`): conv5x5(20,relu) →
maxpool2 → conv5x5(50,relu) → maxpool2 → dense(500,relu) →
softmax output. BASELINE config 0 model."""

from __future__ import annotations

from deeplearning4j_tpu.common.updaters import Adam
from deeplearning4j_tpu.common.weights import WeightInit
from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import (
    ConvolutionLayer,
    DenseLayer,
    OutputLayer,
    SubsamplingLayer,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.zoo.base import PretrainedType, ZooModel


class LeNet(ZooModel):
    def __init__(self, num_classes: int = 10, seed: int = 123,
                 height: int = 28, width: int = 28, channels: int = 1,
                 updater=None):
        super().__init__(num_classes=num_classes, seed=seed)
        self.height, self.width, self.channels = height, width, channels
        self.updater = updater or Adam(1e-3)

    def conf(self):
        return (NeuralNetConfiguration.builder()
                .seed(self.seed)
                .updater(self.updater)
                .weight_init(WeightInit.XAVIER)
                .list()
                .layer(ConvolutionLayer(n_out=20, kernel_size=(5, 5), stride=(1, 1),
                                        activation="relu", name="cnn1"))
                .layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2), name="maxpool1"))
                .layer(ConvolutionLayer(n_out=50, kernel_size=(5, 5), stride=(1, 1),
                                        activation="relu", name="cnn2"))
                .layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2), name="maxpool2"))
                .layer(DenseLayer(n_out=500, activation="relu", name="ffn1"))
                .layer(OutputLayer(n_out=self.num_classes, activation="softmax",
                                   loss="mcxent", name="output"))
                .set_input_type(InputType.convolutional(self.height, self.width, self.channels))
                .build())

    def init(self) -> MultiLayerNetwork:
        return MultiLayerNetwork(self.conf()).init(self.seed)

    # Packaged pretrained checkpoint: trained on the real sklearn
    # handwritten-digits corpus (see zoo/weights/MANIFEST.json for
    # provenance + held-out accuracy). Ships inside the wheel so
    # `init_pretrained(MNIST)` works offline end-to-end (reference
    # `ZooModel.initPretrained` downloads from a blob host :52-81).
    packaged = {PretrainedType.MNIST: "lenet_mnist.zip"}
