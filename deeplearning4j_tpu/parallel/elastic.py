"""Elastic multi-process training runtime.

The layer the reference delegated to Spark TrainingMasters + the Aeron
parameter server (PAPER.md survey layers 7-8), rebuilt TPU-native:
topology is no longer fixed at `jax.distributed.initialize` time. A
lightweight membership coordinator tracks live processes over a tiny
TCP/JSON control plane; when a process joins or misses heartbeats past
the grace window, the coordinator publishes a new GENERATION — a
numbered plan naming the member set, each member's rank, and a fresh
`jax.distributed` coordinator port. Workers drain their fit at an
agreed step boundary, checkpoint, tear the distributed runtime down
(`shutdown_multihost`), re-initialize with the new process set, re-form
the mesh, and resume from the newest valid checkpoint with elastic
re-shard of gradient-sharing residual/τ and per-replica updater stacks
(`fault.reshard_replica_stack`). arXiv:2606.15870 names exactly this
recover-reshape-resume loop as the defining constraint of training
supercomputers; checkpoint-based restart as the recovery primitive
follows arXiv:1605.08695.

Three coordination problems this module solves, and how:

1. **Membership** — `ElasticCoordinator` (any process can host it; by
   convention process 0 of the fleet, or the drill/fleet driver, since
   the host must outlive worker churn). Members register with a stable
   token, heartbeat at `heartbeat_interval_s`, and are evicted after
   `grace_s` without a beat. Changes coalesce for `settle_s` before a
   generation commits, so a wave of simultaneous joins forms ONE new
   generation.

2. **Synchronized drain** — the generation-change notice arrives on
   each worker's heartbeat thread at a different wall time, but every
   process must leave the fit at the SAME step (a process that stops
   early strands its peers inside a collective). At each step boundary
   the drain listener all-reduces a 1-int "I want to reconfigure" flag
   over the data axis — the agreement rides the same collectives as
   training — and only when the GLOBAL flag is set do all processes
   checkpoint (same step → the multi-process commit barrier lines up)
   and raise `ElasticReconfiguration` together.

3. **Survive-the-kill** — a SIGKILLed peer cannot drain. Survivors see
   the break as a collective/coordination error (gloo connection reset,
   coordination-service heartbeat timeout — detection is tightened via
   `initialize_multihost(heartbeat_interval_s=, max_missing_heartbeats=)`),
   and a survivor wedged inside a dead collective is terminated by the
   jax coordination service itself. Either way the escape is
   process-level: `on_fatal="exit"` exits with `RESTART_EXIT_CODE` for
   a supervisor to relaunch (scripts/fault_drill.py does), or
   `on_fatal="exec"` re-execs this process in place. The relaunched
   worker re-registers under the same token and resumes from the newest
   valid checkpoint — recovery is restart-shaped, exactly the
   checkpoint-restart primitive the rest of `fault/` provides.
"""

from __future__ import annotations

import json
import logging
import os
import re
import socket
import socketserver
import sys
import threading
import time
from dataclasses import dataclass, field
from functools import partial
from typing import Callable, Dict, List, Optional

from deeplearning4j_tpu.fault.errors import (
    ElasticMembershipError,
    ElasticReconfiguration,
)
from deeplearning4j_tpu.optimize.listeners import TrainingListener

log = logging.getLogger("deeplearning4j_tpu.parallel.elastic")

#: exit code a worker uses for "relaunch me into the current
#: generation" (distinct from success and from ordinary failures)
RESTART_EXIT_CODE = 17

# error-message markers classifying a raised exception as "the
# distributed runtime broke under us" (peer death) rather than a bug
_FATAL_MARKERS = ("Gloo", "gloo", "heartbeat", "DEADLINE_EXCEEDED",
                  "UNAVAILABLE", "coordination", "Coordination",
                  "Connection reset", "Socket closed", "Connection refused",
                  "distributed service", "INTERNAL:")


def distributed_failure(err: BaseException) -> bool:
    """True when `err` looks like a broken distributed runtime (a peer
    died mid-collective / coordination-service failure) rather than an
    ordinary training error."""
    msg = str(err)
    return any(m in msg for m in _FATAL_MARKERS)


# =====================================================================
# control-plane wire helpers (newline-delimited JSON, one request per
# connection — tiny payloads, worst-case a few KB of plan)
# =====================================================================
def _send_request(address: str, payload: dict, timeout: float) -> dict:
    host, port = address.rsplit(":", 1)
    with socket.create_connection((host, int(port)), timeout=timeout) as s:
        s.settimeout(timeout)
        s.sendall((json.dumps(payload) + "\n").encode())
        buf = b""
        while not buf.endswith(b"\n"):
            chunk = s.recv(65536)
            if not chunk:
                break
            buf += chunk
    if not buf:
        raise ConnectionError("empty control-plane response")
    return json.loads(buf.decode())


def retry_request(address: str, payload: dict, *, timeout: float = 5.0,
                  attempts: int = 5, backoff_s: float = 0.2) -> dict:
    """Bounded retry-with-backoff around one control-plane request.
    Raises `ElasticMembershipError` once the attempts are exhausted —
    callers decide whether a lost control plane is fatal (the fit loop
    keeps training on the last known topology)."""
    last: Optional[Exception] = None
    for attempt in range(max(1, int(attempts))):
        try:
            reply = _send_request(address, payload, timeout)
            if not reply.get("ok", False):
                raise ElasticMembershipError(
                    f"control plane rejected {payload.get('op')!r}: "
                    f"{reply.get('error')}")
            return reply
        except ElasticMembershipError:
            raise
        except (OSError, ValueError, ConnectionError) as e:
            last = e
            if attempt + 1 < max(1, int(attempts)):
                time.sleep(backoff_s * (2 ** attempt))
    raise ElasticMembershipError(
        f"control plane at {address} unreachable after {attempts} "
        f"attempts: {last}") from last


# =====================================================================
# coordinator
# =====================================================================
@dataclass
class _Member:
    token: str
    host: str
    device_count: int
    last_seen: float
    info: dict = field(default_factory=dict)


class ElasticCoordinator:
    """Membership + generation service (the control plane).

    State machine: any membership change (register of a NEW token,
    leave, eviction after `grace_s` missed heartbeats) marks the
    member set dirty; once `settle_s` passes without further change —
    and at least `min_members` are present for the FIRST generation —
    a new generation commits: members rank-ordered by token, the jax
    coordinator placed on rank 0's host at `jax_port_base +
    (generation % jax_port_span)` (a bumped port per generation, so a
    half-dead predecessor service can never poison the next world).

    Metrics (when `monitor.enable()` is on in the hosting process):
    ``elastic_live_processes``, ``elastic_generation`` gauges and
    ``elastic_reconfigurations_total`` counter (bumps counted after
    the initial formation).
    """

    def __init__(self, *, host: str = "127.0.0.1", port: int = 0,
                 grace_s: float = 5.0, settle_s: float = 1.0,
                 tick_s: float = 0.25, min_members: int = 1,
                 jax_port_base: int = 52000, jax_port_span: int = 500):
        self.host = host
        self.grace_s = float(grace_s)
        self.settle_s = float(settle_s)
        self.tick_s = float(tick_s)
        self.min_members = int(min_members)
        self.jax_port_base = int(jax_port_base)
        self.jax_port_span = int(jax_port_span)
        self._lock = threading.Lock()
        self._members: Dict[str, _Member] = {}
        self._completed: set = set()
        self._generation = 0
        self._plan: Optional[dict] = None
        self._dirty_since: Optional[float] = time.monotonic()
        self._stopped = threading.Event()
        coordinator = self

        class _Handler(socketserver.StreamRequestHandler):
            def handle(self):
                try:
                    line = self.rfile.readline(1 << 20)
                    req = json.loads(line.decode())
                    reply = coordinator._handle(req)
                except Exception as e:  # noqa: BLE001 — wire errors
                    reply = {"ok": False, "error": f"{type(e).__name__}: {e}"}
                try:
                    self.wfile.write((json.dumps(reply) + "\n").encode())
                except OSError:
                    pass

        class _Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = _Server((host, port), _Handler)
        self.port = self._server.server_address[1]
        self.address = f"{host}:{self.port}"
        self._serve_thread = threading.Thread(
            target=self._server.serve_forever, name="elastic-coordinator",
            daemon=True)
        self._monitor_thread = threading.Thread(
            target=self._monitor_loop, name="elastic-membership-monitor",
            daemon=True)

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "ElasticCoordinator":
        self._serve_thread.start()
        self._monitor_thread.start()
        log.info("elastic coordinator serving on %s", self.address)
        return self

    def stop(self):
        self._stopped.set()
        self._server.shutdown()
        self._server.server_close()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    # ------------------------------------------------------------- requests
    def _handle(self, req: dict) -> dict:
        op = req.get("op")
        if op == "register":
            return self._op_register(req)
        if op == "heartbeat":
            return self._op_heartbeat(req)
        if op == "leave":
            return self._op_leave(req)
        if op == "plan":
            with self._lock:
                return {"ok": True, "generation": self._generation,
                        "plan": self._plan}
        if op == "status":
            return {"ok": True, "status": self.status()}
        raise ValueError(f"unknown control-plane op {op!r}")

    def _op_register(self, req: dict) -> dict:
        token = str(req["token"])
        now = time.monotonic()
        with self._lock:
            fresh = token not in self._members
            self._members[token] = _Member(
                token=token, host=str(req.get("host", "127.0.0.1")),
                device_count=int(req.get("device_count", 1)),
                last_seen=now, info=dict(req.get("info") or {}))
            self._completed.discard(token)
            if fresh:
                self._dirty_since = now
                log.info("member %s registered (%d live)", token,
                         len(self._members))
            return {"ok": True, "generation": self._generation,
                    "plan": self._plan, "member": True}

    def _op_heartbeat(self, req: dict) -> dict:
        token = str(req["token"])
        now = time.monotonic()
        with self._lock:
            m = self._members.get(token)
            if m is None:
                # evicted (or unknown): tell the worker to re-register
                return {"ok": True, "generation": self._generation,
                        "member": False}
            m.last_seen = now
            if req.get("info"):
                m.info.update(req["info"])
            reply = {"ok": True, "generation": self._generation,
                     "member": True}
            if int(req.get("generation", -1)) != self._generation:
                reply["plan"] = self._plan
            return reply

    def _op_leave(self, req: dict) -> dict:
        token = str(req["token"])
        with self._lock:
            if token in self._members:
                del self._members[token]
                if req.get("reason") == "complete":
                    self._completed.add(token)
                self._dirty_since = time.monotonic()
                log.info("member %s left (%s; %d live)", token,
                         req.get("reason", "unspecified"),
                         len(self._members))
            return {"ok": True, "generation": self._generation}

    # -------------------------------------------------------- plan machine
    def _monitor_loop(self):
        while not self._stopped.wait(self.tick_s):
            now = time.monotonic()
            with self._lock:
                stale = [t for t, m in self._members.items()
                         if now - m.last_seen > self.grace_s]
                for t in stale:
                    del self._members[t]
                    self._dirty_since = now
                    log.warning("member %s evicted after %.1fs without a "
                                "heartbeat (%d live)", t, self.grace_s,
                                len(self._members))
                if (self._dirty_since is not None
                        and now - self._dirty_since >= self.settle_s
                        and (self._plan is not None
                             or len(self._members) >= self.min_members)):
                    self._commit_generation()

    def _commit_generation(self):
        # lock held by caller
        self._generation += 1
        # serving members advertise capacity, not training ranks: they
        # never enter the rank-numbered data-parallel plan (a decode
        # replica must not shift every trainer's rank when it joins),
        # but ride the SAME generation number so a router sees one
        # consistent replica view across joins/deaths
        members = sorted((m for m in self._members.values()
                          if m.info.get("role") != "serving"),
                         key=lambda m: m.token)
        serving = sorted((m for m in self._members.values()
                          if m.info.get("role") == "serving"),
                         key=lambda m: m.token)
        port = self.jax_port_base + (self._generation % self.jax_port_span)
        self._plan = {
            "generation": self._generation,
            "num_processes": len(members),
            "members": [{"token": m.token, "host": m.host,
                         "device_count": m.device_count, "rank": r}
                        for r, m in enumerate(members)],
            "serving_members": [{"token": m.token, "host": m.host,
                                 "info": dict(m.info)}
                                for m in serving],
            "coordinator_address": (f"{members[0].host}:{port}"
                                    if members else None),
        }
        self._dirty_since = None
        from deeplearning4j_tpu.monitor.flightrec import (
            GLOBAL_FLIGHT_RECORDER,
        )
        GLOBAL_FLIGHT_RECORDER.record(
            "elastic_reconfiguration", generation=self._generation,
            members=[m.token for m in members])
        log.info("committed generation %d: %s", self._generation,
                 [m.token for m in members])
        self._record_metrics()

    def _record_metrics(self):
        from deeplearning4j_tpu import monitor
        if not monitor.is_enabled():
            return
        reg = monitor.registry()
        reg.gauge("elastic_live_processes",
                  help="members of the current elastic generation"
                  ).set(len(self._members))
        reg.gauge("elastic_generation",
                  help="current elastic membership generation"
                  ).set(self._generation)
        if self._generation > 1:
            reg.counter(
                "elastic_reconfigurations_total",
                help="committed membership changes after initial "
                     "formation").inc()

    # --------------------------------------------------------------- views
    def status(self) -> dict:
        with self._lock:
            return {"generation": self._generation, "plan": self._plan,
                    "completed": sorted(self._completed),
                    "members": {t: {"host": m.host,
                                    "device_count": m.device_count,
                                    "info": dict(m.info)}
                                for t, m in self._members.items()}}


def serving_directory(status: dict, model: Optional[str] = None) -> dict:
    """Replica view over a coordinator `status()` payload: the live
    serving-role members (optionally filtered to one model) with the
    freshest heartbeat-carried load gauges, under the membership
    generation number. This is what a router polls — `status()`
    reflects member info updated on EVERY heartbeat, while the
    committed plan only snapshots info at generation boundaries.

    Returns ``{"generation": g, "replicas": [{token, host, port,
    model, load}, ...]}`` with replicas in stable token order; `load`
    carries whatever gauges the replica advertised (queue_depth,
    outstanding_tokens, ewma_tok_s, open_streams, n_slots)."""
    replicas = []
    for token, m in (status.get("members") or {}).items():
        info = m.get("info") or {}
        if info.get("role") != "serving":
            continue
        if model is not None and info.get("model") != model:
            continue
        addr = info.get("addr") or [m.get("host"), None]
        replicas.append({
            "token": token,
            "host": addr[0],
            "port": None if addr[1] is None else int(addr[1]),
            "model": info.get("model"),
            "version": info.get("version"),
            "load": {k: info[k] for k in
                     ("queue_depth", "outstanding_tokens", "ewma_tok_s",
                      "open_streams", "n_slots") if k in info},
        })
    replicas.sort(key=lambda r: r["token"])
    return {"generation": int(status.get("generation") or 0),
            "replicas": replicas}


# =====================================================================
# client
# =====================================================================
class ElasticClient:
    """Worker-side view of the control plane: registration, a daemon
    heartbeat thread, and the latest generation/plan. All I/O goes
    through `retry_request` (bounded retry + exponential backoff); a
    lost control plane degrades to a warning — training continues on
    the last known topology until it returns."""

    def __init__(self, address: str, token: str, *,
                 heartbeat_interval_s: float = 0.5, io_timeout_s: float = 5.0,
                 io_attempts: int = 5, backoff_s: float = 0.2):
        self.address = address
        self.token = token
        self.heartbeat_interval_s = float(heartbeat_interval_s)
        self.io_timeout_s = float(io_timeout_s)
        self.io_attempts = int(io_attempts)
        self.backoff_s = float(backoff_s)
        self._lock = threading.Lock()
        self._generation = 0
        self._plan: Optional[dict] = None
        self._info: dict = {}
        self._registration: Optional[dict] = None
        self._stopped = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._unreachable_since: Optional[float] = None
        self._federate_worker: Optional[str] = None

    # ------------------------------------------------------------------ io
    def _request(self, payload: dict) -> dict:
        return retry_request(self.address, payload,
                             timeout=self.io_timeout_s,
                             attempts=self.io_attempts,
                             backoff_s=self.backoff_s)

    def register(self, *, host: str = "127.0.0.1",
                 device_count: int = 1, info: Optional[dict] = None) -> dict:
        self._registration = {"op": "register", "token": self.token,
                              "host": host, "device_count": device_count,
                              "info": info or {}}
        reply = self._request(self._registration)
        self._absorb(reply)
        return reply

    def register_serving(self, *, model: str, host: str, port: int,
                         info: Optional[dict] = None) -> dict:
        """Register as a SERVING member: advertises capacity for
        `model` at `host:port` instead of training ranks. Serving
        members never enter the rank-numbered training plan; they show
        up in `plan["serving_members"]` / `serving_directory()` under
        the same generation numbers. Load gauges (queue depth,
        outstanding tokens, tok/s EWMA) ride `set_info` on every
        heartbeat."""
        full = {"role": "serving", "model": str(model),
                "addr": [host, int(port)]}
        full.update(info or {})
        with self._lock:
            self._info.update(full)
        return self.register(host=host, device_count=0, info=full)

    def leave(self, reason: str = "unspecified"):
        try:
            self._request({"op": "leave", "token": self.token,
                           "reason": reason})
        except ElasticMembershipError as e:
            log.warning("leave(%s) failed: %s", reason, e)

    def status(self) -> dict:
        return self._request({"op": "status"})["status"]

    # ----------------------------------------------------------- heartbeat
    def start_heartbeats(self):
        if self._thread is not None and self._thread.is_alive():
            return
        self._stopped.clear()
        self._thread = threading.Thread(target=self._beat_loop,
                                        name=f"elastic-hb-{self.token}",
                                        daemon=True)
        self._thread.start()

    def stop(self):
        self._stopped.set()
        if self._thread is not None:
            self._thread.join(timeout=2 * self.heartbeat_interval_s + 1)

    def _beat_loop(self):
        while not self._stopped.wait(self.heartbeat_interval_s):
            self._refresh_federated_metrics()
            with self._lock:
                payload = {"op": "heartbeat", "token": self.token,
                           "generation": self._generation,
                           "info": dict(self._info)}
            try:
                reply = retry_request(self.address, payload,
                                      timeout=self.io_timeout_s,
                                      attempts=2, backoff_s=self.backoff_s)
            except ElasticMembershipError as e:
                if self._unreachable_since is None:
                    self._unreachable_since = time.monotonic()
                    log.warning("control plane unreachable (%s); training "
                                "continues on the current topology", e)
                continue
            self._unreachable_since = None
            if not reply.get("member", True) and self._registration:
                # evicted while alive (e.g. a long stall): re-register
                log.warning("member %s was evicted; re-registering",
                            self.token)
                try:
                    reply = self._request(self._registration)
                except ElasticMembershipError as e:
                    log.warning("re-register failed: %s", e)
                    continue
            self._absorb(reply)

    def _absorb(self, reply: dict):
        with self._lock:
            gen = int(reply.get("generation", self._generation))
            if reply.get("plan") is not None:
                self._plan = reply["plan"]
            if gen != self._generation:
                self._generation = gen

    # --------------------------------------------------------------- views
    def set_info(self, **info):
        with self._lock:
            self._info.update(info)

    def federate_metrics(self, worker: Optional[str] = None):
        """Piggyback this worker's metrics registry on the heartbeat
        info channel: every beat refreshes ``info["metrics"]`` with a
        `monitor.federate.export_snapshot`, so the coordinator's
        `status()` carries one labeled snapshot per live member and
        `monitor.federate.ingest_elastic_status` can merge the whole
        training fleet into a single /metrics view — no extra
        transport, no extra sockets."""
        self._federate_worker = worker or self.token
        self._refresh_federated_metrics()

    def _refresh_federated_metrics(self):
        if self._federate_worker is None:
            return
        from deeplearning4j_tpu import monitor
        if not monitor.is_enabled():
            return
        from deeplearning4j_tpu.monitor.federate import export_snapshot
        snap = export_snapshot(monitor.registry(),
                               worker=self._federate_worker)
        with self._lock:
            self._info["metrics"] = snap

    def generation(self) -> int:
        with self._lock:
            return self._generation

    def current_plan(self) -> Optional[dict]:
        with self._lock:
            return self._plan

    def my_rank(self, plan: Optional[dict] = None) -> Optional[int]:
        plan = plan if plan is not None else self.current_plan()
        if not plan:
            return None
        for m in plan["members"]:
            if m["token"] == self.token:
                return int(m["rank"])
        return None

    def await_member_plan(self, *, timeout_s: float = 120.0,
                          poll_s: float = 0.2) -> dict:
        """Block until a plan naming this member exists; refreshes from
        the control plane (register-time replies can predate the first
        commit). Raises `ElasticMembershipError` on timeout."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            reply = self._request({"op": "plan"})
            self._absorb(reply)
            plan = self.current_plan()
            if plan is not None and self.my_rank(plan) is not None:
                return plan
            time.sleep(poll_s)
        raise ElasticMembershipError(
            f"no plan including member {self.token!r} within {timeout_s}s")


# =====================================================================
# drain listener — synchronized exit from a running fit
# =====================================================================
class _DrainListener(TrainingListener):
    """Listener that, at each fused step boundary, all-reduces the
    local "my generation is stale" flag over the data axis. When the
    GLOBAL flag is set, every process — at the SAME step — saves a
    drain checkpoint, waits for the commit, and raises
    `ElasticReconfiguration`."""

    def __init__(self, client: ElasticClient, run_generation: int,
                 drain_check: Callable[[bool], bool],
                 ckpt_listener=None):
        self.client = client
        self.run_generation = run_generation
        self.drain_check = drain_check
        self.ckpt_listener = ckpt_listener

    def iteration_done(self, model, iteration, epoch, score, **info):
        if not info.get("step_boundary", True):
            return
        step = iteration + 1
        self.client.set_info(step=step, phase="fit")
        local = self.client.generation() != self.run_generation
        if not self.drain_check(local):
            return
        # every process reaches this branch at the same step boundary
        if self.ckpt_listener is not None:
            self.ckpt_listener.save_now(model, step, epoch)
            self.ckpt_listener.checkpointer.wait()
        from deeplearning4j_tpu import monitor
        if monitor.is_enabled():
            monitor.registry().counter(
                "elastic_drains_total",
                help="synchronized drains out of a running fit").inc()
        raise ElasticReconfiguration(self.client.generation(), step)


def make_drain_check(mesh, data_axis: str = "data"):
    """The in-band agreement primitive: a jitted psum of one int32 per
    device over the data axis. Each process contributes its LOCAL flag
    on its addressable shard; the reduced value is the global OR. One
    tiny dispatch per step boundary — it rides the same collectives as
    training, so agreement and training share fate."""
    import jax
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from deeplearning4j_tpu.parallel.compat import shard_map

    n = int(np.prod([mesh.shape[a] for a in (data_axis,)]))
    sharding = NamedSharding(mesh, P(data_axis))

    @partial(shard_map, mesh=mesh, in_specs=P(data_axis), out_specs=P(),
             check_vma=False)
    def agg(flags):
        return jax.lax.psum(flags, data_axis)

    agg = jax.jit(agg)
    n_local = len([d for d in mesh.devices.flat
                   if d.process_index == jax.process_index()])

    def check(local_flag: bool) -> bool:
        local = np.full((max(1, n_local),), int(bool(local_flag)), np.int32)
        arr = jax.make_array_from_process_local_data(sharding, local, (n,))
        return int(np.asarray(agg(arr))[0]) > 0

    return check


# =====================================================================
# elastic trainer
# =====================================================================
@dataclass
class ElasticConfig:
    """Knobs of the elastic runtime (control plane + jax runtime)."""

    control_address: str
    token: str
    host: str = "127.0.0.1"
    heartbeat_interval_s: float = 0.5
    io_timeout_s: float = 5.0
    io_attempts: int = 5
    backoff_s: float = 0.2
    join_timeout_s: float = 120.0
    #: jax.distributed knobs — elastic recovery wants peer death
    #: detected in seconds, and init attempts short enough to re-fetch
    #: a newer plan when a generation is superseded mid-join
    init_timeout_s: float = 30.0
    init_attempts: int = 3
    jax_heartbeat_interval_s: float = 1.0
    jax_max_missing_heartbeats: int = 5
    #: what to do when the distributed runtime breaks under us (a peer
    #: was hard-killed): "raise" re-raises for the caller/supervisor,
    #: "exit" exits with RESTART_EXIT_CODE, "exec" re-execs sys.argv
    on_fatal: str = "raise"
    max_generations: int = 50


class ElasticTrainer:
    """Restartable fit around `ParallelTrainer` (sync dense / threshold
    / rs modes): joins the current membership generation, trains until
    either the run completes or the generation changes, then drains,
    re-forms the mesh and resumes — forever, until `epochs` epochs are
    done. See the module docstring for the protocol.

    `build_model` is called once per generation (the model/jit programs
    are mesh-shaped); state continuity comes exclusively from the fault
    checkpointer, which is also what makes a SIGKILLed-and-relaunched
    worker indistinguishable from a drained one."""

    def __init__(self, build_model: Callable[[], object], *,
                 config: ElasticConfig, ckpt_dir, ckpt_frequency: int = 5,
                 keep_last: int = 5, mode: str = "sync",
                 gradient_sharing: Optional[str] = None,
                 trainer_kwargs: Optional[dict] = None):
        self.build_model = build_model
        self.config = config
        self.ckpt_dir = ckpt_dir
        self.ckpt_frequency = int(ckpt_frequency)
        self.keep_last = int(keep_last)
        self.mode = mode
        self.gradient_sharing = gradient_sharing
        self.trainer_kwargs = dict(trainer_kwargs or {})
        self.client = ElasticClient(
            config.control_address, config.token,
            heartbeat_interval_s=config.heartbeat_interval_s,
            io_timeout_s=config.io_timeout_s,
            io_attempts=config.io_attempts, backoff_s=config.backoff_s)
        #: per-generation resume reports (drill/test introspection):
        #: {generation, n_workers, resumed, residual_restored, step}
        self.history: List[dict] = []

    # ----------------------------------------------------- runtime seams
    # overridable for in-process tests (no real jax.distributed)
    def _init_runtime(self, plan: dict):
        from deeplearning4j_tpu.parallel.multihost import (
            _clear_topology_caches,
            initialize_multihost,
            multihost_active,
        )
        if plan["num_processes"] <= 1:
            return
        cfg = self.config
        if not multihost_active():
            # a stray pre-init device probe instantiates a 1-process
            # backend that would silently pin the whole "multi-process"
            # world at n_workers=1 — clear it before forming the real one
            _clear_topology_caches()
        initialize_multihost(
            plan["coordinator_address"], plan["num_processes"],
            self.client.my_rank(plan),
            initialization_timeout=cfg.init_timeout_s,
            heartbeat_interval_s=cfg.jax_heartbeat_interval_s,
            max_missing_heartbeats=cfg.jax_max_missing_heartbeats,
            max_attempts=cfg.init_attempts)

    def _teardown_runtime(self):
        from deeplearning4j_tpu.parallel.multihost import shutdown_multihost
        shutdown_multihost()

    def _mesh(self, plan: dict):
        from deeplearning4j_tpu.parallel.mesh import device_mesh
        return device_mesh()

    # ------------------------------------------------------------- fit
    def fit(self, iterator_factory: Callable[[], object], *,
            epochs: int, batch_size: int, steps_per_execution: int = 1,
            extra_listeners: Optional[Callable[[int], list]] = None):
        """Run `epochs` epochs elastically. `iterator_factory` builds a
        fresh seekable DataSetIterator per generation (the checkpoint
        cursor repositions it). `extra_listeners(generation)` may
        contribute per-generation listeners (score collectors etc.).
        Returns the trained model of the final generation."""
        cfg = self.config
        self.client.register(host=cfg.host,
                             device_count=self._local_device_count(),
                             info={"phase": "join"})
        self.client.start_heartbeats()
        try:
            return self._fit_loop(iterator_factory, epochs, batch_size,
                                  steps_per_execution, extra_listeners)
        finally:
            self.client.stop()

    def _fit_loop(self, iterator_factory, epochs, batch_size,
                  steps_per_execution, extra_listeners):
        cfg = self.config
        for _ in range(cfg.max_generations):
            plan = self.client.await_member_plan(
                timeout_s=cfg.join_timeout_s)
            gen = int(plan["generation"])
            self.client.set_info(phase="init", generation=gen)
            try:
                self._init_runtime(plan)
            except Exception as e:  # noqa: BLE001 — classify below
                self._teardown_runtime()
                if self.client.generation() != gen:
                    log.warning("generation %d superseded while joining "
                                "(%s); rejoining", gen, str(e)[:120])
                    continue
                raise
            try:
                model, done = self._run_generation(
                    plan, iterator_factory, epochs, batch_size,
                    steps_per_execution, extra_listeners)
            except ElasticReconfiguration as e:
                log.info("generation %d drained at step %d; re-forming",
                         gen, e.step)
                self._teardown_runtime()
                continue
            except Exception as e:  # noqa: BLE001 — classify below
                if distributed_failure(e):
                    self._handle_fatal(e, gen)
                raise
            if done:
                self.client.leave(reason="complete")
                return model
        raise ElasticMembershipError(
            f"run did not complete within {cfg.max_generations} "
            f"membership generations")

    def _run_generation(self, plan, iterator_factory, epochs, batch_size,
                        steps_per_execution, extra_listeners):
        from deeplearning4j_tpu import fault, monitor
        from deeplearning4j_tpu.parallel.trainer import ParallelTrainer

        gen = int(plan["generation"])
        mesh = self._mesh(plan)
        model = self.build_model()
        trainer = ParallelTrainer(model, mesh, mode=self.mode,
                                  gradient_sharing=self.gradient_sharing,
                                  **self.trainer_kwargs)
        iterator = iterator_factory()
        resumed = False
        try:
            trainer.resume(self.ckpt_dir, iterator=iterator)
            resumed = True
        except FileNotFoundError:
            if not getattr(model, "_initialized", False):
                model.init()
        report = {"generation": gen, "n_workers": trainer.n_workers,
                  "resumed": resumed,
                  "residual_restored": trainer._thr_residual_r is not None,
                  "step": int(model.iteration_count)}
        self.history.append(report)
        if monitor.is_enabled():
            reg = monitor.registry()
            reg.gauge("elastic_generation",
                      help="current elastic membership generation").set(gen)
            if resumed:
                reg.counter("elastic_resume_total",
                            help="elastic resumes from checkpoint").inc()
        log.info("generation %d: %d workers, resumed=%s at step %d",
                 gen, trainer.n_workers, resumed, model.iteration_count)

        ck = fault.AsyncCheckpointer(self.ckpt_dir,
                                     keep_last=self.keep_last)
        ckl = fault.CheckpointListener(ck, frequency=self.ckpt_frequency,
                                       iterator=iterator)
        drain = _DrainListener(self.client, gen,
                               make_drain_check(mesh), ckpt_listener=ckl)
        extras = list(extra_listeners(gen)) if extra_listeners else []
        for lst in extras + [ckl, drain]:
            model.add_listener(lst)
        self.client.set_info(phase="fit", generation=gen,
                             step=int(model.iteration_count))
        remaining = int(epochs) - int(model.epoch_count)
        try:
            if remaining > 0:
                trainer.fit(iterator, epochs=remaining,
                            batch_size=batch_size,
                            steps_per_execution=steps_per_execution)
        finally:
            # the drain path needs pending saves durable BEFORE teardown
            try:
                ck.wait()
            except Exception as e:  # noqa: BLE001
                log.warning("checkpoint drain on generation exit: %s", e)
        self.client.set_info(phase="done", step=int(model.iteration_count))
        return model, True

    # ----------------------------------------------------------- plumbing
    @staticmethod
    def _local_device_count() -> int:
        # MUST NOT instantiate a backend: registration happens before
        # `initialize_multihost`, and a pre-init device query would
        # create a single-process CPU client that pins the world at one
        # process. Query jax only when a backend already exists.
        from jax._src import xla_bridge as xb
        if getattr(xb, "_backends", None):
            import jax
            return jax.local_device_count()
        m = re.search(r"xla_force_host_platform_device_count=(\d+)",
                      os.environ.get("XLA_FLAGS", ""))
        return int(m.group(1)) if m else 1

    def _handle_fatal(self, err: BaseException, generation: int):
        cfg = self.config
        log.error("distributed runtime failed under generation %d: %s",
                  generation, str(err)[:300])
        if cfg.on_fatal == "exit":
            # a wedged peer is unrecoverable in-process; the supervisor
            # relaunches us and we resume from the newest checkpoint
            os._exit(RESTART_EXIT_CODE)
        if cfg.on_fatal == "exec":
            log.warning("re-execing %s %s", sys.executable, sys.argv)
            os.execv(sys.executable, [sys.executable] + sys.argv)
        # "raise": fall through — caller re-raises


def elastic_fit(build_model, iterator_factory, *, config: ElasticConfig,
                ckpt_dir, epochs: int, batch_size: int,
                mode: str = "sync", gradient_sharing: Optional[str] = None,
                ckpt_frequency: int = 5, steps_per_execution: int = 1,
                extra_listeners=None, trainer_kwargs=None,
                keep_last: int = 5):
    """One-call elastic training: build the trainer, join the
    membership, survive reconfigurations, return the trained model.
    See `ElasticTrainer`."""
    et = ElasticTrainer(build_model, config=config, ckpt_dir=ckpt_dir,
                        ckpt_frequency=ckpt_frequency, keep_last=keep_last,
                        mode=mode, gradient_sharing=gradient_sharing,
                        trainer_kwargs=trainer_kwargs)
    model = et.fit(iterator_factory, epochs=epochs, batch_size=batch_size,
                   steps_per_execution=steps_per_execution,
                   extra_listeners=extra_listeners)
    return model, et
