"""Ring attention — sequence/context parallelism over a mesh axis.

No reference equivalent (the 2017 codebase scales sequences with TBPTT
only, SURVEY §5); this is new-design territory the TPU rebuild treats
as first-class: the sequence axis is sharded across devices, K/V blocks
rotate around the ICI ring via `ppermute`, and each device accumulates
its queries' attention with the numerically-stable online-softmax
(flash-attention style) running max/denominator. Math is EXACTLY
standard attention; wall-clock is one ring rotation (P-1 ppermutes)
with compute/communication overlap left to XLA.

Use inside `shard_map` over a mesh with a "seq" axis, or through
`sequence_parallel_attention` which wraps the shard_map for full
[B, T, H, D] arrays.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.parallel.compat import axis_size, shard_map


def ring_attention(q, k, v, axis_name: str, causal: bool = False,
                   use_flash: bool = False):
    """Per-shard blocks: q, k, v [B, T_local, H, Dh] (this device's
    sequence chunk). Returns o [B, T_local, H, Dh].

    Must run inside shard_map/pmap with `axis_name` bound.

    `use_flash=True` folds each rotated K/V block through the streaming
    Pallas carry kernel (`kernels.flash_attention.flash_attention_carry`)
    instead of the XLA einsum path: the local [T_local, T_local] score
    tile never materializes in HBM, compounding the sequence-parallel
    memory win with the flash one. Chunk visibility (fully visible /
    diagonal / fully masked) is dispatched by `lax.switch` on the
    rotated block's origin, so the kernels stay static.
    """
    if use_flash:
        return _ring_attention_flash(q, k, v, axis_name, causal)

    P_ = axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    B, Tl, H, Dh = q.shape
    scale = 1.0 / jnp.sqrt(jnp.asarray(Dh, q.dtype))

    q_pos = idx * Tl + jnp.arange(Tl)                      # global positions

    def attend(acc, k_blk, v_blk, step):
        """Fold one K/V block into the online-softmax accumulator."""
        m, l, o = acc
        # the block currently held originated on device (idx + step) % P
        src = (idx + step) % P_
        k_pos = src * Tl + jnp.arange(Tl)
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k_blk) * scale
        if causal:
            ok = k_pos[None, :] <= q_pos[:, None]          # [Tq, Tk]
            scores = jnp.where(ok[None, None], scores, -jnp.inf)
        blk_max = jnp.max(scores, axis=-1)                 # [B,H,Tq]
        m_new = jnp.maximum(m, blk_max)
        # guard -inf rows (no valid key yet): exp(-inf - -inf) → use where
        m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        p = jnp.exp(jnp.where(jnp.isfinite(scores),
                              scores - m_safe[..., None], -jnp.inf))
        p = jnp.where(jnp.isfinite(scores), p, 0.0)
        corr = jnp.where(jnp.isfinite(m), jnp.exp(m - m_safe), 0.0)
        l_new = l * corr + jnp.sum(p, axis=-1)
        o_new = o * corr[..., None] + jnp.einsum("bhqk,bkhd->bhqd", p, v_blk)
        return (m_new, l_new, o_new)

    perm = _ring_perm(P_)

    def block(carry, step):
        k_blk, v_blk, acc = carry
        acc = attend(acc, k_blk, v_blk, step)
        k_next = lax.ppermute(k_blk, axis_name, perm)
        v_next = lax.ppermute(v_blk, axis_name, perm)
        return (k_next, v_next, acc), None

    m0 = jnp.full((B, H, Tl), -jnp.inf, q.dtype)
    l0 = jnp.zeros((B, H, Tl), q.dtype)
    o0 = jnp.zeros((B, H, Tl, Dh), q.dtype)
    # P-1 (attend, rotate) steps, then fold the final block with no
    # trailing rotate — exactly P-1 ppermute rounds
    (k_f, v_f, acc), _ = lax.scan(block, (k, v, (m0, l0, o0)),
                                  jnp.arange(P_ - 1))
    m, l, o = attend(acc, k_f, v_f, P_ - 1)
    o = o / jnp.clip(l[..., None], 1e-20, None)
    return jnp.transpose(o, (0, 2, 1, 3))                  # [B,Tl,H,Dh]


def _ring_perm(P_):
    return [(j, (j - 1) % P_) for j in range(P_)]  # i receives from i+1


def _ring_case(idx, src):
    """0: src > idx (future chunk, fully masked), 1: diagonal,
    2: src < idx (past chunk, fully visible)."""
    return jnp.where(src < idx, 2, jnp.where(src == idx, 1, 0))


def _ring_flash_fwd_impl(q, k, v, axis_name, causal):
    """Flash-kernel ring body: same rotation schedule as the XLA path,
    but each fold goes through `flash_attention_carry` (O(block) VMEM,
    no [Tl, Tl] HBM tile). Returns (o [B,Tl,H,Dh], lse [B,H,Tl])."""
    from deeplearning4j_tpu.kernels.flash_attention import (
        _NEG_INF, flash_attention_carry,
    )

    P_ = axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    B, Tl, H, Dh = q.shape
    out_dtype = q.dtype

    def fold_visible(carry, kb, vb):
        m, l, acc = carry
        return flash_attention_carry(q, kb, vb, m, l, acc, diag=False)

    def fold_diag(carry, kb, vb):
        m, l, acc = carry
        return flash_attention_carry(q, kb, vb, m, l, acc, diag=True)

    def fold_masked(carry, kb, vb):
        return carry

    def attend(carry, k_blk, v_blk, step):
        if not causal:
            return fold_visible(carry, k_blk, v_blk)
        src = (idx + step) % P_
        return lax.switch(_ring_case(idx, src),
                          (fold_masked, fold_diag, fold_visible),
                          carry, k_blk, v_blk)

    perm = _ring_perm(P_)

    def block(carry_kv, step):
        k_blk, v_blk, acc = carry_kv
        acc = attend(acc, k_blk, v_blk, step)
        k_next = lax.ppermute(k_blk, axis_name, perm)
        v_next = lax.ppermute(v_blk, axis_name, perm)
        return (k_next, v_next, acc), None

    m0 = jnp.full((B, H, Tl), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, H, Tl), jnp.float32)
    acc0 = jnp.zeros((B, H, Tl, Dh), jnp.float32)
    (k_f, v_f, carry), _ = lax.scan(block, (k, v, (m0, l0, acc0)),
                                    jnp.arange(P_ - 1))
    m, l, acc = attend(carry, k_f, v_f, P_ - 1)
    l_safe = jnp.clip(l, 1e-20, None)
    o = acc / l_safe[..., None]
    lse = m + jnp.log(l_safe)
    return jnp.transpose(o, (0, 2, 1, 3)).astype(out_dtype), lse


@partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _ring_attention_flash(q, k, v, axis_name, causal):
    """Differentiable flash ring attention (per-shard, inside
    shard_map). The backward runs a SECOND ring: each rotating K/V
    chunk carries its own dK/dV accumulator, fed by the chunked flash
    backward kernels, and lands home after the final rotation — so the
    [Tl, Tl] tile never materializes in either direction and training
    memory stays O(block) per device."""
    o, _ = _ring_flash_fwd_impl(q, k, v, axis_name, causal)
    return o


def _ring_flash_fwd(q, k, v, axis_name, causal):
    o, lse = _ring_flash_fwd_impl(q, k, v, axis_name, causal)
    return o, (q, k, v, o, lse)


def _ring_flash_bwd(axis_name, causal, res, g):
    from deeplearning4j_tpu.kernels.flash_attention import (
        _bwd_dkv_chunk, _bwd_dq_chunk, attention_delta,
    )

    q, k, v, o, lse = res
    P_ = axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    delta = attention_delta(g, o)                    # [B, H, Tl] fp32

    def contrib_for(chunk_causal):
        def f(kb, vb):
            dq_c = _bwd_dq_chunk(q, kb, vb, g, lse, delta,
                                 causal=chunk_causal, block_q=512,
                                 block_k=1024, interpret=None)
            dk_c, dv_c = _bwd_dkv_chunk(q, kb, vb, g, lse, delta,
                                        causal=chunk_causal, block_q=512,
                                        block_k=1024, interpret=None)
            return (dq_c.astype(jnp.float32), dk_c.astype(jnp.float32),
                    dv_c.astype(jnp.float32))
        return f

    def contrib_masked(kb, vb):
        return (jnp.zeros(q.shape, jnp.float32),
                jnp.zeros(kb.shape, jnp.float32),
                jnp.zeros(vb.shape, jnp.float32))

    def contrib(k_blk, v_blk, step):
        if not causal:
            return contrib_for(False)(k_blk, v_blk)
        src = (idx + step) % P_
        return lax.switch(_ring_case(idx, src),
                          (contrib_masked, contrib_for(True),
                           contrib_for(False)),
                          k_blk, v_blk)

    perm = _ring_perm(P_)

    def block(carry, step):
        k_blk, v_blk, dk_a, dv_a, dq_a = carry
        dq_c, dk_c, dv_c = contrib(k_blk, v_blk, step)
        dq_a = dq_a + dq_c
        dk_a = dk_a + dk_c
        dv_a = dv_a + dv_c
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        # the chunk's grad accumulator travels WITH the chunk
        dk_a = lax.ppermute(dk_a, axis_name, perm)
        dv_a = lax.ppermute(dv_a, axis_name, perm)
        return (k_blk, v_blk, dk_a, dv_a, dq_a), None

    dq0 = jnp.zeros(q.shape, jnp.float32)
    dk0 = jnp.zeros(k.shape, jnp.float32)
    dv0 = jnp.zeros(v.shape, jnp.float32)
    (k_f, v_f, dk_a, dv_a, dq_a), _ = lax.scan(
        block, (k, v, dk0, dv0, dq0), jnp.arange(P_ - 1))
    # final fold (no trailing K/V rotate), then ONE more accumulator
    # rotation: the block held now originated at idx-1, so a single
    # ppermute lands every chunk's dK/dV back on its origin device
    dq_c, dk_c, dv_c = contrib(k_f, v_f, P_ - 1)
    dq_a = dq_a + dq_c
    dk_a = lax.ppermute(dk_a + dk_c, axis_name, perm)
    dv_a = lax.ppermute(dv_a + dv_c, axis_name, perm)
    return (dq_a.astype(q.dtype), dk_a.astype(k.dtype),
            dv_a.astype(v.dtype))


_ring_attention_flash.defvjp(_ring_flash_fwd, _ring_flash_bwd)


def sequence_parallel_attention(q, k, v, mesh: Mesh, *,
                                seq_axis: str = "seq",
                                causal: bool = False,
                                use_flash: bool = False):
    """Full arrays [B, T, H, Dh] → ring attention with T sharded over
    `seq_axis` of `mesh`."""
    spec = P(None, seq_axis)

    @partial(shard_map, mesh=mesh,
             in_specs=(spec, spec, spec), out_specs=spec,
             check_vma=False)
    def run(ql, kl, vl):
        return ring_attention(ql, kl, vl, seq_axis, causal=causal,
                              use_flash=use_flash)

    return run(q, k, v)


def reference_attention(q, k, v, causal: bool = False):
    """Single-device ground truth for parity tests."""
    Dh = q.shape[-1]
    scale = 1.0 / jnp.sqrt(jnp.asarray(Dh, q.dtype))
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        T = q.shape[1]
        scores = jnp.where(jnp.tril(jnp.ones((T, T), bool))[None, None],
                           scores, -jnp.inf)
    w = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", w, v)
