"""Ring attention — sequence/context parallelism over a mesh axis.

No reference equivalent (the 2017 codebase scales sequences with TBPTT
only, SURVEY §5); this is new-design territory the TPU rebuild treats
as first-class: the sequence axis is sharded across devices, K/V blocks
rotate around the ICI ring via `ppermute`, and each device accumulates
its queries' attention with the numerically-stable online-softmax
(flash-attention style) running max/denominator. Math is EXACTLY
standard attention; wall-clock is one ring rotation (P-1 ppermutes)
with compute/communication overlap left to XLA.

Use inside `shard_map` over a mesh with a "seq" axis, or through
`sequence_parallel_attention` which wraps the shard_map for full
[B, T, H, D] arrays.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def ring_attention(q, k, v, axis_name: str, causal: bool = False):
    """Per-shard blocks: q, k, v [B, T_local, H, Dh] (this device's
    sequence chunk). Returns o [B, T_local, H, Dh].

    Must run inside shard_map/pmap with `axis_name` bound.
    """
    P_ = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    B, Tl, H, Dh = q.shape
    scale = 1.0 / jnp.sqrt(jnp.asarray(Dh, q.dtype))

    q_pos = idx * Tl + jnp.arange(Tl)                      # global positions

    def attend(acc, k_blk, v_blk, step):
        """Fold one K/V block into the online-softmax accumulator."""
        m, l, o = acc
        # the block currently held originated on device (idx + step) % P
        src = (idx + step) % P_
        k_pos = src * Tl + jnp.arange(Tl)
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k_blk) * scale
        if causal:
            ok = k_pos[None, :] <= q_pos[:, None]          # [Tq, Tk]
            scores = jnp.where(ok[None, None], scores, -jnp.inf)
        blk_max = jnp.max(scores, axis=-1)                 # [B,H,Tq]
        m_new = jnp.maximum(m, blk_max)
        # guard -inf rows (no valid key yet): exp(-inf - -inf) → use where
        m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        p = jnp.exp(jnp.where(jnp.isfinite(scores),
                              scores - m_safe[..., None], -jnp.inf))
        p = jnp.where(jnp.isfinite(scores), p, 0.0)
        corr = jnp.where(jnp.isfinite(m), jnp.exp(m - m_safe), 0.0)
        l_new = l * corr + jnp.sum(p, axis=-1)
        o_new = o * corr[..., None] + jnp.einsum("bhqk,bkhd->bhqd", p, v_blk)
        return (m_new, l_new, o_new)

    perm = [(j, (j - 1) % P_) for j in range(P_)]  # i receives from i+1

    def block(carry, step):
        k_blk, v_blk, acc = carry
        acc = attend(acc, k_blk, v_blk, step)
        k_next = lax.ppermute(k_blk, axis_name, perm)
        v_next = lax.ppermute(v_blk, axis_name, perm)
        return (k_next, v_next, acc), None

    m0 = jnp.full((B, H, Tl), -jnp.inf, q.dtype)
    l0 = jnp.zeros((B, H, Tl), q.dtype)
    o0 = jnp.zeros((B, H, Tl, Dh), q.dtype)
    # P-1 (attend, rotate) steps, then fold the final block with no
    # trailing rotate — exactly P-1 ppermute rounds
    (k_f, v_f, acc), _ = lax.scan(block, (k, v, (m0, l0, o0)),
                                  jnp.arange(P_ - 1))
    m, l, o = attend(acc, k_f, v_f, P_ - 1)
    o = o / jnp.clip(l[..., None], 1e-20, None)
    return jnp.transpose(o, (0, 2, 1, 3))                  # [B,Tl,H,Dh]


def sequence_parallel_attention(q, k, v, mesh: Mesh, *,
                                seq_axis: str = "seq",
                                causal: bool = False):
    """Full arrays [B, T, H, Dh] → ring attention with T sharded over
    `seq_axis` of `mesh`."""
    spec = P(None, seq_axis)

    @partial(jax.shard_map, mesh=mesh,
             in_specs=(spec, spec, spec), out_specs=spec,
             check_vma=False)
    def run(ql, kl, vl):
        return ring_attention(ql, kl, vl, seq_axis, causal=causal)

    return run(q, k, v)


def reference_attention(q, k, v, causal: bool = False):
    """Single-device ground truth for parity tests."""
    Dh = q.shape[-1]
    scale = 1.0 / jnp.sqrt(jnp.asarray(Dh, q.dtype))
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        T = q.shape[1]
        scores = jnp.where(jnp.tril(jnp.ones((T, T), bool))[None, None],
                           scores, -jnp.inf)
    w = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", w, v)
