"""TrainingMaster round statistics + timeline export.

Reference: `ParameterAveragingTrainingMasterStats.java` (per-round
timing of split/broadcast/fit/aggregate, `SparkTrainingStats` counters)
and `spark/stats/StatsUtils.java` (`exportStatsAsHtml` timeline chart).

Here: the master (or ParallelTrainer directly) records one event per
phase occurrence — broadcast, local_fit, average, sync_step — with
wall-clock start/duration. Collection deliberately inserts a device
sync per timed phase (as the reference's stats collection does around
its Spark stages); leave stats off for peak-throughput runs.
"""

from __future__ import annotations

import json
import time
from typing import Callable, Dict, List, Optional


class TrainingMasterStats:
    PHASES = ("broadcast", "local_fit", "average", "sync_step")

    def __init__(self):
        self.events: List[Dict] = []
        self._t0 = time.perf_counter()
        self._listeners: List[Callable[[Dict], None]] = []
        self.round_count = 0

    # ------------------------------------------------------------ recording
    def add_listener(self, fn: Callable[[Dict], None]):
        """fn(event_dict) called on every recorded phase event."""
        self._listeners.append(fn)
        return self

    def record(self, phase: str, seconds: float, **meta):
        ev = {"phase": phase,
              "start_ms": round((time.perf_counter() - self._t0
                                 - seconds) * 1000.0, 3),
              "duration_ms": round(seconds * 1000.0, 3),
              **meta}
        self.events.append(ev)
        for fn in self._listeners:
            fn(ev)

    def next_round(self):
        self.round_count += 1
        return self.round_count

    class _Timer:
        def __init__(self, stats, phase, meta):
            self.stats, self.phase, self.meta = stats, phase, meta

        def __enter__(self):
            self._start = time.perf_counter()
            return self

        def __exit__(self, *exc):
            self.stats.record(self.phase,
                              time.perf_counter() - self._start, **self.meta)
            return False

    def time_phase(self, phase: str, **meta):
        """`with stats.time_phase("average", round=r): ...`"""
        return self._Timer(self, phase, meta)

    # ------------------------------------------------------------ summaries
    def phase_totals_ms(self) -> Dict[str, float]:
        out: Dict[str, float] = {}
        for ev in self.events:
            out[ev["phase"]] = out.get(ev["phase"], 0.0) + ev["duration_ms"]
        return {k: round(v, 3) for k, v in out.items()}

    def phase_counts(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for ev in self.events:
            out[ev["phase"]] = out.get(ev["phase"], 0) + 1
        return out

    def summary(self) -> Dict:
        return {"rounds": self.round_count,
                "phase_totals_ms": self.phase_totals_ms(),
                "phase_counts": self.phase_counts(),
                "events": len(self.events)}

    # -------------------------------------------------------------- export
    def to_json(self) -> str:
        return json.dumps({"summary": self.summary(),
                           "timeline": self.events})

    def export_json(self, path: str):
        with open(path, "w") as f:
            f.write(self.to_json())
        return path

    _COLORS = {"broadcast": "#8a6fc8", "local_fit": "#4a7dbd",
               "average": "#c8763b", "sync_step": "#3b9c6e"}

    def export_html(self, path: str):
        """Standalone HTML timeline (the `StatsUtils.exportStatsAsHtml`
        role): one horizontal lane per phase, bars positioned by
        wall-clock start/duration."""
        if self.events:
            end = max(ev["start_ms"] + ev["duration_ms"] for ev in self.events)
        else:
            end = 1.0
        end = max(end, 1e-6)
        lanes = sorted({ev["phase"] for ev in self.events})
        rows = []
        for lane_i, phase in enumerate(lanes):
            bars = []
            for ev in self.events:
                if ev["phase"] != phase:
                    continue
                left = 100.0 * ev["start_ms"] / end
                width = max(100.0 * ev["duration_ms"] / end, 0.05)
                tip = (f"{phase} {ev['duration_ms']:.1f} ms @ "
                       f"{ev['start_ms']:.1f} ms")
                bars.append(
                    f'<div class="bar" title="{tip}" style="left:{left:.3f}%;'
                    f'width:{width:.3f}%;background:'
                    f'{self._COLORS.get(phase, "#888")}"></div>')
            rows.append(f'<div class="lane"><span class="label">{phase}'
                        f'</span><div class="track">{"".join(bars)}</div></div>')
        totals = self.phase_totals_ms()
        tot_rows = "".join(
            f"<tr><td>{k}</td><td>{v:.1f}</td>"
            f"<td>{self.phase_counts()[k]}</td></tr>"
            for k, v in sorted(totals.items()))
        html = f"""<!doctype html><html><head><meta charset="utf-8">
<title>TrainingMaster timeline</title><style>
body{{font-family:sans-serif;margin:24px}}
.lane{{display:flex;align-items:center;margin:4px 0}}
.label{{width:90px;font-size:12px}}
.track{{position:relative;flex:1;height:18px;background:#f0f0f0}}
.bar{{position:absolute;top:2px;height:14px;min-width:1px}}
table{{border-collapse:collapse;margin-top:16px}}
td,th{{border:1px solid #ccc;padding:4px 10px;font-size:13px}}
</style></head><body>
<h2>TrainingMaster timeline ({self.round_count} rounds,
{len(self.events)} events, {end:.1f} ms)</h2>
{"".join(rows)}
<table><tr><th>phase</th><th>total ms</th><th>count</th></tr>{tot_rows}</table>
</body></html>"""
        with open(path, "w") as f:
            f.write(html)
        return path
