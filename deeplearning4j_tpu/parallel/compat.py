"""Version portability for the JAX SPMD / config surface.

`shard_map` moved from `jax.experimental.shard_map` (jax<=0.4.x, where
its replication-check kwarg is `check_rep`) to `jax.shard_map` (where
the kwarg became `check_vma`); `lax.axis_size` and the public
`jax.enable_x64` context only exist on the new line. Every user in
this package goes through these shims so the trainers run on both.
"""

from __future__ import annotations

import functools

import jax
from jax import lax


def axis_size(axis_name):
    """`lax.axis_size(axis_name)` where it exists; on 0.4.x fall back
    to `lax.psum(1, axis_name)`, which JAX folds to a Python int at
    trace time (no runtime collective)."""
    impl = getattr(lax, "axis_size", None)
    if impl is not None:
        return impl(axis_name)
    return lax.psum(1, axis_name)


def enable_x64(new_val: bool = True):
    """`jax.enable_x64` context manager on new JAX,
    `jax.experimental.enable_x64` on 0.4.x."""
    impl = getattr(jax, "enable_x64", None)
    if impl is None:
        from jax.experimental import enable_x64 as impl
    return impl(new_val)


def shard_map(f=None, **kwargs):
    """`jax.shard_map` on new JAX, `jax.experimental.shard_map` on 0.4.x
    (translating `check_vma` to its old name `check_rep`). Usable like
    the real thing: `@partial(shard_map, mesh=..., in_specs=...,
    out_specs=..., check_vma=False)`."""
    impl = getattr(jax, "shard_map", None)
    if impl is None:
        from jax.experimental.shard_map import shard_map as impl
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
    if f is None:
        return functools.partial(shard_map, **kwargs)
    return impl(f, **kwargs)
