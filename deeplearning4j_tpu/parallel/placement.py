"""Global-array placement helpers shared by the distributed trainers.

`gput` places a host array under a sharding in a way that works in BOTH
runtime shapes:
- single process: plain `jax.device_put`;
- multi process (`jax.distributed`): every process holds the same host
  value and contributes its addressable shards via
  `make_array_from_callback` — `device_put` cannot address remote
  devices. This is what lets the same global-view `fit()` run unchanged
  under 1 or N processes (the Spark-RDD partition feed of
  `ParameterAveragingTrainingMaster` collapses into the sharding).
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Sharding

from deeplearning4j_tpu import monitor


def gput(arr, sharding):
    # a leaf can already be a global array spanning non-addressable
    # devices (e.g. TP-sharded params kept on-device by host_view_tree
    # after a previous fit) — np.asarray on it would raise; pass it
    # through or let device_put reshard global->global
    if isinstance(arr, jax.Array) and not arr.is_fully_addressable:
        if arr.sharding == sharding:
            return arr
        return jax.device_put(arr, sharding)
    a = np.asarray(arr)
    # counts the placement the program was doing anyway — no sync added
    monitor.record_transfer(a.nbytes, "h2d")
    if jax.process_count() > 1:
        return jax.make_array_from_callback(a.shape, sharding,
                                            lambda idx: a[idx])
    return jax.device_put(a, sharding)


def gput_tree(tree, sharding):
    """Place every leaf. `sharding` is either one Sharding applied to
    all leaves, or a pytree of Shardings matching `tree`."""
    if isinstance(sharding, Sharding):
        return jax.tree_util.tree_map(lambda a: gput(a, sharding), tree)
    return jax.tree_util.tree_map(gput, tree, sharding)


def host_view_tree(tree):
    """Bring leaves back to host numpy where legal. Under multi-process,
    a model/tensor-sharded leaf is not fully addressable from any one
    process — those stay as global device arrays (every consumer in
    this framework accepts either)."""
    def to_host(a):
        if getattr(a, "is_fully_replicated", True) or jax.process_count() == 1:
            h = np.asarray(a)
            monitor.record_transfer(h.nbytes, "d2h")
            return h
        return a
    return jax.tree_util.tree_map(to_host, tree)
