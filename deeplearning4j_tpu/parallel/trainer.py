"""ParallelTrainer — multi-device training engine.

Reference equivalence (SURVEY.md §3.3, §3.4):
- sync mode ≙ `ParallelWrapper` gradient-sharing + `SharedTrainingMaster`:
  every step computes gradients on a data-sharded batch; because the
  loss is a mean over the global batch and params are replicated, XLA
  inserts a `psum` over the "data" axis — the ICI all-reduce that
  replaces `EncodedGradientsAccumulator`'s threshold-compressed UDP
  gossip (`EncodingHandler.java:136-178`). No compression needed at
  ICI bandwidth.
- averaging mode ≙ `ParallelWrapper` param-averaging /
  `ParameterAveragingTrainingMaster`: each replica holds its OWN params
  + updater state (leading replica axis sharded over "data") and runs
  `averaging_frequency` local steps with no cross-device traffic
  (`shard_map`), then params/updater state are `pmean`-averaged —
  exactly the reference's averaging round
  (`ParallelWrapper.java:327` `Nd4j.averageAndPropagate`, incl. updater
  state :339-366). Useful over DCN where local SGD beats per-step sync.

Both modes reuse the model's own loss/updater machinery — no separate
"trainer thread + model replica" objects; the mesh does the fan-out.
"""

from __future__ import annotations

import time
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.common.updaters import Sgd
from deeplearning4j_tpu.datasets.iterator import as_iterator
from deeplearning4j_tpu.optimize.gradients import apply_gradient_normalization
from deeplearning4j_tpu.optimize.listeners import ComposedListeners
from deeplearning4j_tpu.parallel.mesh import device_mesh
from deeplearning4j_tpu import monitor
from deeplearning4j_tpu.monitor import diagnostics as _diag


from deeplearning4j_tpu.nd.donation import donate_argnums as _donate


# shared with ShardedParallelTrainer — see parallel/placement.py
from deeplearning4j_tpu.parallel.placement import (  # noqa: E402
    gput as _gput,
    gput_tree as _gput_tree,
)


def _require_single_process(what="mesh evaluate()"):
    """The host-side `np.asarray` readback needs fully-addressable
    arrays. Called FIRST so multi-process callers fail before any
    compile or device transfer is paid."""
    if jax.process_count() > 1:
        raise NotImplementedError(
            f"{what} reads results back to one host and needs fully-"
            f"addressable arrays; under multi-process execution score "
            f"each process's local data shard on the host "
            f"(evaluator.eval(y, model.output(x)) per process) and "
            f"combine the evaluators with merge() — they all serialize "
            f"via to_json for the transport")


def _mesh_evaluate(model, iterator, merged, n_div, forward, put_x):
    """Shared mesh-evaluation loop (ParallelTrainer and
    ShardedParallelTrainer): every batch runs through the SHARDED
    forward; ragged tails are zero-padded up to the data-axis multiple
    and the padded rows sliced off before scoring — no example is
    skipped and no full-model host replica is ever materialized (a
    TP-sharded model may not even fit on one device)."""
    for ds in iterator:
        n = ds.num_examples()
        x = np.asarray(ds.features)
        if n % n_div != 0:
            pad = n_div - n % n_div
            x = np.concatenate(
                [x, np.zeros((pad,) + x.shape[1:], x.dtype)], axis=0)
        out = np.asarray(forward(put_x(x)))[:n]
        merged.eval(np.asarray(ds.labels), out)
    return merged


class ParallelTrainer:
    def __init__(self, model, mesh: Optional[Mesh] = None, *,
                 mode: str = "sync", averaging_frequency: int = 5,
                 average_updater_state: bool = True, data_axis: str = "data",
                 gradient_sharing: Optional[str] = None,
                 threshold_config=None, stats=None,
                 bucketed: Optional[bool] = None, rs_param_specs=None):
        if mode not in ("sync", "averaging"):
            raise ValueError(f"mode must be sync|averaging, got {mode}")
        # stats: optional TrainingMasterStats — per-phase round timing
        # (broadcast / local_fit / average / sync_step) at the cost of a
        # device sync per timed phase (reference stats semantics)
        self.stats = stats
        self.model = model
        self.mesh = mesh if mesh is not None else device_mesh()
        self.mode = mode
        self.averaging_frequency = max(1, averaging_frequency)
        self.average_updater_state = average_updater_state
        self.data_axis = data_axis
        self.n_workers = int(np.prod([self.mesh.shape[a] for a in [data_axis]]))
        # gradient exchange mode for sync training: dense fp32 exchange,
        # error-feedback threshold encoding (reference
        # SharedTrainingMaster semantics), or the ZeRO-style
        # reduce-scatter modes dense_rs/threshold_rs
        # (parallel/gradient_sharing.py). Resolution:
        # DL4J_GRADIENT_SHARING env > explicit arg > model conf's
        # gradient_sharing field > "dense".
        from deeplearning4j_tpu.parallel import gradient_sharing as _gs
        self.gradient_sharing = _gs.resolve_mode(gradient_sharing,
                                                 model.conf)
        if self.gradient_sharing != "dense" and mode != "sync":
            want = self.gradient_sharing
            if (_gs.env_mode() == want
                    and (gradient_sharing or "dense") != want
                    and getattr(model.conf, "gradient_sharing",
                                "dense") != want):
                # global env A/B toggle: degrade gracefully where the
                # compressed/sharded exchange does not apply (averaging
                # mode exchanges parameters, not gradients) — only an
                # EXPLICIT arg/conf request is a hard error
                self.gradient_sharing = "dense"
            else:
                raise ValueError(
                    f"gradient_sharing={want!r} restructures the per-step "
                    "gradient exchange and only applies to mode='sync'; "
                    "averaging mode exchanges parameters, not gradients")
        if self.gradient_sharing in ("threshold", "threshold_rs"):
            _gs.wire_dtype(self.n_workers)  # replica-count ceiling check
        if (self.gradient_sharing in _gs.RS_MODES
                and not _gs.rs_supported_gn(model.conf)):
            raise ValueError(
                "the dense_rs/threshold_rs modes run gradient "
                "normalization on reduced gradient SHARDS and support "
                "only elementwise modes (none / "
                "clip_elementwise_absolute_value); this configuration's "
                f"{model.conf.gradient_normalization!r} needs whole-layer "
                "norms — use dense/threshold instead")
        # bucketed (per-layer-run, overlapped) exchange: default ON —
        # each packed run / unpacked layer exchanges inside the backward
        # pass. DL4J_BUCKETED_EXCHANGE=0 or bucketed=False restores the
        # PR-4 single-barrier program (the rs modes are inherently
        # bucketed). docs/COMMS.md "Bucketed collectives".
        self.bucketed = _gs.resolve_bucketed(bucketed)
        # optional PartitionSpec tree (e.g. tensor.fsdp_param_specs
        # output) steering WHICH leaves the rs modes reduce-scatter —
        # the FSDP composition seam; default derives the same rule from
        # shapes at first fit
        self.rs_param_specs = rs_param_specs
        self._rs_plan_cache = None
        self.threshold_config = (threshold_config if threshold_config
                                 is not None
                                 else _gs.ThresholdConfig.from_conf(
                                     model.conf))
        self._thr_step = None
        self._thr_multi = None
        self._bkt_step = None         # bucketed step (any mode)
        self._bkt_multi = None
        self._thr_residual_r = None   # per-replica error-feedback residual
        self._thr_tau = None          # adaptive threshold: per-bucket
        #                               {layer_key: f32} tree (bucketed)
        #                               or device scalar (single-barrier)
        # exact-resume stacks restored by _restore_fault_state (fault/):
        # consumed by the next fit() instead of replicating the model's
        # host trees (per-replica updater/param state drifts — a
        # broadcast would erase the drift the checkpoint preserved)
        self._resume_upd_r = None
        self._resume_avg = None
        self._sync_step = None
        self._sync_multi = None
        self._local_step = None
        self._local_multi = None
        self._average_fn = None
        # ComputationGraph models: the bucketed engine supports
        # single-input/single-output graphs (gradient_sharing's
        # _local_loss_fn packs the tuples); multi-io graphs keep the
        # GSPMD single-barrier dense program
        self._is_graph = not hasattr(model, "_forward_core")
        self._multi_io_graph = self._is_graph and (
            len(model.conf.network_inputs) != 1
            or len(model.conf.network_outputs) != 1)

    # ------------------------------------------------------------- sync mode
    def _build_sync_step(self):
        model = self.model
        mesh = self.mesh
        repl = NamedSharding(mesh, P())
        batch_sharded = NamedSharding(mesh, P(self.data_axis))

        raw_step = model._make_train_step(tbptt=False)

        def step(params, upd, state, it, x, y, rng):
            return raw_step(params, upd, state, it, x, y, rng, None, None, None)

        self._sync_step = jax.jit(
            step,
            in_shardings=(repl, repl, repl, None, batch_sharded, batch_sharded, None),
            out_shardings=(repl, repl, repl, None, None, None),
            donate_argnums=_donate(0, 1, 2),
        )

    def _build_sync_multi(self):
        """k fused sync steps in ONE dispatch — the model's own
        `_multi_step_fn` body (one copy of the fused numerics), re-jit
        with mesh shardings: batch stacks [k, B/d, ...] over the data
        axis, everything else replicated; XLA inserts the per-step psum
        exactly as in `_build_sync_step`."""
        mesh = self.mesh
        repl = NamedSharding(mesh, P())
        stack_sh = NamedSharding(mesh, P(None, self.data_axis))
        self._sync_multi = jax.jit(
            self.model._multi_step_fn(),
            in_shardings=(repl, repl, repl, None, stack_sh, stack_sh, None),
            out_shardings=(repl, repl, repl, None, None),
            donate_argnums=_donate(0, 1, 2),
        )

    # ------------------------------------------- threshold gradient sharing
    def _build_threshold_step(self):
        """Per-step threshold sync: the explicit-collective shard_map
        program from parallel/gradient_sharing.py — local grads on the
        batch shard, error-feedback threshold encode, integer all-reduce,
        decode, shared update. The per-replica residual enters/exits with
        a leading replica axis sharded over the data axis (the averaging
        mode's rep-spec idiom); ``stacked::`` run packing happens inside
        the step, so the residual the trainer holds stays per-layer."""
        from deeplearning4j_tpu.parallel import gradient_sharing as gs
        from deeplearning4j_tpu.parallel.compat import shard_map

        mesh, axis = self.mesh, self.data_axis
        step = gs.make_threshold_step(
            self.model, axis, self.threshold_config,
            n_workers=self.n_workers, is_graph=self._is_graph,
            diag=self.model._diag)
        rep = P(axis)
        strip = lambda t: jax.tree_util.tree_map(lambda a: a[0], t)
        expand = lambda t: jax.tree_util.tree_map(lambda a: a[None], t)

        @partial(shard_map, mesh=mesh,
                 in_specs=(P(), rep, P(), None, rep, P(),
                           P(axis), P(axis), None),
                 out_specs=(P(), rep, P(), rep, P(), P(), P(), P()),
                 check_vma=False)
        def thr_step(params, upd_r, state, it, res_r, tau, x, y, rng):
            params, upd, state, res, tau, loss, sp, dv = step(
                params, strip(upd_r), state, it, strip(res_r), tau,
                x, y, rng)
            return (params, expand(upd), state, expand(res), tau, loss,
                    sp, dv)

        self._thr_step = jax.jit(thr_step, donate_argnums=_donate(0, 1, 2, 4))

    def _build_threshold_multi(self):
        """k fused threshold sync steps in ONE dispatch: the scan lives
        inside shard_map and the residual + τ ride its carry next to the
        updater state (gradient_sharing.make_threshold_multi); packing
        of ``stacked::`` runs is paid once per program."""
        from deeplearning4j_tpu.parallel import gradient_sharing as gs
        from deeplearning4j_tpu.parallel.compat import shard_map

        mesh, axis = self.mesh, self.data_axis
        multi = gs.make_threshold_multi(
            self.model, axis, self.threshold_config,
            n_workers=self.n_workers, is_graph=self._is_graph,
            diag=self.model._diag)
        rep = P(axis)
        strip = lambda t: jax.tree_util.tree_map(lambda a: a[0], t)
        expand = lambda t: jax.tree_util.tree_map(lambda a: a[None], t)

        @partial(shard_map, mesh=mesh,
                 in_specs=(P(), rep, P(), None, rep, P(),
                           P(None, axis), P(None, axis), None),
                 out_specs=(P(), rep, P(), rep, P(), P(), P(), P()),
                 check_vma=False)
        def thr_multi(params, upd_r, state, it0, res_r, tau, xs, ys, rngs):
            params, upd, state, res, tau, losses, sps, dvs = multi(
                params, strip(upd_r), state, it0, strip(res_r), tau,
                xs, ys, rngs)
            return (params, expand(upd), state, expand(res), tau, losses,
                    sps, dvs)

        self._thr_multi = jax.jit(thr_multi,
                                  donate_argnums=_donate(0, 1, 2, 4))

    def _threshold_state(self, per_bucket: bool = False):
        """(residual_r, tau) device state — created lazily, persisted
        across fit() calls exactly like updater state (the reference's
        accumulator survives across training rounds). τ is a per-bucket
        {layer_key: scalar} tree on the bucketed paths and one scalar
        on the single-barrier path; switching paths between fits (or
        resuming a checkpoint written by the other one) coerces the
        form (scalar broadcast / bucket mean)."""
        from deeplearning4j_tpu.parallel import gradient_sharing as gs
        if self._thr_residual_r is None:
            self._thr_residual_r = self._replicate_tree(
                gs.zeros_residual(self.model.params))
        self._thr_tau = gs.ensure_tau_form(
            self._thr_tau, per_bucket, self.model.params,
            self.threshold_config)
        return self._thr_residual_r, self._thr_tau

    # ------------------------------------------ bucketed exchange (any mode)
    def _updater_state_floats(self) -> bool:
        """True when every updater-state leaf is floating — the
        precondition for threading updater state through the bucketed
        VJP's cotangent channel (all built-in updaters qualify)."""
        return all(jnp.issubdtype(jnp.result_type(l), jnp.floating)
                   for l in jax.tree_util.tree_leaves(
                       self.model.updater_state))

    def _rs_plan(self):
        """Which param leaves the `_rs` modes reduce-scatter — derived
        once from `rs_param_specs` (e.g. `tensor.fsdp_param_specs`
        output: the FSDP composition) or from shapes by the same
        rule."""
        from deeplearning4j_tpu.parallel import gradient_sharing as gs
        if self._rs_plan_cache is None:
            self._rs_plan_cache = gs.rs_shard_plan(
                self.model.params, self.n_workers,
                specs=self.rs_param_specs, data_axis=self.data_axis)
        return self._rs_plan_cache

    def _shard_rs_state(self, tree):
        """Cold-start ZeRO placement of the (full, per-layer) updater
        state: sharded leaves split along their LAST axis into one
        stacked shard per replica, replicated leaves broadcast — the
        leading replica axis is sharded over the data axis so each
        device physically holds 1/N of the sharded optimizer state."""
        plan = self._rs_plan()
        n = self.n_workers
        out = {}
        for lk, lupd in tree.items():
            out[lk] = {}
            for pk, slots in lupd.items():
                if plan[lk][pk]:
                    f = lambda a: np.stack(
                        np.split(np.asarray(a), n, axis=-1))
                else:
                    f = lambda a: np.broadcast_to(
                        np.asarray(a)[None], (n,) + np.shape(a)).copy()
                out[lk][pk] = jax.tree_util.tree_map(f, slots)
        return self._place_replica_stack(out)

    def _rs_full_state_fn(self):
        """jit that reassembles the full per-layer updater tree from
        the sharded stack (replicated out-sharding — multi-process
        fetchable): concatenate shards along the sharded axis,
        replica 0 for replicated leaves. The checkpoint/model view of
        ZeRO state is ALWAYS the full tree, so checkpoints are
        independent of the replica count that wrote them and elastic
        resume is plain re-slicing at the next fit."""
        plan = self._rs_plan()
        n = self.n_workers
        repl = NamedSharding(self.mesh, P())

        def full(upd_r):
            out = {}
            for lk, lupd in upd_r.items():
                out[lk] = {}
                for pk, slots in lupd.items():
                    if plan[lk][pk]:
                        f = lambda a: jnp.concatenate(
                            [a[i] for i in range(n)], axis=-1)
                    else:
                        f = lambda a: a[0]
                    out[lk][pk] = jax.tree_util.tree_map(f, slots)
            return out

        return jax.jit(full, out_shardings=repl)

    def _build_bucketed(self, mode: str, multi: bool):
        """Bucketed sync program (per-step or k-fused) for any exchange
        mode: the shard_map wrapper strips/expands the leading replica
        axis of the per-replica trees (threshold updater stacks, rs
        updater shards, the error-feedback residual) and leaves
        replicated trees alone."""
        from deeplearning4j_tpu.parallel import gradient_sharing as gs
        from deeplearning4j_tpu.parallel.compat import shard_map

        mesh, axis = self.mesh, self.data_axis
        rs_plan = self._rs_plan() if mode in gs.RS_MODES else None
        maker = gs.make_bucketed_multi if multi else gs.make_bucketed_step
        fn = maker(self.model, axis, self.threshold_config,
                   n_workers=self.n_workers, mode=mode,
                   is_graph=self._is_graph, rs_plan=rs_plan,
                   diag=self.model._diag)
        per_replica_upd = mode != "dense"
        has_thr = mode in ("threshold", "threshold_rs")
        rep = P(axis)
        upd_spec = rep if per_replica_upd else P()
        res_spec = rep if has_thr else P()
        batch_spec = P(None, axis) if multi else P(axis)
        strip = lambda t: jax.tree_util.tree_map(lambda a: a[0], t)
        expand = lambda t: jax.tree_util.tree_map(lambda a: a[None], t)

        @partial(shard_map, mesh=mesh,
                 in_specs=(P(), upd_spec, P(), None, res_spec, P(),
                           batch_spec, batch_spec, None),
                 out_specs=(P(), upd_spec, P(), res_spec, P(), P(), P(),
                            P()),
                 check_vma=False)
        def run(params, upd_r, state, it, res_r, tau, x, y, rng):
            u = strip(upd_r) if per_replica_upd else upd_r
            r = strip(res_r) if has_thr else res_r
            params, u, state, r, tau, loss, sp, dv = fn(
                params, u, state, it, r, tau, x, y, rng)
            return (params, expand(u) if per_replica_upd else u, state,
                    expand(r) if has_thr else r, tau, loss, sp, dv)

        donate = _donate(0, 1, 2, 4) if has_thr else _donate(0, 1, 2)
        return jax.jit(run, donate_argnums=donate)

    def _replicated_view(self, tree):
        """Gather a per-replica (data-axis-sharded) device tree into
        replicated form so every PROCESS can address the full stack —
        the multi-process capture path for checkpoints of residual/τ
        and per-replica updater stacks (a data-axis-sharded leaf is not
        fully addressable from any one host, and `flatten_arrays`
        rejects it). One all-gather per capture, at checkpoint cadence
        only; a no-op reshard under a single process."""
        if getattr(self, "_rep_view_fn", None) is None:
            repl = NamedSharding(self.mesh, P())
            self._rep_view_fn = jax.jit(lambda t: t, out_shardings=repl)
        return self._rep_view_fn(tree)

    def threshold_residual(self):
        """Host view of the per-replica error-feedback residual
        (per-LAYER keys — the ``stacked::`` packing exists only inside
        the step program), or None before the first threshold step."""
        if self._thr_residual_r is None:
            return None
        tree = self._thr_residual_r
        if jax.process_count() > 1:
            tree = self._replicated_view(tree)
        return jax.tree_util.tree_map(np.asarray, tree)

    # -------------------------------------------------------- averaging mode
    def _make_local_one_step(self):
        model = self.model
        gn = model.conf.gradient_normalization
        gn_t = model.conf.gradient_normalization_threshold

        def local_one_step(params, upd, state, it, x, y, rng):
            """One fully-local step on one replica's shard (no collectives)."""
            def lf(p):
                return model._loss_fn(p, state, x, y, rng, None, None, train=True)
            (loss, (new_state, _)), grads = jax.value_and_grad(lf, has_aux=True)(params)
            grads = apply_gradient_normalization(grads, gn, gn_t)
            new_params, new_upd = model._apply_updates(params, grads, upd, it)
            return new_params, new_upd, new_state, loss

        return local_one_step

    def _build_averaging(self):
        mesh = self.mesh
        axis = self.data_axis
        local_one_step = self._make_local_one_step()

        from deeplearning4j_tpu.parallel.compat import shard_map

        # per-replica params: leading axis of size n_workers, sharded over "data"
        rep_spec = P(axis)

        @partial(shard_map, mesh=mesh,
                 in_specs=(rep_spec, rep_spec, rep_spec, None, P(axis), P(axis), None),
                 out_specs=(rep_spec, rep_spec, rep_spec, P(axis)),
                 check_vma=False)
        def local_step(params_r, upd_r, state_r, it, x, y, rng):
            # strip the per-replica leading axis (size 1 inside the shard)
            params = jax.tree_util.tree_map(lambda a: a[0], params_r)
            upd = jax.tree_util.tree_map(lambda a: a[0], upd_r)
            state = jax.tree_util.tree_map(lambda a: a[0], state_r)
            axis_idx = jax.lax.axis_index(axis)
            rng = jax.random.fold_in(rng, axis_idx)
            params, upd, state, loss = local_one_step(params, upd, state, it, x, y, rng)
            expand = lambda t: jax.tree_util.tree_map(lambda a: a[None], t)
            return expand(params), expand(upd), expand(state), loss[None]

        @partial(shard_map, mesh=mesh,
                 in_specs=(rep_spec,), out_specs=rep_spec, check_vma=False)
        def average(tree_r):
            tree = jax.tree_util.tree_map(lambda a: a[0], tree_r)
            avg = jax.tree_util.tree_map(lambda a: jax.lax.pmean(a, axis), tree)
            return jax.tree_util.tree_map(lambda a: a[None], avg)

        self._local_step = jax.jit(local_step, donate_argnums=_donate(0, 1, 2))
        self._average_fn = jax.jit(average, donate_argnums=_donate(0))

    def _build_averaging_multi(self):
        """k fused local-SGD steps in ONE dispatch: the scan lives
        INSIDE shard_map, and the pmean averaging round fires at its
        `averaging_frequency` cadence via `lax.cond` — numerics
        identical to the per-step path (same rng folds, same iteration
        counters, same averaging boundaries), dispatch paid once per
        group."""
        mesh = self.mesh
        axis = self.data_axis
        freq = self.averaging_frequency
        avg_upd = self.average_updater_state
        local_one_step = self._make_local_one_step()

        from deeplearning4j_tpu.parallel.compat import shard_map
        from jax import lax

        rep_spec = P(axis)

        @partial(shard_map, mesh=mesh,
                 in_specs=(rep_spec, rep_spec, rep_spec, None, None,
                           P(None, axis), P(None, axis), None),
                 out_specs=(rep_spec, rep_spec, rep_spec, P(None, axis)),
                 check_vma=False)
        def local_multi(params_r, upd_r, state_r, it0, since0, xs, ys, rngs):
            params = jax.tree_util.tree_map(lambda a: a[0], params_r)
            upd = jax.tree_util.tree_map(lambda a: a[0], upd_r)
            state = jax.tree_util.tree_map(lambda a: a[0], state_r)
            axis_idx = jax.lax.axis_index(axis)

            def avg(tree):
                return jax.tree_util.tree_map(
                    lambda a: jax.lax.pmean(a, axis), tree)

            def body(carry, inp):
                params, upd, state, it, since = carry
                x, y, rng = inp
                rng = jax.random.fold_in(rng, axis_idx)
                params, upd, state, loss = local_one_step(
                    params, upd, state, it, x, y, rng)
                do = since + 1 >= freq
                params = lax.cond(do, avg, lambda t: t, params)
                state = lax.cond(do, avg, lambda t: t, state)
                if avg_upd:
                    upd = lax.cond(do, avg, lambda t: t, upd)
                since = jnp.where(do, 0, since + 1)
                return (params, upd, state, it + 1, since), loss

            (params, upd, state, _, _), losses = lax.scan(
                body,
                (params, upd, state, jnp.asarray(it0, jnp.int32),
                 jnp.asarray(since0, jnp.int32)),
                (xs, ys, rngs))
            expand = lambda t: jax.tree_util.tree_map(lambda a: a[None], t)
            return expand(params), expand(upd), expand(state), losses[:, None]

        self._local_multi = jax.jit(local_multi, donate_argnums=_donate(0, 1, 2))

    @staticmethod
    def _run_grouped(iterator, epochs, spe, divisible, run_single, drain,
                     model, listeners=None):
        """Shared epoch/grouping loop for both modes: accumulate up to
        `spe` same-shape batches, drain each FULL group through one
        fused dispatch; spe == 1 runs per-step. Partial groups (epoch
        tails, shape changes) go through run_single so only ONE fused
        shape [spe, ...] ever compiles — a distinct executable per tail
        length would cost minutes of XLA compile each on a real TPU.

        Epoch/fit listener events fire like the containers' own fit
        loops (epoch-cadence checkpointing and the end-of-fit
        durability drain depend on them)."""
        def flush(pending):
            if len(pending) == spe:
                drain(pending)
            else:
                for d in pending:
                    run_single(d)

        if listeners is not None:
            listeners.on_fit_start(model)
        for _ in range(epochs):
            if listeners is not None:
                listeners.on_epoch_start(model, model.epoch_count)
            iterator.reset()
            pending = []
            for ds in iterator:
                if not divisible(ds):
                    continue
                if spe == 1:
                    run_single(ds)
                    continue
                if pending and np.shape(ds.features) != np.shape(
                        pending[0].features):
                    flush(pending)   # shape change: close the group
                    pending = []
                pending.append(ds)
                if len(pending) >= spe:
                    drain(pending)
                    pending = []
            flush(pending)
            if listeners is not None:
                listeners.on_epoch_end(model, model.epoch_count)
            model.epoch_count += 1
        if listeners is not None:
            listeners.on_fit_end(model)

    def _replicate_tree(self, tree):
        """Stack n_workers copies along a new leading axis, shard over data."""
        n = self.n_workers
        stacked = jax.tree_util.tree_map(
            lambda a: np.broadcast_to(np.asarray(a)[None], (n,) + np.shape(a)),
            tree)
        sharding = NamedSharding(self.mesh, P(self.data_axis))
        return _gput_tree(stacked, sharding)

    def _unreplicate_tree(self, tree):
        return jax.tree_util.tree_map(lambda a: np.asarray(a[0]), tree)

    def _place_replica_stack(self, stacked):
        """Place an ALREADY-stacked per-replica host tree (leading
        replica axis of size n_workers) sharded over the data axis —
        the restore-side counterpart of `_replicate_tree`, which
        broadcasts one copy instead."""
        return _gput_tree(stacked, NamedSharding(self.mesh,
                                                 P(self.data_axis)))

    # ---------------------------------------------------------- fault/resume
    def _restore_fault_state(self, arrays, meta):
        """fault.resume() hook: restore gradient-sharing residual + τ,
        per-replica updater state and the averaging-mode stacks from a
        checkpoint — re-sharding the replica axis when the checkpoint
        was written at a different replica count (elastic resume)."""
        if not arrays and not meta:
            return
        from deeplearning4j_tpu.fault import state as fs
        kind = meta.get("kind")
        n = self.n_workers
        if kind in ("threshold", "threshold_rs"):
            res_r = arrays.get("residual_r")
            if res_r:
                res_r = fs.reshard_replica_stack(res_r, n, kind="residual")
                self._thr_residual_r = self._place_replica_stack(res_r)
            tau = arrays.get("tau")
            if tau is not None:
                # scalar (PR-4) or per-bucket tree, restored as written;
                # _threshold_state coerces at the next fit if the
                # trainer runs the other path
                from deeplearning4j_tpu.parallel import (
                    gradient_sharing as _gs)
                self._thr_tau = _gs.restore_tau(tau)
            upd_r = arrays.get("upd_r")
            if upd_r:
                # threshold_rs carries NO per-replica stack: its sharded
                # updater state round-trips through the model-level full
                # tree and re-slices at the next fit (elastic by
                # construction)
                upd_r = fs.reshard_replica_stack(upd_r, n, kind="state")
                self._resume_upd_r = self._place_replica_stack(upd_r)
        elif kind == "averaging":
            stacks = {}
            for k in ("params_r", "upd_r", "state_r"):
                t = arrays.get(k)
                stacks[k] = self._place_replica_stack(
                    fs.reshard_replica_stack(t, n, kind="state")) \
                    if t else {}
            stacks["since_avg"] = int(meta.get("since_avg", 0))
            self._resume_avg = stacks

    def resume(self, directory, *, iterator=None):
        """Restore model + trainer state from the newest VALID
        checkpoint under `directory` (fault/ runtime): params, layer
        state, per-replica updater stacks, threshold residual/τ or
        averaging-cadence phase, counters, and the iterator cursor when
        one is passed. Returns the model; a following `fit()` continues
        the interrupted run exactly (elastic: a changed mesh replica
        count re-shards the per-replica leaves)."""
        from deeplearning4j_tpu import fault
        model, _ = fault.resume(directory, model=self.model, trainer=self,
                                iterator=iterator)
        return model

    # -------------------------------------------------------------- evaluate
    def evaluate(self, data, labels=None, *, batch_size: int = 32,
                 evaluation=None):
        """Mesh-wide evaluation (reference: the Spark eval functions,
        `spark/impl/multilayer/scoring/` — workers score their shard,
        results merged via `Evaluation.merge`). Each batch's forward
        runs ONCE over the mesh with the batch sharded over the data
        axis; per-shard Evaluation objects are then merged, so the
        result is bit-identical to a single-device evaluation while the
        compute scales with the mesh."""
        from deeplearning4j_tpu.eval import Evaluation

        _require_single_process()
        model = self.model
        if not model._initialized:
            model.init()
        iterator = as_iterator(data, labels, batch_size=batch_size)
        repl = NamedSharding(self.mesh, P())
        batch_sh = NamedSharding(self.mesh, P(self.data_axis))
        params = _gput_tree(model.params, repl)
        state = _gput_tree(model.net_state, repl)

        if getattr(self, "_eval_forward", None) is None:
            def fwd(params, state, x):
                h, _, _, _, _ = model._forward_core(params, state, x,
                                                    train=False, rng=None)
                return h
            self._eval_forward = jax.jit(
                fwd, in_shardings=(repl, repl, batch_sh),
                out_shardings=batch_sh)

        merged = evaluation if evaluation is not None else Evaluation()
        # accumulating into `merged` directly keeps its top_n / labels /
        # threshold settings; `Evaluation.merge` remains the
        # cross-process combiner (masters / multihost)
        return _mesh_evaluate(
            model, iterator, merged, self.n_workers,
            lambda x: self._eval_forward(params, state, x),
            lambda f: _gput(f, batch_sh))

    def _fit_sync_threshold(self, iterator, listeners, rng_root, epochs,
                            steps_per_execution, divisible, check_trained):
        """Sync-mode fit with threshold-encoded gradient exchange
        (gradient_sharing="threshold"): same grouping/looping contract
        as the dense path, but each step's all-reduce moves the int8
        sign tensor instead of fp32 gradients, with the per-replica
        error-feedback residual and adaptive τ persisted across steps
        (and across fit() calls) like updater state."""
        from deeplearning4j_tpu.parallel import gradient_sharing as gs

        model = self.model
        if self._thr_step is None:
            self._build_threshold_step()
        spe = max(1, int(steps_per_execution))
        if spe > 1 and self._thr_multi is None:
            self._build_threshold_multi()
        repl = NamedSharding(self.mesh, P())

        # updater state is PER-REPLICA in threshold mode (each reference
        # worker advances its own updater on its local gradients) —
        # leading replica axis, same layout as the residual. An exact
        # resume (fault/) hands back the drifted per-replica stack; a
        # cold start replicates the model's view.
        def place():
            p = _gput_tree(model.params, repl)
            if self._resume_upd_r is not None:
                u, self._resume_upd_r = self._resume_upd_r, None
            else:
                u = self._replicate_tree(model.updater_state)
            return p, u, _gput_tree(model.net_state, repl)
        if self.stats is not None:
            with self.stats.time_phase("broadcast"):
                params, upd_r, state = place()
                jax.block_until_ready(params)
        else:
            params, upd_r, state = place()
        res_r, tau = self._threshold_state()
        batch_sh = NamedSharding(self.mesh, P(self.data_axis))
        stack_sh = NamedSharding(self.mesh, P(None, self.data_axis))
        eager_loss = bool(model.listeners) or self.stats is not None
        # comm accounting is host math on static shapes — every step is
        # counted with zero device syncs (docs/COMMS.md)
        wire_b = gs.exchange_wire_bytes(model.params, "threshold",
                                        n_workers=self.n_workers)
        dense_b = gs.exchange_wire_bytes(
            model.params, "dense", grad_dtype=model.dtype.compute_dtype)
        last_loss = None
        last_sparsity = None
        # replica-0 slice with a REPLICATED out-sharding (multi-process
        # fetchable) — the model-level updater view inside checkpoints
        rep0 = jax.jit(
            lambda t: jax.tree_util.tree_map(lambda a: a[0], t),
            out_shardings=repl)

        def live_state():
            # fault/ checkpointing: the fit's device-local trees are the
            # live training state (model attributes are stale until fit
            # returns); the per-replica updater stack and residual/τ
            # ride along for exact resume — gathered replicated so every
            # process can address them (multi-process elastic capture)
            return {"params": params, "net_state": state,
                    "updater_state": rep0(upd_r),
                    "trainer_arrays": {
                        "upd_r": self._replicated_view(upd_r),
                        "residual_r": self._replicated_view(res_r),
                        "tau": tau},
                    "trainer_meta": {"kind": "threshold",
                                     "trainer": "parallel",
                                     "n_workers": self.n_workers}}

        def run_single(ds):
            nonlocal params, upd_r, state, res_r, tau
            nonlocal last_loss, last_sparsity
            x = _gput(ds.features, batch_sh)
            y = _gput(ds.labels, batch_sh)
            rng = jax.random.fold_in(rng_root, model.iteration_count)
            t0 = time.perf_counter()
            params, upd_r, state, res_r, tau, loss, sp, dv = self._thr_step(
                params, upd_r, state, model.iteration_count, res_r, tau,
                x, y, rng)
            last_loss, last_sparsity = loss, sp
            gs.record_exchange("threshold", wire_b, dense_b, 1,
                               trainer="parallel")
            if eager_loss:
                model.score_value = float(loss)
                gs.record_threshold_stats(float(tau), float(sp),
                                          trainer="parallel")
            rows = _diag.process_if_due(model, dv, "exchange",
                                        model.iteration_count)
            if self.stats is not None:
                self.stats.record("sync_step", time.perf_counter() - t0,
                                  iteration=model.iteration_count)
                self.stats.next_round()
            listeners.iteration_done(model, model.iteration_count,
                                     model.epoch_count,
                                     model.score_value if eager_loss
                                     else float("nan"),
                                     batch_size=ds.num_examples(),
                                     diagnostics=rows[-1] if rows else None)
            model.iteration_count += 1

        def drain(pending):
            nonlocal params, upd_r, state, res_r, tau
            nonlocal last_loss, last_sparsity
            if not pending:
                return
            if len(pending) == 1:
                run_single(pending[0])
                return
            xs = _gput(np.stack([np.asarray(d.features) for d in pending]),
                       stack_sh)
            ys = _gput(np.stack([np.asarray(d.labels) for d in pending]),
                       stack_sh)
            it0 = model.iteration_count
            rngs = jax.vmap(lambda i: jax.random.fold_in(rng_root, i))(
                jnp.arange(it0, it0 + len(pending)))
            t0 = time.perf_counter()
            (params, upd_r, state, res_r, tau, losses, sps,
             dvs) = self._thr_multi(
                params, upd_r, state, it0, res_r, tau, xs, ys, rngs)
            last_loss, last_sparsity = losses, sps
            gs.record_exchange("threshold", wire_b, dense_b, len(pending),
                               trainer="parallel")
            lv = np.asarray(losses) if eager_loss else None
            if eager_loss:
                gs.record_threshold_stats(float(tau),
                                          float(np.asarray(sps)[-1]),
                                          trainer="parallel")
            rows = _diag.process_if_due(model, dvs, "exchange", it0,
                                        steps=len(pending))
            if self.stats is not None:
                self.stats.record("sync_step", time.perf_counter() - t0,
                                  iteration=it0, fused_steps=len(pending))
                self.stats.next_round()
            for j, d in enumerate(pending):
                if eager_loss:
                    model.score_value = float(lv[j])
                listeners.iteration_done(model, model.iteration_count,
                                         model.epoch_count,
                                         model.score_value if eager_loss
                                         else float("nan"),
                                         batch_size=d.num_examples(),
                                         step_boundary=(
                                             j == len(pending) - 1),
                                         diagnostics=(
                                             rows[j] if rows
                                             and model._diag.due(
                                                 model.iteration_count)
                                             else None))
                model.iteration_count += 1

        model._live_state_provider = live_state
        try:
            self._run_grouped(iterator, epochs, spe, divisible,
                              run_single, drain, model, listeners)
        finally:
            model._live_state_provider = None
        check_trained()
        self._thr_residual_r, self._thr_tau = res_r, tau
        if last_loss is not None and not eager_loss:
            lv = np.asarray(last_loss)
            model.score_value = float(lv[-1] if lv.ndim else lv)
        if last_sparsity is not None:
            sv = np.asarray(last_sparsity)
            gs.record_threshold_stats(float(np.asarray(tau)),
                                      float(sv[-1] if sv.ndim else sv),
                                      trainer="parallel")
        model.params = jax.tree_util.tree_map(np.asarray, params)
        model.net_state = jax.tree_util.tree_map(np.asarray, state)
        # per-replica updater states drift (each advanced on its own
        # shard, reference semantics); the model keeps replica 0's view.
        # The slice is taken with a REPLICATED out-sharding so the host
        # fetch is legal under multi-process execution (a bare a[0]
        # lands on replica 0's devices, which other processes cannot
        # read back)
        rep0 = jax.jit(
            lambda t: jax.tree_util.tree_map(lambda a: a[0], t),
            out_shardings=repl)
        model.updater_state = jax.tree_util.tree_map(np.asarray,
                                                     rep0(upd_r))
        return model

    def _fit_sync_bucketed(self, mode, iterator, listeners, rng_root,
                           epochs, steps_per_execution, divisible,
                           check_trained):
        """Sync-mode fit with the bucketed (overlapped) exchange: every
        ``stacked::`` packed run / unpacked layer exchanges inside the
        backward pass (dense pmean, threshold encode+int-psum, or the
        ZeRO reduce-scatter+all-gather of the `_rs` modes), per-bucket
        residual/τ persisted across steps and fit() calls like updater
        state. Same grouping/looping contract as the single-barrier
        paths."""
        from deeplearning4j_tpu.parallel import gradient_sharing as gs

        model = self.model
        per_replica_upd = mode != "dense"
        has_thr = mode in ("threshold", "threshold_rs")
        rs = mode in gs.RS_MODES
        if not self._updater_state_floats():
            # the updater advances INSIDE the VJP hooks — its state
            # threads the cotangent channel, which carries float leaves
            # only (every built-in updater qualifies; fit() already
            # degraded plain dense to the single-barrier program)
            raise ValueError(
                f"gradient_sharing={mode!r} threads updater state "
                "through the bucketed VJP and requires float state "
                "leaves, but this model's updater has non-float state. "
                "The rs modes are inherently bucketed (bucketed=False "
                "does not apply); use gradient_sharing='dense' or "
                "'threshold' with bucketed=False instead")
        if self._bkt_step is None:
            self._bkt_step = self._build_bucketed(mode, multi=False)
        spe = max(1, int(steps_per_execution))
        if spe > 1 and self._bkt_multi is None:
            self._bkt_multi = self._build_bucketed(mode, multi=True)
        repl = NamedSharding(self.mesh, P())

        def place_upd():
            if rs:
                return self._shard_rs_state(model.updater_state)
            if mode == "threshold":
                if self._resume_upd_r is not None:
                    u, self._resume_upd_r = self._resume_upd_r, None
                    return u
                return self._replicate_tree(model.updater_state)
            return _gput_tree(model.updater_state, repl)

        def place():
            return (_gput_tree(model.params, repl), place_upd(),
                    _gput_tree(model.net_state, repl))
        if self.stats is not None:
            with self.stats.time_phase("broadcast"):
                params, upd_r, state = place()
                jax.block_until_ready(params)
        else:
            params, upd_r, state = place()
        if has_thr:
            res_r, tau = self._threshold_state(per_bucket=True)
        else:
            res_r, tau = {}, {}
        batch_sh = NamedSharding(self.mesh, P(self.data_axis))
        stack_sh = NamedSharding(self.mesh, P(None, self.data_axis))
        eager_loss = bool(model.listeners) or self.stats is not None
        # comm accounting is host math on static shapes — every step is
        # counted with zero device syncs (docs/COMMS.md)
        wire_b = gs.exchange_wire_bytes(
            model.params, mode, n_workers=self.n_workers,
            rs_plan=self._rs_plan() if rs else None,
            grad_dtype=model.dtype.compute_dtype)
        dense_b = gs.exchange_wire_bytes(
            model.params, "dense", grad_dtype=model.dtype.compute_dtype)
        last_loss = None
        last_sparsity = None
        rep0 = jax.jit(
            lambda t: jax.tree_util.tree_map(lambda a: a[0], t),
            out_shardings=repl)
        rs_full = self._rs_full_state_fn() if rs else None

        def updater_view():
            # the model/checkpoint view of the live updater state:
            # replica 0 of the drifted per-replica stack (threshold),
            # the reassembled full tree (rs — checkpoints stay
            # replica-count independent), or the replicated tree itself
            if rs:
                return rs_full(upd_r)
            if mode == "threshold":
                return rep0(upd_r)
            return upd_r

        def live_state():
            # fault/ checkpointing: the fit's device-local trees are the
            # live training state (model attributes are stale until fit
            # returns); threshold-family modes add the per-bucket
            # residual/τ — and per-replica updater drift where it exists
            src = {"params": params, "net_state": state,
                   "updater_state": updater_view(),
                   "trainer_meta": {"kind": {"dense": "sync_dense",
                                             "threshold": "threshold",
                                             "dense_rs": "sync_dense_rs",
                                             "threshold_rs": "threshold_rs",
                                             }[mode],
                                    "trainer": "parallel",
                                    "bucketed": True,
                                    "n_workers": self.n_workers}}
            if has_thr:
                # per-replica stacks gathered replicated so every
                # process can address them (multi-process elastic
                # capture); τ is replicated by construction
                arrays = {"residual_r": self._replicated_view(res_r),
                          "tau": tau}
                if mode == "threshold":
                    arrays["upd_r"] = self._replicated_view(upd_r)
                src["trainer_arrays"] = arrays
            return src

        def record(steps):
            gs.record_exchange(mode, wire_b, dense_b, steps,
                               trainer="parallel")

        def run_single(ds):
            nonlocal params, upd_r, state, res_r, tau
            nonlocal last_loss, last_sparsity
            x = _gput(ds.features, batch_sh)
            y = _gput(ds.labels, batch_sh)
            rng = jax.random.fold_in(rng_root, model.iteration_count)
            t0 = time.perf_counter()
            params, upd_r, state, res_r, tau, loss, sp, dv = self._bkt_step(
                params, upd_r, state, model.iteration_count, res_r, tau,
                x, y, rng)
            last_loss, last_sparsity = loss, sp
            record(1)
            if eager_loss:
                model.score_value = float(loss)
                if has_thr:
                    gs.record_threshold_stats(gs.tau_scalar(tau),
                                              float(sp),
                                              trainer="parallel")
            rows = _diag.process_if_due(model, dv, "exchange",
                                        model.iteration_count)
            if self.stats is not None:
                self.stats.record("sync_step", time.perf_counter() - t0,
                                  iteration=model.iteration_count)
                self.stats.next_round()
            listeners.iteration_done(model, model.iteration_count,
                                     model.epoch_count,
                                     model.score_value if eager_loss
                                     else float("nan"),
                                     batch_size=ds.num_examples(),
                                     diagnostics=rows[-1] if rows else None)
            model.iteration_count += 1

        def drain(pending):
            nonlocal params, upd_r, state, res_r, tau
            nonlocal last_loss, last_sparsity
            if not pending:
                return
            if len(pending) == 1:
                run_single(pending[0])
                return
            xs = _gput(np.stack([np.asarray(d.features) for d in pending]),
                       stack_sh)
            ys = _gput(np.stack([np.asarray(d.labels) for d in pending]),
                       stack_sh)
            it0 = model.iteration_count
            rngs = jax.vmap(lambda i: jax.random.fold_in(rng_root, i))(
                jnp.arange(it0, it0 + len(pending)))
            t0 = time.perf_counter()
            (params, upd_r, state, res_r, tau, losses, sps,
             dvs) = self._bkt_multi(
                params, upd_r, state, it0, res_r, tau, xs, ys, rngs)
            last_loss, last_sparsity = losses, sps
            record(len(pending))
            lv = np.asarray(losses) if eager_loss else None
            if eager_loss and has_thr:
                gs.record_threshold_stats(gs.tau_scalar(tau),
                                          float(np.asarray(sps)[-1]),
                                          trainer="parallel")
            rows = _diag.process_if_due(model, dvs, "exchange", it0,
                                        steps=len(pending))
            if self.stats is not None:
                self.stats.record("sync_step", time.perf_counter() - t0,
                                  iteration=it0, fused_steps=len(pending))
                self.stats.next_round()
            for j, d in enumerate(pending):
                if eager_loss:
                    model.score_value = float(lv[j])
                listeners.iteration_done(model, model.iteration_count,
                                         model.epoch_count,
                                         model.score_value if eager_loss
                                         else float("nan"),
                                         batch_size=d.num_examples(),
                                         step_boundary=(
                                             j == len(pending) - 1),
                                         diagnostics=(
                                             rows[j] if rows
                                             and model._diag.due(
                                                 model.iteration_count)
                                             else None))
                model.iteration_count += 1

        model._live_state_provider = live_state
        try:
            self._run_grouped(iterator, epochs, spe, divisible,
                              run_single, drain, model, listeners)
        finally:
            model._live_state_provider = None
        check_trained()
        if has_thr:
            self._thr_residual_r, self._thr_tau = res_r, tau
        if last_loss is not None and not eager_loss:
            lv = np.asarray(last_loss)
            model.score_value = float(lv[-1] if lv.ndim else lv)
        if has_thr and last_sparsity is not None:
            sv = np.asarray(last_sparsity)
            gs.record_threshold_stats(gs.tau_scalar(tau),
                                      float(sv[-1] if sv.ndim else sv),
                                      trainer="parallel")
        model.params = jax.tree_util.tree_map(np.asarray, params)
        model.net_state = jax.tree_util.tree_map(np.asarray, state)
        model.updater_state = jax.tree_util.tree_map(np.asarray,
                                                     updater_view())
        return model

    # ------------------------------------------------------------------- fit
    def fit(self, data, labels=None, *, epochs: int = 1, batch_size: int = 32,
            steps_per_execution: int = 1):
        """Global-batch training over the mesh. `batch_size` is the GLOBAL
        batch; it must divide by the data-axis size.

        `steps_per_execution > 1` fuses that many steps into one
        `lax.scan` dispatch — numerics identical, host dispatch paid
        once per group. Both modes honor it (sync: scan over sharded
        batch stacks; averaging: the pmean round fires in-scan at its
        cadence); stats collection forces per-step execution in
        averaging mode because fused dispatch has no observable phase
        boundaries. The per-step loss device→host sync is also skipped
        when no listeners/stats need it, so small-model distributed
        training is not serialized on scalar readbacks."""
        model = self.model
        if not model._initialized:
            model.init()
        iterator = as_iterator(data, labels, batch_size=batch_size)
        # when the telemetry substrate is on, phase events flow onto the
        # global registry/tracer and the fit feeds /metrics like any
        # single-model fit (monitor.extra_listeners() is [] when off)
        monitor.attach_master_stats(self.stats)
        listeners = ComposedListeners(model.listeners
                                      + monitor.extra_listeners())
        rng_root = jax.random.PRNGKey(model.conf.seed + 3)

        n_div = self.n_workers
        batch_stats = {"trained": 0, "dropped": 0}

        def divisible(ds):
            # data-parallel shards need batch % devices == 0; ragged
            # TAILS are dropped (TF drop_remainder semantics) with a
            # warning — but a configuration where EVERY batch is
            # indivisible must fail loudly, not no-op (see fit() end)
            n = ds.num_examples()
            if n % n_div == 0:
                batch_stats["trained"] += 1
                return True
            batch_stats["dropped"] += 1
            if not getattr(self, "_warned_ragged", False):
                import logging
                logging.getLogger(__name__).warning(
                    "dropping ragged batch of %d examples (not divisible "
                    "by %d-way data parallelism); pad the dataset or pick "
                    "a divisible batch_size to train on every example",
                    n, n_div)
                self._warned_ragged = True
            return False

        def check_trained():
            if batch_stats["dropped"] and not batch_stats["trained"]:
                raise ValueError(
                    f"every batch was indivisible by the {n_div}-way data "
                    f"axis — fit() would be a silent no-op; use a "
                    f"batch_size divisible by {n_div}")

        if self.mode == "sync":
            from deeplearning4j_tpu.parallel import gradient_sharing as _gs
            gsmode = self.gradient_sharing
            if (gsmode == "dense" and self.bucketed
                    and not self._updater_state_floats()):
                # a custom updater with non-float state cannot thread
                # the bucketed VJP's cotangent channel — plain dense
                # silently keeps the single-barrier GSPMD program
                # (threshold/rs modes raise in _fit_sync_bucketed)
                gsmode = None
            if self._multi_io_graph and gsmode is not None:
                if gsmode == "dense":
                    # multi-input/-output graphs keep the GSPMD
                    # single-barrier program (the bucketed loss body
                    # packs exactly one features/labels pair)
                    gsmode = None
                else:
                    raise NotImplementedError(
                        f"gradient_sharing={gsmode!r} supports single-"
                        "input single-output models; train multi-io "
                        "graphs with gradient_sharing='dense' or via "
                        "model.fit")
            if gsmode is not None and (
                    gsmode in _gs.RS_MODES
                    or (self.bucketed and gsmode in ("dense",
                                                     "threshold"))):
                # default: bucketed per-layer-run exchange inside the
                # backward pass (the rs modes are inherently bucketed)
                return self._fit_sync_bucketed(
                    gsmode, iterator, listeners, rng_root, epochs,
                    steps_per_execution, divisible, check_trained)
            gsmode = self.gradient_sharing
            if gsmode == "threshold":
                # single-barrier PR-4 program (bucketed=False /
                # DL4J_BUCKETED_EXCHANGE=0)
                return self._fit_sync_threshold(
                    iterator, listeners, rng_root, epochs,
                    steps_per_execution, divisible, check_trained)

        if self.mode == "sync":
            if self._sync_step is None:
                self._build_sync_step()
            spe = max(1, int(steps_per_execution))
            if spe > 1 and self._sync_multi is None:
                self._build_sync_multi()
            repl = NamedSharding(self.mesh, P())
            if self.stats is not None:
                with self.stats.time_phase("broadcast"):
                    params = _gput_tree(model.params, repl)
                    upd = _gput_tree(model.updater_state, repl)
                    state = _gput_tree(model.net_state, repl)
                    jax.block_until_ready(params)
            else:
                params = _gput_tree(model.params, repl)
                upd = _gput_tree(model.updater_state, repl)
                state = _gput_tree(model.net_state, repl)
            batch_sh = NamedSharding(self.mesh, P(self.data_axis))
            stack_sh = NamedSharding(self.mesh, P(None, self.data_axis))
            # loss readback serializes host on device each step; only pay
            # it when someone (listener/stats consumer) will look at it
            eager_loss = bool(model.listeners) or self.stats is not None
            last_loss = None
            from deeplearning4j_tpu.parallel import gradient_sharing as gs
            # real wire dtype: the GSPMD all-reduce moves COMPUTE-dtype
            # grads (bf16 under mixed_bf16 — half the fp32 payload)
            dense_b = gs.exchange_wire_bytes(
                model.params, "dense", grad_dtype=model.dtype.compute_dtype)

            def live_state():
                # fault/ checkpointing: fit-local device trees (the
                # model's attributes are stale until fit returns)
                return {"params": params, "net_state": state,
                        "updater_state": upd,
                        "trainer_meta": {"kind": "sync_dense",
                                         "trainer": "parallel",
                                         "n_workers": self.n_workers}}

            def run_single(ds):
                nonlocal params, upd, state, last_loss
                x = _gput(ds.features, batch_sh)
                y = _gput(ds.labels, batch_sh)
                rng = jax.random.fold_in(rng_root, model.iteration_count)
                t0 = time.perf_counter()
                params, upd, state, loss, _, dv = self._sync_step(
                    params, upd, state, model.iteration_count, x, y, rng)
                gs.record_exchange("dense", dense_b, dense_b, 1,
                                   trainer="parallel")
                last_loss = loss
                if eager_loss:
                    model.score_value = float(loss)
                rows = _diag.process_if_due(model, dv, "fit",
                                            model.iteration_count)
                if self.stats is not None:
                    # float(loss) above already synced the step
                    self.stats.record("sync_step",
                                      time.perf_counter() - t0,
                                      iteration=model.iteration_count)
                    self.stats.next_round()
                # non-eager: NaN = "score not read back this step" (the
                # monitor listener's sentinel), never a stale score
                listeners.iteration_done(model, model.iteration_count,
                                         model.epoch_count,
                                         model.score_value if eager_loss
                                         else float("nan"),
                                         batch_size=ds.num_examples(),
                                         diagnostics=rows[-1] if rows
                                         else None)
                model.iteration_count += 1

            def drain(pending):
                nonlocal params, upd, state, last_loss
                if not pending:
                    return
                if len(pending) == 1:
                    run_single(pending[0])
                    return
                xs = _gput(np.stack([np.asarray(d.features) for d in pending]),
                           stack_sh)
                ys = _gput(np.stack([np.asarray(d.labels) for d in pending]),
                           stack_sh)
                it0 = model.iteration_count
                rngs = jax.vmap(lambda i: jax.random.fold_in(rng_root, i))(
                    jnp.arange(it0, it0 + len(pending)))
                t0 = time.perf_counter()
                params, upd, state, losses, dvs = self._sync_multi(
                    params, upd, state, it0, xs, ys, rngs)
                gs.record_exchange("dense", dense_b, dense_b, len(pending),
                                   trainer="parallel")
                last_loss = losses
                lv = np.asarray(losses) if eager_loss else None
                rows = _diag.process_if_due(model, dvs, "fit", it0,
                                            steps=len(pending))
                if self.stats is not None:
                    self.stats.record("sync_step",
                                      time.perf_counter() - t0, iteration=it0,
                                      fused_steps=len(pending))
                    self.stats.next_round()
                for j, d in enumerate(pending):
                    if eager_loss:
                        model.score_value = float(lv[j])
                    listeners.iteration_done(model, model.iteration_count,
                                             model.epoch_count,
                                             model.score_value if eager_loss
                                             else float("nan"),
                                             batch_size=d.num_examples(),
                                             step_boundary=(
                                                 j == len(pending) - 1),
                                             diagnostics=(
                                                 rows[j] if rows
                                                 and model._diag.due(
                                                     model.iteration_count)
                                                 else None))
                    model.iteration_count += 1

            model._live_state_provider = live_state
            try:
                self._run_grouped(iterator, epochs, spe, divisible,
                                  run_single, drain, model, listeners)
            finally:
                model._live_state_provider = None
            check_trained()
            if last_loss is not None and not eager_loss:
                lv = np.asarray(last_loss)
                model.score_value = float(lv[-1] if lv.ndim else lv)
            model.params = jax.tree_util.tree_map(np.asarray, params)
            model.net_state = jax.tree_util.tree_map(np.asarray, state)
            model.updater_state = jax.tree_util.tree_map(np.asarray, upd)
            return model

        # averaging (local SGD) mode. `steps_per_execution > 1` drains
        # k-batch groups through ONE shard_map dispatch whose scan fires
        # the pmean round at the averaging_frequency cadence — numerics
        # identical to per-step. Per-phase stats need the per-step path
        # (fused dispatch has no observable phase boundaries), so stats
        # collection forces spe=1.
        if self._local_step is None:
            self._build_averaging()
        spe = max(1, int(steps_per_execution))
        if self.stats is not None:
            spe = 1
        if spe > 1 and self._local_multi is None:
            self._build_averaging_multi()
        # exact resume (fault/) hands back the drifted per-replica
        # stacks + the averaging-cadence phase; a cold start replicates
        def place():
            if self._resume_avg is not None:
                ra, self._resume_avg = self._resume_avg, None
                return (ra["params_r"], ra["upd_r"], ra["state_r"],
                        ra["since_avg"])
            return (self._replicate_tree(model.params),
                    self._replicate_tree(model.updater_state),
                    self._replicate_tree(model.net_state), 0)
        if self.stats is not None:
            with self.stats.time_phase("broadcast"):
                params_r, upd_r, state_r, since_avg = place()
                jax.block_until_ready(params_r)
        else:
            params_r, upd_r, state_r, since_avg = place()
        batch_sh = NamedSharding(self.mesh, P(self.data_axis))
        stack_sh = NamedSharding(self.mesh, P(None, self.data_axis))
        # same lazy-readback gate as sync mode: the per-step scalar sync
        # is only paid when a listener/stats consumer will look at it
        eager_loss = bool(model.listeners) or self.stats is not None
        last_losses = None
        repl = NamedSharding(self.mesh, P())
        rep0 = jax.jit(
            lambda t: jax.tree_util.tree_map(lambda a: a[0], t),
            out_shardings=repl)

        def live_state():
            # fault/ checkpointing: every replica's params/updater/state
            # drifted independently since the last pmean round — the
            # full stacks plus the cadence phase are the live state;
            # replica 0 stands in for the model-level view
            return {"params": rep0(params_r), "net_state": rep0(state_r),
                    "updater_state": rep0(upd_r),
                    "trainer_arrays": {
                        "params_r": self._replicated_view(params_r),
                        "upd_r": self._replicated_view(upd_r),
                        "state_r": self._replicated_view(state_r)},
                    "trainer_meta": {"kind": "averaging",
                                     "trainer": "parallel",
                                     "since_avg": int(since_avg),
                                     "n_workers": self.n_workers}}

        def run_single(ds):
            nonlocal params_r, upd_r, state_r, since_avg, last_losses
            x = _gput(ds.features, batch_sh)
            y = _gput(ds.labels, batch_sh)
            rng = jax.random.fold_in(rng_root, model.iteration_count)
            t0 = time.perf_counter()
            params_r, upd_r, state_r, losses = self._local_step(
                params_r, upd_r, state_r, model.iteration_count, x, y, rng)
            last_losses = losses
            if eager_loss:
                model.score_value = float(jnp.mean(losses))
            if self.stats is not None:
                self.stats.record("local_fit", time.perf_counter() - t0,
                                  iteration=model.iteration_count)
            since_avg += 1
            if since_avg >= self.averaging_frequency:
                t0 = time.perf_counter()
                params_r = self._average_fn(params_r)
                state_r = self._average_fn(state_r)
                if self.average_updater_state:
                    upd_r = self._average_fn(upd_r)
                if self.stats is not None:
                    jax.block_until_ready(params_r)
                    self.stats.record("average",
                                      time.perf_counter() - t0,
                                      round=self.stats.next_round())
                since_avg = 0
            listeners.iteration_done(model, model.iteration_count,
                                     model.epoch_count,
                                     model.score_value if eager_loss
                                     else float("nan"),
                                     batch_size=ds.num_examples())
            model.iteration_count += 1

        def drain(pending):
            nonlocal params_r, upd_r, state_r, since_avg, last_losses
            if not pending:
                return
            if len(pending) == 1:
                run_single(pending[0])
                return
            xs = _gput(np.stack([np.asarray(d.features) for d in pending]),
                       stack_sh)
            ys = _gput(np.stack([np.asarray(d.labels) for d in pending]),
                       stack_sh)
            it0 = model.iteration_count
            rngs = jax.vmap(lambda i: jax.random.fold_in(rng_root, i))(
                jnp.arange(it0, it0 + len(pending)))
            params_r, upd_r, state_r, losses = self._local_multi(
                params_r, upd_r, state_r, it0, since_avg, xs, ys, rngs)
            last_losses = losses[-1]
            # cadence advances deterministically (since_avg < freq is
            # invariant) — host mirror of the in-scan update, no sync
            since_avg = (since_avg + len(pending)) % self.averaging_frequency
            lv = np.asarray(losses) if eager_loss else None
            for j, d in enumerate(pending):
                if eager_loss:
                    model.score_value = float(lv[j].mean())
                listeners.iteration_done(model, model.iteration_count,
                                         model.epoch_count,
                                         model.score_value if eager_loss
                                         else float("nan"),
                                         batch_size=d.num_examples(),
                                         step_boundary=(
                                             j == len(pending) - 1))
                model.iteration_count += 1

        model._live_state_provider = live_state
        try:
            self._run_grouped(iterator, epochs, spe, divisible,
                              run_single, drain, model, listeners)
        finally:
            model._live_state_provider = None
        if since_avg:
            params_r = self._average_fn(params_r)
            state_r = self._average_fn(state_r)
            if self.average_updater_state:
                upd_r = self._average_fn(upd_r)
        if last_losses is not None and not eager_loss:
            model.score_value = float(jnp.mean(last_losses))
        check_trained()
        model.params = self._unreplicate_tree(params_r)
        model.net_state = self._unreplicate_tree(state_r)
        model.updater_state = self._unreplicate_tree(upd_r)
        return model
