"""Tensor parallelism — param sharding specs + sharded trainer.

No reference equivalent (SURVEY §2.13: the reference has no TP; its
README's "model parallelism" is device data-parallelism). TPU-native
TP is a *sharding annotation*, not an engine: weights get
`PartitionSpec`s over the "model" mesh axis and GSPMD/XLA inserts the
all-gathers/reduce-scatters. Semantics are unchanged (annotations never
change math) — only layout/communication differ, which is exactly why
this composes freely with the data axis.

Default policy (Megatron-style for MLPs): every ≥2-D param is sharded
on its LAST axis (the output-features axis for Dense "W" [in, out] and
conv HWIO "W"), 1-D params follow on their only axis, and the model's
FINAL output layer stays replicated so the loss computation does not
gather logits across the mesh boundary.
"""

from __future__ import annotations

import time
from functools import partial
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.datasets.iterator import as_iterator
from deeplearning4j_tpu.monitor import diagnostics as _diagmod
from deeplearning4j_tpu.optimize.listeners import ComposedListeners


from deeplearning4j_tpu.nd.donation import donate_argnums as _donate


def tp_param_specs(model, model_axis: str = "model",
                   shard_output_layer: bool = False,
                   axis_size: Optional[int] = None) -> Dict:
    """PartitionSpec tree matching `model.params` for BOTH containers.

    MultiLayerNetwork params are keyed by layer index; ComputationGraph
    params by node name (output detection switches accordingly). Every
    ≥2-D param shards its LAST axis — Dense "W" [in, out] and conv HWIO
    "W" [H, W, I, O] both put output features last, so one rule covers
    MLPs and conv stacks; 1-D params (biases, BN gamma/beta — per
    output channel) follow on their only axis. `axis_size` (pass the
    mesh's model-axis extent) gates sharding on divisibility: an axis
    the mesh does not divide evenly stays replicated rather than
    tripping GSPMD's uneven-partition restrictions.
    """
    if hasattr(model, "layers"):        # MultiLayerNetwork
        n_layers = len(model.layers)

        def is_output(lk):
            return int(lk) == n_layers - 1
    else:                                # ComputationGraph
        outputs = set(model.conf.network_outputs)

        def is_output(lk):
            return lk in outputs

    def replicate(lk, pn, arr):
        return is_output(lk) and not shard_output_layer

    return _last_axis_specs(model, model_axis, axis_size, replicate,
                            shard_1d=True)


def _last_axis_specs(model, axis, axis_size, replicate_pred, *,
                     shard_1d):
    """Shared spec builder: every param shards its LAST axis over
    `axis` unless `replicate_pred(lk, pn, arr)` says otherwise, the
    axis does not divide by `axis_size`, or it is a scalar. 1-D params
    follow only when `shard_1d`."""
    def divides(dim):
        return axis_size is None or (dim % axis_size == 0)

    specs: Dict[str, Dict] = {}
    for lk, lparams in model.params.items():
        lspec = {}
        for pn, arr in lparams.items():
            nd = np.ndim(arr)
            if (nd == 0 or replicate_pred(lk, pn, arr)
                    or not divides(np.shape(arr)[-1])
                    or (nd == 1 and not shard_1d)):
                lspec[pn] = P()
            else:
                lspec[pn] = P(*([None] * (nd - 1) + [axis]))
        specs[lk] = lspec
    return specs


def fsdp_param_specs(model, data_axis: str = "data", *,
                     axis_size: int,
                     min_shard_elems: int = 1024) -> Dict:
    """ZeRO-3 / FSDP as a sharding annotation: every large param
    shards over the SAME axis the batch shards over, so each device
    holds 1/N of the weights and optimizer state; GSPMD inserts the
    all-gather at use and reduce-scatters the gradients. No wrapper
    engine — the capability the torch ecosystem builds FSDP for is one
    PartitionSpec tree here (beyond-reference: SURVEY §2.13 leaves the
    mesh axes open for exactly this).

    `axis_size` is REQUIRED (pass the mesh's data-axis extent): the
    divisibility gate is what keeps a [*, n_classes] head from hitting
    GSPMD's uneven-partition errors at fit time. Params shard on their
    LAST axis when divisible; small params (< `min_shard_elems`)
    replicate — gathering a bias costs more than storing it."""
    def replicate(lk, pn, arr):
        return int(np.prod(np.shape(arr))) < min_shard_elems

    return _last_axis_specs(model, data_axis, int(axis_size), replicate,
                            shard_1d=True)


def moe_param_specs(model, expert_axis: str = "expert",
                    model_axis: Optional[str] = None) -> Dict:
    """Expert parallelism: MixtureOfExperts params get their leading
    expert axis sharded over `expert_axis`; other params replicated (or
    TP-sharded over `model_axis` when given). GSPMD inserts the
    dispatch/combine collectives."""
    specs: Dict[str, Dict] = {}
    for lk, lparams in model.params.items():
        layer = model.layers[int(lk)]
        lspec = {}
        is_moe = layer.layer_name == "mixture_of_experts"
        for pn, arr in lparams.items():
            if is_moe and pn.startswith(("We", "be")):
                lspec[pn] = P(*([expert_axis] + [None] * (np.ndim(arr) - 1)))
            else:
                lspec[pn] = P()
        specs[lk] = lspec
    if model_axis is not None:
        tp = tp_param_specs(model, model_axis)
        for lk in specs:
            for pn in specs[lk]:
                if specs[lk][pn] == P():
                    specs[lk][pn] = tp[lk][pn]
    return specs


class ShardedParallelTrainer:
    """DP x TP training: batch sharded over `data_axis`, params sharded
    by `tp_param_specs` over `model_axis`; XLA inserts all collectives
    (gradient psum over data, activation gathers over model)."""

    def __init__(self, model, mesh: Mesh, *, data_axis: str = "data",
                 model_axis: str = "model", param_specs: Optional[Dict] = None,
                 gradient_sharing: Optional[str] = None,
                 threshold_config=None, stats=None,
                 bucketed: Optional[bool] = None):
        self.model = model
        self.mesh = mesh
        # stats: optional TrainingMasterStats — per-phase round timing
        # (broadcast / sync_step), same opt-in sync cost as
        # ParallelTrainer's stats collection
        self.stats = stats
        self.data_axis = data_axis
        self.model_axis = model_axis
        if not model._initialized:
            model.init()
        if param_specs is None:
            ax = (int(mesh.shape[model_axis])
                  if model_axis in mesh.shape else None)
            param_specs = tp_param_specs(model, model_axis, axis_size=ax)
        self.param_specs = param_specs
        # gradient exchange over the DATA axis: dense fp32 (GSPMD psum)
        # or error-feedback threshold encoding — the data-axis exchange
        # goes manual (shard_map) while the model-axis TP collectives
        # stay GSPMD-inserted (`auto` axes). Resolution mirrors
        # ParallelTrainer: env > arg > conf > dense.
        from deeplearning4j_tpu.parallel import gradient_sharing as _gs
        self.gradient_sharing = _gs.resolve_mode(gradient_sharing,
                                                 model.conf)
        if self.gradient_sharing in _gs.RS_MODES:
            if _gs.env_mode() == self.gradient_sharing and (
                    gradient_sharing or "dense") not in _gs.RS_MODES \
                    and getattr(model.conf, "gradient_sharing",
                                "dense") not in _gs.RS_MODES:
                # global env A/B toggle: degrade where the ZeRO path
                # does not apply (params here may be TP/FSDP-sharded
                # over mesh axes GSPMD owns) — back to what the ARG/CONF
                # would have resolved without the env, NOT blanket dense
                # (an explicitly configured threshold exchange must
                # survive a fleet-wide rs A/B)
                for v in (gradient_sharing,
                          getattr(model.conf, "gradient_sharing", None)):
                    if v is not None:
                        self.gradient_sharing = v
                        break
                else:
                    self.gradient_sharing = "dense"
            else:
                raise NotImplementedError(
                    "dense_rs/threshold_rs shard the updater over the "
                    "data axis of a pure-DP mesh (ParallelTrainer); "
                    "under ShardedParallelTrainer the params are "
                    "GSPMD-sharded and FSDP-style sharding goes through "
                    "param_specs=fsdp_param_specs(...) instead")
        # bucketed (per-layer-run, overlapped) threshold exchange:
        # default ON, same resolution as ParallelTrainer
        self.bucketed = _gs.resolve_bucketed(bucketed)
        n_data = int(mesh.shape[data_axis]) if data_axis in mesh.shape else 1
        if self.gradient_sharing == "threshold":
            _gs.wire_dtype(n_data)      # replica-count ceiling check
        self.threshold_config = (threshold_config if threshold_config
                                 is not None
                                 else _gs.ThresholdConfig.from_conf(
                                     model.conf))
        self._thr_step = None
        self._thr_residual_r = None
        self._thr_tau = None
        # exact-resume per-replica updater stack restored by
        # _restore_fault_state (fault/), consumed by the next fit()
        self._resume_upd_r = None
        self._step = None
        # ComputationGraph models pack features/labels as tuples
        self._is_graph = not hasattr(model, "_forward_core")
        if self._is_graph and (len(model.conf.network_inputs) != 1
                               or len(model.conf.network_outputs) != 1):
            raise NotImplementedError(
                "ShardedParallelTrainer supports single-input single-"
                "output graphs; train multi-io graphs via "
                "ParallelTrainer or model.fit")

    def _sharding(self, spec):
        return NamedSharding(self.mesh, spec)

    def _param_shardings(self):
        return jax.tree_util.tree_map(
            self._sharding, self.param_specs,
            is_leaf=lambda x: isinstance(x, P))

    def _build_shardings(self):
        if getattr(self, "_psh", None) is not None:
            return
        psh = self._param_shardings()
        # updater state mirrors the param tree one level down (per-param
        # dicts of updater slots) — replicate lookup by param name
        ush = {lk: {pn: jax.tree_util.tree_map(lambda _: psh[lk][pn], slots)
                    for pn, slots in lupd.items()}
               for lk, lupd in self.model.updater_state.items()}
        self._psh, self._ush = psh, ush
        self._repl = self._sharding(P())
        self._bsh = self._sharding(P(self.data_axis))

    def _build(self):
        model = self.model
        raw_step = model._make_train_step(tbptt=False)

        if self._is_graph:
            def step(params, upd, state, it, x, y, rng):
                return raw_step(params, upd, state, it, (x,), (y,), rng,
                                (None,), (None,), None)
        else:
            def step(params, upd, state, it, x, y, rng):
                return raw_step(params, upd, state, it, x, y, rng,
                                None, None, None)

        self._build_shardings()
        self._step = jax.jit(
            step,
            in_shardings=(self._psh, self._ush, self._repl, None,
                          self._bsh, self._bsh, None),
            out_shardings=(self._psh, self._ush, self._repl, None, None,
                           None),
            donate_argnums=_donate(0, 1, 2))

    # ------------------------------------------- threshold gradient sharing
    def _rep_sharding(self, leaf, spec):
        """Sharding for a per-replica (leading data-axis) stacked leaf:
        replica axis sharded over `data_axis`, the underlying TP spec
        preserved on the trailing dims when ranks line up (scalar-state
        leaves just shard the replica axis)."""
        dims = tuple(spec)
        if np.ndim(leaf) == len(dims):     # leaf given UNSTACKED
            return NamedSharding(self.mesh, P(self.data_axis, *dims))
        return NamedSharding(self.mesh, P(self.data_axis))

    def _replicate_per_worker(self, tree, spec_for):
        """Stack n_data copies on a new leading axis and shard it over
        the data axis (the per-replica residual / updater-state layout
        of the threshold exchange)."""
        from deeplearning4j_tpu.parallel.placement import gput
        n = int(self.mesh.shape[self.data_axis])

        def place(path_spec, a):
            a = np.asarray(a)
            stacked = np.broadcast_to(a[None], (n,) + a.shape)
            return gput(stacked, self._rep_sharding(a, path_spec))

        out = {}
        for lk, sub in tree.items():
            out[lk] = {}
            for pn, v in sub.items():
                spec = spec_for(lk, pn)
                out[lk][pn] = jax.tree_util.tree_map(
                    lambda a: place(spec, a), v)
        return out

    def _place_per_worker(self, stacked, spec_for):
        """Place an ALREADY-stacked per-replica host tree (leading
        replica axis) under the rep shardings — the restore-side
        counterpart of `_replicate_per_worker` (fault/ resume hands
        back per-replica state that must keep its drift, not be
        re-broadcast)."""
        from deeplearning4j_tpu.parallel.placement import gput

        def place(path_spec, a):
            a = np.asarray(a)
            return gput(a, self._rep_sharding(a[0] if a.ndim else a,
                                              path_spec))

        out = {}
        for lk, sub in stacked.items():
            out[lk] = {}
            for pn, v in sub.items():
                spec = spec_for(lk, pn)
                out[lk][pn] = jax.tree_util.tree_map(
                    lambda a: place(spec, a), v)
        return out

    # ---------------------------------------------------------- fault/resume
    def _restore_fault_state(self, arrays, meta):
        """fault.resume() hook: threshold residual + τ + per-replica
        updater stacks back under their DP x TP shardings, re-sharding
        the replica axis on an elastic replica-count change."""
        if meta.get("kind") != "threshold" or not arrays:
            return
        from deeplearning4j_tpu.fault import state as fs
        from deeplearning4j_tpu.parallel import gradient_sharing as gs
        self._build_shardings()
        n = (int(self.mesh.shape[self.data_axis])
             if self.data_axis in self.mesh.shape else 1)
        spec_for = lambda lk, pn: self.param_specs[lk][pn]
        res_r = arrays.get("residual_r")
        if res_r:
            self._thr_residual_r = self._place_per_worker(
                fs.reshard_replica_stack(res_r, n, kind="residual"),
                spec_for)
        tau = arrays.get("tau")
        if tau is not None:
            # scalar (PR-4 / single-barrier) or per-bucket tree
            # (bucketed) — restored as written, coerced at next fit
            self._thr_tau = gs.restore_tau(tau)
        upd_r = arrays.get("upd_r")
        if upd_r:
            self._resume_upd_r = self._place_per_worker(
                fs.reshard_replica_stack(upd_r, n, kind="state"), spec_for)

    def resume(self, directory, *, iterator=None):
        """Restore model + trainer state from the newest VALID
        checkpoint under `directory` (fault/ runtime). Returns the
        model; a following `fit()` continues the interrupted run."""
        from deeplearning4j_tpu import fault
        model, _ = fault.resume(directory, model=self.model, trainer=self,
                                iterator=iterator)
        return model

    def _build_threshold(self):
        """Threshold sync step for DP x TP: shard_map is MANUAL over the
        data axis only (the compressed integer all-reduce), while every
        other mesh axis stays `auto` — GSPMD keeps inserting the TP
        activation/weight collectives inside the body, so tensor
        parallelism composes with the compressed gradient exchange
        without hand-written model-axis collectives."""
        from deeplearning4j_tpu.parallel import gradient_sharing as gs
        from deeplearning4j_tpu.parallel.compat import shard_map

        mesh, axis = self.mesh, self.data_axis
        n = int(mesh.shape[axis])
        autoaxes = frozenset(mesh.axis_names) - {axis}
        # jaxlib 0.4.x SPMD partitioner limitation: an inner lax.scan
        # under a partially-manual shard_map hard-crashes (`Check
        # failed: sharding.IsManualSubgroup()`) — but newer jaxlibs
        # partition it fine and keep the scan-over-layers compiled-size
        # win, so the decision is a trace-time PROBE
        # (gs.partial_manual_scan_supported: version-gated for the
        # crash-prone line, compile-probed beyond it) instead of an
        # unconditional unroll
        allow_scan = (not autoaxes) or gs.partial_manual_scan_supported()
        if self.bucketed and any(
                not jnp.issubdtype(jnp.result_type(l), jnp.floating)
                for l in jax.tree_util.tree_leaves(
                    self.model.updater_state)):
            # the bucketed VJP threads updater state through the
            # cotangent channel (float leaves only) — fail with the
            # escape hatch named instead of an obscure custom_vjp
            # cotangent TypeError at trace time
            raise ValueError(
                "bucketed threshold gradient sharing threads updater "
                "state through the VJP and requires float state leaves; "
                "this model's updater has non-float state — pass "
                "bucketed=False for the single-barrier program")
        maker = (gs.make_bucketed_step if self.bucketed
                 else gs.make_threshold_step)
        step = maker(
            self.model, axis, self.threshold_config, n_workers=n,
            is_graph=self._is_graph, allow_scan=allow_scan,
            diag=self.model._diag,
            **({"mode": "threshold"} if self.bucketed else {}))
        self._build_shardings()
        rep = P(axis)
        strip = lambda t: jax.tree_util.tree_map(lambda a: a[0], t)
        expand = lambda t: jax.tree_util.tree_map(lambda a: a[None], t)
        kwargs = dict(mesh=mesh,
                      in_specs=(P(), rep, P(), None, rep, P(),
                                P(axis), P(axis), None),
                      out_specs=(P(), rep, P(), rep, P(), P(), P(), P()),
                      check_vma=False)
        if autoaxes:
            kwargs["auto"] = autoaxes

        @partial(shard_map, **kwargs)
        def thr_step(params, upd_r, state, it, res_r, tau, x, y, rng):
            params, upd, state, res, tau, loss, sp, dv = step(
                params, strip(upd_r), state, it, strip(res_r), tau,
                x, y, rng)
            return (params, expand(upd), state, expand(res), tau, loss,
                    sp, dv)

        self._thr_step = jax.jit(thr_step, donate_argnums=_donate(0, 1, 2, 4))

    def _threshold_state(self):
        from deeplearning4j_tpu.parallel import gradient_sharing as gs
        if self._thr_residual_r is None:
            zeros = gs.zeros_residual(self.model.params)
            self._thr_residual_r = self._replicate_per_worker(
                zeros, lambda lk, pn: self.param_specs[lk][pn])
        # τ form follows the step program: per-bucket tree (bucketed)
        # vs one scalar (single-barrier) — one coercion seam for both
        # trainers (path switches + cross-form checkpoint restores)
        self._thr_tau = gs.ensure_tau_form(
            self._thr_tau, self.bucketed, self.model.params,
            self.threshold_config)
        return self._thr_residual_r, self._thr_tau

    def evaluate(self, data, labels=None, *, batch_size: int = 32,
                 evaluation=None):
        """Evaluation with the SAME shardings training uses: params stay
        TP-sharded over `model_axis`, the batch shards over `data_axis`,
        XLA inserts the activation collectives. Ragged tails are zero-
        padded to the data-axis multiple and sliced after the forward —
        the model never materializes on one device (it may not fit)."""
        from deeplearning4j_tpu.eval import Evaluation
        from deeplearning4j_tpu.parallel.placement import gput, gput_tree
        from deeplearning4j_tpu.parallel.trainer import (
            _mesh_evaluate,
            _require_single_process,
        )

        _require_single_process("ShardedParallelTrainer.evaluate()")
        model = self.model
        self._build_shardings()
        if not hasattr(model, "_forward_core"):
            # ComputationGraph support here would need multi-input
            # feature packing and per-output evaluators — score those
            # per-output on the host or extend this when needed
            if (len(model.conf.network_inputs) != 1
                    or len(model.conf.network_outputs) != 1):
                raise NotImplementedError(
                    "ShardedParallelTrainer.evaluate supports single-"
                    "input single-output graphs; evaluate multi-io "
                    "graphs on the host via model.evaluate()")
        if getattr(self, "_eval_forward", None) is None:
            if hasattr(model, "_forward_core"):  # MultiLayerNetwork
                def fwd(params, state, x):
                    h, _, _, _, _ = model._forward_core(
                        params, state, x, train=False, rng=None)
                    return h
            else:  # single-in/out ComputationGraph
                def fwd(params, state, x):
                    acts, _, _, _ = model._forward_all(
                        params, state, [x], train=False, rng=None)
                    return acts[model.conf.network_outputs[0]]
            self._eval_forward = jax.jit(
                fwd, in_shardings=(self._psh, self._repl, self._bsh),
                out_shardings=self._bsh)
        params = gput_tree(model.params, self._psh)
        state = gput_tree(model.net_state, self._repl)
        iterator = as_iterator(data, labels, batch_size=batch_size)
        merged = evaluation if evaluation is not None else Evaluation()
        return _mesh_evaluate(
            model, iterator, merged, int(self.mesh.shape[self.data_axis]),
            lambda x: self._eval_forward(params, state, x),
            lambda f: gput(f, self._bsh))

    def fit(self, data, labels=None, *, epochs: int = 1, batch_size: int = 32):
        from deeplearning4j_tpu.parallel.placement import (
            gput, gput_tree, host_view_tree)

        model = self.model
        thr = self.gradient_sharing == "threshold"
        if thr and self._thr_step is None:
            self._build_threshold()
        if not thr and self._step is None:
            self._build()
        from deeplearning4j_tpu import monitor
        from deeplearning4j_tpu.parallel import gradient_sharing as gs
        monitor.attach_master_stats(self.stats)
        n_data = int(self.mesh.shape[self.data_axis])
        # multi-process aware placement: each process contributes only
        # its addressable shards of the TP-sharded param tree. Threshold
        # mode holds updater state PER-REPLICA (leading data axis — each
        # reference worker advances its own updater).
        def place_upd():
            if thr:
                # exact resume (fault/) hands back the drifted per-
                # replica stack; a cold start replicates the model view
                if self._resume_upd_r is not None:
                    u, self._resume_upd_r = self._resume_upd_r, None
                    return u
                return self._replicate_per_worker(
                    model.updater_state,
                    lambda lk, pn: self.param_specs[lk][pn])
            return gput_tree(model.updater_state, self._ush)
        if self.stats is not None:
            with self.stats.time_phase("broadcast"):
                params = gput_tree(model.params, self._psh)
                upd = place_upd()
                state = gput_tree(model.net_state, self._repl)
                jax.block_until_ready(params)
        else:
            params = gput_tree(model.params, self._psh)
            upd = place_upd()
            state = gput_tree(model.net_state, self._repl)
        if thr:
            res_r, tau = self._threshold_state()
            wire_b = gs.exchange_wire_bytes(model.params, "threshold",
                                            n_workers=n_data)
        dense_b = gs.exchange_wire_bytes(
            model.params, "dense", grad_dtype=model.dtype.compute_dtype)
        iterator = as_iterator(data, labels, batch_size=batch_size)
        listeners = ComposedListeners(model.listeners
                                      + monitor.extra_listeners())
        rng_root = jax.random.PRNGKey(model.conf.seed + 5)
        # per-step scalar readback serializes host on device; only pay
        # it when a listener/stats consumer will look at the score (same
        # gate as ParallelTrainer's sync path)
        eager_loss = bool(model.listeners) or self.stats is not None
        loss = None
        sp = None
        rep0_live = jax.jit(
            lambda t: jax.tree_util.tree_map(lambda a: a[0], t),
            out_shardings=self._ush) if thr else None

        def live_state():
            # fault/ checkpointing: fit-local device trees (the model's
            # attributes are stale until fit returns); threshold mode
            # adds the per-replica updater stack + residual/τ
            src = {"params": params, "net_state": state}
            if thr:
                src["updater_state"] = rep0_live(upd)
                src["trainer_arrays"] = {"upd_r": upd,
                                         "residual_r": res_r, "tau": tau}
                src["trainer_meta"] = {"kind": "threshold",
                                       "trainer": "sharded",
                                       "bucketed": self.bucketed,
                                       "n_workers": n_data}
            else:
                src["updater_state"] = upd
                src["trainer_meta"] = {"kind": "sync_dense",
                                       "trainer": "sharded",
                                       "n_workers": n_data}
            return src

        model._live_state_provider = live_state
        try:
            # epoch/fit listener events fire like the containers' fit
            # loops (checkpoint listeners drain their writer at fit end)
            listeners.on_fit_start(model)
            for _ in range(epochs):
                listeners.on_epoch_start(model, model.epoch_count)
                iterator.reset()
                for ds in iterator:
                    x = gput(ds.features, self._bsh)
                    y = gput(ds.labels, self._bsh)
                    rng = jax.random.fold_in(rng_root, model.iteration_count)
                    t0 = time.perf_counter() if self.stats is not None else 0.0
                    if thr:
                        params, upd, state, res_r, tau, loss, sp, dv = \
                            self._thr_step(params, upd, state,
                                           model.iteration_count, res_r, tau,
                                           x, y, rng)
                        gs.record_exchange("threshold", wire_b, dense_b, 1,
                                           trainer="sharded")
                    else:
                        params, upd, state, loss, _, dv = self._step(
                            params, upd, state, model.iteration_count, x, y,
                            rng)
                        gs.record_exchange("dense", dense_b, dense_b, 1,
                                           trainer="sharded")
                    if self.stats is not None:
                        jax.block_until_ready(loss)
                        self.stats.record("sync_step",
                                          time.perf_counter() - t0,
                                          iteration=model.iteration_count)
                        self.stats.next_round()
                    if eager_loss:
                        model.score_value = float(loss)
                    rows = _diagmod.process_if_due(
                        model, dv, "exchange" if thr else "fit",
                        model.iteration_count)
                    # non-eager: NaN = "score not read back this step" (the
                    # monitor listener's sentinel), never a stale score
                    listeners.iteration_done(model, model.iteration_count,
                                             model.epoch_count,
                                             model.score_value if eager_loss
                                             else float("nan"),
                                             batch_size=ds.num_examples(),
                                             diagnostics=rows[-1] if rows
                                             else None)
                    model.iteration_count += 1
                listeners.on_epoch_end(model, model.epoch_count)
                model.epoch_count += 1
            listeners.on_fit_end(model)
        finally:
            model._live_state_provider = None
        if loss is not None and not eager_loss:
            model.score_value = float(loss)
        if thr:
            self._thr_residual_r, self._thr_tau = res_r, tau
            if sp is not None:
                gs.record_threshold_stats(gs.tau_scalar(tau),
                                          float(np.asarray(sp)),
                                          trainer="sharded")
            # per-replica updater states drift (reference semantics);
            # the model keeps replica 0's view, sliced with the dense
            # updater shardings so the result is fetchable/reusable
            # under multi-process execution
            rep0 = jax.jit(
                lambda t: jax.tree_util.tree_map(lambda a: a[0], t),
                out_shardings=self._ush)
            upd = rep0(upd)
        # model-sharded leaves are not host-gatherable from one process
        # under multi-process execution; those stay as global arrays
        model.params = host_view_tree(params)
        model.updater_state = host_view_tree(upd)
        model.net_state = host_view_tree(state)
        return model
