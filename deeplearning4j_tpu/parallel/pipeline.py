"""Pipeline parallelism (GPipe-style) over the "pipe" mesh axis.

No reference equivalent (SURVEY §2.13: pipeline parallelism ❌). TPU
design: the model is a stack of S IDENTICAL blocks (the transformer /
repeated-MLP case — the standard JAX pipelining pattern); stage s holds
block s's params (leading stage axis sharded over "pipe"), microbatches
flow through the ring via `ppermute`, and the schedule is a
`lax.scan` over M + S - 1 ticks (fill + drain). Autodiff works through
the whole schedule (ppermute transposes to the reverse permute), so
one `jax.grad` gives pipeline-parallel backprop — no hand-written 1F1B
bookkeeping.

API: `pipeline_apply(block_fn, stage_params, x_microbatches, axis_name)`
runs inside shard_map; `pipeline_forward` wraps the shard_map for full
arrays.
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.parallel.compat import axis_size, shard_map


def pipeline_apply(block_fn: Callable, stage_params, x_mb, axis_name: str):
    """Per-shard: stage_params = THIS stage's block params (pytree),
    x_mb [M, B, ...] microbatches (replicated on every stage). Returns
    [M, B, ...] outputs (valid on the LAST stage; zeros elsewhere).

    Must run inside shard_map with `axis_name` bound.
    """
    S = axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    M = x_mb.shape[0]
    ticks = M + S - 1
    zero = jnp.zeros_like(x_mb[0])
    shift_down = [(j, (j + 1) % S) for j in range(S)]  # stage s → s+1

    def tick(carry, t):
        incoming, out_acc = carry
        # stage 0 injects microbatch t (if still filling); others use the
        # activation handed over from stage s-1 on the previous tick
        x_t = lax.dynamic_index_in_dim(x_mb, jnp.minimum(t, M - 1), 0,
                                       keepdims=False)
        inp = jnp.where(idx == 0, jnp.where(t < M, x_t, zero), incoming)
        y = block_fn(stage_params, inp)
        # last stage: microbatch m = t - (S-1) completes at tick t
        m = t - (S - 1)
        is_ready = jnp.logical_and(idx == S - 1, m >= 0)
        out_acc = lax.cond(
            jnp.logical_and(is_ready, m < M),
            lambda acc: lax.dynamic_update_index_in_dim(
                acc, y, jnp.clip(m, 0, M - 1), 0),
            lambda acc: acc, out_acc)
        handed = lax.ppermute(y, axis_name, shift_down)
        return (handed, out_acc), None

    out0 = jnp.zeros_like(x_mb)
    (final_in, outputs), _ = lax.scan(tick, (zero, out0), jnp.arange(ticks))
    return outputs


def pipeline_forward(block_fn, stacked_params, x, mesh: Mesh, *,
                     pipe_axis: str = "pipe", microbatches: int = 4,
                     data_axis: str = None):
    """Full-array wrapper: `stacked_params` has a leading stage axis
    (size = mesh["pipe"]), x is [B_total, ...]; B_total must divide by
    `microbatches`. Returns [B_total, ...] of the final stage.

    `data_axis` composes DP with the pipeline: the microbatch BATCH
    dim shards over it (each data-shard runs its own GPipe stream over
    the same pipe ring; params replicate across "data"), so a
    ("data", "pipe") mesh trains with both axes live."""
    B = x.shape[0]
    assert B % microbatches == 0, "batch must divide microbatches"
    x_mb = x.reshape((microbatches, B // microbatches) + x.shape[1:])
    # jax 0.4.x GSPMD miscompiles the reshard of a jit-traced
    # intermediate into a shard_map in_spec that partitions one mesh
    # axis while leaving another unmentioned (the value arrives SUMMED
    # over the unmentioned axis instead of sliced — observed on the
    # 0.4.37 CPU backend with a ("data", "pipe") mesh). On that line,
    # hand every stage the full replicated stack (in_spec P()) and
    # slice its stage inside the body; new-line JAX keeps the intended
    # P(pipe) param sharding.
    replicate_params = not hasattr(jax, "shard_map")
    p_spec = jax.tree_util.tree_map(
        lambda _: P() if replicate_params else P(pipe_axis),
        stacked_params)
    mb_spec = P(None, data_axis) if data_axis else P()

    @partial(shard_map, mesh=mesh,
             in_specs=(p_spec, mb_spec), out_specs=mb_spec,
             check_vma=False)
    def run(params_stage, mb):
        if replicate_params:
            s = lax.axis_index(pipe_axis)
            local = jax.tree_util.tree_map(
                lambda a: lax.dynamic_index_in_dim(a, s, 0, keepdims=False),
                params_stage)
        else:
            local = jax.tree_util.tree_map(lambda a: a[0], params_stage)
        out = pipeline_apply(block_fn, local, mb, pipe_axis)
        # outputs are valid only on the last stage; broadcast them
        return _broadcast_from(out, pipe_axis, axis_size(pipe_axis) - 1)

    out_mb = run(stacked_params, x_mb)
    return out_mb.reshape((B,) + out_mb.shape[2:])


def _broadcast_from(x, axis_name, src):
    """All stages receive stage `src`'s value (psum of masked values)."""
    idx = lax.axis_index(axis_name)
    masked = jnp.where(idx == src, x, jnp.zeros_like(x))
    return lax.psum(masked, axis_name)
