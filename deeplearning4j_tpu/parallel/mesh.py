"""Device mesh construction.

The mesh is the TPU-native replacement for the reference's device
topology handling (`ParallelWrapper`'s one-thread-per-GPU model and the
Spark cluster layout): a named grid of devices over which arrays are
sharded with `jax.sharding.NamedSharding`. Axis conventions:

- "data":  data parallelism (gradient all-reduce rides ICI)
- "model": tensor parallelism (activations/weights split)
- "seq":   sequence/context parallelism (ring attention)
- "pipe":  pipeline stages
- "expert": MoE expert parallelism

Multi-host: the same mesh spans hosts transparently once
`jax.distributed.initialize()` has run (DCN-spanning axes should be the
outermost/slowest-varying — `make_mesh` orders axes as given).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Serializable mesh description: ordered {axis_name: size}."""

    axes: tuple  # of (name, size)

    @staticmethod
    def data_parallel(n: Optional[int] = None) -> "MeshSpec":
        n = n or len(jax.devices())
        return MeshSpec((("data", n),))

    @staticmethod
    def of(**axes: int) -> "MeshSpec":
        return MeshSpec(tuple(axes.items()))

    def names(self):
        return tuple(n for n, _ in self.axes)

    def shape(self):
        return tuple(s for _, s in self.axes)

    def size(self):
        return int(np.prod(self.shape())) if self.axes else 1

    def to_dict(self):
        return {"axes": list(map(list, self.axes))}

    @staticmethod
    def from_dict(d):
        return MeshSpec(tuple((n, int(s)) for n, s in d["axes"]))


def make_mesh(spec: MeshSpec | Dict[str, int] | None = None,
              devices: Optional[Sequence] = None) -> Mesh:
    if spec is None:
        spec = MeshSpec.data_parallel()
    if isinstance(spec, dict):
        spec = MeshSpec(tuple(spec.items()))
    devices = list(devices) if devices is not None else jax.devices()
    n = spec.size()
    if len(devices) < n:
        raise ValueError(f"Mesh {spec} needs {n} devices, have {len(devices)}")
    grid = np.array(devices[:n]).reshape(spec.shape())
    return Mesh(grid, spec.names())


def device_mesh(n_data: Optional[int] = None) -> Mesh:
    """Convenience: 1-axis data-parallel mesh over all (or n) devices."""
    return make_mesh(MeshSpec.data_parallel(n_data))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())


def data_sharded(mesh: Mesh, axis: str = "data") -> NamedSharding:
    """Batch-dim sharding over the data axis."""
    return NamedSharding(mesh, PartitionSpec(axis))
