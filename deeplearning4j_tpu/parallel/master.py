"""TrainingMaster — the multi-node training strategy surface.

Reference: `spark/api/TrainingMaster.java:28` with its two
implementations, `ParameterAveragingTrainingMaster.java` (sync rounds:
split data, fit locally, tree-aggregate + average params each round)
and `SharedTrainingMaster.java` (Aeron parameter server streaming
threshold-compressed updates).

TPU mapping: both collapse onto mesh programs (SURVEY §2.13 / §5):
- ParameterAveragingTrainingMaster → local-SGD mode: k local steps per
  replica, then `pmean` over the data axis — `averaging_frequency` is
  the reference's same-named knob (and `batch_size_per_worker` its
  `batchSizePerWorker`).
- SharedTrainingMaster → per-step synchronous gradient all-reduce
  (ICI bandwidth removes the need for the threshold compression the
  Aeron design required; the knobs that configured compression are
  accepted and ignored with a log note, so reference configs port).

Multi-host: call `parallel.initialize_multihost()` first; the mesh then
spans all hosts and the same masters drive DCN-wide training — the
Spark driver/executor split disappears into SPMD.
"""

from __future__ import annotations

import logging

import numpy as np
from typing import Optional

from deeplearning4j_tpu.parallel.mesh import device_mesh
from deeplearning4j_tpu.parallel.stats import TrainingMasterStats
from deeplearning4j_tpu.parallel.trainer import ParallelTrainer

log = logging.getLogger(__name__)


class TrainingMaster:
    """`executeTraining(model, data)` contract. `data` is anything the
    trainers accept: a DataSetIterator, a DataSet, or an (x, y) pair."""

    def execute_training(self, model, data, *, epochs: int = 1):
        raise NotImplementedError

    @staticmethod
    def _split(data):
        if (isinstance(data, tuple) and len(data) == 2
                and not hasattr(data[0], "features")):
            return data[0], data[1]
        return data, None

    def execute_evaluation(self, model, data, *, batch_size: int = 32,
                           evaluation=None, n_shards: Optional[int] = None):
        """Distributed evaluation (reference: the Spark eval RDD
        functions, `spark/impl/multilayer/scoring/` — each worker
        scores its partition, the driver merges). The data is split
        into worker shards, each shard scored through the mesh-sharded
        forward into its OWN Evaluation, and the per-shard results
        combined with `Evaluation.merge` — the tree-aggregate shape,
        so the path multi-process deployments use is the one tested."""
        import copy

        from deeplearning4j_tpu.eval import Evaluation

        mesh = getattr(self, "mesh", None) or device_mesh()
        trainer = ParallelTrainer(model, mesh)
        x, y = self._split(data)
        merged = evaluation if evaluation is not None else Evaluation()
        if y is None:  # iterator/DataSet input: score in one pass
            return trainer.evaluate(x, batch_size=batch_size,
                                    evaluation=merged)
        n = n_shards or mesh.shape["data"]
        n = max(1, min(n, len(x)))
        # per-shard evaluator = an emptied CLONE of the caller's, so its
        # configuration (threshold, cost array, labels, top_n) applies
        # on every shard; evaluator types without reset() score into
        # `merged` directly (no merge demonstration, same result)
        can_clone = hasattr(merged, "reset")
        for xs, ys in zip(np.array_split(np.asarray(x), n),
                          np.array_split(np.asarray(y), n)):
            if can_clone:
                shard_ev = copy.deepcopy(merged)
                shard_ev.reset()
                trainer.evaluate(xs, ys, batch_size=batch_size,
                                 evaluation=shard_ev)
                merged.merge(shard_ev)
            else:
                trainer.evaluate(xs, ys, batch_size=batch_size,
                                 evaluation=merged)
        return merged

    # -------------------------------------------------- fault tolerance
    # The reference's fault story is Spark re-running failed executors;
    # the TPU-era equivalent is checkpoint/restore (preempted TPU jobs
    # resume from the last checkpoint). Both masters share this driver:
    # one trainer.fit() per epoch, a checkpoint every
    # `checkpoint_every` epochs, a retry budget that restores the last
    # checkpoint on failure, and resume-from-latest on start.
    def _run_epochs(self, model, trainer, x, y, *, epochs, batch_size):
        spe = max(1, getattr(self, "steps_per_execution", 1))
        import glob
        import os

        from deeplearning4j_tpu.util.serializer import ModelSerializer

        ckpt_dir = getattr(self, "checkpoint_dir", None)
        every = max(0, getattr(self, "checkpoint_every", 0))
        retries = max(0, getattr(self, "max_retries", 0))

        if not ckpt_dir and not retries:
            # no fault tolerance configured: one fit() for all epochs —
            # avoids per-epoch param re-broadcast round-trips
            return trainer.fit(x, y, epochs=epochs, batch_size=batch_size,
                               steps_per_execution=spe)

        import jax as _jax

        # in-memory epoch-0 snapshot: the restore target when a failure
        # precedes the first on-disk checkpoint (restarting from trained
        # params would silently over-train with a desynced LR schedule);
        # only taken when retries can actually consume it
        init_snap = None
        if retries:
            init_snap = (
                _jax.tree_util.tree_map(np.asarray, model.params),
                _jax.tree_util.tree_map(np.asarray, model.net_state),
                _jax.tree_util.tree_map(np.asarray, model.updater_state),
                model.iteration_count, model.epoch_count)

        def restore_from(net):
            model.params = net.params
            model.net_state = net.net_state
            model.updater_state = net.updater_state
            model.iteration_count = net.iteration_count
            model.epoch_count = net.epoch_count
            model._initialized = True

        def _ckpt_epoch(path):
            #  .../epoch00042.zip  or  .../epoch00042.ckpt — parse ALL
            # digits (epochs can widen past the 05d padding)
            return int(os.path.splitext(os.path.basename(path))[0][5:])

        def _list_ckpts():
            return sorted(
                glob.glob(os.path.join(ckpt_dir, "epoch*.zip"))
                + glob.glob(os.path.join(ckpt_dir, "epoch*.ckpt")),
                key=_ckpt_epoch)

        def _restore_ckpt(path):
            if path.endswith(".zip"):
                restore_from(ModelSerializer.restore_model(path))
            else:
                from deeplearning4j_tpu.util.sharded_checkpoint import (
                    ShardedCheckpoint)
                # restore each array to the LIVE model's current
                # sharding (multi-host: a process can only address its
                # own shards; default placement would try to
                # materialize full arrays everywhere)
                shardings = {
                    "params": _jax.tree_util.tree_map(
                        lambda a: getattr(a, "sharding", None), model.params),
                    "net_state": _jax.tree_util.tree_map(
                        lambda a: getattr(a, "sharding", None),
                        model.net_state),
                    "updater_state": _jax.tree_util.tree_map(
                        lambda a: getattr(a, "sharding", None),
                        model.updater_state),
                }
                ShardedCheckpoint.restore(path, model=model,
                                          shardings=shardings)

        start_epoch = 0
        if ckpt_dir:
            os.makedirs(ckpt_dir, exist_ok=True)
            existing = _list_ckpts()
            if existing and getattr(self, "resume", True):
                latest = existing[-1]
                _restore_ckpt(latest)
                start_epoch = _ckpt_epoch(latest) + 1
                log.info("resuming from %s (epoch %d)", latest, start_epoch)

        def save(epoch):
            if ckpt_dir and every and (epoch + 1) % every == 0:
                base = os.path.join(ckpt_dir, f"epoch{epoch:05d}")
                tmp = base + ".zip.tmp"
                try:
                    # write-then-rename: a failed gather must not leave
                    # a structurally-valid-but-empty zip that a later
                    # resume would silently load as fresh-init weights
                    ModelSerializer.write_model(model, tmp)
                    os.replace(tmp, base + ".zip")
                except Exception as e:
                    if os.path.exists(tmp):
                        os.unlink(tmp)
                    # params sharded past host-gatherability (or other
                    # zip failure — logged so the root cause survives):
                    # fall back to the Orbax sharded format
                    log.warning("zip checkpoint failed (%s: %s); saving "
                                "sharded", type(e).__name__, e)
                    from deeplearning4j_tpu.util.sharded_checkpoint import (
                        ShardedCheckpoint)
                    ShardedCheckpoint.save(base + ".ckpt", model)

        epoch = start_epoch
        budget = retries
        while epoch < epochs:
            try:
                trainer.fit(x, y, epochs=1, batch_size=batch_size,
                            steps_per_execution=spe)
                save(epoch)
                epoch += 1
            except Exception:
                if budget <= 0:
                    raise
                budget -= 1
                existing = _list_ckpts() if ckpt_dir else []
                if existing:
                    _restore_ckpt(existing[-1])
                    # rewind to just after the restored checkpoint —
                    # params (and iteration_count, for LR schedules) are
                    # from that epoch, so later epochs must re-run
                    epoch = _ckpt_epoch(existing[-1]) + 1
                    log.warning("failure; restored %s, resuming at epoch "
                                "%d (%d retries left)", existing[-1],
                                epoch, budget)
                else:
                    (model.params, model.net_state, model.updater_state,
                     model.iteration_count, model.epoch_count) = (
                        _jax.tree_util.tree_map(np.asarray, init_snap[0]),
                        _jax.tree_util.tree_map(np.asarray, init_snap[1]),
                        _jax.tree_util.tree_map(np.asarray, init_snap[2]),
                        init_snap[3], init_snap[4])
                    epoch = 0
                    log.warning("failure with no checkpoint yet; restored "
                                "the initial state, restarting from epoch "
                                "0 (%d retries left)", budget)
        return model


class ParameterAveragingTrainingMaster(TrainingMaster):
    def __init__(self, *, batch_size_per_worker: int = 32,
                 averaging_frequency: int = 5,
                 average_updater_state: bool = True, mesh=None,
                 collect_training_stats: bool = False,
                 checkpoint_dir: Optional[str] = None,
                 checkpoint_every: int = 1, max_retries: int = 0,
                 resume: bool = True):
        self.batch_size_per_worker = batch_size_per_worker
        self.averaging_frequency = averaging_frequency
        self.average_updater_state = average_updater_state
        self.mesh = mesh
        self.checkpoint_dir = checkpoint_dir
        self.checkpoint_every = checkpoint_every
        self.max_retries = max_retries
        self.resume = resume
        # per-round phase timing + timeline export, the
        # `ParameterAveragingTrainingMasterStats` role; opt-in like the
        # reference's setCollectTrainingStats — it adds one device sync
        # per timed phase
        self.collect_training_stats = collect_training_stats
        self.stats: TrainingMasterStats = None

    def execute_training(self, model, data, *, epochs: int = 1):
        mesh = self.mesh or device_mesh()
        n_workers = mesh.shape["data"]
        self.stats = (TrainingMasterStats()
                      if self.collect_training_stats else None)
        trainer = ParallelTrainer(
            model, mesh, mode="averaging",
            averaging_frequency=self.averaging_frequency,
            average_updater_state=self.average_updater_state,
            stats=self.stats)
        x, y = self._split(data)
        return self._run_epochs(
            model, trainer, x, y, epochs=epochs,
            batch_size=self.batch_size_per_worker * n_workers)

    def get_training_stats(self) -> TrainingMasterStats:
        """Reference `getTrainingStats()` — per-round timeline; use
        `.export_html(path)` / `.export_json(path)` (StatsUtils role)."""
        return self.stats


class SharedTrainingMaster(TrainingMaster):
    def __init__(self, *, batch_size_per_worker: int = 32, mesh=None,
                 threshold: Optional[float] = None,
                 collect_training_stats: bool = False,
                 checkpoint_dir: Optional[str] = None,
                 checkpoint_every: int = 1, max_retries: int = 0,
                 resume: bool = True, steps_per_execution: int = 1,
                 **compression_knobs):
        self.batch_size_per_worker = batch_size_per_worker
        self.steps_per_execution = steps_per_execution
        self.mesh = mesh
        self.collect_training_stats = collect_training_stats
        self.stats: TrainingMasterStats = None
        self.checkpoint_dir = checkpoint_dir
        self.checkpoint_every = checkpoint_every
        self.max_retries = max_retries
        self.resume = resume
        if threshold is not None or compression_knobs:
            log.info(
                "SharedTrainingMaster: threshold-compression knobs %s are "
                "accepted for config compatibility but unused — synchronous "
                "all-reduce over ICI/DCN replaces the compressed Aeron path",
                {"threshold": threshold, **compression_knobs})

    def execute_training(self, model, data, *, epochs: int = 1):
        mesh = self.mesh or device_mesh()
        n_workers = mesh.shape["data"]
        self.stats = (TrainingMasterStats()
                      if self.collect_training_stats else None)
        trainer = ParallelTrainer(model, mesh, mode="sync",
                                  stats=self.stats)
        x, y = self._split(data)
        return self._run_epochs(
            model, trainer, x, y, epochs=epochs,
            batch_size=self.batch_size_per_worker * n_workers)

    def get_training_stats(self) -> TrainingMasterStats:
        return self.stats
