"""Multi-process distributed-training smoke proof.

The reference proves its distributed path in-process on every CI run
(`dl4j-spark/src/test/java/.../BaseSparkTest.java:89` — Spark
`local[N]`). The TPU-native equivalent: N OS processes around a
`jax.distributed` coordinator on the CPU backend, each owning 2 virtual
local devices, all running the SAME global-view `ParallelTrainer` sync
program over one global mesh. XLA's collectives ride the distributed
runtime exactly as they would across TPU hosts over DCN.

Usage (also wired into `__graft_entry__.dryrun_multichip` and
`tests/test_multihost.py`):

    python -m deeplearning4j_tpu.parallel.multihost_smoke --n 2

Exit 0 iff (a) both processes see the 4-device global mesh, (b) sync
training runs, and (c) the loss trajectory matches a single-process run
on the same 4-device mesh (same global batch, same seeds) to float
tolerance — proving the multi-process path computes the same global
program.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys

_LOCAL_DEVICES = 2   # virtual CPU devices per process


def _build_model():
    from deeplearning4j_tpu.common.updaters import Adam
    from deeplearning4j_tpu.common.weights import WeightInit
    from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    conf = (NeuralNetConfiguration.builder()
            .seed(21).updater(Adam(5e-2)).weight_init(WeightInit.XAVIER)
            .list()
            .layer(DenseLayer(n_in=6, n_out=16, activation="tanh"))
            .layer(OutputLayer(n_in=16, n_out=3, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.feed_forward(6))
            .build())
    return MultiLayerNetwork(conf).init()


def _run_training():
    """Global-view training on whatever global mesh exists: (a) DP sync
    (ParallelTrainer), then (b) DP x TP (ShardedParallelTrainer —
    params sharded over "model" ACROSS processes). Returns (losses
    covering both phases, this process's local-shard Evaluation as
    JSON — the distributed-evaluation recipe's transport payload)."""
    import jax
    import numpy as np
    from jax.sharding import Mesh

    from deeplearning4j_tpu.optimize.listeners import CollectScoresListener
    from deeplearning4j_tpu.parallel.mesh import MeshSpec, make_mesh
    from deeplearning4j_tpu.parallel.tensor import ShardedParallelTrainer
    from deeplearning4j_tpu.parallel.trainer import ParallelTrainer

    devs = np.array(jax.devices())
    mesh = Mesh(devs, ("data",))
    model = _build_model()
    listener = CollectScoresListener()
    model.set_listeners(listener)
    rng = np.random.default_rng(0)
    B = 16
    x = rng.standard_normal((B, 6)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, B)]
    ParallelTrainer(model, mesh, mode="sync").fit(x, y, epochs=5,
                                                  batch_size=B)
    losses = [s for _, s in listener.scores]

    # DP x TP across the same global devices. "model" is the OUTERMOST
    # mesh axis: jax.devices() is process-major and make_mesh reshapes
    # row-major, so the model-axis pairs straddle the process boundary
    # and every TP activation gather crosses the distributed runtime
    # (innermost "model" would keep TP intra-process and prove nothing)
    n_dev = len(devs)
    tp_mesh = make_mesh(MeshSpec.of(model=2, data=max(n_dev // 2, 1)),
                        devices=devs.tolist())
    tp_model = _build_model()
    tp_listener = CollectScoresListener()
    tp_model.set_listeners(tp_listener)
    tp_trainer = ShardedParallelTrainer(tp_model, tp_mesh)
    tp_trainer.fit(x, y, epochs=2, batch_size=B)
    # second fit: model.params now holds TP-sharded GLOBAL arrays (not
    # host-gatherable from one process) — placement must pass them
    # through instead of np.asarray-ing them (regression: resumed/
    # multi-call training under multi-process TP)
    tp_trainer.fit(x, y, epochs=1, batch_size=B)

    # Threshold-encoded gradient sharing over the SAME global mesh
    # (parallel/gradient_sharing.py): the int8 all-reduce + residual/τ
    # shard_map program must compute the identical trajectory under 1
    # and N processes — the multihost proof of the compressed exchange
    # (its collectives ride the distributed runtime like the dense psum)
    thr_model = _build_model()
    thr_listener = CollectScoresListener()
    thr_model.set_listeners(thr_listener)
    ParallelTrainer(thr_model, mesh, mode="sync",
                    gradient_sharing="threshold").fit(x, y, epochs=3,
                                                      batch_size=B)
    thr_losses = [s for _, s in thr_listener.scores]

    # Distributed-evaluation recipe (what the mesh evaluate() guard
    # tells multi-process callers to do): each process scores ITS OWN
    # data shard on the host, the evaluators travel as JSON, and the
    # collector merges them. Here the "transport" is this process's
    # stdout; run_smoke merges and compares against the single-process
    # full-data evaluation.
    from deeplearning4j_tpu.eval import Evaluation

    pi, pc = jax.process_index(), jax.process_count()
    # array_split boundaries: uneven B/pc must not drop the remainder
    bounds = np.cumsum([0] + [len(a) for a in np.array_split(x, pc)])
    shard = slice(int(bounds[pi]), int(bounds[pi + 1]))
    local_ev = Evaluation()
    local_ev.eval(y[shard], np.asarray(model.output(x[shard])))
    return (losses + [s for _, s in tp_listener.scores], thr_losses,
            local_ev.to_json())


def _worker_main(coordinator: str, n: int, i: int):
    import jax

    jax.config.update("jax_platforms", "cpu")
    from deeplearning4j_tpu.parallel.multihost import initialize_multihost

    initialize_multihost(coordinator, n, i)
    assert jax.process_count() == n, jax.process_count()
    assert len(jax.devices()) == n * _LOCAL_DEVICES, len(jax.devices())
    losses, thr_losses, eval_json = _run_training()
    print("LOSSES " + json.dumps(losses), flush=True)
    print("THRLOSSES " + json.dumps(thr_losses), flush=True)
    print("EVALJSON " + eval_json, flush=True)


def _single_main():
    import jax

    jax.config.update("jax_platforms", "cpu")
    losses, thr_losses, eval_json = _run_training()
    print("LOSSES " + json.dumps(losses), flush=True)
    print("THRLOSSES " + json.dumps(thr_losses), flush=True)
    print("EVALJSON " + eval_json, flush=True)


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _spawn(args, n_local_devices):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    flags = [f for f in env.get("XLA_FLAGS", "").split()
             if "xla_force_host_platform_device_count" not in f]
    flags.append(f"--xla_force_host_platform_device_count={n_local_devices}")
    env["XLA_FLAGS"] = " ".join(flags)
    return subprocess.Popen(
        [sys.executable, "-m", "deeplearning4j_tpu.parallel.multihost_smoke",
         *args],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))))


def _parse_tag(out: str, tag: str):
    for line in out.splitlines():
        if line.startswith(tag + " "):
            return line[len(tag) + 1:]
    return None


def _parse_losses(out: str):
    s = _parse_tag(out, "LOSSES")
    return None if s is None else json.loads(s)


def _parse_eval(out: str):
    return _parse_tag(out, "EVALJSON")


class _PortBindRace(RuntimeError):
    """The jax coordinator lost the race for its pre-probed port (a
    parallel CI job re-grabbed it between `_free_port` and bind)."""


_BIND_MARKERS = ("Address already in use", "address already in use",
                 "Failed to bind")


def run_smoke(n: int = 2, timeout: int = 420, *,
              bind_attempts: int = 3) -> dict:
    """Orchestrate: n distributed workers + 1 single-process reference,
    compare loss trajectories. Returns a report dict; raises on fail.

    The coordinator port is probed-then-bound, which is a race under
    parallel CI — a bind failure retries the whole worker cycle on a
    fresh port, `bind_attempts` times."""
    last: Exception = RuntimeError("unreachable")
    for attempt in range(max(1, int(bind_attempts))):
        try:
            return _run_smoke_once(n, timeout)
        except _PortBindRace as e:
            last = e
            import logging
            logging.getLogger(__name__).warning(
                "coordinator port bind race (attempt %d/%d): %s — "
                "retrying on a fresh port", attempt + 1, bind_attempts,
                str(e)[-200:])
    raise last


def _run_smoke_once(n: int, timeout: int) -> dict:
    port = _free_port()
    coord = f"127.0.0.1:{port}"
    procs = []
    try:
        workers = [_spawn(["--worker", str(i), "--n", str(n),
                           "--coordinator", coord], _LOCAL_DEVICES)
                   for i in range(n)]
        procs.extend(workers)
        single = _spawn(["--single"], n * _LOCAL_DEVICES)
        procs.append(single)

        results, thr_results, worker_evals = [], [], []
        for w in workers:
            out, err = w.communicate(timeout=timeout)
            if w.returncode != 0:
                if any(m in err for m in _BIND_MARKERS):
                    raise _PortBindRace(err[-400:])
                raise RuntimeError(
                    f"worker failed rc={w.returncode}: {err[-800:]}")
            results.append(_parse_losses(out))
            thr_results.append(json.loads(_parse_tag(out, "THRLOSSES")
                                          or "null"))
            worker_evals.append(_parse_eval(out))
        sout, serr = single.communicate(timeout=timeout)
        if single.returncode != 0:
            raise RuntimeError(f"single-proc run failed: {serr[-800:]}")
        ref = _parse_losses(sout)
        thr_ref = json.loads(_parse_tag(sout, "THRLOSSES") or "null")
        ref_eval = _parse_eval(sout)
    finally:
        # a dead worker leaves its peer blocked at the coordinator
        # barrier forever — never leak the siblings
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()

    if any(r is None for r in results) or ref is None:
        raise RuntimeError("missing LOSSES output")

    def check_match(worker_traj, ref_traj, what):
        for i, r in enumerate(worker_traj):
            if r is None or ref_traj is None or len(r) != len(ref_traj):
                raise RuntimeError(
                    f"worker {i} {what} trajectory length mismatch: "
                    f"{r} vs {ref_traj}")
            for a, b in zip(r, ref_traj):
                if abs(a - b) > 1e-4 * max(1.0, abs(b)):
                    raise RuntimeError(
                        f"worker {i} {what} loss diverged from single-"
                        f"process run: {r} vs {ref_traj}")

    check_match(results, ref, "dense")
    # the compressed exchange must be process-count invariant too
    check_match(thr_results, thr_ref, "threshold")
    # merge the per-process evaluators (the documented multi-process
    # evaluation recipe) and compare with the single-process full-data
    # evaluation — confusion matrices must be identical
    import numpy as np

    from deeplearning4j_tpu.eval import Evaluation

    if any(e is None for e in worker_evals) or ref_eval is None:
        raise RuntimeError("missing EVALJSON output")
    merged = Evaluation()
    for e in worker_evals:
        merged.merge(Evaluation.from_json(e))
    ref_ev = Evaluation.from_json(ref_eval)
    # the loss check above tolerates ~1e-4 cross-run drift (collective
    # reduction order), so an argmax near-tie may flip ONE sample's
    # predicted class between runs — require identical totals and allow
    # at most one flipped count in the confusion matrices
    diff = int(np.abs(merged.confusion.matrix
                      - ref_ev.confusion.matrix).sum())
    eval_match = merged.total == ref_ev.total and diff <= 2
    if not eval_match:
        raise RuntimeError(
            f"merged distributed evaluation != single-process "
            f"(L1 diff {diff}): {merged.confusion.matrix.tolist()} vs "
            f"{ref_ev.confusion.matrix.tolist()}")
    return {"n_processes": n, "losses": results[0], "single_process": ref,
            "threshold_losses": thr_results[0], "match": True,
            "threshold_match": True, "eval_merge_match": True}


def main(argv=None):
    argv = sys.argv[1:] if argv is None else argv
    args = {argv[i]: argv[i + 1] if i + 1 < len(argv) else None
            for i in range(len(argv)) if argv[i].startswith("--")}
    if "--worker" in args:
        _worker_main(args["--coordinator"], int(args["--n"]),
                     int(args["--worker"]))
    elif "--single" in args:
        _single_main()
    else:
        report = run_smoke(int(args.get("--n", 2) or 2))
        print(json.dumps(report))


if __name__ == "__main__":
    main()
