"""SPMD parallelism — ONE engine replacing the reference's three
(SURVEY.md §2.13):

- `ParallelWrapper` (thread-per-device replicas + param averaging or
  encoded gradient sharing) → data-sharded jitted train step; XLA
  inserts the gradient all-reduce over ICI.
- Spark `ParameterAveragingTrainingMaster` (sync rounds, tree
  aggregation) → local-SGD mode: k per-replica steps under `shard_map`,
  then parameter `pmean` (the `averaging_frequency` knob survives).
- `SharedTrainingMaster` + Aeron parameter server (async threshold-
  compressed updates over UDP) → on ICI the synchronous `psum` at
  ~TB/s replaces compressed gossip outright; for DCN-spanning /
  bandwidth-bound topologies the reference's threshold encoding
  survives as `gradient_sharing="threshold"` — error-feedback int8
  compressed collectives with adaptive τ (gradient_sharing.py,
  docs/COMMS.md), selectable on both sync trainers.

Mesh axes are named ("data", "model", "seq", "pipe") so tensor/sequence/
pipeline parallelism are sharding specs, not new engines.
"""

from deeplearning4j_tpu.parallel.mesh import MeshSpec, make_mesh, device_mesh
from deeplearning4j_tpu.parallel.gradient_sharing import ThresholdConfig
from deeplearning4j_tpu.parallel.trainer import ParallelTrainer
from deeplearning4j_tpu.parallel.inference import ParallelInference
from deeplearning4j_tpu.parallel.ulysses import (
    ulysses_attention,
    ulysses_parallel_attention,
)
from deeplearning4j_tpu.parallel.ring import (
    reference_attention,
    ring_attention,
    sequence_parallel_attention,
)
from deeplearning4j_tpu.parallel.tensor import (
    ShardedParallelTrainer,
    fsdp_param_specs,
    moe_param_specs,
    tp_param_specs,
)
from deeplearning4j_tpu.parallel.pipeline import pipeline_apply, pipeline_forward
from deeplearning4j_tpu.parallel.pipeline_container import (
    PipelineParallelTrainer,
    find_homogeneous_run,
)
from deeplearning4j_tpu.parallel.master import (
    ParameterAveragingTrainingMaster,
    SharedTrainingMaster,
    TrainingMaster,
)
from deeplearning4j_tpu.parallel.context import (
    current_sequence_mesh,
    sequence_sharding,
)
from deeplearning4j_tpu.parallel.stats import TrainingMasterStats
from deeplearning4j_tpu.parallel.multihost import (
    initialize_multihost,
    is_main_process,
    multihost_active,
    process_count,
    process_index,
    shutdown_multihost,
)
from deeplearning4j_tpu.parallel.elastic import (
    ElasticConfig,
    ElasticCoordinator,
    ElasticClient,
    ElasticTrainer,
    elastic_fit,
)
