"""ParallelInference — high-throughput batched serving.

Reference: `ParallelInference.java:32` (worker pool; `ObservablesProvider`
dynamic batching :84): many small `output()` requests are coalesced into
device-sized batches.

TPU-native version: ONE jitted forward sharded over the mesh replaces
the worker pool (replica threads are a GPU idiom); dynamic batching
survives as request coalescing with pad-to-bucket so XLA sees a few
static shapes instead of one compile per request size.
"""

from __future__ import annotations

import queue
import threading
import time
from concurrent.futures import Future
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.parallel.mesh import device_mesh


class ParallelInference:
    def __init__(self, model, mesh: Optional[Mesh] = None, *,
                 batch_limit: int = 64, queue_limit_ms: float = 5.0,
                 data_axis: str = "data"):
        self.model = model
        self.mesh = mesh if mesh is not None else device_mesh()
        self.batch_limit = batch_limit
        self.queue_limit_ms = queue_limit_ms
        self.data_axis = data_axis
        self._fwd = None
        self._lock = threading.Lock()
        self._buckets = [1, 2, 4, 8, 16, 32, 64, 128, 256]
        # background coalescing loop (ObservablesProvider role)
        self._queue: "queue.Queue" = queue.Queue()
        self._collector: Optional[threading.Thread] = None
        self._running = False
        # executed device-batch sizes — the observable proof that
        # concurrent callers were actually coalesced (bounded: a
        # long-lived server must not leak one int per batch forever)
        from collections import deque
        self.batch_size_history = deque(maxlen=1024)

    def _build(self):
        model = self.model
        mesh = self.mesh
        repl = NamedSharding(mesh, P())
        sharded = NamedSharding(mesh, P(self.data_axis))

        def fwd(params, state, x):
            h, _, _, _, _ = model._forward_core(params, state, x, train=False, rng=None)
            return h

        self._fwd = jax.jit(fwd, in_shardings=(repl, repl, sharded),
                            out_shardings=sharded)

    def _ensure_built(self):
        """Build the jitted forward + init the model exactly once, even
        under concurrent cold starts: two threads racing a cold
        `output()` would both trace/compile the forward (and could both
        run `model.init()`, one clobbering params the other is already
        using). Double-checked under `self._lock`; the publish of
        `self._fwd` is the release point."""
        if self._fwd is not None and self.model._initialized:
            return
        with self._lock:
            if not self.model._initialized:
                self.model.init()
            if self._fwd is None:
                self._build()

    def _resolve_metrics(self, cache_attr, build):
        """Shared resolve-and-cache for hot-loop metric families (this
        collector and the GenerationServer scheduler) — the ONE memo
        rule lives in `monitor.resolve_cached_metrics`."""
        from deeplearning4j_tpu import monitor
        return monitor.resolve_cached_metrics(self, cache_attr, build)

    def _metrics(self):
        """The coalescing signal plane (ROADMAP names these as the
        shedding inputs)."""
        return self._resolve_metrics("_metrics_by_registry", lambda reg: (
            reg.timer("inference_request_latency_seconds",
                      "enqueue-to-result latency per output_async "
                      "request"),
            reg.gauge("inference_queue_depth",
                      "requests waiting to join a coalesced batch"),
            reg.histogram("inference_batch_size",
                          "rows per executed device batch",
                          buckets=(1, 2, 4, 8, 16, 32, 64, 128,
                                   256, 512))))

    def _bucket(self, n: int) -> int:
        mesh_n = self.mesh.shape[self.data_axis]
        for b in self._buckets:
            if b >= n and b % mesh_n == 0:
                return b
        return ((n + mesh_n - 1) // mesh_n) * mesh_n

    def output(self, x):
        """Single-call inference; pads the batch to a bucket size that
        divides the mesh, trims the result."""
        self._ensure_built()
        model = self.model
        x = np.asarray(x)
        n = x.shape[0]
        b = self._bucket(n)
        if b != n:
            pad = np.zeros((b - n,) + x.shape[1:], x.dtype)
            x = np.concatenate([x, pad], axis=0)
        out = self._fwd(model.params, model.net_state, jnp.asarray(x))
        return np.asarray(out)[:n]

    # -------------------------------------------- background batching loop
    def start(self) -> "ParallelInference":
        """Start the collector thread: concurrent `output()` callers are
        coalesced into one device batch within `queue_limit_ms`
        (reference `ObservablesProvider` :84 — requests observable until
        the batch fires)."""
        if getattr(self, "_shutdown", False):
            raise RuntimeError("ParallelInference is shut down")
        if self._running:
            return self
        self._ensure_built()
        self._running = True
        self._collector = threading.Thread(target=self._collect_loop,
                                           daemon=True)
        self._collector.start()
        return self

    def stop(self):
        self._running = False
        if self._collector is not None:
            self._queue.put(None)  # wake the collector
            self._collector.join(timeout=5)
            self._collector = None
        self._fail_pending()

    def _fail_pending(self):
        """Drain requests that never made it into a batch: leaving
        their Futures unresolved would hang callers blocked in
        `.result()`."""
        while True:
            try:
                item = self._queue.get_nowait()
            except queue.Empty:
                break
            if item is not None and not item[1].done():
                item[1].set_exception(
                    RuntimeError("ParallelInference stopped before this "
                                 "request was executed"))

    def shutdown(self):
        """Terminal teardown: stop the collector thread, fail every
        pending Future, and refuse further `output_async` calls. Unlike
        `stop()` (which a later `start()` can undo), shutdown closes
        the enqueue side FIRST, so a request racing with teardown
        either gets the terminal error immediately or is drained and
        failed — nothing can hang at `.result()`."""
        self._shutdown = True
        self.stop()
        # a racing output_async may have enqueued between the drain and
        # the flag becoming visible — sweep once more
        self._fail_pending()

    def __enter__(self):
        return self.start()

    def __exit__(self, *a):
        self.stop()

    def output_async(self, x) -> Future:
        """Enqueue one request; the Future resolves with this request's
        rows once the coalesced batch it joined has executed."""
        if getattr(self, "_shutdown", False):
            raise RuntimeError("ParallelInference is shut down")
        if not self._running:
            raise RuntimeError("call start() before output_async()")
        fut: Future = Future()
        self._queue.put((np.asarray(x), fut, time.monotonic()))
        # enqueue/teardown race: shutdown() may have completed between
        # the flag check and the put — no collector will ever drain this
        # request, so fail it ourselves (the queue is the sync point; a
        # request the collector DID take resolves normally)
        if getattr(self, "_shutdown", False):
            self._fail_pending()
        return fut

    def _collect_loop(self):
        while self._running:
            m = self._metrics()
            if m is not None:
                m[1].set(self._queue.qsize())
            try:
                first = self._queue.get(timeout=0.1)
            except queue.Empty:
                continue
            if first is None:
                continue
            batch = [first]
            total = first[0].shape[0]
            deadline = time.monotonic() + self.queue_limit_ms / 1000.0
            while total < self.batch_limit:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                try:
                    nxt = self._queue.get(timeout=remaining)
                except queue.Empty:
                    break
                if nxt is None:
                    break
                batch.append(nxt)
                total += nxt[0].shape[0]
            self._execute(batch)

    def _execute(self, batch):
        futs = [item[1] for item in batch]
        try:
            n_rows = sum(item[0].shape[0] for item in batch)
            self.batch_size_history.append(n_rows)
            outs = self.output_batched([item[0] for item in batch])
            done_t = time.monotonic()
            # collector-thread metric emission: wall-clock math on
            # already-materialized host arrays — ZERO added device syncs
            # (the monitor overhead contract, docs/OBSERVABILITY.md)
            m = self._metrics()
            if m is not None:
                m[2].observe(n_rows)
            for item, o in zip(batch, outs):
                item[1].set_result(o)
                if m is not None and len(item) > 2:
                    m[0].observe(done_t - item[2])
        except Exception as e:  # propagate to every waiting caller
            for f in futs:
                if not f.done():
                    f.set_exception(e)

    def output_batched(self, requests: List[np.ndarray]):
        """Coalesce many requests into one device batch (ObservablesProvider
        semantics) and split the results back out."""
        sizes = [np.asarray(r).shape[0] for r in requests]
        merged = np.concatenate([np.asarray(r) for r in requests], axis=0)
        out = self.output(merged)
        result, off = [], 0
        for s in sizes:
            result.append(out[off:off + s])
            off += s
        return result
