"""ParallelInference — high-throughput batched serving.

Reference: `ParallelInference.java:32` (worker pool; `ObservablesProvider`
dynamic batching :84): many small `output()` requests are coalesced into
device-sized batches.

TPU-native version: ONE jitted forward sharded over the mesh replaces
the worker pool (replica threads are a GPU idiom); dynamic batching
survives as request coalescing with pad-to-bucket so XLA sees a few
static shapes instead of one compile per request size.
"""

from __future__ import annotations

import queue
import threading
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.parallel.mesh import device_mesh


class ParallelInference:
    def __init__(self, model, mesh: Optional[Mesh] = None, *,
                 batch_limit: int = 64, queue_limit_ms: float = 5.0,
                 data_axis: str = "data"):
        self.model = model
        self.mesh = mesh if mesh is not None else device_mesh()
        self.batch_limit = batch_limit
        self.queue_limit_ms = queue_limit_ms
        self.data_axis = data_axis
        self._fwd = None
        self._lock = threading.Lock()
        self._buckets = [1, 2, 4, 8, 16, 32, 64, 128, 256]

    def _build(self):
        model = self.model
        mesh = self.mesh
        repl = NamedSharding(mesh, P())
        sharded = NamedSharding(mesh, P(self.data_axis))

        def fwd(params, state, x):
            h, _, _, _, _ = model._forward_core(params, state, x, train=False, rng=None)
            return h

        self._fwd = jax.jit(fwd, in_shardings=(repl, repl, sharded),
                            out_shardings=sharded)

    def _bucket(self, n: int) -> int:
        mesh_n = self.mesh.shape[self.data_axis]
        for b in self._buckets:
            if b >= n and b % mesh_n == 0:
                return b
        return ((n + mesh_n - 1) // mesh_n) * mesh_n

    def output(self, x):
        """Single-call inference; pads the batch to a bucket size that
        divides the mesh, trims the result."""
        if self._fwd is None:
            self._build()
        model = self.model
        if not model._initialized:
            model.init()
        x = np.asarray(x)
        n = x.shape[0]
        b = self._bucket(n)
        if b != n:
            pad = np.zeros((b - n,) + x.shape[1:], x.dtype)
            x = np.concatenate([x, pad], axis=0)
        out = self._fwd(model.params, model.net_state, jnp.asarray(x))
        return np.asarray(out)[:n]

    def output_batched(self, requests: List[np.ndarray]):
        """Coalesce many requests into one device batch (ObservablesProvider
        semantics) and split the results back out."""
        sizes = [np.asarray(r).shape[0] for r in requests]
        merged = np.concatenate([np.asarray(r) for r in requests], axis=0)
        out = self.output(merged)
        result, off = [], 0
        for s in sizes:
            result.append(out[off:off + s])
            off += s
        return result
