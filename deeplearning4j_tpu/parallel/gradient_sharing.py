"""Threshold-encoded gradient sharing: error-feedback compressed collectives.

Reference equivalence: the signature distributed-training feature of
`SharedTrainingMaster` — `Nd4j.getExecutioner().thresholdEncode`
(sign-magnitude quantization at threshold τ), residual accumulation
(`EncodedGradientsAccumulator` keeps what was not sent and re-adds it
next step), and `AdaptiveThresholdAlgorithm` (τ chases a target
sparsity band). Communication characterization (arXiv:1810.11112)
shows dense gradient exchange dominating scaled-out step time; the
TensorFlow system paper (arXiv:1605.08695) argues the exchange
schedule should be a first-class, tunable part of the program. Here it
is: a jittable encode/decode the trainers select with
``gradient_sharing="dense"|"threshold"`` (env A/B override
``DL4J_GRADIENT_SHARING``, mirroring ``DL4J_SCAN_LAYERS``).

XLA-friendly wire format: instead of the reference's sparse
index/value chunks (data-dependent shapes XLA cannot compile), the
encoded update is a **dense int8 tensor of {-1, 0, +1}** — the
all-reduce payload drops from 4 bytes/element (fp32) to 1 byte/element
(int8), a fixed 4x wire reduction, while the threshold controls
*fidelity* (what fraction of the accumulated update magnitude gets
through this step) rather than wire size. Summing N int8 sign tensors
is exact for N ≤ 127 replicas; larger data axes automatically widen to
int16 (2x reduction).

Numeric contract (error feedback / EF-SGD):

    u_r        = updater_r(grad_r)               (per-replica updater —
                                                  each reference worker
                                                  runs its own)
    acc_r      = u_r + residual_r                (per replica)
    enc_r      = sign(acc_r) * (|acc_r| >= τ)    (int8 on the wire)
    residual_r = acc_r - τ * enc_r               (nothing is lost)
    û          = τ * Σ_r enc_r / N               (the shared update every
                                                  replica applies)

What gets encoded is the post-updater UPDATE, exactly as in the
reference (`EncodingHandler` encodes the updater's output): τ then
lives on the learning-rate scale, and every update magnitude the
threshold suppresses stays in the replica-local residual and re-enters
the accumulator next step, so the *sum* of applied updates tracks the
sum of true updates — the property the convergence-parity tests in
tests/test_gradient_sharing.py enforce against dense training.

τ adaptation (reference `AdaptiveThresholdAlgorithm` semantics):
``sparsity`` here is the encoded fraction — the share of elements that
made it onto the wire this step, pmean'd over replicas. Above the
target band, τ is boosted (send less); below it, τ decays (send
more); always clamped to [min_threshold, max_threshold]. τ and the
residual ride the fused multi-step scan carry next to the updater
state, and pack/unpack across the ``stacked::`` run boundary exactly
like updater state does (nn/scan_stack.py).

Bucketed (overlapped) exchange — the default for sync trainers:
instead of one post-backward barrier, every ``stacked::`` packed run
and every unpacked layer is a **bucket** whose exchange is emitted by
a `jax.custom_vjp` hook the moment backward finishes that bucket's
VJP: the cotangent of bucket i's params is data-independent of the
backward compute of buckets i+1.. (layers earlier in forward order),
so XLA's scheduler can run collective i concurrently with the
remaining backward — the comm/compute overlap the CUDA-aware-MPI
characterization (arXiv:1810.11112) identifies as the scaling
headroom beyond compression. In threshold mode the per-bucket
residual and τ thread THROUGH the VJP via the hook's cotangent
channel (the bwd rule returns the advanced residual/τ/updater state
as the "gradients" of those inputs), preserving the error-feedback
identity enc·τ + res_new = update + res_old **per bucket**. Opt out
with ``DL4J_BUCKETED_EXCHANGE=0`` (or ``bucketed=False`` on the
trainers) for the PR-4 single-barrier program.

ZeRO-style sharded-updater modes ``dense_rs`` / ``threshold_rs``:
on the same bucket structure, gradients are **reduce-scattered** over
the data axis instead of all-reduced, each replica runs the updater
only on its gradient shard (updater state sharded over the data axis
— 1/N optimizer memory, the ZeRO partitioning), updates its param
shard, and the updated params are **all-gathered**. Which leaves
shard follows the same rule as `parallel.tensor.fsdp_param_specs`
(last axis, divisibility-gated, small leaves replicated) so the wire
layout composes with FSDP sharding annotations. ``dense_rs`` is
bit-identical to bucketed ``dense`` (reduce-scatter + all-gather is
the same sum, elementwise updater math is shard-oblivious);
``threshold_rs`` threshold-encodes the RAW gradient (+ residual)
before the integer reduce-scatter — the updater runs post-decode on
the shard, so τ lives on the gradient scale there, unlike
``threshold`` where it lives on the update scale.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nn import scan_stack

MODES = ("dense", "threshold", "dense_rs", "threshold_rs")
RS_MODES = ("dense_rs", "threshold_rs")

# env values that force each mode (mirrors DL4J_SCAN_LAYERS's spelling
# tolerance: 0/off/false disable the feature, i.e. force dense)
_ENV_VAR = "DL4J_GRADIENT_SHARING"
_ENV_DENSE = ("dense", "0", "off", "false", "no")
_ENV_THRESHOLD = ("threshold", "1", "on", "true", "yes")

# bucketed (per-layer-run, overlapped) exchange toggle: default ON;
# DL4J_BUCKETED_EXCHANGE=0 restores the PR-4 single-barrier program
_BUCKET_ENV_VAR = "DL4J_BUCKETED_EXCHANGE"


def resolve_bucketed(explicit: Optional[bool] = None) -> bool:
    """Bucketed-exchange resolution: the ``DL4J_BUCKETED_EXCHANGE``
    env override wins (A/B the overlap without touching code), then an
    explicit trainer argument, then the default True. Unknown env
    spellings raise (mirroring ``DL4J_GRADIENT_SHARING``) — a typo'd
    opt-out must not silently keep the bucketed program running."""
    env = os.environ.get(_BUCKET_ENV_VAR)
    if env is not None and env.strip():
        v = env.strip().lower()
        if v in ("0", "off", "false", "no"):
            return False
        if v in ("1", "on", "true", "yes"):
            return True
        raise ValueError(
            f"{_BUCKET_ENV_VAR}={env!r}: expected one of "
            f"('0', 'off', 'false', 'no', '1', 'on', 'true', 'yes')")
    if explicit is not None:
        return bool(explicit)
    return True


@dataclasses.dataclass(frozen=True)
class ThresholdConfig:
    """Knobs of the threshold encoder + adaptive-τ controller.

    Defaults follow the reference's AdaptiveThresholdAlgorithm shape:
    start at `initial_threshold`, keep the encoded fraction inside
    [sparsity_target_min, sparsity_target_max], step τ geometrically
    when outside the band."""

    initial_threshold: float = 1e-3
    sparsity_target_min: float = 1e-3   # sending less than this: τ decays
    sparsity_target_max: float = 1e-1   # sending more than this: τ boosts
    decay: float = 1.0 / 1.2            # τ multiplier below the band
    boost: float = 1.2                  # τ multiplier above the band
    min_threshold: float = 1e-8
    max_threshold: float = 1.0

    def __post_init__(self):
        if not (0.0 < self.sparsity_target_min
                <= self.sparsity_target_max <= 1.0):
            raise ValueError(
                f"sparsity target band must satisfy 0 < min <= max <= 1, "
                f"got [{self.sparsity_target_min}, "
                f"{self.sparsity_target_max}]")
        if not (0.0 < self.decay < 1.0 < self.boost):
            raise ValueError(
                f"need decay < 1 < boost, got decay={self.decay} "
                f"boost={self.boost}")
        if not (0.0 < self.min_threshold <= self.initial_threshold
                <= self.max_threshold):
            raise ValueError(
                f"need min_threshold <= initial_threshold <= "
                f"max_threshold, got {self.min_threshold} / "
                f"{self.initial_threshold} / {self.max_threshold}")

    @staticmethod
    def from_conf(conf) -> "ThresholdConfig":
        """Config-carried initial τ (`gradient_sharing_threshold`),
        controller defaults for the rest."""
        tau0 = getattr(conf, "gradient_sharing_threshold", None)
        if tau0 is None:
            return ThresholdConfig()
        return ThresholdConfig(initial_threshold=float(tau0))


def env_mode() -> Optional[str]:
    """The ``DL4J_GRADIENT_SHARING`` override if set (validated), else
    None. Exposed so trainers can tell an env-forced mode (a global A/B
    toggle that must degrade gracefully where it does not apply) from
    an explicit arg/conf choice (a hard error when invalid)."""
    env = os.environ.get(_ENV_VAR)
    if env is None or not env.strip():
        return None
    v = env.strip().lower()
    if v in _ENV_DENSE:
        return "dense"
    if v in _ENV_THRESHOLD:
        return "threshold"
    if v in RS_MODES:
        return v
    raise ValueError(
        f"{_ENV_VAR}={env!r}: expected one of "
        f"{_ENV_DENSE + _ENV_THRESHOLD + RS_MODES}")


def resolve_mode(explicit: Optional[str] = None, conf=None) -> str:
    """Gradient-sharing mode resolution: the ``DL4J_GRADIENT_SHARING``
    env override wins (benchmark A/B without touching code), then an
    explicit trainer argument, then the model configuration's
    ``gradient_sharing`` field, then "dense"."""
    forced = env_mode()
    if forced is not None:
        return forced
    for v in (explicit, getattr(conf, "gradient_sharing", None)):
        if v is not None:
            if v not in MODES:
                raise ValueError(
                    f"gradient_sharing must be one of {MODES}, got {v!r}")
            return v
    return "dense"


def wire_dtype(n_workers: int):
    """Narrowest integer type whose sum of n_workers sign values is
    exact. int8 up to 127 replicas (4x vs fp32), int16 beyond."""
    if n_workers <= 127:
        return jnp.int8
    if n_workers <= 32767:
        return jnp.int16
    raise ValueError(
        f"threshold gradient sharing supports data axes up to 32767 "
        f"replicas, got {n_workers}")


# ------------------------------------------------------------ encode/decode
def encode_leaf(acc, tau, wdtype):
    """One leaf of the threshold encoder: (wire tensor, residual,
    elements sent). `acc` is gradient + carried residual."""
    mask = jnp.abs(acc) >= tau.astype(acc.dtype)
    enc = jnp.where(mask, jnp.sign(acc), 0.0).astype(wdtype)
    residual = acc - enc.astype(acc.dtype) * tau.astype(acc.dtype)
    return enc, residual, jnp.sum(mask, dtype=jnp.float32)


def adapt_threshold(tau, sparsity, cfg: ThresholdConfig):
    """One controller step: boost τ above the target band (sending too
    much), decay it below (sending too little), clamp always."""
    tau = jnp.where(sparsity > cfg.sparsity_target_max, tau * cfg.boost,
                    jnp.where(sparsity < cfg.sparsity_target_min,
                              tau * cfg.decay, tau))
    return jnp.clip(tau, cfg.min_threshold, cfg.max_threshold)


def tree_elements(tree) -> float:
    """Static element count of a pytree (host math, trace-safe)."""
    return float(sum(int(np.prod(np.shape(l)))
                     for l in jax.tree_util.tree_leaves(tree)))


def threshold_exchange(grads, residual, tau, axis: str,
                       cfg: ThresholdConfig, *, n_workers: int):
    """The complete compressed collective: encode (with error
    feedback), all-reduce the integer wire tensors over `axis`, decode
    to the shared update, adapt τ from the globally-averaged encoded
    fraction.

    Returns (ĝ, new_residual, new_tau, sparsity). ĝ replaces
    pmean(grads) in the sync step; `sparsity` is the achieved encoded
    fraction (the compression-fidelity observable the reference's
    EncodingHandler logs)."""
    wdtype = wire_dtype(n_workers)
    flat_g, treedef = jax.tree_util.tree_flatten(grads)
    flat_r = treedef.flatten_up_to(residual)
    enc, new_res, sent = [], [], 0.0
    for g, r in zip(flat_g, flat_r):
        e, nr, s = encode_leaf(g + r.astype(g.dtype), tau, wdtype)
        enc.append(e)
        new_res.append(nr)
        sent = sent + s
    summed = [jax.lax.psum(e, axis) for e in enc]
    inv_n = 1.0 / float(n_workers)
    ghat = [s.astype(g.dtype) * (tau.astype(g.dtype) * g.dtype.type(inv_n))
            for s, g in zip(summed, flat_g)]
    total = tree_elements(grads)
    sparsity = jax.lax.pmean(sent, axis) / total
    new_tau = adapt_threshold(tau, sparsity, cfg)
    unflatten = treedef.unflatten
    return unflatten(ghat), unflatten(new_res), new_tau, sparsity


def dense_exchange(grads, axis: str):
    """The uncompressed baseline as an *explicit* collective —
    numerically what GSPMD inserts for the jit dense path (mean of
    per-replica gradients), made manual so its wire payload is
    measurable by the same jaxpr accounting as the threshold path."""
    return jax.tree_util.tree_map(
        lambda g: jax.lax.pmean(g, axis), grads)


def zeros_residual(params):
    """Fresh per-layer residual tree matching `params` (the same shape
    contract updater state follows — per-layer keys at the boundary,
    packed to ``stacked::`` entries only inside the program). Reads
    shapes/dtypes only, so global (non-fetchable) param leaves are
    fine."""
    return jax.tree_util.tree_map(
        lambda a: np.zeros(np.shape(a),
                           getattr(a, "dtype", None) or np.asarray(a).dtype),
        params)


# --------------------------------------------------------------- step bodies
def _layer_for_key(model, is_graph: bool, lk: str):
    """The layer owning a grads/params entry — ``stacked::`` run entries
    resolve to their first member (the run template), mirroring the
    containers' `_apply_updates`."""
    if scan_stack.is_run_key(lk):
        lk = scan_stack.run_members(lk)[0]
    return (model.conf.nodes[lk].layer if is_graph
            else model.layers[int(lk)])


def compute_updater_deltas(model, is_graph: bool, params, grads,
                           upd_state, step):
    """Run every layer's OWN updater on its local gradients, returning
    the update tree (what the reference threshold-encodes —
    `SharedTrainingMaster` workers encode post-updater UPDATES, not raw
    gradients, which is what lets a fixed τ ≈ learning-rate scale work)
    plus the advanced per-replica updater state. Mirrors the layer/run
    dispatch of the containers' `_apply_updates` without applying."""
    from deeplearning4j_tpu.common.updaters import Sgd

    deltas, new_upd = {}, {}
    for lk, lgrads in grads.items():
        layer = _layer_for_key(model, is_graph, lk)
        updater = layer.updater or Sgd(1e-3)
        ld, lu = {}, {}
        for pk, g in lgrads.items():
            # mixed policy: grads arrive in compute dtype (bf16) —
            # upcast BEFORE the updater so the deltas the threshold
            # encoder consumes (and the EF identity) live in fp32
            g = g.astype(params[lk][pk].dtype)
            delta, new_s = updater.apply(g, upd_state[lk][pk], step)
            ld[pk] = delta.astype(params[lk][pk].dtype)
            lu[pk] = new_s
        deltas[lk] = ld
        new_upd[lk] = lu
    return deltas, new_upd


def apply_decoded_updates(model, is_graph: bool, params, dhat):
    """params minus the decoded shared update, then the shared
    post-update constraint pipeline (`_apply_constraints_tree` — one
    copy for the threshold and bucketed dense/rs paths)."""
    new_params = {lk: {pk: params[lk][pk] - d for pk, d in ld.items()}
                  for lk, ld in dhat.items()}
    return _apply_constraints_tree(model, is_graph, new_params)


def _pmean_state(state, axis):
    """Keep layer state replicated across the data axis: float leaves
    (batchnorm running stats — per-shard batch statistics) are
    averaged, everything else (identical per-replica counters) passes
    through."""
    def avg(a):
        if jnp.issubdtype(jnp.result_type(a), jnp.floating):
            return jax.lax.pmean(a, axis)
        return a
    return jax.tree_util.tree_map(avg, state)


def _local_loss_fn(model, is_graph: bool):
    if is_graph:
        def lf(params, state, x, y, rng):
            return model._loss_fn(params, state, (x,), (y,), rng,
                                  (None,), (None,), train=True)
    else:
        def lf(params, state, x, y, rng):
            return model._loss_fn(params, state, x, y, rng, None, None,
                                  train=True)
    return lf


def _exchange_diag(model, diag, axis, *, params_old, upd_old, res_old,
                   tau_old, state_old, params_new, upd_new, res_new,
                   tau_new, state_new, loss):
    """Shared diagnostics tail of every exchange-step body: collect the
    POST-exCHANGE update/param stats (the decoded, applied updates —
    for the bucketed modes these are exactly what left the VJP-hook
    channel), fold the error-feedback residual into the finite flags
    (a non-finite gradient saturates the int encode but poisons the
    residual, so the flags must see it), and under the ``skip``
    watchdog discard the WHOLE step in-graph — params, updater state,
    residual, τ and layer state all keep their previous values, keeping
    the EF identity consistent. Flags are psum'd over the data axis so
    every replica gates identically.

    Returns (params, upd, residual, tau, state, dv)."""
    if diag is None:
        return params_new, upd_new, res_new, tau_new, state_new, {}
    from deeplearning4j_tpu.monitor.diagnostics import keep_finite
    dv, ok = diag.collect(
        "exchange", params_new=params_new, params_old=params_old,
        loss=loss, extra_finite=res_new if res_new else None,
        axis_name=axis)
    if diag.config.watchdog == "skip":
        params_new = keep_finite(ok, params_new, params_old)
        upd_new = keep_finite(ok, upd_new, upd_old)
        if res_new:
            res_new = keep_finite(ok, res_new, res_old)
        if isinstance(tau_new, dict):
            tau_new = jax.tree_util.tree_map(
                lambda n, o: jnp.where(ok, n, o), tau_new, tau_old)
        elif tau_new is not None and tau_old is not None:
            tau_new = jnp.where(ok, tau_new, tau_old)
        state_new = {k: (keep_finite(ok, v, state_old[k])
                         if k in state_old else v)
                     for k, v in state_new.items()}
    return params_new, upd_new, res_new, tau_new, state_new, dv


def make_threshold_core(model, axis: str, cfg: ThresholdConfig, *,
                        n_workers: int, is_graph: bool = False,
                        diag=None):
    """Per-replica threshold sync-step body on ALREADY-PACKED trees
    (params/updater-state/residual may contain ``stacked::`` run
    entries — the encoder is elementwise, so a stacked leading axis
    changes nothing; the layer/run dispatch goes through
    `scan_stack.is_run_key` exactly like `_apply_updates`).

    Reference pipeline order (`SharedTrainingMaster` workers): local
    gradients → local gradient normalization → local UPDATER (per-
    replica state, like each worker's own updater) → threshold-encode
    the update with error feedback → integer all-reduce → every replica
    applies the same decoded mean update to its (replicated) params.
    Encoding updates rather than raw gradients is what makes a fixed
    τ ≈ learning-rate scale meaningful and keeps error feedback honest
    under adaptive updaters (Adam's normalization would otherwise wash
    out the residual's accumulated magnitude).

    Loss is the local-shard mean; the returned loss/state are pmean'd
    so every replica exits replicated."""
    from deeplearning4j_tpu.optimize.gradients import (
        apply_gradient_normalization,
    )

    gn = model.conf.gradient_normalization
    gn_t = model.conf.gradient_normalization_threshold
    local_loss = _local_loss_fn(model, is_graph)

    def core(params, upd, state, it, residual, tau, x, y, rng):
        rng = jax.random.fold_in(rng, jax.lax.axis_index(axis))
        # cast outside value_and_grad: bf16 grads under a mixed policy
        # (compute_updater_deltas upcasts before the EF encode)
        (loss, (new_state, _)), grads = jax.value_and_grad(
            lambda p: local_loss(p, state, x, y, rng),
            has_aux=True)(model.dtype.cast_params(params))
        grads = apply_gradient_normalization(grads, gn, gn_t)
        deltas, new_upd = compute_updater_deltas(
            model, is_graph, params, grads, upd, it)
        dhat, new_residual, new_tau, sparsity = threshold_exchange(
            deltas, residual, tau, axis, cfg, n_workers=n_workers)
        new_params = apply_decoded_updates(model, is_graph, params, dhat)
        pstate = _pmean_state(new_state, axis)
        ploss = jax.lax.pmean(loss, axis)
        (new_params, new_upd, new_residual, new_tau, pstate, dv) = \
            _exchange_diag(
                model, diag, axis, params_old=params, upd_old=upd,
                res_old=residual, tau_old=tau, state_old=state,
                params_new=new_params, upd_new=new_upd,
                res_new=new_residual, tau_new=new_tau, state_new=pstate,
                loss=ploss)
        return (new_params, new_upd, pstate,
                new_residual, new_tau, ploss, sparsity, dv)

    return core


def make_threshold_step(model, axis: str, cfg: ThresholdConfig, *,
                        n_workers: int, is_graph: bool = False,
                        allow_scan: bool = True, diag=None):
    """One threshold sync step on per-layer (boundary) trees: packs
    ``stacked::`` runs for params, updater state AND residual at entry,
    unpacks at exit — the residual follows updater state through the
    pack boundary exactly (nn/scan_stack.py contract).

    ``allow_scan=False`` traces the whole body with the unrolled layer
    path (`scan_stack.force_unrolled`) — required when the caller wraps
    this in a partially-manual shard_map (DP x TP), where jaxlib
    0.4.x's SPMD partitioner crashes on inner scan bodies."""
    core = make_threshold_core(model, axis, cfg, n_workers=n_workers,
                               is_graph=is_graph, diag=diag)

    def step(params, upd, state, it, residual, tau, x, y, rng):
        with scan_stack.force_unrolled(not allow_scan):
            runs = (model._packed_runs(params)
                    if scan_stack.scan_enabled(model.conf) else [])
            if runs:
                params = scan_stack.pack_tree(params, runs)
                upd = scan_stack.pack_tree(upd, runs)
                residual = scan_stack.pack_tree(residual, runs)
            params, upd, state, residual, tau, loss, sparsity, dv = core(
                params, upd, state, it, residual, tau, x, y, rng)
            if runs:
                params = scan_stack.unpack_tree(params, runs)
                upd = scan_stack.unpack_tree(upd, runs)
                residual = scan_stack.unpack_tree(residual, runs)
        return params, upd, state, residual, tau, loss, sparsity, dv

    return step


def make_threshold_multi(model, axis: str, cfg: ThresholdConfig, *,
                         n_workers: int, is_graph: bool = False,
                         allow_scan: bool = True, diag=None):
    """k fused threshold sync steps: ONE `lax.scan` whose carry is
    (params, updater state, layer state, iteration, residual, τ) — the
    residual and τ ride the carry next to the updater state, and the
    ``stacked::`` run packing happens once per PROGRAM, not per step.
    Per-step diag vectors ride the scan ys (one batched transfer per
    listener cadence).

    Scan-carry structure rule (same as the containers'
    `_multi_step_fn`): only state keys present at entry survive across
    fused steps."""
    core = make_threshold_core(model, axis, cfg, n_workers=n_workers,
                               is_graph=is_graph, diag=diag)

    def multi(params, upd, state, it0, residual, tau, xs, ys, rngs):
        with scan_stack.force_unrolled(not allow_scan):
            runs = (model._packed_runs(params)
                    if scan_stack.scan_enabled(model.conf) else [])
            if runs:
                params = scan_stack.pack_tree(params, runs)
                upd = scan_stack.pack_tree(upd, runs)
                residual = scan_stack.pack_tree(residual, runs)

            def body(carry, inp):
                params, upd, state, it, residual, tau = carry
                x, y, rng = inp
                (params, upd, new_state, residual, tau, loss, sparsity,
                 dv) = core(
                    params, upd, state, it, residual, tau, x, y, rng)
                state = {k: new_state.get(k, v) for k, v in state.items()}
                return ((params, upd, state, it + 1, residual, tau),
                        (loss, sparsity, dv))

            carry = (params, upd, state, jnp.asarray(it0, jnp.int32),
                     residual, jnp.asarray(tau, jnp.float32))
            ((params, upd, state, _, residual, tau),
             (losses, sparsities, dvs)) = \
                jax.lax.scan(body, carry, (xs, ys, rngs))
            if runs:
                params = scan_stack.unpack_tree(params, runs)
                upd = scan_stack.unpack_tree(upd, runs)
                residual = scan_stack.unpack_tree(residual, runs)
        return params, upd, state, residual, tau, losses, sparsities, dvs

    return multi


# ----------------------------------------- partial-manual scan support probe
# jaxlib's 0.4.x SPMD partitioner hard-crashes (C++ CHECK failure —
# `Check failed: sharding.IsManualSubgroup()` — NOT a catchable Python
# exception) on an inner `lax.scan` under a partially-manual shard_map
# (`auto=` axes, the DP x TP threshold exchange). Newer jaxlibs
# partition it fine, and unconditionally unrolling there throws away
# the scan-over-layers compiled-size win. This probe decides at trace
# time: known-crashy versions are version-gated WITHOUT ever compiling
# (a compile attempt would abort the process, so try/except cannot
# probe them), newer ones are proven by actually compiling a tiny
# scan-under-partial-manual program once per process.
_PARTIAL_MANUAL_SCAN_MIN_JAXLIB = (0, 5, 0)
_partial_manual_scan_cache: Optional[bool] = None


def _jaxlib_version() -> tuple:
    try:
        import jaxlib
        return tuple(int(p) for p in jaxlib.__version__.split(".")[:3])
    except Exception:  # noqa: BLE001 — unparseable version: assume old
        return (0, 0, 0)


def _probe_partial_manual_scan() -> bool:
    """Compile a minimal inner-scan-under-partial-manual program. Only
    called on jaxlibs past the version gate, where partitioner failures
    surface as Python exceptions. The AUTO (model) axis gets size 2
    whenever a second device exists — a 1-partition auto axis would
    skip the partial-manual subgroup path entirely and prove nothing;
    on a genuinely single-device host the probe stays weak and the
    version gate is the real decision."""
    from functools import partial

    from jax.sharding import Mesh, PartitionSpec as P

    from deeplearning4j_tpu.parallel.compat import shard_map

    devs = jax.devices()
    n_auto = 2 if len(devs) >= 2 else 1
    mesh = Mesh(np.array(devs[:n_auto]).reshape(1, n_auto),
                ("data", "model"))

    @partial(shard_map, mesh=mesh, in_specs=(P("data"),), out_specs=P("data"),
             auto=frozenset({"model"}), check_vma=False)
    def prog(x):
        def body(c, s):
            return c + s, None
        out, _ = jax.lax.scan(body, x[0], jnp.ones((3,) + x.shape[1:]))
        return out[None] + jax.lax.psum(x, "data")

    jax.jit(prog).lower(jnp.ones((1, 4))).compile()
    return True


def partial_manual_scan_supported() -> bool:
    """True when this jaxlib can partition an inner `lax.scan` under a
    partially-manual shard_map — the gate for keeping scan-over-layers
    compilation in the DP x TP step instead of `force_unrolled`.
    Cached per process; see docs/COMMS.md ("Scan under DP x TP")."""
    global _partial_manual_scan_cache
    if _partial_manual_scan_cache is None:
        if _jaxlib_version() < _PARTIAL_MANUAL_SCAN_MIN_JAXLIB:
            _partial_manual_scan_cache = False
        else:
            try:
                _partial_manual_scan_cache = _probe_partial_manual_scan()
            except Exception:  # noqa: BLE001 — any failure: stay unrolled
                _partial_manual_scan_cache = False
    return _partial_manual_scan_cache


# ------------------------------------------- bucketed (overlapped) exchange
# Bucket = one top-level key of the packed gradient tree: a
# ``stacked::`` run or a single unpacked layer. Each bucket's exchange
# is a `jax.custom_vjp` hook on that bucket's params: backward produces
# the bucket's cotangent the moment its VJP completes, the hook's bwd
# rule emits the collective right there, and XLA schedules it against
# the backward compute still pending for earlier layers. State the
# exchange advances (per-replica updater state, error-feedback
# residual, the [τ, sparsity] control vector) enters the hook as extra
# primal inputs and exits through their cotangents — the only data
# path out of a VJP rule — so the error-feedback identity holds per
# bucket with no post-backward barrier.

def _ctrl(tau):
    """[τ, sparsity] control vector for one bucket (sparsity slot is
    an output: the bwd rule fills it with the achieved encoded
    fraction)."""
    return jnp.stack([jnp.asarray(tau, jnp.float32), jnp.float32(0.0)])


def _elementwise_gn(g, gn, gn_t):
    """The gradient-normalization subset the rs modes support: modes
    that factorize per ELEMENT (so clipping a reduced shard equals
    clipping the reduced full tensor). Norm-based modes need the whole
    layer and are rejected at trainer build time."""
    gn = getattr(gn, "value", gn) or "none"
    if gn == "clip_elementwise_absolute_value":
        return jnp.clip(g, -gn_t, gn_t)
    return g


def rs_supported_gn(conf) -> bool:
    """True when this configuration's gradient normalization factorizes
    per element (the `_rs` modes normalize reduced gradient SHARDS)."""
    gn = getattr(conf, "gradient_normalization", None)
    gn = getattr(gn, "value", gn) or "none"
    return gn in ("none", "clip_elementwise_absolute_value")


def rs_shard_plan(params, n_workers: int, *, specs=None,
                  data_axis: str = "data",
                  min_shard_elems: int = 1024) -> dict:
    """{layer_key: {param_name: bool}} — which leaves the `_rs` modes
    reduce-scatter on their LAST axis. With `specs` (a PartitionSpec
    tree, e.g. `parallel.tensor.fsdp_param_specs` output) a leaf shards
    iff its spec's last entry names `data_axis` — the composition seam
    with FSDP annotations. Without, the same rule fsdp_param_specs
    applies is derived from shapes: last axis divisible by n_workers,
    at least `min_shard_elems` elements."""
    plan = {}
    for lk, lparams in params.items():
        lplan = {}
        for pn, arr in lparams.items():
            if specs is not None:
                spec = specs[lk][pn]
                dims = tuple(spec)
                lplan[pn] = bool(dims and dims[-1] == data_axis)
            else:
                shape = np.shape(arr)
                lplan[pn] = bool(
                    shape and shape[-1] % n_workers == 0
                    and int(np.prod(shape)) >= min_shard_elems)
        plan[lk] = lplan
    return plan


def _plan_for(rs_plan: dict, lk: str) -> dict:
    """Bucket-key lookup into a per-layer rs plan: a ``stacked::`` run
    resolves to its first member (structural identity guarantees every
    member shares the plan)."""
    if scan_stack.is_run_key(lk):
        lk = scan_stack.run_members(lk)[0]
    return rs_plan[lk]




def _threshold_bucket_hook(model, is_graph: bool, lk: str, axis: str,
                           cfg: ThresholdConfig, n_workers: int,
                           gn, gn_t):
    """Threshold exchange for ONE bucket, emitted inside the backward
    pass. Primal: identity on the bucket's params. VJP: local gradient
    → gradient normalization (every GN mode factorizes per layer key,
    so per-bucket == whole-tree) → per-replica updater → error-feedback
    threshold encode at this bucket's τ → integer all-reduce → decode.
    The advanced updater state / residual / [τ', sparsity] leave
    through the cotangents of the matching primal inputs."""
    from deeplearning4j_tpu.common.updaters import Sgd
    from deeplearning4j_tpu.optimize.gradients import (
        apply_gradient_normalization,
    )

    layer = _layer_for_key(model, is_graph, lk)
    updater = layer.updater or Sgd(1e-3)
    policy = model.dtype

    @jax.custom_vjp
    def hook(p, u, r, c, it_f):
        # primal casts to compute dtype INSIDE the hook: forward runs
        # bf16 under a mixed policy while the saved p stays the fp32
        # master, and the incoming cotangent (the gradient) is bf16
        return policy.cast_params(p)

    def fwd(p, u, r, c, it_f):
        return policy.cast_params(p), (p, u, r, c, it_f)

    def bwd(saved, g):
        p, u, r, c, it_f = saved
        g = apply_gradient_normalization({lk: g}, gn, gn_t)[lk]
        deltas, new_u = {}, {}
        for pk, gg in g.items():
            # bf16 grad → fp32 BEFORE the updater/EF encode, so
            # enc·τ + res' = upd + res holds exactly in fp32
            d, s = updater.apply(gg.astype(p[pk].dtype), u[pk], it_f)
            deltas[pk] = d.astype(p[pk].dtype)
            new_u[pk] = s
        dhat, new_r, new_tau, sp = threshold_exchange(
            deltas, r, c[0], axis, cfg, n_workers=n_workers)
        new_r = jax.tree_util.tree_map(
            lambda nr, rr: nr.astype(rr.dtype), new_r, r)
        return (dhat, new_u, new_r, jnp.stack([new_tau, sp]),
                jnp.zeros_like(it_f))

    hook.defvjp(fwd, bwd)
    return hook


def _dense_bucket_hook(model, is_graph: bool, lk: str, axis: str,
                       n_workers: int, gn, gn_t, plan_b: dict, *,
                       full_gn: bool):
    """Dense / ZeRO exchange for ONE bucket, emitted inside the
    backward pass. Per leaf: all-reduce-mean (plan False) or
    reduce-scatter-mean over the data axis (plan True — each replica
    then holds only its gradient shard), gradient normalization, the
    updater on exactly what this replica holds (full tensor, or the
    shard with SHARDED updater state — 1/N optimizer memory), update
    the held params, all-gather updated shards. The cotangent of the
    bucket's params is the UPDATED params (constraints applied by the
    caller).

    ``dense`` is this hook with an all-False plan (`full_gn=True`:
    every GN mode factorizes per layer key, so per-bucket GN on the
    reduced full gradient equals whole-tree GN); ``dense_rs`` shards
    by plan with elementwise-only GN (build-time gated). Under
    elementwise GN the two run the SAME per-element op sequence —
    reduce-scatter + all-gather is the same sum as the all-reduce —
    which is what makes dense_rs bit-identical to bucketed dense."""
    from deeplearning4j_tpu.common.updaters import Sgd
    from deeplearning4j_tpu.optimize.gradients import (
        apply_gradient_normalization,
    )

    layer = _layer_for_key(model, is_graph, lk)
    updater = layer.updater or Sgd(1e-3)
    n = n_workers
    policy = model.dtype

    @jax.custom_vjp
    def hook(p, u, it_f):
        return policy.cast_params(p)

    def fwd(p, u, it_f):
        # saved p = the fp32 master; the hook OUTPUT (and therefore the
        # incoming cotangent) is compute dtype — under mixed_bf16 the
        # gradient collective below moves bf16 on the wire (half the
        # dense fp32 payload), upcast to fp32 only after the reduce
        return policy.cast_params(p), (p, u, it_f)

    def bwd(saved, g):
        p, u, it_f = saved
        idx = jax.lax.axis_index(axis)
        reduced = {}
        for pk, gg in g.items():
            if plan_b.get(pk):
                red = jax.lax.psum_scatter(
                    gg, axis, scatter_dimension=gg.ndim - 1, tiled=True) / n
            else:
                red = jax.lax.pmean(gg, axis)
            reduced[pk] = red.astype(p[pk].dtype)
        if full_gn:
            reduced = apply_gradient_normalization({lk: reduced},
                                                   gn, gn_t)[lk]
        else:
            reduced = {pk: _elementwise_gn(v, gn, gn_t)
                       for pk, v in reduced.items()}
        # fusion barrier: pin the reduce | updater | apply cluster
        # boundaries so the dense and dense_rs programs compile the
        # SAME elementwise updater kernels — the dense_rs==dense
        # bit-parity contract would otherwise be broken by
        # context-dependent FMA contraction (1-ulp drift). Costs
        # nothing material: the updater is a vanishing share of step
        # FLOPs and collective scheduling is unaffected.
        reduced = jax.lax.optimization_barrier(reduced)
        new_p, new_u = {}, {}
        for pk, gg in g.items():
            d, su = updater.apply(reduced[pk], u[pk], it_f)
            d = jax.lax.optimization_barrier(d)
            if plan_b.get(pk):
                s = gg.shape[-1] // n
                psh = jax.lax.dynamic_slice_in_dim(
                    p[pk], idx * s, s, axis=gg.ndim - 1)
                new_p[pk] = jax.lax.all_gather(
                    psh - d.astype(psh.dtype), axis,
                    axis=gg.ndim - 1, tiled=True)
            else:
                new_p[pk] = p[pk] - d.astype(p[pk].dtype)
            new_u[pk] = su
        return new_p, new_u, jnp.zeros_like(it_f)

    hook.defvjp(fwd, bwd)
    return hook


def _threshold_rs_bucket_hook(model, is_graph: bool, lk: str, axis: str,
                              cfg: ThresholdConfig, n_workers: int,
                              gn, gn_t, plan_b: dict, elems: float):
    """Compressed ZeRO exchange for ONE bucket: threshold-encode the
    RAW local gradient (+ error-feedback residual) to the integer wire
    format, reduce-scatter the int tensor, decode the gradient SHARD
    (τ·Σ/N), run the updater on the shard (sharded updater state),
    update the param shard, all-gather updated params. Unlike
    ``threshold``, the updater runs post-decode — so τ lives on the
    GRADIENT scale here, and the residual keeps un-sent gradient (not
    update) mass."""
    from deeplearning4j_tpu.common.updaters import Sgd

    layer = _layer_for_key(model, is_graph, lk)
    updater = layer.updater or Sgd(1e-3)
    n = n_workers
    wdtype = wire_dtype(n)
    inv_n = 1.0 / float(n)
    policy = model.dtype

    @jax.custom_vjp
    def hook(p, u, r, c, it_f):
        return policy.cast_params(p)

    def fwd(p, u, r, c, it_f):
        return policy.cast_params(p), (p, u, r, c, it_f)

    def bwd(saved, g):
        p, u, r, c, it_f = saved
        tau = c[0]
        idx = jax.lax.axis_index(axis)
        new_p, new_u, new_r = {}, {}, {}
        sent_total = jnp.float32(0.0)
        for pk, gg in g.items():
            # bf16 grad → fp32 residual dtype BEFORE the EF encode (a
            # bf16 accumulate would erase the carried residual mass)
            gg = gg.astype(r[pk].dtype)
            acc = gg + r[pk].astype(gg.dtype)
            enc, res_new, sent = encode_leaf(acc, tau, wdtype)
            sent_total = sent_total + sent
            new_r[pk] = res_new.astype(r[pk].dtype)
            scale = tau.astype(gg.dtype) * gg.dtype.type(inv_n)
            if plan_b.get(pk):
                wire = jax.lax.psum_scatter(
                    enc, axis, scatter_dimension=enc.ndim - 1, tiled=True)
                # GN on the REDUCED (decoded) shard — the same
                # post-reduce order dense_rs uses, which is the
                # contract the trainer's elementwise-GN gate states
                gsh = _elementwise_gn(wire.astype(gg.dtype) * scale,
                                      gn, gn_t)
                s = gg.shape[-1] // n
                psh = jax.lax.dynamic_slice_in_dim(
                    p[pk], idx * s, s, axis=gg.ndim - 1)
                d, su = updater.apply(gsh, u[pk], it_f)
                nps = psh - d.astype(psh.dtype)
                new_p[pk] = jax.lax.all_gather(
                    nps, axis, axis=gg.ndim - 1, tiled=True)
            else:
                ghat = _elementwise_gn(
                    jax.lax.psum(enc, axis).astype(gg.dtype) * scale,
                    gn, gn_t)
                d, su = updater.apply(ghat, u[pk], it_f)
                new_p[pk] = p[pk] - d.astype(p[pk].dtype)
            new_u[pk] = su
        sp = jax.lax.pmean(sent_total, axis) / elems
        new_tau = adapt_threshold(tau, sp, cfg)
        return (new_p, new_u, new_r, jnp.stack([new_tau, sp]),
                jnp.zeros_like(it_f))

    hook.defvjp(fwd, bwd)
    return hook


def _apply_constraints_tree(model, is_graph: bool, new_params):
    """The post-update constraint pipeline `_apply_updates` runs, for
    params the rs hooks already updated: per-layer constraints (never
    on packed runs — `packable_runs` guarantees it), then the global
    max-norm. Replicated math on replicated params."""
    from deeplearning4j_tpu.optimize.gradients import (
        apply_max_norm_constraint,
    )

    out = {}
    for lk, lp in new_params.items():
        layer = _layer_for_key(model, is_graph, lk)
        out[lk] = (lp if scan_stack.is_run_key(lk)
                   else layer.apply_constraints(lp))
    if model.conf.max_norm is not None:
        out = apply_max_norm_constraint(out, model.conf.max_norm)
    return out


def make_bucketed_core(model, axis: str, cfg: ThresholdConfig, *,
                       n_workers: int, mode: str, is_graph: bool = False,
                       rs_plan: Optional[dict] = None, diag=None):
    """Per-replica bucketed sync-step body on ALREADY-PACKED trees.
    Uniform signature across the four modes:

        core(params, upd, state, it, residual, tau, x, y, rng)
          -> (params, upd, state, residual, tau, loss, sparsity, dv)

    ``dv`` is the packed diagnostics vector (monitor/diagnostics.py;
    ``{}`` when diagnostics are off): per-layer POST-EXCHANGE
    update/param stats — the applied updates that came back through the
    VJP-hook channel — plus watchdog finite flags.

    `tau` is a PER-BUCKET dict of f32 scalars (empty for the dense
    modes, as is `residual`); `upd` is the per-replica updater view for
    ``threshold`` (each replica its own, PR-4 semantics), the SHARDED
    updater view for the `_rs` modes (ZeRO partitioning), and the
    single replicated tree for ``dense``. `sparsity` is the
    element-weighted mean encoded fraction over buckets (1.0 for
    dense modes — everything is sent)."""
    from deeplearning4j_tpu.optimize.gradients import (
        apply_gradient_normalization,
    )

    gn = model.conf.gradient_normalization
    gn_t = model.conf.gradient_normalization_threshold
    local_loss = _local_loss_fn(model, is_graph)

    def core(params, upd, state, it, residual, tau, x, y, rng):
        rng = jax.random.fold_in(rng, jax.lax.axis_index(axis))
        it_f = jnp.asarray(it, jnp.float32)

        if mode in ("dense", "dense_rs"):
            no_shard: dict = {}
            hooks = {lk: _dense_bucket_hook(
                model, is_graph, lk, axis, n_workers, gn, gn_t,
                no_shard if mode == "dense" else _plan_for(rs_plan, lk),
                full_gn=mode == "dense") for lk in params}

            def lf(p, u):
                hp = {lk: hooks[lk](p[lk], u[lk], it_f) for lk in p}
                return local_loss(hp, state, x, y, rng)

            (loss, (new_state, _)), (upd_p, new_upd) = jax.value_and_grad(
                lf, argnums=(0, 1), has_aux=True)(params, upd)
            new_params = _apply_constraints_tree(model, is_graph, upd_p)
            pstate = _pmean_state(new_state, axis)
            ploss = jax.lax.pmean(loss, axis)
            (new_params, new_upd, _, _, pstate, dv) = _exchange_diag(
                model, diag, axis, params_old=params, upd_old=upd,
                res_old=residual, tau_old=tau, state_old=state,
                params_new=new_params, upd_new=new_upd, res_new={},
                tau_new={}, state_new=pstate, loss=ploss)
            return (new_params, new_upd, pstate,
                    residual, tau, ploss, jnp.float32(1.0), dv)

        if mode == "threshold":
            hooks = {lk: _threshold_bucket_hook(
                model, is_graph, lk, axis, cfg, n_workers, gn, gn_t)
                for lk in params}
            ctrl = {lk: _ctrl(tau[lk]) for lk in params}

            def lf(p, u, r, c):
                hp = {lk: hooks[lk](p[lk], u[lk], r[lk], c[lk], it_f)
                      for lk in p}
                return local_loss(hp, state, x, y, rng)

            (loss, (new_state, _)), (dhat, new_upd, new_res, new_ctrl) = \
                jax.value_and_grad(lf, argnums=(0, 1, 2, 3),
                                   has_aux=True)(params, upd, residual,
                                                 ctrl)
            new_params = apply_decoded_updates(model, is_graph, params,
                                               dhat)

        elif mode == "threshold_rs":
            hooks = {lk: _threshold_rs_bucket_hook(
                model, is_graph, lk, axis, cfg, n_workers, gn, gn_t,
                _plan_for(rs_plan, lk), tree_elements(params[lk]))
                for lk in params}
            ctrl = {lk: _ctrl(tau[lk]) for lk in params}

            def lf(p, u, r, c):
                hp = {lk: hooks[lk](p[lk], u[lk], r[lk], c[lk], it_f)
                      for lk in p}
                return local_loss(hp, state, x, y, rng)

            (loss, (new_state, _)), (upd_p, new_upd, new_res, new_ctrl) = \
                jax.value_and_grad(lf, argnums=(0, 1, 2, 3),
                                   has_aux=True)(params, upd, residual,
                                                 ctrl)
            new_params = _apply_constraints_tree(model, is_graph, upd_p)

        else:
            raise ValueError(f"mode must be one of {MODES}, got {mode!r}")

        new_tau = {lk: new_ctrl[lk][0] for lk in new_ctrl}
        total = tree_elements(params)
        sparsity = sum(new_ctrl[lk][1] * tree_elements(params[lk])
                       for lk in new_ctrl) / total
        pstate = _pmean_state(new_state, axis)
        ploss = jax.lax.pmean(loss, axis)
        (new_params, new_upd, new_res, new_tau, pstate, dv) = \
            _exchange_diag(
                model, diag, axis, params_old=params, upd_old=upd,
                res_old=residual, tau_old=tau, state_old=state,
                params_new=new_params, upd_new=new_upd, res_new=new_res,
                tau_new=new_tau, state_new=pstate, loss=ploss)
        return (new_params, new_upd, pstate,
                new_res, new_tau, ploss, sparsity, dv)

    return core


def _pack_scalar_tree(tree, runs):
    """Per-layer scalar tree (per-bucket τ) packed to bucket keys: a
    run's bucket carries its FIRST member's scalar (unpack broadcasts
    it back, so all members of a run share τ by invariant)."""
    members = {k for keys in runs for k in keys}
    out = {k: v for k, v in tree.items() if k not in members}
    for keys in runs:
        out[scan_stack.run_key(keys)] = tree[keys[0]]
    return out


def _unpack_scalar_tree(tree, runs):
    out = {k: v for k, v in tree.items() if not scan_stack.is_run_key(k)}
    for keys in runs:
        v = tree[scan_stack.run_key(keys)]
        for k in keys:
            out[k] = v
    return out


def init_tau_tree(params, cfg: ThresholdConfig) -> dict:
    """Fresh per-bucket τ state with per-LAYER keys (the checkpoint
    contract: ``stacked::`` packing exists only inside the program)."""
    return {lk: np.float32(cfg.initial_threshold) for lk in params}


def coerce_tau(tau, layer_keys, cfg: Optional[ThresholdConfig] = None):
    """Checkpoint-form τ → per-layer tree: PR-4 checkpoints carry ONE
    scalar (broadcast to every layer), bucketed checkpoints a per-layer
    dict; a missing τ falls back to the config's initial value."""
    keys = list(layer_keys)
    if tau is None:
        cfg = cfg or ThresholdConfig()
        return {lk: np.float32(cfg.initial_threshold) for lk in keys}
    if isinstance(tau, dict):
        cfg = cfg or ThresholdConfig()
        return {lk: np.float32(tau[lk]) if lk in tau
                else np.float32(cfg.initial_threshold) for lk in keys}
    return {lk: np.float32(np.asarray(tau)) for lk in keys}


def ensure_tau_form(tau, per_bucket: bool, params,
                    cfg: ThresholdConfig):
    """The second half of the τ seam (`restore_tau` is the first):
    bring an existing τ state — or None — into the form the CURRENT
    step program needs: a per-bucket `{layer_key: scalar}` tree when
    `per_bucket`, one scalar otherwise. Cross-form inputs coerce
    (scalar broadcasts; a tree collapses to its bucket mean). One
    helper for both trainers so path switches and cross-form
    checkpoint restores can never diverge between them."""
    if tau is None:
        return (init_tau_tree(params, cfg) if per_bucket
                else jnp.float32(cfg.initial_threshold))
    if per_bucket and not isinstance(tau, dict):
        return coerce_tau(np.asarray(tau), params.keys(), cfg)
    if not per_bucket and isinstance(tau, dict):
        return jnp.float32(tau_scalar(tau))
    return tau


def restore_tau(tau):
    """Checkpoint-form τ → trainer state AS WRITTEN: a per-bucket
    {layer_key: scalar} tree (bucketed checkpoints) or one scalar
    (PR-4 single-barrier checkpoints). Coercion to the current path's
    form happens at the next fit (`coerce_tau` / `tau_scalar`); the
    single restore seam keeps both trainers' checkpoint handling from
    diverging."""
    if isinstance(tau, dict):
        return {lk: np.float32(np.asarray(v)) for lk, v in tau.items()}
    return jnp.float32(np.asarray(tau))


def tau_scalar(tau) -> float:
    """Observability scalar for a τ state of either form (scalar or
    per-layer tree): the mean over buckets. Tree leaves are stacked on
    device and fetched in ONE transfer — a per-leaf float() would cost
    one host round-trip per layer per step on the eager-listener
    path."""
    if isinstance(tau, dict):
        if not tau:
            return 0.0
        vals = np.asarray(jnp.stack([jnp.asarray(v)
                                     for v in tau.values()]))
        return float(vals.mean())
    return float(np.asarray(tau))


def make_bucketed_step(model, axis: str, cfg: ThresholdConfig, *,
                       n_workers: int, mode: str, is_graph: bool = False,
                       allow_scan: bool = True,
                       rs_plan: Optional[dict] = None, diag=None):
    """One bucketed sync step on per-layer (boundary) trees: packs
    ``stacked::`` runs for params, updater state, residual AND the
    per-bucket τ at entry, unpacks at exit. Signature matches
    `make_threshold_step` with τ as a per-layer scalar tree (empty
    dicts for residual/τ in the dense modes)."""
    core = make_bucketed_core(model, axis, cfg, n_workers=n_workers,
                              mode=mode, is_graph=is_graph,
                              rs_plan=rs_plan, diag=diag)
    threshold_state = mode in ("threshold", "threshold_rs")

    def step(params, upd, state, it, residual, tau, x, y, rng):
        with scan_stack.force_unrolled(not allow_scan):
            runs = (model._packed_runs(params)
                    if scan_stack.scan_enabled(model.conf) else [])
            if runs:
                params = scan_stack.pack_tree(params, runs)
                upd = scan_stack.pack_tree(upd, runs)
                if threshold_state:
                    residual = scan_stack.pack_tree(residual, runs)
                    tau = _pack_scalar_tree(tau, runs)
            params, upd, state, residual, tau, loss, sparsity, dv = core(
                params, upd, state, it, residual, tau, x, y, rng)
            if runs:
                params = scan_stack.unpack_tree(params, runs)
                upd = scan_stack.unpack_tree(upd, runs)
                if threshold_state:
                    residual = scan_stack.unpack_tree(residual, runs)
                    tau = _unpack_scalar_tree(tau, runs)
        return params, upd, state, residual, tau, loss, sparsity, dv

    return step


def make_bucketed_multi(model, axis: str, cfg: ThresholdConfig, *,
                        n_workers: int, mode: str, is_graph: bool = False,
                        allow_scan: bool = True,
                        rs_plan: Optional[dict] = None, diag=None):
    """k fused bucketed sync steps: ONE `lax.scan` whose carry is
    (params, updater state, layer state, iteration, residual, τ-tree)
    — the per-bucket residual/τ ride the carry next to the updater
    state, and the ``stacked::`` packing happens once per PROGRAM.
    Per-step diag vectors ride the scan ys. Bit-identical to k per-step
    calls (same rng folds, same counters)."""
    core = make_bucketed_core(model, axis, cfg, n_workers=n_workers,
                              mode=mode, is_graph=is_graph,
                              rs_plan=rs_plan, diag=diag)
    threshold_state = mode in ("threshold", "threshold_rs")

    def multi(params, upd, state, it0, residual, tau, xs, ys, rngs):
        with scan_stack.force_unrolled(not allow_scan):
            runs = (model._packed_runs(params)
                    if scan_stack.scan_enabled(model.conf) else [])
            if runs:
                params = scan_stack.pack_tree(params, runs)
                upd = scan_stack.pack_tree(upd, runs)
                if threshold_state:
                    residual = scan_stack.pack_tree(residual, runs)
                    tau = _pack_scalar_tree(tau, runs)
            tau = jax.tree_util.tree_map(
                lambda t: jnp.asarray(t, jnp.float32), tau)

            def body(carry, inp):
                params, upd, state, it, residual, tau = carry
                x, y, rng = inp
                (params, upd, new_state, residual, tau, loss,
                 sparsity, dv) = core(params, upd, state, it, residual,
                                      tau, x, y, rng)
                state = {k: new_state.get(k, v) for k, v in state.items()}
                return ((params, upd, state, it + 1, residual, tau),
                        (loss, sparsity, dv))

            carry = (params, upd, state, jnp.asarray(it0, jnp.int32),
                     residual, tau)
            ((params, upd, state, _, residual, tau),
             (losses, sps, dvs)) = \
                jax.lax.scan(body, carry, (xs, ys, rngs))
            if runs:
                params = scan_stack.unpack_tree(params, runs)
                upd = scan_stack.unpack_tree(upd, runs)
                if threshold_state:
                    residual = scan_stack.unpack_tree(residual, runs)
                    tau = _unpack_scalar_tree(tau, runs)
        return params, upd, state, residual, tau, losses, sps, dvs

    return multi


def bucket_plan(model) -> list:
    """Ordered (bucket_key, [member layer keys]) list of the model's
    exchange buckets in FORWARD order — packed ``stacked::`` runs plus
    singleton layers. Reversed, this is the backward ISSUE order the
    comm-overlap accounting in benchtools/hlo_cost.py walks (the last
    layer's bucket exchanges first)."""
    params = model.params
    runs = (model._packed_runs(params)
            if scan_stack.scan_enabled(model.conf) else [])
    members = {k for keys in runs for k in keys}
    entries = []
    for keys in runs:
        entries.append((scan_stack.run_key(keys), list(keys)))
    for lk in params:
        if lk not in members:
            entries.append((lk, [lk]))

    if hasattr(model, "layers"):
        order = {str(i): i for i in range(len(model.layers))}
    else:
        order = {name: i for i, name in enumerate(model.conf.topo_order)}
    entries.sort(key=lambda e: min(order.get(m, 0) for m in e[1]))
    return entries


# ------------------------------------------------------ comm-bytes accounting
def exchange_wire_bytes(params, mode: str, *, n_workers: int = 2,
                        rs_plan: Optional[dict] = None,
                        grad_dtype=None) -> float:
    """Host-side accounting of one step's gradient-exchange payload
    per replica (collective operand bytes): gradients in their ACTUAL
    dtype for dense (`grad_dtype` — the policy's compute dtype; bf16
    under mixed_bf16 halves the dense wire), the integer wire tensors
    + the sent-count/loss scalars for threshold. The `_rs` modes count
    the gradient reduce-scatter operand (grad-dtype or the int wire
    tensor) plus the updated-param all-gather operand (one PARAM-dtype
    shard per replica — the fp32 master is what gets gathered).
    Static — no device work, so the trainers can count every step
    without a sync (the FLOP-accounting discipline applied to
    communication)."""
    def leaf_itemsize(l):
        # shape/dtype only — a leaf may be a multi-process global array
        # whose VALUE no single host can fetch (TP-sharded params after
        # a previous fit); never materialize it
        dt = getattr(l, "dtype", None)
        return jnp.dtype(dt if dt is not None else type(l)).itemsize

    grad_item_of = leaf_itemsize
    if grad_dtype is not None:
        gsize = jnp.dtype(grad_dtype).itemsize

        def grad_item_of(l):  # noqa: F811 — floating grads ride
            dt = getattr(l, "dtype", None)  # grad_dtype, ints as-is
            dt = jnp.dtype(dt if dt is not None else type(l))
            return gsize if jnp.issubdtype(dt, jnp.floating) else dt.itemsize

    if mode == "dense":
        return float(sum(
            int(np.prod(np.shape(l))) * grad_item_of(l)
            for l in jax.tree_util.tree_leaves(params)))
    if mode in RS_MODES:
        if rs_plan is None:
            rs_plan = rs_shard_plan(params, n_workers)
        wire_item = (jnp.dtype(wire_dtype(n_workers)).itemsize
                     if mode == "threshold_rs" else None)
        total = 8.0 if mode == "threshold_rs" else 0.0
        for lk, lparams in params.items():
            for pn, arr in lparams.items():
                e = float(int(np.prod(np.shape(arr))))
                grad_item = (wire_item if wire_item is not None
                             else grad_item_of(arr))
                total += e * grad_item
                if rs_plan[lk][pn]:
                    # updated-PARAM shard all-gather: master dtype
                    total += (e / n_workers) * leaf_itemsize(arr)
        return total
    itemsize = jnp.dtype(wire_dtype(n_workers)).itemsize
    # + sent-count pmean (f32) + loss pmean (f32)
    return tree_elements(params) * itemsize + 8.0


def record_exchange(mode: str, wire_bytes: float, dense_bytes: float,
                    steps: int = 1, *, trainer: str = "parallel"):
    """Trainer-side monitor counters: exchanged bytes + steps per mode,
    and the wire compression ratio gauge. No-op (and no device sync —
    all inputs are host floats) when monitoring is disabled."""
    from deeplearning4j_tpu import monitor
    if not monitor.is_enabled():
        return
    reg = monitor.registry()
    reg.counter("gradient_exchange_bytes_total",
                help="gradient all-reduce payload bytes per replica",
                mode=mode, trainer=trainer).inc(wire_bytes * steps)
    reg.counter("gradient_exchange_steps_total",
                help="sync steps per gradient-sharing mode",
                mode=mode, trainer=trainer).inc(steps)
    if wire_bytes > 0:
        reg.gauge("gradient_sharing_compression_ratio",
                  help="dense/wire bytes of the gradient exchange",
                  trainer=trainer).set(dense_bytes / wire_bytes)


def record_threshold_stats(tau: float, sparsity: float, *,
                           trainer: str = "parallel"):
    """Gauge the adaptive controller's observables (called with values
    already read back to host — never forces a sync itself)."""
    from deeplearning4j_tpu import monitor
    if not monitor.is_enabled():
        return
    reg = monitor.registry()
    reg.gauge("gradient_sharing_threshold",
              help="current adaptive threshold tau",
              trainer=trainer).set(float(tau))
    reg.gauge("gradient_sharing_sparsity",
              help="achieved encoded fraction of the last exchange",
              trainer=trainer).set(float(sparsity))


# ------------------------------------------------- AOT analysis seam (jaxpr)
def exchange_jaxpr(params, mode: str, n_workers: int, *,
                   axis: str = "data", cfg: Optional[ThresholdConfig] = None,
                   rs_plan: Optional[dict] = None, grad_dtype=None):
    """ClosedJaxpr of ONE gradient exchange (dense pmean vs threshold
    encode→int-psum→decode) over an **AbstractMesh** — traceable on a
    single-device host with no mesh at all, which is what lets
    `benchtools/hlo_cost.py` emit committed dense-vs-threshold
    comm-bytes with a dead tunnel. Gradient avals are taken from
    `params` (shapes; floating leaves take `grad_dtype` when given —
    the mixed policy's compute dtype, so the analyzed program carries
    the REAL bf16 wire)."""
    from functools import partial

    from jax.sharding import AbstractMesh, PartitionSpec as P

    from deeplearning4j_tpu.parallel.compat import shard_map

    cfg = cfg or ThresholdConfig()
    mesh = AbstractMesh(((axis, int(n_workers)),))
    # per-replica operands enter with a leading replica axis (the
    # rep-spec representation the trainers use for residuals)
    def leaf_dtype(a):
        # shape/dtype only — a leaf may be a non-fetchable global array
        # (TP-sharded params after a multi-process fit), and a host
        # round-trip per leaf would be waste even when legal
        dt = getattr(a, "dtype", None)
        if dt is None:
            dt = np.asarray(a).dtype
        return jnp.dtype(dt)

    def aval_r(a, dtype_override=None):
        dt = leaf_dtype(a)
        if dtype_override is not None and jnp.issubdtype(dt, jnp.floating):
            dt = jnp.dtype(dtype_override)
        return jax.ShapeDtypeStruct((int(n_workers),) + tuple(np.shape(a)),
                                    dt)
    # the grad-dtype override shapes the wire only where the wire IS
    # the gradient (dense / dense_rs); the threshold modes encode fp32
    # accumulators (post-upcast) to an int wire either way
    dense_like = mode in ("dense", "dense_rs")
    grads_r = jax.tree_util.tree_map(
        lambda a: aval_r(a, grad_dtype if dense_like else None), params)
    param_dtypes = jax.tree_util.tree_map(leaf_dtype, params)
    strip = lambda t: jax.tree_util.tree_map(lambda a: a[0], t)
    expand = lambda t: jax.tree_util.tree_map(lambda a: a[None], t)
    rep = P(axis)

    if mode == "dense":
        @partial(shard_map, mesh=mesh, in_specs=(rep,), out_specs=rep,
                 check_vma=False)
        def ex(g_r):
            return expand(dense_exchange(strip(g_r), axis))

        return jax.make_jaxpr(ex)(grads_r)

    if mode in RS_MODES:
        plan = rs_plan if rs_plan is not None else rs_shard_plan(
            params, n_workers)
        wdtype = wire_dtype(n_workers)
        inv_n = 1.0 / float(n_workers)

        @partial(shard_map, mesh=mesh, in_specs=(rep,), out_specs=rep,
                 check_vma=False)
        def ex(g_r):
            g = strip(g_r)
            tau = jnp.float32(cfg.initial_threshold)
            out = {}
            for lk, lgrads in g.items():
                lout = {}
                for pn, gg in lgrads.items():
                    if mode == "threshold_rs":
                        enc, _, _ = encode_leaf(gg, tau, wdtype)
                    else:
                        enc = gg
                    if plan[lk][pn]:
                        sh = jax.lax.psum_scatter(
                            enc, axis, scatter_dimension=enc.ndim - 1,
                            tiled=True)
                        nsh = (sh.astype(gg.dtype) * gg.dtype.type(inv_n)
                               ).astype(param_dtypes[lk][pn])
                        lout[pn] = jax.lax.all_gather(
                            nsh, axis, axis=nsh.ndim - 1, tiled=True)
                    else:
                        lout[pn] = (jax.lax.psum(enc, axis)
                                    .astype(gg.dtype)
                                    * gg.dtype.type(inv_n))
                out[lk] = lout
            return expand(out)

        return jax.make_jaxpr(ex)(grads_r)

    if mode != "threshold":
        raise ValueError(f"mode must be one of {MODES}, got {mode!r}")

    @partial(shard_map, mesh=mesh, in_specs=(rep, rep, P()),
             out_specs=(rep, rep, P(), P()), check_vma=False)
    def ex(g_r, r_r, tau):
        ghat, res, tau, sp = threshold_exchange(
            strip(g_r), strip(r_r), tau, axis, cfg, n_workers=n_workers)
        return expand(ghat), expand(res), tau, sp

    tau0 = jax.ShapeDtypeStruct((), jnp.float32)
    return jax.make_jaxpr(ex)(grads_r, grads_r, tau0)
