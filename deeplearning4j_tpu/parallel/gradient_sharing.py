"""Threshold-encoded gradient sharing: error-feedback compressed collectives.

Reference equivalence: the signature distributed-training feature of
`SharedTrainingMaster` — `Nd4j.getExecutioner().thresholdEncode`
(sign-magnitude quantization at threshold τ), residual accumulation
(`EncodedGradientsAccumulator` keeps what was not sent and re-adds it
next step), and `AdaptiveThresholdAlgorithm` (τ chases a target
sparsity band). Communication characterization (arXiv:1810.11112)
shows dense gradient exchange dominating scaled-out step time; the
TensorFlow system paper (arXiv:1605.08695) argues the exchange
schedule should be a first-class, tunable part of the program. Here it
is: a jittable encode/decode the trainers select with
``gradient_sharing="dense"|"threshold"`` (env A/B override
``DL4J_GRADIENT_SHARING``, mirroring ``DL4J_SCAN_LAYERS``).

XLA-friendly wire format: instead of the reference's sparse
index/value chunks (data-dependent shapes XLA cannot compile), the
encoded update is a **dense int8 tensor of {-1, 0, +1}** — the
all-reduce payload drops from 4 bytes/element (fp32) to 1 byte/element
(int8), a fixed 4x wire reduction, while the threshold controls
*fidelity* (what fraction of the accumulated update magnitude gets
through this step) rather than wire size. Summing N int8 sign tensors
is exact for N ≤ 127 replicas; larger data axes automatically widen to
int16 (2x reduction).

Numeric contract (error feedback / EF-SGD):

    u_r        = updater_r(grad_r)               (per-replica updater —
                                                  each reference worker
                                                  runs its own)
    acc_r      = u_r + residual_r                (per replica)
    enc_r      = sign(acc_r) * (|acc_r| >= τ)    (int8 on the wire)
    residual_r = acc_r - τ * enc_r               (nothing is lost)
    û          = τ * Σ_r enc_r / N               (the shared update every
                                                  replica applies)

What gets encoded is the post-updater UPDATE, exactly as in the
reference (`EncodingHandler` encodes the updater's output): τ then
lives on the learning-rate scale, and every update magnitude the
threshold suppresses stays in the replica-local residual and re-enters
the accumulator next step, so the *sum* of applied updates tracks the
sum of true updates — the property the convergence-parity tests in
tests/test_gradient_sharing.py enforce against dense training.

τ adaptation (reference `AdaptiveThresholdAlgorithm` semantics):
``sparsity`` here is the encoded fraction — the share of elements that
made it onto the wire this step, pmean'd over replicas. Above the
target band, τ is boosted (send less); below it, τ decays (send
more); always clamped to [min_threshold, max_threshold]. τ and the
residual ride the fused multi-step scan carry next to the updater
state, and pack/unpack across the ``stacked::`` run boundary exactly
like updater state does (nn/scan_stack.py).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nn import scan_stack

MODES = ("dense", "threshold")

# env values that force each mode (mirrors DL4J_SCAN_LAYERS's spelling
# tolerance: 0/off/false disable the feature, i.e. force dense)
_ENV_VAR = "DL4J_GRADIENT_SHARING"
_ENV_DENSE = ("dense", "0", "off", "false", "no")
_ENV_THRESHOLD = ("threshold", "1", "on", "true", "yes")


@dataclasses.dataclass(frozen=True)
class ThresholdConfig:
    """Knobs of the threshold encoder + adaptive-τ controller.

    Defaults follow the reference's AdaptiveThresholdAlgorithm shape:
    start at `initial_threshold`, keep the encoded fraction inside
    [sparsity_target_min, sparsity_target_max], step τ geometrically
    when outside the band."""

    initial_threshold: float = 1e-3
    sparsity_target_min: float = 1e-3   # sending less than this: τ decays
    sparsity_target_max: float = 1e-1   # sending more than this: τ boosts
    decay: float = 1.0 / 1.2            # τ multiplier below the band
    boost: float = 1.2                  # τ multiplier above the band
    min_threshold: float = 1e-8
    max_threshold: float = 1.0

    def __post_init__(self):
        if not (0.0 < self.sparsity_target_min
                <= self.sparsity_target_max <= 1.0):
            raise ValueError(
                f"sparsity target band must satisfy 0 < min <= max <= 1, "
                f"got [{self.sparsity_target_min}, "
                f"{self.sparsity_target_max}]")
        if not (0.0 < self.decay < 1.0 < self.boost):
            raise ValueError(
                f"need decay < 1 < boost, got decay={self.decay} "
                f"boost={self.boost}")
        if not (0.0 < self.min_threshold <= self.initial_threshold
                <= self.max_threshold):
            raise ValueError(
                f"need min_threshold <= initial_threshold <= "
                f"max_threshold, got {self.min_threshold} / "
                f"{self.initial_threshold} / {self.max_threshold}")

    @staticmethod
    def from_conf(conf) -> "ThresholdConfig":
        """Config-carried initial τ (`gradient_sharing_threshold`),
        controller defaults for the rest."""
        tau0 = getattr(conf, "gradient_sharing_threshold", None)
        if tau0 is None:
            return ThresholdConfig()
        return ThresholdConfig(initial_threshold=float(tau0))


def env_mode() -> Optional[str]:
    """The ``DL4J_GRADIENT_SHARING`` override if set (validated), else
    None. Exposed so trainers can tell an env-forced mode (a global A/B
    toggle that must degrade gracefully where it does not apply) from
    an explicit arg/conf choice (a hard error when invalid)."""
    env = os.environ.get(_ENV_VAR)
    if env is None or not env.strip():
        return None
    v = env.strip().lower()
    if v in _ENV_DENSE:
        return "dense"
    if v in _ENV_THRESHOLD:
        return "threshold"
    raise ValueError(
        f"{_ENV_VAR}={env!r}: expected one of "
        f"{_ENV_DENSE + _ENV_THRESHOLD}")


def resolve_mode(explicit: Optional[str] = None, conf=None) -> str:
    """Gradient-sharing mode resolution: the ``DL4J_GRADIENT_SHARING``
    env override wins (benchmark A/B without touching code), then an
    explicit trainer argument, then the model configuration's
    ``gradient_sharing`` field, then "dense"."""
    forced = env_mode()
    if forced is not None:
        return forced
    for v in (explicit, getattr(conf, "gradient_sharing", None)):
        if v is not None:
            if v not in MODES:
                raise ValueError(
                    f"gradient_sharing must be one of {MODES}, got {v!r}")
            return v
    return "dense"


def wire_dtype(n_workers: int):
    """Narrowest integer type whose sum of n_workers sign values is
    exact. int8 up to 127 replicas (4x vs fp32), int16 beyond."""
    if n_workers <= 127:
        return jnp.int8
    if n_workers <= 32767:
        return jnp.int16
    raise ValueError(
        f"threshold gradient sharing supports data axes up to 32767 "
        f"replicas, got {n_workers}")


# ------------------------------------------------------------ encode/decode
def encode_leaf(acc, tau, wdtype):
    """One leaf of the threshold encoder: (wire tensor, residual,
    elements sent). `acc` is gradient + carried residual."""
    mask = jnp.abs(acc) >= tau.astype(acc.dtype)
    enc = jnp.where(mask, jnp.sign(acc), 0.0).astype(wdtype)
    residual = acc - enc.astype(acc.dtype) * tau.astype(acc.dtype)
    return enc, residual, jnp.sum(mask, dtype=jnp.float32)


def adapt_threshold(tau, sparsity, cfg: ThresholdConfig):
    """One controller step: boost τ above the target band (sending too
    much), decay it below (sending too little), clamp always."""
    tau = jnp.where(sparsity > cfg.sparsity_target_max, tau * cfg.boost,
                    jnp.where(sparsity < cfg.sparsity_target_min,
                              tau * cfg.decay, tau))
    return jnp.clip(tau, cfg.min_threshold, cfg.max_threshold)


def tree_elements(tree) -> float:
    """Static element count of a pytree (host math, trace-safe)."""
    return float(sum(int(np.prod(np.shape(l)))
                     for l in jax.tree_util.tree_leaves(tree)))


def threshold_exchange(grads, residual, tau, axis: str,
                       cfg: ThresholdConfig, *, n_workers: int):
    """The complete compressed collective: encode (with error
    feedback), all-reduce the integer wire tensors over `axis`, decode
    to the shared update, adapt τ from the globally-averaged encoded
    fraction.

    Returns (ĝ, new_residual, new_tau, sparsity). ĝ replaces
    pmean(grads) in the sync step; `sparsity` is the achieved encoded
    fraction (the compression-fidelity observable the reference's
    EncodingHandler logs)."""
    wdtype = wire_dtype(n_workers)
    flat_g, treedef = jax.tree_util.tree_flatten(grads)
    flat_r = treedef.flatten_up_to(residual)
    enc, new_res, sent = [], [], 0.0
    for g, r in zip(flat_g, flat_r):
        e, nr, s = encode_leaf(g + r.astype(g.dtype), tau, wdtype)
        enc.append(e)
        new_res.append(nr)
        sent = sent + s
    summed = [jax.lax.psum(e, axis) for e in enc]
    inv_n = 1.0 / float(n_workers)
    ghat = [s.astype(g.dtype) * (tau.astype(g.dtype) * g.dtype.type(inv_n))
            for s, g in zip(summed, flat_g)]
    total = tree_elements(grads)
    sparsity = jax.lax.pmean(sent, axis) / total
    new_tau = adapt_threshold(tau, sparsity, cfg)
    unflatten = treedef.unflatten
    return unflatten(ghat), unflatten(new_res), new_tau, sparsity


def dense_exchange(grads, axis: str):
    """The uncompressed baseline as an *explicit* collective —
    numerically what GSPMD inserts for the jit dense path (mean of
    per-replica gradients), made manual so its wire payload is
    measurable by the same jaxpr accounting as the threshold path."""
    return jax.tree_util.tree_map(
        lambda g: jax.lax.pmean(g, axis), grads)


def zeros_residual(params):
    """Fresh per-layer residual tree matching `params` (the same shape
    contract updater state follows — per-layer keys at the boundary,
    packed to ``stacked::`` entries only inside the program). Reads
    shapes/dtypes only, so global (non-fetchable) param leaves are
    fine."""
    return jax.tree_util.tree_map(
        lambda a: np.zeros(np.shape(a),
                           getattr(a, "dtype", None) or np.asarray(a).dtype),
        params)


# --------------------------------------------------------------- step bodies
def _layer_for_key(model, is_graph: bool, lk: str):
    """The layer owning a grads/params entry — ``stacked::`` run entries
    resolve to their first member (the run template), mirroring the
    containers' `_apply_updates`."""
    if scan_stack.is_run_key(lk):
        lk = scan_stack.run_members(lk)[0]
    return (model.conf.nodes[lk].layer if is_graph
            else model.layers[int(lk)])


def compute_updater_deltas(model, is_graph: bool, params, grads,
                           upd_state, step):
    """Run every layer's OWN updater on its local gradients, returning
    the update tree (what the reference threshold-encodes —
    `SharedTrainingMaster` workers encode post-updater UPDATES, not raw
    gradients, which is what lets a fixed τ ≈ learning-rate scale work)
    plus the advanced per-replica updater state. Mirrors the layer/run
    dispatch of the containers' `_apply_updates` without applying."""
    from deeplearning4j_tpu.common.updaters import Sgd

    deltas, new_upd = {}, {}
    for lk, lgrads in grads.items():
        layer = _layer_for_key(model, is_graph, lk)
        updater = layer.updater or Sgd(1e-3)
        ld, lu = {}, {}
        for pk, g in lgrads.items():
            delta, new_s = updater.apply(g, upd_state[lk][pk], step)
            ld[pk] = delta.astype(params[lk][pk].dtype)
            lu[pk] = new_s
        deltas[lk] = ld
        new_upd[lk] = lu
    return deltas, new_upd


def apply_decoded_updates(model, is_graph: bool, params, dhat):
    """params minus the decoded shared update, with the same
    constraint pipeline `_apply_updates` runs post-update (per-layer
    constraints — never present on packed runs, `packable_runs`
    guarantees it — then the global max-norm)."""
    from deeplearning4j_tpu.optimize.gradients import (
        apply_max_norm_constraint,
    )

    new_params = {}
    for lk, ld in dhat.items():
        layer = _layer_for_key(model, is_graph, lk)
        lp = {pk: params[lk][pk] - d for pk, d in ld.items()}
        new_params[lk] = (lp if scan_stack.is_run_key(lk)
                          else layer.apply_constraints(lp))
    if model.conf.max_norm is not None:
        new_params = apply_max_norm_constraint(new_params,
                                               model.conf.max_norm)
    return new_params


def _pmean_state(state, axis):
    """Keep layer state replicated across the data axis: float leaves
    (batchnorm running stats — per-shard batch statistics) are
    averaged, everything else (identical per-replica counters) passes
    through."""
    def avg(a):
        if jnp.issubdtype(jnp.result_type(a), jnp.floating):
            return jax.lax.pmean(a, axis)
        return a
    return jax.tree_util.tree_map(avg, state)


def _local_loss_fn(model, is_graph: bool):
    if is_graph:
        def lf(params, state, x, y, rng):
            return model._loss_fn(params, state, (x,), (y,), rng,
                                  (None,), (None,), train=True)
    else:
        def lf(params, state, x, y, rng):
            return model._loss_fn(params, state, x, y, rng, None, None,
                                  train=True)
    return lf


def make_threshold_core(model, axis: str, cfg: ThresholdConfig, *,
                        n_workers: int, is_graph: bool = False):
    """Per-replica threshold sync-step body on ALREADY-PACKED trees
    (params/updater-state/residual may contain ``stacked::`` run
    entries — the encoder is elementwise, so a stacked leading axis
    changes nothing; the layer/run dispatch goes through
    `scan_stack.is_run_key` exactly like `_apply_updates`).

    Reference pipeline order (`SharedTrainingMaster` workers): local
    gradients → local gradient normalization → local UPDATER (per-
    replica state, like each worker's own updater) → threshold-encode
    the update with error feedback → integer all-reduce → every replica
    applies the same decoded mean update to its (replicated) params.
    Encoding updates rather than raw gradients is what makes a fixed
    τ ≈ learning-rate scale meaningful and keeps error feedback honest
    under adaptive updaters (Adam's normalization would otherwise wash
    out the residual's accumulated magnitude).

    Loss is the local-shard mean; the returned loss/state are pmean'd
    so every replica exits replicated."""
    from deeplearning4j_tpu.optimize.gradients import (
        apply_gradient_normalization,
    )

    gn = model.conf.gradient_normalization
    gn_t = model.conf.gradient_normalization_threshold
    local_loss = _local_loss_fn(model, is_graph)

    def core(params, upd, state, it, residual, tau, x, y, rng):
        rng = jax.random.fold_in(rng, jax.lax.axis_index(axis))
        (loss, (new_state, _)), grads = jax.value_and_grad(
            lambda p: local_loss(p, state, x, y, rng), has_aux=True)(params)
        grads = apply_gradient_normalization(grads, gn, gn_t)
        deltas, new_upd = compute_updater_deltas(
            model, is_graph, params, grads, upd, it)
        dhat, new_residual, new_tau, sparsity = threshold_exchange(
            deltas, residual, tau, axis, cfg, n_workers=n_workers)
        new_params = apply_decoded_updates(model, is_graph, params, dhat)
        return (new_params, new_upd, _pmean_state(new_state, axis),
                new_residual, new_tau, jax.lax.pmean(loss, axis), sparsity)

    return core


def make_threshold_step(model, axis: str, cfg: ThresholdConfig, *,
                        n_workers: int, is_graph: bool = False,
                        allow_scan: bool = True):
    """One threshold sync step on per-layer (boundary) trees: packs
    ``stacked::`` runs for params, updater state AND residual at entry,
    unpacks at exit — the residual follows updater state through the
    pack boundary exactly (nn/scan_stack.py contract).

    ``allow_scan=False`` traces the whole body with the unrolled layer
    path (`scan_stack.force_unrolled`) — required when the caller wraps
    this in a partially-manual shard_map (DP x TP), where jaxlib
    0.4.x's SPMD partitioner crashes on inner scan bodies."""
    core = make_threshold_core(model, axis, cfg, n_workers=n_workers,
                               is_graph=is_graph)

    def step(params, upd, state, it, residual, tau, x, y, rng):
        with scan_stack.force_unrolled(not allow_scan):
            runs = (model._packed_runs(params)
                    if scan_stack.scan_enabled(model.conf) else [])
            if runs:
                params = scan_stack.pack_tree(params, runs)
                upd = scan_stack.pack_tree(upd, runs)
                residual = scan_stack.pack_tree(residual, runs)
            params, upd, state, residual, tau, loss, sparsity = core(
                params, upd, state, it, residual, tau, x, y, rng)
            if runs:
                params = scan_stack.unpack_tree(params, runs)
                upd = scan_stack.unpack_tree(upd, runs)
                residual = scan_stack.unpack_tree(residual, runs)
        return params, upd, state, residual, tau, loss, sparsity

    return step


def make_threshold_multi(model, axis: str, cfg: ThresholdConfig, *,
                         n_workers: int, is_graph: bool = False,
                         allow_scan: bool = True):
    """k fused threshold sync steps: ONE `lax.scan` whose carry is
    (params, updater state, layer state, iteration, residual, τ) — the
    residual and τ ride the carry next to the updater state, and the
    ``stacked::`` run packing happens once per PROGRAM, not per step.

    Scan-carry structure rule (same as the containers'
    `_multi_step_fn`): only state keys present at entry survive across
    fused steps."""
    core = make_threshold_core(model, axis, cfg, n_workers=n_workers,
                               is_graph=is_graph)

    def multi(params, upd, state, it0, residual, tau, xs, ys, rngs):
        with scan_stack.force_unrolled(not allow_scan):
            runs = (model._packed_runs(params)
                    if scan_stack.scan_enabled(model.conf) else [])
            if runs:
                params = scan_stack.pack_tree(params, runs)
                upd = scan_stack.pack_tree(upd, runs)
                residual = scan_stack.pack_tree(residual, runs)

            def body(carry, inp):
                params, upd, state, it, residual, tau = carry
                x, y, rng = inp
                params, upd, new_state, residual, tau, loss, sparsity = core(
                    params, upd, state, it, residual, tau, x, y, rng)
                state = {k: new_state.get(k, v) for k, v in state.items()}
                return ((params, upd, state, it + 1, residual, tau),
                        (loss, sparsity))

            carry = (params, upd, state, jnp.asarray(it0, jnp.int32),
                     residual, jnp.asarray(tau, jnp.float32))
            (params, upd, state, _, residual, tau), (losses, sparsities) = \
                jax.lax.scan(body, carry, (xs, ys, rngs))
            if runs:
                params = scan_stack.unpack_tree(params, runs)
                upd = scan_stack.unpack_tree(upd, runs)
                residual = scan_stack.unpack_tree(residual, runs)
        return params, upd, state, residual, tau, losses, sparsities

    return multi


# ------------------------------------------------------ comm-bytes accounting
def exchange_wire_bytes(params, mode: str, *, n_workers: int = 2) -> float:
    """Host-side accounting of one step's gradient-exchange payload
    per replica (the all-reduce operand): fp32 gradients for dense,
    the integer wire tensors + the sent-count/loss scalars for
    threshold. Static — no device work, so the trainers can count
    every step without a sync (the FLOP-accounting discipline applied
    to communication)."""
    def leaf_itemsize(l):
        # shape/dtype only — a leaf may be a multi-process global array
        # whose VALUE no single host can fetch (TP-sharded params after
        # a previous fit); never materialize it
        dt = getattr(l, "dtype", None)
        return jnp.dtype(dt if dt is not None else type(l)).itemsize

    if mode == "dense":
        return float(sum(
            int(np.prod(np.shape(l))) * leaf_itemsize(l)
            for l in jax.tree_util.tree_leaves(params)))
    itemsize = jnp.dtype(wire_dtype(n_workers)).itemsize
    # + sent-count pmean (f32) + loss pmean (f32)
    return tree_elements(params) * itemsize + 8.0


def record_exchange(mode: str, wire_bytes: float, dense_bytes: float,
                    steps: int = 1, *, trainer: str = "parallel"):
    """Trainer-side monitor counters: exchanged bytes + steps per mode,
    and the wire compression ratio gauge. No-op (and no device sync —
    all inputs are host floats) when monitoring is disabled."""
    from deeplearning4j_tpu import monitor
    if not monitor.is_enabled():
        return
    reg = monitor.registry()
    reg.counter("gradient_exchange_bytes_total",
                help="gradient all-reduce payload bytes per replica",
                mode=mode, trainer=trainer).inc(wire_bytes * steps)
    reg.counter("gradient_exchange_steps_total",
                help="sync steps per gradient-sharing mode",
                mode=mode, trainer=trainer).inc(steps)
    if wire_bytes > 0:
        reg.gauge("gradient_sharing_compression_ratio",
                  help="dense/wire bytes of the gradient exchange",
                  trainer=trainer).set(dense_bytes / wire_bytes)


def record_threshold_stats(tau: float, sparsity: float, *,
                           trainer: str = "parallel"):
    """Gauge the adaptive controller's observables (called with values
    already read back to host — never forces a sync itself)."""
    from deeplearning4j_tpu import monitor
    if not monitor.is_enabled():
        return
    reg = monitor.registry()
    reg.gauge("gradient_sharing_threshold",
              help="current adaptive threshold tau",
              trainer=trainer).set(float(tau))
    reg.gauge("gradient_sharing_sparsity",
              help="achieved encoded fraction of the last exchange",
              trainer=trainer).set(float(sparsity))


# ------------------------------------------------- AOT analysis seam (jaxpr)
def exchange_jaxpr(params, mode: str, n_workers: int, *,
                   axis: str = "data", cfg: Optional[ThresholdConfig] = None):
    """ClosedJaxpr of ONE gradient exchange (dense pmean vs threshold
    encode→int-psum→decode) over an **AbstractMesh** — traceable on a
    single-device host with no mesh at all, which is what lets
    `benchtools/hlo_cost.py` emit committed dense-vs-threshold
    comm-bytes with a dead tunnel. Gradient avals are taken from
    `params` (gradients share the param tree's shapes/dtypes)."""
    from functools import partial

    from jax.sharding import AbstractMesh, PartitionSpec as P

    from deeplearning4j_tpu.parallel.compat import shard_map

    cfg = cfg or ThresholdConfig()
    mesh = AbstractMesh(((axis, int(n_workers)),))
    # per-replica operands enter with a leading replica axis (the
    # rep-spec representation the trainers use for residuals)
    def aval_r(a):
        # shape/dtype only — a leaf may be a non-fetchable global array
        # (TP-sharded params after a multi-process fit), and a host
        # round-trip per leaf would be waste even when legal
        dt = getattr(a, "dtype", None)
        if dt is None:
            dt = np.asarray(a).dtype
        return jax.ShapeDtypeStruct((int(n_workers),) + tuple(np.shape(a)),
                                    dt)
    grads_r = jax.tree_util.tree_map(aval_r, params)
    strip = lambda t: jax.tree_util.tree_map(lambda a: a[0], t)
    expand = lambda t: jax.tree_util.tree_map(lambda a: a[None], t)
    rep = P(axis)

    if mode == "dense":
        @partial(shard_map, mesh=mesh, in_specs=(rep,), out_specs=rep,
                 check_vma=False)
        def ex(g_r):
            return expand(dense_exchange(strip(g_r), axis))

        return jax.make_jaxpr(ex)(grads_r)

    if mode != "threshold":
        raise ValueError(f"mode must be one of {MODES}, got {mode!r}")

    @partial(shard_map, mesh=mesh, in_specs=(rep, rep, P()),
             out_specs=(rep, rep, P(), P()), check_vma=False)
    def ex(g_r, r_r, tau):
        ghat, res, tau, sp = threshold_exchange(
            strip(g_r), strip(r_r), tau, axis, cfg, n_workers=n_workers)
        return expand(ghat), expand(res), tau, sp

    tau0 = jax.ShapeDtypeStruct((), jnp.float32)
    return jax.make_jaxpr(ex)(grads_r, grads_r, tau0)
