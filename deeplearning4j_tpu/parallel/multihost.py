"""Multi-host initialization.

Reference equivalence: the Spark driver/executor bootstrap +  Aeron
parameter-server wiring (`SharedTrainingMaster.java:423-443`,
`VoidConfiguration` unicast/shard config) collapse on TPU into ONE
call: `jax.distributed.initialize` — after which every host sees the
global device set, meshes span hosts, and the same pjit/shard_map
programs run SPMD over ICI (intra-slice) and DCN (cross-slice) with
XLA-inserted collectives replacing the PS gossip protocol.
"""

from __future__ import annotations

import os
from typing import Optional

import jax


def initialize_multihost(coordinator_address: Optional[str] = None,
                         num_processes: Optional[int] = None,
                         process_id: Optional[int] = None) -> None:
    """Bring up the multi-host runtime (idempotent). On TPU pods with
    standard env (TPU_WORKER_HOSTNAMES etc.) all args auto-detect; on
    GPU/CPU clusters pass coordinator host:port + process counts
    (the reference's `controller address` `SharedTrainingMaster.java:443`)."""
    if getattr(initialize_multihost, "_done", False):
        return
    kwargs = {}
    if coordinator_address is not None:
        kwargs["coordinator_address"] = coordinator_address
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    jax.distributed.initialize(**kwargs)
    initialize_multihost._done = True


def process_count() -> int:
    return jax.process_count()


def process_index() -> int:
    return jax.process_index()


def is_main_process() -> bool:
    return jax.process_index() == 0
