"""Multi-host initialization.

Reference equivalence: the Spark driver/executor bootstrap +  Aeron
parameter-server wiring (`SharedTrainingMaster.java:423-443`,
`VoidConfiguration` unicast/shard config) collapse on TPU into ONE
call: `jax.distributed.initialize` — after which every host sees the
global device set, meshes span hosts, and the same pjit/shard_map
programs run SPMD over ICI (intra-slice) and DCN (cross-slice) with
XLA-inserted collectives replacing the PS gossip protocol.
"""

from __future__ import annotations

import os
from typing import Optional

import jax


def _enable_cpu_collectives() -> None:
    """The CPU backend has no built-in cross-process collectives ("
    Multiprocess computations aren't implemented on the CPU backend") —
    they only exist behind the gloo/mpi plugin selected by
    `jax_cpu_collectives_implementation`, whose default is "none".
    Select gloo when the process targets CPU and nothing was chosen
    explicitly, so the same multi-host programs run on CPU clusters
    (and in the 2-process CI smoke) without per-caller setup."""
    import jax._src.xla_bridge as xb

    if "cpu" not in str(os.environ.get("JAX_PLATFORMS",
                                       jax.config.jax_platforms or "cpu")):
        return
    try:
        current = xb.CPU_COLLECTIVES_IMPLEMENTATION.value
    except AttributeError:     # newer jax: option renamed/absorbed
        current = None
    if current not in (None, "none"):
        return                 # an explicit mpi/gloo choice wins
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:  # noqa: BLE001 — jaxlib without gloo: keep going,
        pass           # initialize() will surface the real capability


def initialize_multihost(coordinator_address: Optional[str] = None,
                         num_processes: Optional[int] = None,
                         process_id: Optional[int] = None) -> None:
    """Bring up the multi-host runtime (idempotent). On TPU pods with
    standard env (TPU_WORKER_HOSTNAMES etc.) all args auto-detect; on
    GPU/CPU clusters pass coordinator host:port + process counts
    (the reference's `controller address` `SharedTrainingMaster.java:443`)."""
    if getattr(initialize_multihost, "_done", False):
        return
    _enable_cpu_collectives()
    kwargs = {}
    if coordinator_address is not None:
        kwargs["coordinator_address"] = coordinator_address
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    jax.distributed.initialize(**kwargs)
    initialize_multihost._done = True


def process_count() -> int:
    return jax.process_count()


def process_index() -> int:
    return jax.process_index()


def is_main_process() -> bool:
    return jax.process_index() == 0
