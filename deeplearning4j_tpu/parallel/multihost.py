"""Multi-host initialization and teardown.

Reference equivalence: the Spark driver/executor bootstrap +  Aeron
parameter-server wiring (`SharedTrainingMaster.java:423-443`,
`VoidConfiguration` unicast/shard config) collapse on TPU into ONE
call: `jax.distributed.initialize` — after which every host sees the
global device set, meshes span hosts, and the same pjit/shard_map
programs run SPMD over ICI (intra-slice) and DCN (cross-slice) with
XLA-inserted collectives replacing the PS gossip protocol.

Elastic lifecycle (parallel/elastic.py): the runtime is no longer
initialize-once. `shutdown_multihost()` tears the distributed client /
service down AND clears every cache that pins the old topology (the
xla_bridge backend registry, the `process_count`/`process_index`
lru_caches, jit executable caches), so a following
`initialize_multihost(...)` with a DIFFERENT process set or coordinator
address builds a fresh world — the mesh re-formation primitive the
membership coordinator drives on join/leave.
"""

from __future__ import annotations

import logging
import os
import time
from typing import Optional

import jax

log = logging.getLogger("deeplearning4j_tpu.parallel.multihost")

# which exceptions the bounded-retry path treats as "the coordinator is
# not up yet / transient RPC failure" — jax surfaces them as RuntimeError
# (DEADLINE_EXCEEDED / UNAVAILABLE grpc statuses stringified) and
# XlaRuntimeError subclasses of it
_TRANSIENT_MARKERS = ("DEADLINE_EXCEEDED", "UNAVAILABLE", "timed out",
                      "Timed out", "failed to connect", "Connection refused",
                      "connection attempt", "Socket closed",
                      "Address already in use")


def _enable_cpu_collectives() -> None:
    """The CPU backend has no built-in cross-process collectives ("
    Multiprocess computations aren't implemented on the CPU backend") —
    they only exist behind the gloo/mpi plugin selected by
    `jax_cpu_collectives_implementation`, whose default is "none".
    Select gloo when the process targets CPU and nothing was chosen
    explicitly, so the same multi-host programs run on CPU clusters
    (and in the 2-process CI smoke) without per-caller setup."""
    import jax._src.xla_bridge as xb

    if "cpu" not in str(os.environ.get("JAX_PLATFORMS",
                                       jax.config.jax_platforms or "cpu")):
        return
    try:
        current = xb.CPU_COLLECTIVES_IMPLEMENTATION.value
    except AttributeError:     # newer jax: option renamed/absorbed
        current = None
    if current not in (None, "none"):
        return                 # an explicit mpi/gloo choice wins
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:  # noqa: BLE001 — jaxlib without gloo: keep going,
        pass           # initialize() will surface the real capability


def _transient(err: BaseException) -> bool:
    msg = str(err)
    return any(m in msg for m in _TRANSIENT_MARKERS)


def _raw_initialize(coordinator_address, num_processes, process_id, *,
                    initialization_timeout: Optional[float],
                    heartbeat_interval_s: Optional[float],
                    max_missing_heartbeats: Optional[int]):
    """One initialization attempt. Prefers the internal
    `global_state.initialize` entry point when heartbeat tuning is
    requested (the public API grew those knobs only later): elastic
    recovery needs peer death detected in seconds, not the default
    10 s x 10 misses."""
    kwargs = {}
    if coordinator_address is not None:
        kwargs["coordinator_address"] = coordinator_address
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    if initialization_timeout is not None:
        kwargs["initialization_timeout"] = int(initialization_timeout)
    if heartbeat_interval_s is None and max_missing_heartbeats is None:
        jax.distributed.initialize(**kwargs)
        return
    hb = {}
    if heartbeat_interval_s is not None:
        hb["service_heartbeat_interval_seconds"] = int(
            max(1, heartbeat_interval_s))
        hb["client_heartbeat_interval_seconds"] = int(
            max(1, heartbeat_interval_s))
    if max_missing_heartbeats is not None:
        hb["service_max_missing_heartbeats"] = int(max_missing_heartbeats)
        hb["client_max_missing_heartbeats"] = int(max_missing_heartbeats)
    try:
        from jax._src import distributed as _dist
        _dist.global_state.initialize(**kwargs, **hb)
    except TypeError:
        # jax version without tunable heartbeats: fall back to defaults
        # (elastic recovery still works, peer-death detection is slower)
        log.warning("this jax version does not expose heartbeat tuning; "
                    "using default heartbeat intervals")
        jax.distributed.initialize(**kwargs)


def _reset_distributed_state():
    """Best-effort teardown of a half-initialized distributed runtime
    (a failed initialize attempt can leave a dangling client/service
    that would make the next attempt fail with 'already initialized')."""
    try:
        from jax._src import distributed as _dist
        state = _dist.global_state
        if state.client is not None or state.service is not None:
            state.shutdown()
    except Exception as e:  # noqa: BLE001 — peers may already be gone
        log.warning("distributed-state reset during retry raised %s "
                    "(continuing)", e)
        try:
            from jax._src import distributed as _dist
            _dist.global_state.client = None
            _dist.global_state.service = None
            _dist.global_state.preemption_sync_manager = None
        except Exception:  # noqa: BLE001
            pass


def initialize_multihost(coordinator_address: Optional[str] = None,
                         num_processes: Optional[int] = None,
                         process_id: Optional[int] = None, *,
                         initialization_timeout: Optional[float] = None,
                         heartbeat_interval_s: Optional[float] = None,
                         max_missing_heartbeats: Optional[int] = None,
                         max_attempts: int = 3,
                         backoff_s: float = 1.0) -> None:
    """Bring up the multi-host runtime (idempotent while up). On TPU
    pods with standard env (TPU_WORKER_HOSTNAMES etc.) all args
    auto-detect; on GPU/CPU clusters pass coordinator host:port +
    process counts (the reference's `controller address`
    `SharedTrainingMaster.java:443`).

    Connection setup retries with bounded exponential backoff: the
    coordinator process routinely comes up AFTER its workers (elastic
    re-formation, CI process races) and the raw failure mode is an
    opaque RPC timeout. `max_attempts` attempts, `backoff_s * 2**k`
    sleep between them; non-transient errors raise immediately.

    After `shutdown_multihost()` a new call re-initializes — with a
    different process set / coordinator address if the topology
    changed (the elastic membership path)."""
    if getattr(initialize_multihost, "_done", False):
        return
    # persistent XLA compile cache (DL4J_COMPILE_CACHE_DIR): elastic
    # re-formation re-jits the train step per membership generation —
    # revisited replica counts load their executables from disk
    # instead of paying the full re-compile (the ROADMAP's
    # per-width-compile-cache lever; no-op without the env var)
    from deeplearning4j_tpu.nd.compile_cache import enable_compile_cache
    enable_compile_cache()
    _enable_cpu_collectives()
    last_err: Optional[BaseException] = None
    for attempt in range(max(1, int(max_attempts))):
        try:
            _raw_initialize(
                coordinator_address, num_processes, process_id,
                initialization_timeout=initialization_timeout,
                heartbeat_interval_s=heartbeat_interval_s,
                max_missing_heartbeats=max_missing_heartbeats)
            initialize_multihost._done = True
            return
        except Exception as e:  # noqa: BLE001 — inspect + classify
            last_err = e
            _reset_distributed_state()
            if not _transient(e):
                raise
            if attempt + 1 < max(1, int(max_attempts)):
                delay = backoff_s * (2 ** attempt)
                log.warning(
                    "jax.distributed.initialize attempt %d/%d failed "
                    "(coordinator %s not reachable yet?): %s — retrying "
                    "in %.1fs", attempt + 1, max_attempts,
                    coordinator_address, str(e)[:200], delay)
                time.sleep(delay)
    raise RuntimeError(
        f"initialize_multihost: all {max_attempts} attempts failed "
        f"(transient coordinator race?)") from last_err


def multihost_active() -> bool:
    """True between a successful `initialize_multihost` and the next
    `shutdown_multihost`."""
    return bool(getattr(initialize_multihost, "_done", False))


def shutdown_multihost() -> None:
    """Tear down the distributed runtime so it can be re-initialized
    with a DIFFERENT topology (elastic membership change).

    Clears, in order: the `jax.distributed` client/service, the
    initialize latch, every cached backend (the CPU/TPU client bakes
    the world size in at creation), the `process_count`/`process_index`
    lru_caches (they would keep answering for the dead world), and the
    jit executable caches (compiled programs pin devices of the old
    backend). No-op when the runtime was never initialized."""
    if not multihost_active():
        return
    try:
        jax.distributed.shutdown()
    except Exception as e:  # noqa: BLE001 — a dead peer can fail the
        # shutdown barrier; the local teardown below must still run
        log.warning("jax.distributed.shutdown raised %s (continuing "
                    "with local teardown)", e)
        _reset_distributed_state()
    finally:
        initialize_multihost._done = False
        _clear_topology_caches()


def _clear_topology_caches():
    """Drop every cache that pins the previous process set. Split out
    so tests can exercise the latch lifecycle without a real
    distributed runtime."""
    from jax._src import api as _api
    from jax._src import xla_bridge as xb

    _api.clear_caches()
    try:
        xb._clear_backends()
    except Exception as e:  # noqa: BLE001
        log.warning("backend-cache clear raised %s", e)
    for fn_name in ("process_count", "process_index", "device_count",
                    "local_device_count"):
        fn = getattr(xb, fn_name, None)
        if fn is not None and hasattr(fn, "cache_clear"):
            fn.cache_clear()


def process_count() -> int:
    return jax.process_count()


def process_index() -> int:
    return jax.process_index()


def is_main_process() -> bool:
    return jax.process_index() == 0
