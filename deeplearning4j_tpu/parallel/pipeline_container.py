"""Container-level pipeline parallelism: stage-partition a
MultiLayerNetwork over the "pipe" mesh axis.

No reference equivalent (SURVEY §2.13: pipeline parallelism ❌ — this
is the mesh-axis design the SPMD engine left open). The primitive
GPipe schedule lives in `parallel/pipeline.py` (ppermute ring +
lax.scan ticks); this module connects it to the PUBLIC container API
so a real model — not a hand-rolled closure — trains under PP:

- the network is split prolog | homogeneous run | epilog, where the
  run is the longest streak of consecutive layers with identical
  (layer type, param shapes) — the repeated transformer-block /
  stacked-MLP body where the FLOPs live. The run must divide evenly
  into mesh["pipe"] stages (`per = run/S` blocks per stage, applied by
  a `lax.scan` inside the stage).
- prolog/epilog (embedding / positional encoding / output loss) are
  computed replicated on every pipe device: same math everywhere, so
  parity with the single-device container is exact; their cost is the
  cheap gather/projection ends of the model.
- the training step keeps the MODEL's param tree (str(i)-keyed) as the
  optimization state: the loss stacks the run's params on the fly
  under jit, so gradients come back per-layer and the container's own
  `_apply_updates` (updaters, schedules, constraints) applies
  unchanged — numerical parity with `model.fit` is by construction,
  not by re-implementation.

Autodiff runs through the whole schedule (ppermute transposes to the
reverse permute), giving pipeline-parallel backprop from one
`jax.value_and_grad`.
"""

from __future__ import annotations

import time
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from deeplearning4j_tpu.datasets.iterator import as_iterator
from deeplearning4j_tpu.optimize.listeners import ComposedListeners
from deeplearning4j_tpu.parallel.pipeline import pipeline_forward


from deeplearning4j_tpu.nd.donation import donate_argnums as _donate


def _layer_signature(layer, lparams):
    import json
    # full config equality, not just type + shapes: two layers with
    # identical param shapes but different activations/head counts must
    # not merge into one run (the stage executes every block through
    # the FIRST layer's forward)
    try:
        conf = json.dumps(layer.to_dict(), sort_keys=True, default=str)
    except Exception:
        conf = repr(layer)
    return (layer.layer_name, conf,
            tuple(sorted((pn, tuple(np.shape(a)))
                         for pn, a in lparams.items())))


def find_homogeneous_run(model) -> Tuple[int, int]:
    """[start, stop) of the longest streak of consecutive layers with
    identical type + param shapes (the pipelineable body). Layers
    without params (activations, dropout) break the streak — they
    would change the stage function."""
    best = (0, 0)
    i = 0
    n = len(model.layers)
    while i < n:
        sig = _layer_signature(model.layers[i], model.params.get(str(i), {}))
        j = i + 1
        while j < n and _layer_signature(
                model.layers[j], model.params.get(str(j), {})) == sig:
            j += 1
        if j - i > best[1] - best[0]:
            best = (i, j)
        i = j
    return best


class PipelineParallelTrainer:
    """GPipe training for a MultiLayerNetwork over `mesh[pipe_axis]`.

    `microbatches` is the GPipe M (bubble fraction = (S-1)/(M+S-1));
    the global batch must divide by it. Masks and TBPTT are not
    supported on this path (assert eagerly); dropout inside the
    pipelined run is driven by the same per-layer rng folding the
    sequential container uses, so loss parity holds whenever the model
    itself is deterministic (no dropout) and holds in distribution
    otherwise."""

    def __init__(self, model, mesh: Mesh, *, pipe_axis: str = "pipe",
                 data_axis: Optional[str] = None, microbatches: int = 4,
                 run: Optional[Tuple[int, int]] = None, stats=None):
        # stats: optional TrainingMasterStats — sync_step timing per
        # pipelined step (one device sync per step when enabled)
        self.stats = stats
        if not model._initialized:
            model.init()
        if not hasattr(model, "_forward_core"):
            raise NotImplementedError(
                "PipelineParallelTrainer stages MultiLayerNetwork stacks; "
                "for a ComputationGraph, pipeline its repeated-block "
                "subgraph as a MultiLayerNetwork or use DP x TP "
                "(ShardedParallelTrainer)")
        self.model = model
        self.mesh = mesh
        self.pipe_axis = pipe_axis
        # DP composition: batch shards over `data_axis` (each data
        # shard streams its own microbatches through the pipe ring;
        # GSPMD sums the replicated-param gradients across shards)
        if data_axis is not None and data_axis not in mesh.shape:
            raise ValueError(
                f"data_axis {data_axis!r} is not a mesh axis "
                f"{tuple(mesh.shape)} — a silent fallback would leave "
                "the batch replicated over that axis and mis-scale "
                "gradients")
        self.data_axis = data_axis
        self.microbatches = int(microbatches)
        if self.microbatches < 1:
            raise ValueError(
                f"microbatches must be >= 1; got {microbatches}")
        S = int(mesh.shape[pipe_axis])
        self.n_stages = S
        r0, r1 = run if run is not None else find_homogeneous_run(model)
        if (r1 - r0) < S:
            raise ValueError(
                f"longest homogeneous layer run [{r0}, {r1}) has "
                f"{r1 - r0} blocks — fewer than {S} pipeline stages. "
                "Reduce the pipe axis or deepen the repeated body.")
        if (r1 - r0) % S:
            raise ValueError(
                f"homogeneous run of {r1 - r0} blocks does not divide "
                f"into {S} stages; choose S | run length")
        for i in range(r0 + 1, r1):
            if i in model.conf.input_preprocessors:
                raise ValueError(
                    f"input preprocessor at layer {i} sits inside the "
                    "pipelined run; preprocessors are only supported in "
                    "the prolog/epilog")
        for i in range(r0, r1):
            layer = model.layers[i]
            if getattr(layer, "dropout", None) or \
                    getattr(layer, "weight_noise", None):
                raise ValueError(
                    f"layer {i} ({layer.layer_name}) uses dropout/weight "
                    "noise inside the pipelined run — per-block rng "
                    "threading is not supported on this path; move the "
                    "stochastic layer out of the run or disable it")
            if model.net_state.get(str(i)) or \
                    layer.layer_name == "mixture_of_experts":
                raise ValueError(
                    f"layer {i} ({layer.layer_name}) is stateful (running "
                    "stats / aux losses) inside the pipelined run — the "
                    "stage function discards per-block state; keep "
                    "stateful layers in the prolog/epilog")
        self.run = (r0, r1)
        self._step = None

    # ------------------------------------------------------ batch shaping
    def _data_shards(self) -> int:
        return (1 if self.data_axis is None
                else int(self.mesh.shape[self.data_axis]))

    def _batch_multiple(self) -> int:
        """Every (micro)batch reshapes to [microbatches, shard, ...] —
        the batch must be a multiple of this."""
        return self.microbatches * self._data_shards()

    def _validate_batch(self, n: int, what: str):
        """Eager divisibility check with a clear error — a bad shape
        must fail HERE, not as a cryptic reshape error inside the
        GPipe schedule (and a ragged tail must never silently train on
        a misaligned microbatch grid)."""
        M, shards = self.microbatches, self._data_shards()
        if n % M:
            raise ValueError(
                f"{what} of {n} examples does not divide into "
                f"microbatches={M}; choose a batch size that is a "
                f"multiple of {self._batch_multiple()} (microbatches x "
                f"mesh['{self.data_axis}']), or drop the ragged tail")
        if (n // M) % shards:
            raise ValueError(
                f"{what} of {n} examples: per-microbatch size "
                f"{n // M} does not divide over the {shards}-way "
                f"'{self.data_axis}' mesh axis; choose a batch size "
                f"that is a multiple of {self._batch_multiple()} "
                f"(microbatches x mesh['{self.data_axis}'])")

    # ------------------------------------------------------------ loss
    def _pp_loss(self, params, state, x, y, rng):
        """Mirrors `MultiLayerNetwork._loss_fn` with the homogeneous
        run executed by the GPipe schedule. Returns (loss, new_state)."""
        model = self.model
        r0, r1 = self.run
        S, per = self.n_stages, (r1 - r0) // self.n_stages
        n = len(model.layers)

        # prolog [0, r0): the container's own forward core
        h, new_state, _, _, mask = model._forward_core(
            params, state, x, train=True, rng=rng, upto=r0)
        assert mask is None, "masks are not supported under PP"

        # pipelined run [r0, r1): stack per-layer params → [S, per, ...]
        template = model.layers[r0]
        run_params = [params[str(i)] for i in range(r0, r1)]
        stacked = jax.tree_util.tree_map(
            lambda *leaves: jnp.stack(leaves).reshape(
                (S, per) + np.shape(leaves[0])), *run_params)

        def stage_fn(stage_params, h):
            # stage_params leaves [per, ...]: apply this stage's `per`
            # blocks sequentially via scan (rng=None — the constructor
            # rejects stochastic layers inside the run); the template's
            # remat_policy wraps the block body exactly like the
            # sequential container's scan path (nn/scan_stack.py)
            from deeplearning4j_tpu.nn import scan_stack

            def body(hh, p_one):
                hh, _ = template.forward(p_one, {}, hh, train=True,
                                         rng=None)
                return hh, None

            body = scan_stack.remat_wrap(
                body, scan_stack.effective_remat_policy(template),
                prevent_cse=False)
            h_out, _ = jax.lax.scan(body, h, stage_params)
            return h_out

        h = pipeline_forward(stage_fn, stacked, h, self.mesh,
                             pipe_axis=self.pipe_axis,
                             microbatches=self.microbatches,
                             data_axis=self.data_axis)

        # epilog [r1, n): remaining hidden layers + output loss — the
        # same tail structure as `MultiLayerNetwork._loss_fn`, incl.
        # weight noise (the prolog gets it via `_forward_core`; without
        # it here an epilog DropConnect layer would silently train
        # different math than `model.fit`)
        from deeplearning4j_tpu.nn import scan_stack
        for i in range(r1, n - 1):
            layer = model.layers[i]
            if i in model.conf.input_preprocessors:
                h = model.conf.input_preprocessors[i].pre_process(h, None)
            lrng = None if rng is None else jax.random.fold_in(rng, i)
            lparams = layer.apply_weight_noise(
                params.get(str(i), {}), True,
                None if lrng is None else jax.random.fold_in(lrng, 0x5EED))
            # layer_forward applies the layer's remat_policy (the
            # containers own remat now — layers no longer self-wrap)
            h, st = scan_stack.layer_forward(
                layer, lparams, state.get(str(i), {}), h, train=True,
                rng=lrng)
            if st:
                new_state[str(i)] = st
        if (n - 1) in model.conf.input_preprocessors:
            h = model.conf.input_preprocessors[n - 1].pre_process(h, None)
        out_layer = model.layers[-1]
        si = str(n - 1)
        lrng = None if rng is None else jax.random.fold_in(rng, n - 1)
        # losses stay in output dtype (fp32 under a mixed policy) —
        # same rule as the containers' _loss_fn
        h = model.dtype.cast_output(h)
        y = model.dtype.cast_output(jnp.asarray(y))
        out_params = out_layer.apply_weight_noise(
            model.dtype.cast_output_params(
                model.dtype.cast_params(params.get(si, {}))), True,
            None if lrng is None else jax.random.fold_in(lrng, 0x5EED))
        loss = out_layer.compute_loss(out_params, state.get(si, {}),
                                      h, y, train=True, rng=lrng)
        reg = 0.0
        for i, layer in enumerate(model.layers):
            p = params.get(str(i))
            if p:
                reg = reg + layer.regularization_score(p)
        for st in new_state.values():
            if "aux_loss" in st:
                reg = reg + st.pop("aux_loss")
        return model.dtype.cast_output(loss) + reg, new_state

    # ------------------------------------------------------------ step
    def _build(self):
        from deeplearning4j_tpu.optimize.gradients import (
            apply_gradient_normalization)
        from deeplearning4j_tpu.monitor import diagnostics as diagx
        model = self.model
        gn = model.conf.gradient_normalization
        gn_t = model.conf.gradient_normalization_threshold
        diag = getattr(model, "_diag", None)

        def step(params, upd, state, it, x, y, rng):
            (loss, new_state), grads = jax.value_and_grad(
                lambda p: self._pp_loss(p, state, x, y, rng),
                has_aux=True)(params)
            grads = apply_gradient_normalization(grads, gn, gn_t)
            new_params, new_upd = model._apply_updates(params, grads, upd, it)
            # aux-only per-layer stats of the pipelined step (no
            # activation stats — interior stage activations live
            # inside the GPipe schedule)
            new_params, new_upd, new_state, dv = diagx.collect_and_gate(
                diag, "pipeline", params_old=params, params_new=new_params,
                upd_old=upd, upd_new=new_upd, state_old=state,
                state_new=new_state, grads=grads, loss=loss)
            return new_params, new_upd, new_state, loss, dv

        self._step = jax.jit(step, donate_argnums=_donate(0, 1))

    def evaluate(self, data, labels=None, *, batch_size: int = 32,
                 evaluation=None):
        """Evaluation through the SAME pipelined forward the trainer
        uses (prolog | GPipe run | epilog), so a stage-partitioned
        model never needs to materialize unsharded. Ragged tails pad
        to the microbatch multiple and slice after the forward."""
        from deeplearning4j_tpu.eval import Evaluation
        model = self.model
        if getattr(self, "_eval_forward", None) is None:
            r0, r1 = self.run
            n = len(model.layers)

            def fwd(params, state, x):
                h, _, _, _, _ = model._forward_core(
                    params, state, x, train=False, rng=None, upto=r0)
                S, per = self.n_stages, (r1 - r0) // self.n_stages
                run_params = [params[str(i)] for i in range(r0, r1)]
                stacked = jax.tree_util.tree_map(
                    lambda *leaves: jnp.stack(leaves).reshape(
                        (S, per) + np.shape(leaves[0])), *run_params)
                template = model.layers[r0]

                def stage_fn(stage_params, hh):
                    def body(h2, p_one):
                        h2, _ = template.forward(p_one, {}, h2,
                                                 train=False, rng=None)
                        return h2, None
                    out, _ = jax.lax.scan(body, hh, stage_params)
                    return out

                h = pipeline_forward(stage_fn, stacked, h, self.mesh,
                                     pipe_axis=self.pipe_axis,
                                     microbatches=self.microbatches,
                                     data_axis=self.data_axis)
                for i in range(r1, n):
                    if i in model.conf.input_preprocessors:
                        h = model.conf.input_preprocessors[i].pre_process(
                            h, None)
                    h, _ = model.layers[i].forward(
                        params.get(str(i), {}), state.get(str(i), {}),
                        h, train=False, rng=None)
                return h

            self._eval_forward = jax.jit(fwd)
        iterator = as_iterator(data, labels, batch_size=batch_size)
        ev = evaluation if evaluation is not None else Evaluation()
        # tails pad to the FULL microbatch grid — microbatches x the
        # data-axis shard count: padding only to `microbatches` would
        # leave a per-microbatch size that doesn't divide over the
        # data mesh axis and fail (or mis-shard) inside the schedule
        M = self._batch_multiple()
        for ds in iterator:
            x = np.asarray(ds.features)
            n_real = x.shape[0]
            pad = (-n_real) % M
            if pad:
                x = np.concatenate([x, np.repeat(x[-1:], pad, axis=0)])
            out = np.asarray(self._eval_forward(
                model.params, model.net_state, jnp.asarray(x)))[:n_real]
            ev.eval(np.asarray(ds.labels), out)
        return ev

    def fit(self, data, labels=None, *, epochs: int = 1,
            batch_size: int = 32):
        model = self.model
        # eager divisibility validation (the requested batch size AND
        # every actual batch — iterators can yield ragged tails)
        self._validate_batch(int(batch_size), "batch_size")
        if self._step is None:
            self._build()
        from deeplearning4j_tpu import monitor
        monitor.attach_master_stats(self.stats)
        iterator = as_iterator(data, labels, batch_size=batch_size)
        listeners = ComposedListeners(model.listeners
                                      + monitor.extra_listeners())
        rng_root = jax.random.PRNGKey(model.conf.seed + 1)
        params, upd, state = model.params, model.updater_state, model.net_state

        def live_state():
            # fault/ checkpointing: fit-local device trees (the model's
            # attributes are only written back when fit returns)
            return {"params": params, "net_state": state,
                    "updater_state": upd,
                    "trainer_meta": {"kind": "pipeline",
                                     "trainer": "pipeline",
                                     "n_stages": self.n_stages}}

        model._live_state_provider = live_state
        try:
            # epoch/fit listener events fire like the containers' fit
            # loops (checkpoint listeners drain their writer at fit end)
            listeners.on_fit_start(model)
            for _ in range(epochs):
                listeners.on_epoch_start(model, model.epoch_count)
                iterator.reset()
                for ds in iterator:
                    if ds.features_mask is not None or \
                            ds.labels_mask is not None:
                        raise ValueError("masks are not supported under PP")
                    self._validate_batch(ds.num_examples(), "fit batch")
                    rng = jax.random.fold_in(rng_root, model.iteration_count)
                    t0 = time.perf_counter() if self.stats is not None else 0.0
                    params, upd, new_state, loss, dv = self._step(
                        params, upd, state, model.iteration_count,
                        jnp.asarray(ds.features), jnp.asarray(ds.labels), rng)
                    state = {**state, **new_state}
                    if self.stats is not None:
                        jax.block_until_ready(loss)
                        self.stats.record("sync_step",
                                          time.perf_counter() - t0,
                                          iteration=model.iteration_count)
                        self.stats.next_round()
                    model.score_value = float(loss)
                    from deeplearning4j_tpu.monitor import (
                        diagnostics as diagx)
                    rows = diagx.process_if_due(model, dv, "pipeline",
                                                model.iteration_count)
                    listeners.iteration_done(model, model.iteration_count,
                                             model.epoch_count,
                                             model.score_value,
                                             batch_size=ds.num_examples(),
                                             diagnostics=rows[-1] if rows
                                             else None)
                    model.iteration_count += 1
                listeners.on_epoch_end(model, model.epoch_count)
                model.epoch_count += 1
            listeners.on_fit_end(model)
        finally:
            model._live_state_provider = None
        model.params, model.updater_state, model.net_state = params, upd, state
        return model

    def resume(self, directory, *, iterator=None):
        """Restore the model's full training state from the newest
        VALID checkpoint under `directory` (fault/ runtime). The GPipe
        step keeps the container's per-layer param tree as the
        optimization state, so a model-level restore is complete — a
        following `fit()` continues the interrupted run."""
        from deeplearning4j_tpu import fault
        model, _ = fault.resume(directory, model=self.model, trainer=self,
                                iterator=iterator)
        return model
