"""All-to-all sequence parallelism (DeepSpeed-Ulysses style).

No 2017-reference equivalent (like ring attention, this is first-class
new-design territory per SURVEY §5 long-context): an alternative to the
ring schedule for long sequences. Instead of rotating K/V blocks around
the ICI ring, ONE all-to-all re-partitions the activations from
sequence-sharded to head-sharded, each device computes EXACT full-
sequence attention for its head subset, and a second all-to-all returns
to sequence sharding.

Trade-off vs ring (why both exist):
- ulysses: 2 collectives total, full-sequence attention kernels (best
  MXU utilization), but requires num_heads % seq_devices == 0 and
  all-to-all bandwidth;
- ring: P-1 ppermutes with compute overlap, no head-count constraint,
  preferred when heads are few or the ring is the fast path (1D ICI
  torus).

Implemented with `shard_map` + `lax.all_to_all` so XLA lowers the
re-partitions to native ICI all-to-alls.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.parallel.compat import axis_size, shard_map

from deeplearning4j_tpu.parallel.ring import reference_attention


def _full_attention(q, k, v, causal: bool):
    """Exact attention on full sequences: [B, T, H, Dh] blocks."""
    scale = 1.0 / jnp.sqrt(jnp.asarray(q.shape[-1], q.dtype))
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        T = q.shape[1]
        ok = jnp.tril(jnp.ones((T, T), bool))
        scores = jnp.where(ok[None, None], scores, -jnp.inf)
    p = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


def ulysses_attention(q, k, v, axis_name: str, causal: bool = False,
                      use_flash: bool = False):
    """Per-shard: q/k/v [B, T_local, H, Dh] (sequence-sharded). Returns
    o [B, T_local, H, Dh]. Run inside shard_map with `axis_name` bound;
    requires H % axis_size == 0.

    `use_flash=True` runs the post-all-to-all full-sequence attention
    through the Pallas flash kernels (`kernels/flash_attention.py`,
    differentiable) — since each device sees the FULL sequence for its
    head subset, this is where the O(block)-VMEM streaming matters most
    in the Ulysses schedule."""
    Pn = axis_size(axis_name)
    B, Tl, H, Dh = q.shape
    if H % Pn != 0:
        raise ValueError(f"num_heads={H} must divide by seq devices={Pn}")

    # seq-sharded [B, Tl, H, Dh] → head-sharded [B, Tl*P, H/P, Dh]:
    # all_to_all splits the head axis across devices and concatenates
    # the gathered sequence chunks along time
    def to_heads(x):
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    def to_seq(x):
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    qh, kh, vh = to_heads(q), to_heads(k), to_heads(v)   # [B, T, H/P, Dh]
    if use_flash:
        from deeplearning4j_tpu.kernels.flash_attention import (
            flash_attention)
        oh = flash_attention(qh, kh, vh, causal)
    else:
        oh = _full_attention(qh, kh, vh, causal)
    return to_seq(oh)                                    # [B, Tl, H, Dh]


def ulysses_parallel_attention(q, k, v, mesh: Mesh, *,
                               axis_name: str = "seq",
                               causal: bool = False,
                               use_flash: bool = False):
    """Full arrays [B, T, H, Dh]; shards T over `axis_name`, runs the
    all-to-all schedule, returns full [B, T, H, Dh]."""
    spec = P(None, axis_name, None, None)

    @partial(shard_map, mesh=mesh, in_specs=(spec, spec, spec),
             out_specs=spec, check_vma=False)
    def run(ql, kl, vl):
        return ulysses_attention(ql, kl, vl, axis_name, causal=causal,
                                 use_flash=use_flash)

    sh = NamedSharding(mesh, spec)
    return run(jax.device_put(q, sh), jax.device_put(k, sh),
               jax.device_put(v, sh))
