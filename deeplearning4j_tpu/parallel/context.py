"""Ambient sequence-parallel context.

Configs are serializable data (a layer can *request*
`sequence_parallel="ring"`), while meshes are runtime hardware state —
so the mesh rides a context manager instead of the config:

    mesh = make_mesh(MeshSpec.of(seq=8))
    with sequence_sharding(mesh, axis="seq"):
        net.fit(x, y, ...)        # attention layers with
                                  # sequence_parallel set now run
                                  # ring/Ulysses over the mesh

The lookup happens at trace time (inside jit tracing, not per step), so
there is no runtime overhead. Thread-local, like the reference's
per-thread workspace configuration.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Optional, Tuple

_state = threading.local()


def current_sequence_mesh() -> Optional[Tuple[object, str]]:
    """The active (mesh, seq_axis) pair, or None."""
    return getattr(_state, "mesh_axis", None)


@contextlib.contextmanager
def sequence_sharding(mesh, axis: str = "seq"):
    prev = getattr(_state, "mesh_axis", None)
    _state.mesh_axis = (mesh, axis)
    try:
        yield mesh
    finally:
        _state.mesh_axis = prev
