"""Transfer learning (reference: `nn/transferlearning/`)."""

from deeplearning4j_tpu.transferlearning.transfer import (
    TransferLearning,
    FineTuneConfiguration,
    TransferLearningHelper,
)
