"""Transfer learning: graph surgery on config-as-data + param copy.

Reference: `nn/transferlearning/TransferLearning.java:73`
(fineTuneConfiguration), `:84` (setFeatureExtractor → frozen layers),
`:98+` (nOutReplace), plus `FineTuneConfiguration` and
`TransferLearningHelper` (featurize-once workflow).

Because configs are data and params are name-keyed pytrees, surgery is:
clone config dicts → edit layer list → rebuild net → copy params whose
layer+shape survive. Freezing = updater→NoOp on the frozen prefix (the
reference wraps in FrozenLayer; effect is identical: no updates, and
the helper below skips even computing their gradients by featurizing).
"""

from __future__ import annotations

import dataclasses
from typing import Any, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.common.updaters import NoOp, Updater, get_updater
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterator import as_iterator
from deeplearning4j_tpu.nn.conf.builder import MultiLayerConfiguration
from deeplearning4j_tpu.nn.layers.base import Layer, layer_from_dict
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork


@dataclasses.dataclass
class FineTuneConfiguration:
    """Global overrides applied to every non-frozen layer (reference
    `FineTuneConfiguration.java`)."""

    updater: Optional[Updater] = None
    l1: Optional[float] = None
    l2: Optional[float] = None
    dropout: Optional[float] = None
    seed: Optional[int] = None

    def apply(self, layer: Layer):
        if self.updater is not None:
            layer.updater = get_updater(self.updater)
        if self.l1 is not None:
            layer.l1 = self.l1
        if self.l2 is not None:
            layer.l2 = self.l2
        if self.dropout is not None:
            layer.dropout = self.dropout


class TransferLearning:
    class Builder:
        def __init__(self, net: MultiLayerNetwork):
            self._net = net
            self._layers: List[Layer] = [l.clone() for l in net.conf.layers]
            self._fine_tune: Optional[FineTuneConfiguration] = None
            self._frozen_upto = -1
            self._replaced: dict = {}
            self._appended: List[Layer] = []
            self._removed_from_output = 0

        def fine_tune_configuration(self, ftc: FineTuneConfiguration):
            self._fine_tune = ftc
            return self

        def set_feature_extractor(self, layer_idx: int):
            """Freeze layers [0..layer_idx] (reference
            `setFeatureExtractor`)."""
            self._frozen_upto = layer_idx
            return self

        def n_out_replace(self, layer_idx: int, n_out: int, weight_init=None):
            """Replace layer's nOut and re-init it + the next layer's nIn
            (reference `nOutReplace`)."""
            self._replaced[layer_idx] = (n_out, weight_init)
            return self

        def remove_output_layer(self):
            return self.remove_layers_from_output(1)

        def remove_layers_from_output(self, n: int):
            self._removed_from_output += n
            return self

        def add_layer(self, layer: Layer):
            self._appended.append(layer)
            return self

        def build(self) -> MultiLayerNetwork:
            old_net = self._net
            layers = self._layers
            if self._removed_from_output:
                layers = layers[:-self._removed_from_output]
            reinit: set = set()
            for idx, (n_out, wi) in self._replaced.items():
                layers[idx].n_out = n_out
                if wi is not None:
                    layers[idx].weight_init = wi
                reinit.add(idx)
                if idx + 1 < len(layers) and hasattr(layers[idx + 1], "n_in"):
                    layers[idx + 1].n_in = n_out
                    reinit.add(idx + 1)
            base = len(layers)
            layers = layers + [l.clone() for l in self._appended]
            for i in range(base, len(layers)):
                reinit.add(i)
            for i, l in enumerate(layers):
                if i <= self._frozen_upto:
                    l.updater = NoOp()
                elif self._fine_tune is not None:
                    self._fine_tune.apply(l)

            old = old_net.conf
            conf = MultiLayerConfiguration(
                layers=layers,
                input_preprocessors={i: p for i, p in old.input_preprocessors.items()
                                     if i < len(layers)},
                input_type=old.input_type,
                seed=(self._fine_tune.seed if self._fine_tune and self._fine_tune.seed
                      else old.seed),
                backprop_type=old.backprop_type,
                tbptt_fwd_length=old.tbptt_fwd_length,
                tbptt_back_length=old.tbptt_back_length,
                gradient_normalization=old.gradient_normalization,
                gradient_normalization_threshold=old.gradient_normalization_threshold,
                max_norm=old.max_norm,
            )
            new_net = MultiLayerNetwork(conf, old_net.dtype).init()
            # copy surviving params (name+shape match, not reinitialized)
            for i in range(min(len(layers), len(old_net.conf.layers))):
                si = str(i)
                if i in reinit or si not in old_net.params:
                    continue
                if si in new_net.params:
                    for pk, arr in old_net.params[si].items():
                        if pk in new_net.params[si] and \
                                new_net.params[si][pk].shape == arr.shape:
                            new_net.params[si][pk] = jnp.asarray(np.asarray(arr))
                if si in old_net.net_state and si in new_net.net_state:
                    for pk, arr in old_net.net_state[si].items():
                        if new_net.net_state[si].get(pk) is not None and \
                                new_net.net_state[si][pk].shape == arr.shape:
                            new_net.net_state[si][pk] = jnp.asarray(np.asarray(arr))
            return new_net


class TransferLearningHelper:
    """Featurize-once workflow (reference `TransferLearningHelper.java`):
    run inputs through the frozen prefix ONCE, then train only the
    unfrozen tail on the cached features."""

    def __init__(self, net: MultiLayerNetwork, frozen_upto: int):
        self.full_net = net
        self.frozen_upto = frozen_upto
        tail_layers = [l.clone() for l in net.conf.layers[frozen_upto + 1:]]
        old = net.conf
        tail_pre = {i - (frozen_upto + 1): p for i, p in old.input_preprocessors.items()
                    if i > frozen_upto}
        conf = MultiLayerConfiguration(
            layers=tail_layers,
            input_preprocessors=tail_pre,
            seed=old.seed,
            backprop_type=old.backprop_type,
            tbptt_fwd_length=old.tbptt_fwd_length,
        )
        self.unfrozen = MultiLayerNetwork(conf, net.dtype).init()
        for i in range(len(tail_layers)):
            src = str(i + frozen_upto + 1)
            dst = str(i)
            if src in net.params and dst in self.unfrozen.params:
                self.unfrozen.params[dst] = jax.tree_util.tree_map(
                    lambda a: a, net.params[src])
            if src in net.net_state and dst in self.unfrozen.net_state:
                self.unfrozen.net_state[dst] = jax.tree_util.tree_map(
                    lambda a: a, net.net_state[src])

    def featurize(self, dataset: DataSet) -> DataSet:
        acts = self.full_net.feed_forward(jnp.asarray(dataset.features))
        return DataSet(np.asarray(acts[self.frozen_upto]), dataset.labels,
                       dataset.features_mask, dataset.labels_mask)

    def fit_featurized(self, data, **kw):
        self.unfrozen.fit(data, **kw)
        # write trained tail params back into the full net
        for i in range(len(self.unfrozen.conf.layers)):
            src, dst = str(i), str(i + self.frozen_upto + 1)
            if src in self.unfrozen.params:
                self.full_net.params[dst] = self.unfrozen.params[src]
            if src in self.unfrozen.net_state:
                self.full_net.net_state[dst] = self.unfrozen.net_state[src]
        return self

    def output_from_featurized(self, features):
        return self.unfrozen.output(features)
