"""Training UI web server.

Reference: `play/PlayUIServer.java` (embedded Play/Netty server) with
pluggable UIModules: `module/train/TrainModule.java` routes
`/train/overview|model|system` (:93-105), `module/tsne/` (t-SNE
visualization), `module/convolutional/` (activation grids), and
`module/remote/RemoteReceiverModule` (train-here-view-there POST
receiver). Here: stdlib ThreadingHTTPServer serving the same route
surface with self-contained pages built from the declarative component
library (`ui/components.py` — the ui-components equivalent), plus JSON
APIs.
"""

from __future__ import annotations

import base64
import html as _html
import io
import json
import math
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse
from typing import Dict, List, Optional

import numpy as np

from deeplearning4j_tpu.ui.components import (
    ChartHistogram,
    ChartLine,
    ChartScatter,
    ComponentTable,
)
from deeplearning4j_tpu.ui.stats import StatsReport
from deeplearning4j_tpu.ui.i18n import LANGUAGES, tr as _tr_i18n
from deeplearning4j_tpu.ui.storage import InMemoryStatsStorage, StatsStorage


class UIServer:
    """`UIServer.getInstance().attach(storage)` equivalent."""

    _instance: Optional["UIServer"] = None

    def __init__(self, port: int = 0, registry=None):
        self.storage: StatsStorage = InMemoryStatsStorage()
        # /metrics exposition source; None → the process-global monitor
        # registry at request time (so enable() after server start works)
        self._registry = registry
        # /alerts source: an AlertEngine attached via attach_alerts()
        self._alerts = None
        self._tsne: Dict[str, dict] = {}          # session → {coords, labels}
        self._activations: Dict[str, bytes] = {}  # name → PNG bytes
        self._module_lock = threading.Lock()      # guards the two dicts
        # per-REQUEST view options (lang/refresh): thread-local because
        # ThreadingHTTPServer handles concurrent requests on separate
        # threads — instance attributes would race between them
        self._req = threading.local()
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _send(self, code, body, ctype="text/html; charset=utf-8"):
                if isinstance(body, str):
                    body = body.encode()
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                parsed = urlparse(self.path)
                path = parsed.path
                q = parse_qs(parsed.query)
                # reference play UI: i18n bundles + live-updating views;
                # here: ?lang=en|ja|zh and ?refresh=<seconds>. lang is
                # WHITELISTED (it is echoed into hrefs — arbitrary
                # values would be a reflected-XSS vector)
                lang = q.get("lang", ["en"])[0]
                outer._req.lang = lang if lang in LANGUAGES else "en"
                try:
                    outer._req.refresh = max(0, int(q.get("refresh", ["0"])[0]))
                except ValueError:
                    outer._req.refresh = 0
                if path in ("/", "/train", "/train/overview"):
                    self._send(200, outer._overview_html())
                elif path == "/train/model":
                    self._send(200, outer._model_html())
                elif path == "/train/system":
                    self._send(200, outer._system_html())
                elif path == "/tsne":
                    self._send(200, outer._tsne_html())
                elif path == "/activations":
                    self._send(200, outer._activations_html())
                elif path.startswith("/activations/img/"):
                    name = path.rsplit("/", 1)[1]
                    with outer._module_lock:
                        png = outer._activations.get(name)
                    if png is None:
                        self._send(404, "not found")
                    else:
                        self._send(200, png, "image/png")
                elif path == "/metrics":
                    # Prometheus text exposition (the telemetry core's
                    # scrape endpoint — see monitor/ and docs/OBSERVABILITY.md)
                    self._send(200, outer.metrics_text(),
                               "text/plain; version=0.0.4; charset=utf-8")
                elif path == "/serving":
                    # continuous-batching server health (serving/ tier:
                    # queue depth, slots, pool blocks, TTFT/TPOT, sheds
                    # — docs/SERVING.md + OBSERVABILITY.md "Serving")
                    self._send(200, outer._serving_html())
                elif path == "/events":
                    # control-plane flight recorder (monitor/flightrec.py):
                    # publishes, swaps, drains, autoscales, drift trips —
                    # ?kind= filters, ?last= bounds, ?format=json for
                    # machine consumers
                    if q.get("format", [""])[0] == "json":
                        self._send(200, outer._events_json(q),
                                   "application/json")
                    else:
                        self._send(200, outer._events_html(q))
                elif path == "/alerts":
                    # declarative alert states (monitor/alerts.py):
                    # the attached AlertEngine's pending/firing/resolved
                    # view — ?format=json for machine consumers
                    if q.get("format", [""])[0] == "json":
                        self._send(200, outer._alerts_json(),
                                   "application/json")
                    else:
                        self._send(200, outer._alerts_html())
                elif path == "/profile":
                    # AOT cost tables + roofline (benchtools/hlo_cost.py
                    # publishes; committed PROFILE_*/cost_*.json fill in)
                    self._send(200, outer._profile_html())
                elif path == "/api/profile":
                    from deeplearning4j_tpu.monitor import xprof
                    self._send(200, json.dumps(xprof.cost_reports(scan=True),
                                               default=str),
                               "application/json")
                elif path == "/api/sessions":
                    self._send(200, json.dumps(outer.storage.list_session_ids()),
                               "application/json")
                elif path.startswith("/api/reports/"):
                    sid = path.rsplit("/", 1)[1]
                    reports = outer.storage.get_reports(sid)
                    self._send(200, json.dumps([{
                        "iteration": r.iteration, "score": r.score,
                        "examples_per_sec": r.examples_per_sec,
                        "memory_rss_mb": r.memory_rss_mb,
                    } for r in reports]), "application/json")
                elif path.startswith("/api/components/"):
                    # declarative-component JSON for custom frontends
                    sid = path.rsplit("/", 1)[1]
                    chart = outer._score_chart(sid)
                    self._send(200, chart.to_json(), "application/json")
                else:
                    self._send(404, "not found")

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(n)
                if self.path == "/remote":
                    try:
                        report = StatsReport.decode(body)
                        outer.storage.put_report(report)
                        self._send(200, '{"status":"ok"}', "application/json")
                    except Exception as e:  # noqa: BLE001 — server boundary
                        self._send(400, json.dumps({"error": str(e)}),
                                   "application/json")
                elif self.path == "/tsne/upload":
                    try:
                        d = json.loads(body)
                        outer.post_tsne(d.get("session", "default"),
                                        d["coords"], d.get("labels"))
                        self._send(200, '{"status":"ok"}', "application/json")
                    except Exception as e:  # noqa: BLE001
                        self._send(400, json.dumps({"error": str(e)}),
                                   "application/json")
                else:
                    self._send(404, "not found")

        self._httpd = ThreadingHTTPServer(("127.0.0.1", port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    # --------------------------------------------------------- module data
    def post_tsne(self, session: str, coords, labels=None):
        """t-SNE module upload (reference play `module/tsne/`)."""
        coords = np.asarray(coords, np.float64)
        if coords.ndim != 2 or coords.shape[1] != 2 or coords.shape[0] == 0:
            raise ValueError(f"coords must be non-empty [N, 2], got {coords.shape}")
        with self._module_lock:
            self._tsne[session] = {
                "coords": coords.tolist(),
                "labels": [str(l) for l in labels] if labels is not None else None,
            }
        return self

    def post_activation_grid(self, name: str, grid: np.ndarray):
        """Activations module feed (reference `module/convolutional/`):
        a [H, W] uint8 grid from `activations_to_grid`."""
        from PIL import Image
        buf = io.BytesIO()
        Image.fromarray(np.asarray(grid, np.uint8)).save(buf, format="PNG")
        with self._module_lock:
            self._activations[name] = buf.getvalue()
        return self

    # ------------------------------------------------------------- pages
    def _sessions(self):
        return self.storage.list_session_ids()

    def _qs(self):
        parts = []
        if getattr(self._req, "lang", "en") != "en":
            parts.append(f"lang={self._req.lang}")
        if getattr(self._req, "refresh", 0):
            parts.append(f"refresh={self._req.refresh}")
        return ("?" + "&".join(parts)) if parts else ""

    def _tr(self, key):
        return _tr_i18n(getattr(self._req, "lang", "en"), key)

    def _nav(self, active):
        qs = self._qs()
        pages = [("overview", "/train/overview"), ("model", "/train/model"),
                 ("system", "/train/system"), ("tsne", "/tsne"),
                 ("activations", "/activations"), ("profile", "/profile"),
                 ("serving", "/serving"), ("events", "/events"),
                 ("alerts", "/alerts")]
        links = "".join(
            f'<a href="{url}{qs}" style="margin-right:16px;'
            f'{"font-weight:bold" if p == active else ""}">'
            f'{_html.escape(self._tr(p))}</a>'
            for p, url in pages)
        return f'<div style="padding:8px;border-bottom:1px solid #ddd">{links}</div>'

    def _score_chart(self, sid, reports=None) -> ChartLine:
        if reports is None:
            reports = self.storage.get_reports(sid)
        chart = ChartLine(title=f"{self._tr('score')} — {sid}")
        chart.add_series(self._tr("score"), [r.iteration for r in reports],
                         [r.score for r in reports])
        return chart

    def _overview_html(self):
        body = [self._nav("overview")]
        for sid in self._sessions():
            reports = self.storage.get_reports(sid)
            xs = [r.iteration for r in reports]
            body.append(f"<h3>{self._tr('session')} {_html.escape(str(sid))}</h3>")
            body.append(self._score_chart(sid, reports).render())
            if reports and any(r.examples_per_sec for r in reports):
                perf = ChartLine(title=self._tr("throughput"))
                perf.add_series(self._tr("examples_per_sec"), xs,
                                [r.examples_per_sec for r in reports])
                body.append(perf.render())
            # training-health strip — REAL in-graph internals from the
            # diagnostics feed (monitor/diagnostics.py): mean gradient
            # magnitude across params, mean update:param ratio, and the
            # watchdog's non-finite step count
            grad_reports = [r for r in reports
                            if getattr(r, "gradient_mean_magnitudes", None)]
            if grad_reports:
                health = ChartLine(title=self._tr("health"))
                health.add_series(
                    self._tr("grad_norm"),
                    [r.iteration for r in grad_reports],
                    [float(np.mean(list(r.gradient_mean_magnitudes
                                        .values())))
                     for r in grad_reports])
                # ratios of 0 (frozen layers, zero-grad biases) have no
                # log — average only the positive ones, and emit a
                # point only where one exists (a NaN coordinate would
                # poison the whole chart's axis bounds)
                ratio_pts = []
                for r in grad_reports:
                    pos = [math.log10(v)
                           for v in getattr(r, "update_ratios",
                                            {}).values() if v > 0]
                    if pos:
                        ratio_pts.append((r.iteration,
                                          float(np.mean(pos))))
                if ratio_pts:
                    health.add_series(
                        self._tr("update_ratio"),
                        [p[0] for p in ratio_pts],
                        [p[1] for p in ratio_pts])
                body.append(health.render())
            wd = [r for r in reports
                  if getattr(r, "watchdog_nonfinite", 0)]
            if wd:
                wchart = ChartLine(title=self._tr("watchdog"))
                wchart.add_series(self._tr("watchdog"),
                                  [r.iteration for r in wd],
                                  [float(r.watchdog_nonfinite)
                                   for r in wd])
                body.append(wchart.render())
            act_latest = next(
                (r for r in reversed(reports)
                 if getattr(r, "activation_stats", None)), None)
            if act_latest is not None:
                body.append(ComponentTable(
                    [self._tr("act_layer"), self._tr("act_mean"),
                     self._tr("act_std"), self._tr("act_dead")],
                    [(k, f"{m:.4g}", f"{s:.4g}", f"{d:.3f}")
                     for k, (m, s, d)
                     in sorted(act_latest.activation_stats.items())],
                    title=self._tr("act_stats")).render())
        stream = self._streaming_rows()
        if stream:
            body.append(ComponentTable(
                [self._tr("stream_source"), self._tr("stream_records"),
                 self._tr("stream_lag"), self._tr("stream_age"),
                 self._tr("stream_publishes"), self._tr("stream_paused")],
                stream, title=self._tr("stream_health")).render())
        if len(body) == 1:
            body.append(f"<p>{self._tr('no_sessions')}</p>")
        return self._page(self._tr("title.overview"), "".join(body))

    def _streaming_rows(self):
        """Online-training staleness rows for the overview, read from
        the live monitor registry (the same `streaming_*`/`online_*`
        families `/metrics` exports; docs/OBSERVABILITY.md "Streaming /
        online training"). Row kinds are SEPARATE — one per stream
        topic (records consumed, consumer lag, watermark age), one per
        published model (publish count), one per drift-gate tag (gate
        state) — because the registry knows no topic↔model↔tag
        mapping, and smearing a global sum / any-paused flag across
        topic rows would misattribute state the moment two streams are
        live."""
        from deeplearning4j_tpu import monitor
        snap = (self._registry or monitor.registry()).snapshot()

        def by_label(fam, label):
            out = {}
            for e in (snap.get(fam) or {}).get("values", []):
                key = e.get("labels", {}).get(label)
                if key is not None:
                    out[key] = e.get("value")
            return out

        records = by_label("streaming_records_consumed_total", "topic")
        if not records:
            return []
        lag = by_label("streaming_lag_records", "topic")
        age = by_label("streaming_watermark_age_seconds", "topic")
        pubs = by_label("online_publishes_total", "model")
        paused = by_label("online_publish_paused", "tag")

        def fmt(v, suffix=""):
            if v is None or (isinstance(v, float) and v != v):
                return "—"
            if isinstance(v, float) and v.is_integer():
                v = int(v)
            return (f"{v:.1f}{suffix}" if isinstance(v, float)
                    else f"{v}{suffix}")

        rows = [(topic, fmt(records.get(topic)), fmt(lag.get(topic)),
                 fmt(age.get(topic), "s"), "—", "—")
                for topic in sorted(records)]
        rows.extend((f"{self._tr('stream_model')} {model}", "—", "—",
                     "—", fmt(pubs.get(model)), "—")
                    for model in sorted(pubs))
        rows.extend((f"{self._tr('stream_gate')} {tag}", "—", "—", "—",
                     "—",
                     self._tr("stream_paused_yes") if paused.get(tag)
                     else self._tr("stream_paused_no"))
                    for tag in sorted(paused))
        return rows

    def _model_html(self):
        """Per-layer drill-down: mean-magnitude timelines for params and
        updates + latest histograms (reference TrainModule model view)."""
        body = [self._nav("model")]
        for sid in self._sessions():
            reports = self.storage.get_reports(sid)
            latest = self.storage.latest_report(sid)
            if latest is None:
                continue
            body.append(f"<h3>{self._tr('session')} {_html.escape(str(sid))}</h3>")
            xs = [r.iteration for r in reports]
            by_layer: Dict[str, List[str]] = {}
            for key in latest.param_mean_magnitudes:
                lk = key.split("_", 1)[0]
                by_layer.setdefault(lk, []).append(key)
            for lk in sorted(by_layer, key=str):
                chart = ChartLine(title=f"layer {lk} — {self._tr('mean_param')}")
                for key in sorted(by_layer[lk]):
                    chart.add_series(
                        key, xs,
                        [r.param_mean_magnitudes.get(key, 0.0)
                         for r in reports])
                upd_keys = [k for k in latest.update_mean_magnitudes
                            if k.split("_", 1)[0] == lk]
                for key in sorted(upd_keys):
                    chart.add_series(
                        f"Δ{key}", xs,
                        [r.update_mean_magnitudes.get(key, 0.0)
                         for r in reports])
                body.append(chart.render())
                # update:param ratio — THE canonical training-health
                # diagnostic (reference TrainModule "Update:Parameter
                # Ratios" chart; healthy training sits around 1e-3)
                ratio_keys = [k for k in sorted(by_layer[lk])
                              if k in latest.update_mean_magnitudes]
                if ratio_keys:
                    rchart = ChartLine(
                        title=f"layer {lk} — {self._tr('update_ratio')}")
                    for key in ratio_keys:
                        ys = []
                        for r in reports:
                            u = r.update_mean_magnitudes.get(key, 0.0)
                            pm = r.param_mean_magnitudes.get(key, 0.0)
                            ys.append(math.log10(u / pm)
                                      if u > 0 and pm > 0 else float("nan"))
                        pts = [(x, y) for x, y in zip(xs, ys)
                               if y == y]  # drop NaN (no update yet)
                        if pts:
                            rchart.add_series(key, [p_[0] for p_ in pts],
                                              [p_[1] for p_ in pts])
                    if rchart.series:
                        body.append(rchart.render())
                for key in sorted(by_layer[lk]):
                    hist = latest.param_histograms.get(key)
                    if hist:
                        edges, counts = hist
                        h = ChartHistogram(title=f"{key} {self._tr('distribution')}")
                        for lo, hi, c in zip(edges[:-1], edges[1:], counts):
                            h.add_bin(lo, hi, c)
                        body.append(h.render())
            body.append(ComponentTable(
                [self._tr("param"), self._tr("mean_value")],
                [(k, f"{v:.6g}")
                 for k, v in sorted(latest.param_mean_magnitudes.items())],
                title=self._tr("latest_magnitudes")).render())
        if len(body) == 1:
            body.append(f"<p>{self._tr('no_model_stats')}</p>")
        return self._page(self._tr("title.model"), "".join(body))

    def _system_html(self):
        body = [self._nav("system")]
        for sid in self._sessions():
            reports = self.storage.get_reports(sid)
            if not reports:
                continue
            xs = [r.iteration for r in reports]
            body.append(f"<h3>{self._tr('session')} {_html.escape(str(sid))}</h3>")
            mem = ChartLine(title=self._tr("memory"))
            mem.add_series("RSS MB", xs, [r.memory_rss_mb for r in reports])
            body.append(mem.render())
            t = ChartLine(title=self._tr("iteration_time"))
            t.add_series("ms/iter", xs,
                         [r.iteration_time_ms for r in reports])
            body.append(t.render())
        return self._page(self._tr("title.system"), "".join(body))

    def _serving_html(self):
        """Serving health from the live metrics registry (the same
        families /metrics exports — one source of truth, rendered
        instead of scraped): one row PER FLEET MODEL (name, version,
        queue depth, active slots, shed count — the `fleet_*` labeled
        families), then the single-server engine snapshot for the
        non-fleet `GenerationServer` case."""
        from deeplearning4j_tpu import monitor

        body = [self._nav("serving")]
        snap = (self._registry or monitor.registry()).snapshot()

        def by_model(fam):
            out = {}
            for e in (snap.get(fam) or {}).get("values", []):
                model = e.get("labels", {}).get("model")
                if model is not None:
                    out[model] = e.get("value")
            return out

        fleet_rows = {}
        for fam, col in (("fleet_model_version", "version"),
                         ("fleet_queue_depth", "queue depth"),
                         ("fleet_active_slots", "active slots"),
                         ("fleet_slot_count", "slots"),
                         ("fleet_open_streams", "open streams"),
                         ("fleet_pool_blocks_used", "pool used"),
                         ("fleet_pool_blocks_free", "pool free"),
                         ("fleet_streams_total", "streams"),
                         ("fleet_shed_total", "shed"),
                         ("fleet_swaps_total", "swaps")):
            for model, v in by_model(fam).items():
                if isinstance(v, float) and v.is_integer():
                    v = int(v)
                fleet_rows.setdefault(model, {})[col] = v
        # version 0 marks a RETIRED model (the fleet zeroes an
        # undeployed model's gauges; the registry can't remove label
        # children) — don't render it as a live row
        fleet_rows = {name: row for name, row in fleet_rows.items()
                      if row.get("version", 0) != 0}
        if fleet_rows:
            cols = ["model", "version", "queue depth", "active slots",
                    "slots", "open streams", "pool used", "pool free",
                    "streams", "shed", "swaps"]
            body.append("<h3>fleet</h3>")
            body.append("<table border='1' cellpadding='4'><tr>")
            body.extend(f"<th>{_html.escape(c)}</th>" for c in cols)
            body.append("</tr>")
            for model in sorted(fleet_rows):
                row = fleet_rows[model]
                body.append("<tr><td>" + _html.escape(model) + "</td>")
                body.extend(
                    f"<td>{_html.escape(str(row.get(c, 0)))}</td>"
                    for c in cols[1:])
                body.append("</tr>")
            body.append("</table>")
            reg_pub = snap.get("registry_published_total")
            if reg_pub and reg_pub.get("values"):
                published = sum(e.get("value", 0)
                                for e in reg_pub["values"])
                body.append(f"<p>registry: "
                            f"{int(published)} versions published</p>")

        def val(name, default="–"):
            fam = snap.get(name)
            if not fam or not fam.get("values"):
                return default
            v = fam["values"][0].get("value", default)
            if isinstance(v, float) and v.is_integer():
                return int(v)
            return v

        def hist(name):
            fam = snap.get(name)
            if not fam or not fam.get("values"):
                return "–"
            e = fam["values"][0]
            n = e.get("count", 0)
            if not n:
                return "–"
            return f"{1e3 * e['sum'] / n:.1f} ms avg over {n}"

        # pool occupancy: used/free from the allocator-view gauges
        # (incremental block grants — docs/SERVING.md)
        used, free = (val("serving_pool_blocks_used"),
                      val("serving_pool_blocks_free"))
        if isinstance(used, int) and isinstance(free, int) and used + free:
            occupancy = (f"{used} used / {free} free "
                         f"({100.0 * used / (used + free):.0f}%)")
        else:
            occupancy = "–"
        def lval(name, default="–", **labels):
            # label-selected series (e.g. per-proposer accept rate —
            # `val` reads values[0], wrong once a family has children)
            fam = snap.get(name)
            for e in (fam or {}).get("values", []):
                if all(e.get("labels", {}).get(k) == v
                       for k, v in labels.items()):
                    v = e.get("value", default)
                    if isinstance(v, float) and not v.is_integer():
                        return f"{v:.3f}"
                    return v
            return default

        rows = [
            ("queue depth", val("serving_queue_depth")),
            ("active slots", val("serving_active_slots")),
            ("free pool blocks", val("serving_free_blocks")),
            ("pool occupancy", occupancy),
            ("blocks granted", val("serving_block_grants_total", 0)),
            ("preempt-requeues", val("serving_evict_requeue_total", 0)),
            ("requests admitted", val("serving_requests_total", 0)),
            ("tokens emitted", val("serving_tokens_total", 0)),
            ("requests shed (SLO)", val("serving_shed_total", 0)),
            ("evicted mid-stream", val("serving_evicted_total", 0)),
            ("radix-cache nodes", val("serving_radix_nodes", 0)),
            ("radix hit tokens", val("serving_radix_hit_tokens_total", 0)),
            ("radix evictions", val("serving_radix_evictions_total", 0)),
            ("spec accept (ngram)",
             lval("serving_spec_accept_rate", proposer="ngram")),
            ("spec accept (truncated)",
             lval("serving_spec_accept_rate", proposer="truncated")),
            ("TTFT", hist("serving_ttft_seconds")),
            ("per-token (TPOT)", hist("serving_tpot_seconds")),
            ("decode dispatch", hist("serving_step_seconds")),
        ]
        if "serving_requests_total" not in snap:
            body.append("<p>no generation server has reported yet — "
                        "start a <code>GenerationServer</code> with "
                        "monitoring enabled</p>")
        body.append("<table border='1' cellpadding='4'>")
        for k, v in rows:
            body.append(f"<tr><td>{_html.escape(k)}</td>"
                        f"<td>{_html.escape(str(v))}</td></tr>")
        body.append("</table>")
        return self._page("serving", "".join(body))

    def _events_query(self, q):
        kind = q.get("kind", [None])[0] or None
        try:
            last = int(q.get("last", ["200"])[0])
        except ValueError:
            last = 200
        from deeplearning4j_tpu.monitor.flightrec import flight_recorder
        rec = flight_recorder()
        return rec, rec.events(kind=kind, last=max(1, last))

    def _events_json(self, q):
        rec, evs = self._events_query(q)
        return json.dumps({"dropped": rec.dropped, "events": evs},
                          default=str)

    def _events_html(self, q):
        """Flight-recorder view (monitor/flightrec.py): the ordered
        control-plane event log — publish/swap/drain/autoscale/
        drift-trip/elastic/watchdog/shed-burst — newest last, the first
        thing an incident review reads (docs/OBSERVABILITY.md "Flight
        recorder")."""
        import time as _time
        rec, evs = self._events_query(q)
        body = [self._nav("events")]
        if rec.dropped:
            body.append(f"<p>{rec.dropped} older events evicted from "
                        f"the ring</p>")
        if not evs:
            body.append(f"<p>{self._tr('no_events')}</p>")
        else:
            body.append("<table border='1' cellpadding='4'>"
                        "<tr><th>seq</th><th>time</th><th>kind</th>"
                        "<th>details</th></tr>")
            for e in evs:
                detail = {k: v for k, v in e.items()
                          if k not in ("ts", "seq", "kind")}
                when = _time.strftime("%H:%M:%S",
                                      _time.localtime(e["ts"]))
                body.append(
                    f"<tr><td>{int(e['seq'])}</td>"
                    f"<td>{when}</td>"
                    f"<td>{_html.escape(str(e['kind']))}</td>"
                    f"<td><code>{_html.escape(json.dumps(detail, default=str))}"
                    f"</code></td></tr>")
            body.append("</table>")
        return self._page(self._tr("title.events"), "".join(body))

    def _alerts_json(self):
        eng = self._alerts
        states = eng.states() if eng is not None else []
        return json.dumps({"attached": eng is not None,
                           "alerts": states}, default=str)

    def _alerts_html(self):
        """Alert-engine view (monitor/alerts.py): every rule's current
        pending/firing/ok state, most urgent first — the codified
        "Default rule pack" table from docs/OBSERVABILITY.md, live."""
        body = [self._nav("alerts")]
        eng = self._alerts
        states = eng.states() if eng is not None else []
        if not states:
            body.append(f"<p>{self._tr('no_alerts')}</p>")
        else:
            colors = {"firing": "#c62828", "pending": "#ef6c00",
                      "ok": "#2e7d32"}
            body.append("<table border='1' cellpadding='4'>"
                        f"<tr><th>{self._tr('alert_rule')}</th>"
                        f"<th>{self._tr('alert_state')}</th>"
                        f"<th>{self._tr('alert_severity')}</th>"
                        f"<th>{self._tr('alert_value')}</th>"
                        f"<th>{self._tr('alert_desc')}</th></tr>")
            for s in states:
                state = str(s["state"])
                val = s.get("value")
                val = "-" if val is None else f"{float(val):.4g}"
                body.append(
                    f"<tr><td><code>{_html.escape(s['name'])}</code></td>"
                    f"<td style='color:{colors.get(state, '#000')};"
                    f"font-weight:bold'>"
                    f"{_html.escape(self._tr('alert_' + state))}</td>"
                    f"<td>{_html.escape(str(s['severity']))}</td>"
                    f"<td>{val}</td>"
                    f"<td>{_html.escape(str(s.get('description') or ''))}"
                    f"</td></tr>")
            body.append("</table>")
        return self._page(self._tr("title.alerts"), "".join(body))

    def _tsne_html(self):
        body = [self._nav("tsne")]
        with self._module_lock:
            tsne = dict(self._tsne)
        for session, d in tsne.items():
            coords = np.asarray(d["coords"])
            chart = ChartScatter(title=f"t-SNE — {session}")
            chart.add_series("points", coords[:, 0].tolist(),
                             coords[:, 1].tolist(), d.get("labels"))
            chart.style.width, chart.style.height = 720, 540
            body.append(chart.render())
        if len(body) == 1:
            body.append("<p>No t-SNE coordinates uploaded. POST JSON "
                        '{"coords": [[x,y],...], "labels": [...]} '
                        "to /tsne/upload.</p>")
        return self._page(self._tr("title.tsne"), "".join(body))

    def _activations_html(self):
        body = [self._nav("activations")]
        with self._module_lock:
            grids = sorted(self._activations.items())
        for name, png in grids:
            b64 = base64.b64encode(png).decode()
            name = _html.escape(name)
            body.append(f"<h4>{name}</h4>"
                        f'<img src="data:image/png;base64,{b64}" '
                        f'style="image-rendering:pixelated;min-width:160px"/>')
        if len(body) == 1:
            body.append("<p>No activation grids posted yet.</p>")
        return self._page(self._tr("title.activations"), "".join(body))

    def _profile_html(self):
        """AOT cost / roofline page: one section per cost report
        (in-process published first, committed ``PROFILE_*/cost_*.json``
        artifacts as fallback — see docs/OBSERVABILITY.md)."""
        from deeplearning4j_tpu.monitor import xprof
        reports = xprof.cost_reports(scan=True)
        body = [self._nav("profile")]
        for model in sorted(reports):
            rep = reports[model]
            per_op = rep.get("per_op", {}) or {}
            roof = rep.get("roofline", {}) or {}
            pred = rep.get("predicted", {}) or {}
            meas = rep.get("measured", {}) or {}
            body.append(f"<h3>{_html.escape(str(model))}</h3>")

            def fmt(v, scale=1.0, nd=3):
                return (f"{v * scale:.{nd}g}"
                        if isinstance(v, (int, float)) else "—")
            rows = [
                ("FLOPs / step", fmt(per_op.get("total_flops_per_step"))),
                ("conv+dot FLOPs / step (MFU numerator)",
                 fmt(per_op.get("conv_dot_flops_per_step"))),
                ("bytes / step (unfused upper bound)",
                 fmt(per_op.get("total_bytes_per_step"))),
                ("arithmetic intensity (FLOP/byte)",
                 fmt(roof.get("arithmetic_intensity_flop_per_byte"))),
                ("binding ceiling", str(roof.get("bound", "—"))),
                ("predicted step time (ms)",
                 fmt(pred.get("step_seconds"), 1e3, 4)),
                ("predicted MFU (lower bound)", fmt(pred.get("mfu"))),
                ("MFU if compute-bound (upper bound)",
                 fmt(pred.get("mfu_if_compute_bound"))),
                ("peak (TFLOP/s)", fmt(roof.get("peak_tflops"))
                 + f" [{_html.escape(str(roof.get('peak_source', '?')))}]"),
            ]
            if meas:
                rows.append(("measured throughput",
                             fmt(meas.get("throughput")) + " "
                             + _html.escape(str(meas.get("unit", "")))))
                rows.append(("predicted / measured step time",
                             fmt(meas.get(
                                 "predicted_over_measured_step_time"))))
            body.append(ComponentTable(
                ["quantity", "value"], [(k, v) for k, v in rows],
                title=f"{model} — {self._tr('profile.summary')}").render())
            top = per_op.get("top10") or []
            if top:
                body.append(ComponentTable(
                    ["op", "shape", "FLOPs/step", "bytes/step", "share"],
                    [(str(s.get("op")), str(s.get("shape", ""))[:80],
                      fmt(s.get("flops")), fmt(s.get("bytes")),
                      fmt(s.get("share")))
                     for s in top],
                    title=f"{model} — {self._tr('profile.top_ops')}").render())
        if len(body) == 1:
            body.append("<p>No AOT cost reports yet — run "
                        "<code>python -m benchtools.hlo_cost --all</code> "
                        "(device-free) or commit PROFILE_*/cost_*.json "
                        "artifacts.</p>")
        return self._page(self._tr("title.profile"), "".join(body))

    def _page(self, title, body):
        refresh = getattr(self._req, "refresh", 0)
        meta = (f'<meta http-equiv="refresh" content="{refresh}">'
                if refresh else "")
        return (f"<!doctype html><html><head><title>{title}</title>{meta}"
                f"</head>"
                f"<body style='font-family:sans-serif'>{body}</body></html>")

    # --------------------------------------------------------------- api
    @classmethod
    def get_instance(cls, port: int = 0) -> "UIServer":
        if cls._instance is None:
            cls._instance = cls(port).start()
        return cls._instance

    def attach(self, storage: StatsStorage):
        self.storage = storage
        return self

    def attach_alerts(self, engine):
        """Serve `/alerts` from this `monitor.alerts.AlertEngine` (the
        states it also publishes as `alert_state` gauges on whatever
        registry it was given)."""
        self._alerts = engine
        return self

    def attach_registry(self, registry):
        """Serve `/metrics` from this MetricsRegistry — or a federation
        `MetricsAggregator` (monitor/federate.py), turning this UI into
        the fleet-wide scrape endpoint — instead of the process-global
        registry."""
        self._registry = registry
        return self

    def metrics_text(self) -> str:
        from deeplearning4j_tpu import monitor
        reg = self._registry if self._registry is not None \
            else monitor.registry()
        # refresh lazy device gauges right before the scrape, into the
        # registry actually being served (no-op on backends without
        # memory_stats, when monitoring is off, and when the source is
        # a federation MetricsAggregator — a merged read-only view with
        # no gauge() to refresh into)
        if monitor.is_enabled() and hasattr(reg, "gauge"):
            mc = monitor.memory_collector()
            if mc is None or mc.registry is not reg:
                mc = monitor.DeviceMemoryCollector(reg)
            mc.collect()
        return reg.exposition()

    def start(self):
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()
        if UIServer._instance is self:
            UIServer._instance = None


def main(argv=None):
    """`dl4j-tpu-ui` console entry (reference: PlayUIServer's JCommander
    CLI, `ui/play/PlayUIServer.java`): standalone dashboard process;
    training processes push stats to its /remote route."""
    import argparse
    import time

    ap = argparse.ArgumentParser(prog="dl4j-tpu-ui")
    ap.add_argument("--port", type=int, default=9000)
    args = ap.parse_args(argv)
    server = UIServer(args.port).start()
    print(f"dl4j-tpu UI listening on http://127.0.0.1:{server.port} "
          f"(POST stats to /remote)")
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        server.stop()


if __name__ == "__main__":
    main()
