"""Training UI web server.

Reference: `play/PlayUIServer.java` (embedded Play/Netty server) with
`module/train/TrainModule.java` routes `/train/overview|model|system`.
Here: stdlib ThreadingHTTPServer (the embedded-server role), same
routes serving a self-contained HTML dashboard (inline SVG charts, no
external assets) plus JSON APIs and the /remote receiver endpoint
(reference `RemoteReceiverModule`).
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from deeplearning4j_tpu.ui.stats import StatsReport
from deeplearning4j_tpu.ui.storage import InMemoryStatsStorage, StatsStorage


def _svg_line_chart(xs, ys, width=640, height=240, label="score"):
    if not xs:
        return "<svg/>"
    xmin, xmax = min(xs), max(xs) or 1
    ymin, ymax = min(ys), max(ys)
    if ymax == ymin:
        ymax = ymin + 1
    pts = []
    for x, y in zip(xs, ys):
        px = 40 + (x - xmin) / max(xmax - xmin, 1e-9) * (width - 60)
        py = height - 30 - (y - ymin) / (ymax - ymin) * (height - 50)
        pts.append(f"{px:.1f},{py:.1f}")
    return (f'<svg width="{width}" height="{height}">'
            f'<rect width="{width}" height="{height}" fill="#fafafa"/>'
            f'<polyline fill="none" stroke="#2a6fdb" stroke-width="1.5" '
            f'points="{" ".join(pts)}"/>'
            f'<text x="45" y="18" font-size="12">{label} '
            f'(last: {ys[-1]:.5g})</text></svg>')


class UIServer:
    """`UIServer.getInstance().attach(storage)` equivalent."""

    _instance: Optional["UIServer"] = None

    def __init__(self, port: int = 0):
        self.storage: StatsStorage = InMemoryStatsStorage()
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _send(self, code, body, ctype="text/html"):
                if isinstance(body, str):
                    body = body.encode()
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                path = self.path.split("?")[0]
                if path in ("/", "/train", "/train/overview"):
                    self._send(200, outer._overview_html())
                elif path == "/train/model":
                    self._send(200, outer._model_html())
                elif path == "/train/system":
                    self._send(200, outer._system_html())
                elif path == "/api/sessions":
                    self._send(200, json.dumps(outer.storage.list_session_ids()),
                               "application/json")
                elif path.startswith("/api/reports/"):
                    sid = path.rsplit("/", 1)[1]
                    reports = outer.storage.get_reports(sid)
                    self._send(200, json.dumps([{
                        "iteration": r.iteration, "score": r.score,
                        "examples_per_sec": r.examples_per_sec,
                        "memory_rss_mb": r.memory_rss_mb,
                    } for r in reports]), "application/json")
                else:
                    self._send(404, "not found")

            def do_POST(self):
                if self.path == "/remote":
                    n = int(self.headers.get("Content-Length", 0))
                    try:
                        report = StatsReport.decode(self.rfile.read(n))
                        outer.storage.put_report(report)
                        self._send(200, '{"status":"ok"}', "application/json")
                    except Exception as e:  # noqa: BLE001 — server boundary
                        self._send(400, json.dumps({"error": str(e)}),
                                   "application/json")
                else:
                    self._send(404, "not found")

        self._httpd = ThreadingHTTPServer(("127.0.0.1", port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------- pages
    def _sessions(self):
        return self.storage.list_session_ids()

    def _nav(self, active):
        links = "".join(
            f'<a href="/train/{p}" style="margin-right:16px;'
            f'{"font-weight:bold" if p == active else ""}">{p.title()}</a>'
            for p in ("overview", "model", "system"))
        return f'<div style="padding:8px;border-bottom:1px solid #ddd">{links}</div>'

    def _overview_html(self):
        body = [self._nav("overview")]
        for sid in self._sessions():
            reports = self.storage.get_reports(sid)
            xs = [r.iteration for r in reports]
            ys = [r.score for r in reports]
            body.append(f"<h3>Session {sid}</h3>")
            body.append(_svg_line_chart(xs, ys, label="score"))
            if reports and reports[-1].examples_per_sec:
                body.append(_svg_line_chart(
                    xs, [r.examples_per_sec for r in reports],
                    label="examples/sec"))
        if len(body) == 1:
            body.append("<p>No training sessions attached yet.</p>")
        return self._page("Training Overview", "".join(body))

    def _model_html(self):
        body = [self._nav("model")]
        for sid in self._sessions():
            latest = self.storage.latest_report(sid)
            if latest is None:
                continue
            body.append(f"<h3>Session {sid} — mean |param| by layer</h3><table border=1 cellpadding=4>")
            body.append("<tr><th>param</th><th>mean magnitude</th></tr>")
            for k, v in sorted(latest.param_mean_magnitudes.items()):
                body.append(f"<tr><td>{k}</td><td>{v:.6g}</td></tr>")
            body.append("</table>")
        return self._page("Model", "".join(body))

    def _system_html(self):
        body = [self._nav("system")]
        for sid in self._sessions():
            reports = self.storage.get_reports(sid)
            if not reports:
                continue
            body.append(f"<h3>Session {sid}</h3>")
            body.append(_svg_line_chart([r.iteration for r in reports],
                                        [r.memory_rss_mb for r in reports],
                                        label="RSS MB"))
        return self._page("System", "".join(body))

    @staticmethod
    def _page(title, body):
        return (f"<!doctype html><html><head><title>{title}</title></head>"
                f"<body style='font-family:sans-serif'>{body}</body></html>")

    # --------------------------------------------------------------- api
    @classmethod
    def get_instance(cls, port: int = 0) -> "UIServer":
        if cls._instance is None:
            cls._instance = cls(port).start()
        return cls._instance

    def attach(self, storage: StatsStorage):
        self.storage = storage
        return self

    def start(self):
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()
        if UIServer._instance is self:
            UIServer._instance = None
