"""Training stats collection.

Reference: `ui/stats/BaseStatsListener.java:44` — per-iteration
collection (`iterationDone` :286-544) of score, param/gradient/update
histograms and mean magnitudes, memory and runtime info, written as a
`StatsReport` to a `StatsStorageRouter`. The reference's SBE codecs
(`stats/sbe/UpdateEncoder.java`) become a compact struct-packed binary
codec here (same role: a stable, versioned wire format the UI and
storage share).
"""

from __future__ import annotations

import dataclasses
import resource
import struct
import sys
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

_MAGIC = b"DL4JSTAT"
_VERSION = 1


def _rss_mb() -> float:
    """Peak RSS of this process in MB. `getrusage().ru_maxrss` is
    KILOBYTES on Linux but BYTES on macOS (see getrusage(2) in each) —
    dividing by 1024 unconditionally inflated mac numbers 1024x."""
    rss = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
    if sys.platform == "darwin":
        return rss / (1024.0 * 1024.0)
    return rss / 1024.0


@dataclasses.dataclass
class StatsReport:
    session_id: str
    worker_id: str
    iteration: int
    epoch: int
    timestamp: float
    score: float
    iteration_time_ms: float = 0.0
    examples_per_sec: float = 0.0
    # per-param-name summaries
    param_mean_magnitudes: Dict[str, float] = dataclasses.field(default_factory=dict)
    update_mean_magnitudes: Dict[str, float] = dataclasses.field(default_factory=dict)
    param_histograms: Dict[str, Tuple[List[float], List[int]]] = \
        dataclasses.field(default_factory=dict)
    # system
    memory_rss_mb: float = 0.0

    # ------------------------------------------------- binary wire format
    def encode(self) -> bytes:
        """Compact binary encoding (SBE-equivalent role)."""
        def pack_str(s: str) -> bytes:
            b = s.encode("utf-8")
            return struct.pack("<H", len(b)) + b

        out = [_MAGIC, struct.pack("<H", _VERSION)]
        out.append(pack_str(self.session_id))
        out.append(pack_str(self.worker_id))
        out.append(struct.pack("<qqdddd", self.iteration, self.epoch,
                               self.timestamp, self.score,
                               self.iteration_time_ms, self.examples_per_sec))
        out.append(struct.pack("<d", self.memory_rss_mb))
        for table in (self.param_mean_magnitudes, self.update_mean_magnitudes):
            out.append(struct.pack("<H", len(table)))
            for k, v in table.items():
                out.append(pack_str(k))
                out.append(struct.pack("<d", v))
        out.append(struct.pack("<H", len(self.param_histograms)))
        for k, (edges, counts) in self.param_histograms.items():
            out.append(pack_str(k))
            out.append(struct.pack("<H", len(counts)))
            out.append(np.asarray(edges, np.float64).tobytes())
            out.append(np.asarray(counts, np.int64).tobytes())
        return b"".join(out)

    @staticmethod
    def decode(data: bytes) -> "StatsReport":
        if data[:8] != _MAGIC:
            raise ValueError("Not a DL4JSTAT payload (bad magic)")
        pos = [10]

        def unpack_str() -> str:
            (n,) = struct.unpack_from("<H", data, pos[0])
            pos[0] += 2
            s = data[pos[0]:pos[0] + n].decode("utf-8")
            pos[0] += n
            return s

        session_id = unpack_str()
        worker_id = unpack_str()
        it, ep, ts, score, itms, eps = struct.unpack_from("<qqdddd", data, pos[0])
        pos[0] += struct.calcsize("<qqdddd")
        (rss,) = struct.unpack_from("<d", data, pos[0])
        pos[0] += 8
        tables = []
        for _ in range(2):
            (n,) = struct.unpack_from("<H", data, pos[0])
            pos[0] += 2
            t = {}
            for _ in range(n):
                k = unpack_str()
                (v,) = struct.unpack_from("<d", data, pos[0])
                pos[0] += 8
                t[k] = v
            tables.append(t)
        (nh,) = struct.unpack_from("<H", data, pos[0])
        pos[0] += 2
        hists = {}
        for _ in range(nh):
            k = unpack_str()
            (nb,) = struct.unpack_from("<H", data, pos[0])
            pos[0] += 2
            edges = np.frombuffer(data, np.float64, nb + 1, pos[0]).tolist()
            pos[0] += 8 * (nb + 1)
            counts = np.frombuffer(data, np.int64, nb, pos[0]).tolist()
            pos[0] += 8 * nb
            hists[k] = (edges, counts)
        return StatsReport(session_id, worker_id, it, ep, ts, score,
                           itms, eps, tables[0], tables[1], hists, rss)


class StatsListener:
    """Reference `StatsListener` — collect + route to a StatsStorage.

    `update_frequency`: collect every N iterations (reference
    listenerFrequency). Histograms are optional (more device→host
    traffic)."""

    def __init__(self, storage, session_id: str = "default",
                 worker_id: str = "worker0", update_frequency: int = 1,
                 collect_histograms: bool = False, histogram_bins: int = 20):
        self.storage = storage
        self.session_id = session_id
        self.worker_id = worker_id
        self.update_frequency = max(1, update_frequency)
        self.collect_histograms = collect_histograms
        self.histogram_bins = histogram_bins
        self._last_time = None
        self._prev_params: Dict[str, np.ndarray] = {}

    # TrainingListener protocol
    def on_fit_start(self, model):
        self._last_time = time.perf_counter()

    def iteration_done(self, model, iteration, epoch, score, **info):
        if iteration % self.update_frequency != 0:
            return
        now = time.perf_counter()
        dt_ms = 0.0 if self._last_time is None else (now - self._last_time) * 1e3
        self._last_time = now
        batch = info.get("batch_size", 0)
        report = StatsReport(
            session_id=self.session_id, worker_id=self.worker_id,
            iteration=iteration, epoch=epoch, timestamp=time.time(),
            score=float(score), iteration_time_ms=dt_ms,
            examples_per_sec=(batch / (dt_ms / 1e3) if dt_ms > 0 and batch else 0.0),
            memory_rss_mb=_rss_mb(),
        )
        for lk, lparams in model.params.items():
            for pn, arr in lparams.items():
                a = np.asarray(arr)
                key = f"{lk}_{pn}"
                report.param_mean_magnitudes[key] = float(np.mean(np.abs(a)))
                prev = self._prev_params.get(key)
                if prev is not None and prev.shape == a.shape:
                    # update magnitude = |param delta| since last report
                    # (reference BaseStatsListener update stats)
                    report.update_mean_magnitudes[key] = float(
                        np.mean(np.abs(a - prev)))
                self._prev_params[key] = a
                if self.collect_histograms:
                    counts, edges = np.histogram(a, bins=self.histogram_bins)
                    report.param_histograms[key] = (edges.tolist(),
                                                    counts.tolist())
        self.storage.put_report(report)

    def on_epoch_start(self, model, epoch):
        pass

    def on_epoch_end(self, model, epoch):
        pass

    def on_fit_end(self, model):
        pass
