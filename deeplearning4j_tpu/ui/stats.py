"""Training stats collection.

Reference: `ui/stats/BaseStatsListener.java:44` — per-iteration
collection (`iterationDone` :286-544) of score, param/gradient/update
histograms and mean magnitudes, memory and runtime info, written as a
`StatsReport` to a `StatsStorageRouter`. The reference's SBE codecs
(`stats/sbe/UpdateEncoder.java`) become a compact struct-packed binary
codec here (same role: a stable, versioned wire format the UI and
storage share).

Since the diagnostics PR, StatsListener consumes the REAL training
internals: when the model runs with diagnostics enabled
(monitor/diagnostics.py), the per-layer gradient/update magnitudes,
update:param ratios and activation stats come from the fused train
step's aux outputs (`model._last_diagnostics` / the
``info["diagnostics"]`` callback payload) — true updates, not
param-delta approximations — and the parameter readback that remains is
ONE batched device→host transfer (`diagnostics.batched_host_tree`)
instead of one per leaf. Models without the diagnostics seam fall back
to the param-delta approximation, exactly as before.

Wire compatibility: the codec is versioned. v1 payloads (pre-
diagnostics) decode unchanged with empty new tables; v2 appends the
gradient/ratio/activation tables and the watchdog counter AFTER the v1
payload, so old decoders reading only their own fields keep working on
a v2 prefix layout-wise identical to v1.
"""

from __future__ import annotations

import dataclasses
import resource
import struct
import sys
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

_MAGIC = b"DL4JSTAT"
_VERSION = 2


def _rss_mb() -> float:
    """Peak RSS of this process in MB. `getrusage().ru_maxrss` is
    KILOBYTES on Linux but BYTES on macOS (see getrusage(2) in each) —
    dividing by 1024 unconditionally inflated mac numbers 1024x."""
    rss = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
    if sys.platform == "darwin":
        return rss / (1024.0 * 1024.0)
    return rss / 1024.0


@dataclasses.dataclass
class StatsReport:
    session_id: str
    worker_id: str
    iteration: int
    epoch: int
    timestamp: float
    score: float
    iteration_time_ms: float = 0.0
    examples_per_sec: float = 0.0
    # per-param-name summaries
    param_mean_magnitudes: Dict[str, float] = dataclasses.field(default_factory=dict)
    update_mean_magnitudes: Dict[str, float] = dataclasses.field(default_factory=dict)
    param_histograms: Dict[str, Tuple[List[float], List[int]]] = \
        dataclasses.field(default_factory=dict)
    # system
    memory_rss_mb: float = 0.0
    # v2 (diagnostics feed): true per-param gradient magnitudes +
    # update:param ratios, per-layer activation stats, watchdog count
    gradient_mean_magnitudes: Dict[str, float] = \
        dataclasses.field(default_factory=dict)
    update_ratios: Dict[str, float] = dataclasses.field(default_factory=dict)
    activation_stats: Dict[str, Tuple[float, float, float]] = \
        dataclasses.field(default_factory=dict)  # (mean, std, dead)
    watchdog_nonfinite: int = 0

    # ------------------------------------------------- binary wire format
    def encode(self) -> bytes:
        """Compact binary encoding (SBE-equivalent role). v2 appends
        the diagnostics tables after the complete v1 payload."""
        def pack_str(s: str) -> bytes:
            b = s.encode("utf-8")
            return struct.pack("<H", len(b)) + b

        out = [_MAGIC, struct.pack("<H", _VERSION)]
        out.append(pack_str(self.session_id))
        out.append(pack_str(self.worker_id))
        out.append(struct.pack("<qqdddd", self.iteration, self.epoch,
                               self.timestamp, self.score,
                               self.iteration_time_ms, self.examples_per_sec))
        out.append(struct.pack("<d", self.memory_rss_mb))
        for table in (self.param_mean_magnitudes, self.update_mean_magnitudes):
            out.append(struct.pack("<H", len(table)))
            for k, v in table.items():
                out.append(pack_str(k))
                out.append(struct.pack("<d", v))
        out.append(struct.pack("<H", len(self.param_histograms)))
        for k, (edges, counts) in self.param_histograms.items():
            out.append(pack_str(k))
            out.append(struct.pack("<H", len(counts)))
            out.append(np.asarray(edges, np.float64).tobytes())
            out.append(np.asarray(counts, np.int64).tobytes())
        # ---- v2 extension block (absent in v1 payloads) ----
        for table in (self.gradient_mean_magnitudes, self.update_ratios):
            out.append(struct.pack("<H", len(table)))
            for k, v in table.items():
                out.append(pack_str(k))
                out.append(struct.pack("<d", v))
        out.append(struct.pack("<H", len(self.activation_stats)))
        for k, (m, s, d) in self.activation_stats.items():
            out.append(pack_str(k))
            out.append(struct.pack("<ddd", m, s, d))
        out.append(struct.pack("<q", self.watchdog_nonfinite))
        return b"".join(out)

    @staticmethod
    def decode(data: bytes) -> "StatsReport":
        if data[:8] != _MAGIC:
            raise ValueError("Not a DL4JSTAT payload (bad magic)")
        (version,) = struct.unpack_from("<H", data, 8)
        pos = [10]

        def unpack_str() -> str:
            (n,) = struct.unpack_from("<H", data, pos[0])
            pos[0] += 2
            s = data[pos[0]:pos[0] + n].decode("utf-8")
            pos[0] += n
            return s

        def unpack_table() -> Dict[str, float]:
            (n,) = struct.unpack_from("<H", data, pos[0])
            pos[0] += 2
            t = {}
            for _ in range(n):
                k = unpack_str()
                (v,) = struct.unpack_from("<d", data, pos[0])
                pos[0] += 8
                t[k] = v
            return t

        session_id = unpack_str()
        worker_id = unpack_str()
        it, ep, ts, score, itms, eps = struct.unpack_from("<qqdddd", data, pos[0])
        pos[0] += struct.calcsize("<qqdddd")
        (rss,) = struct.unpack_from("<d", data, pos[0])
        pos[0] += 8
        tables = [unpack_table(), unpack_table()]
        (nh,) = struct.unpack_from("<H", data, pos[0])
        pos[0] += 2
        hists = {}
        for _ in range(nh):
            k = unpack_str()
            (nb,) = struct.unpack_from("<H", data, pos[0])
            pos[0] += 2
            edges = np.frombuffer(data, np.float64, nb + 1, pos[0]).tolist()
            pos[0] += 8 * (nb + 1)
            counts = np.frombuffer(data, np.int64, nb, pos[0]).tolist()
            pos[0] += 8 * nb
            hists[k] = (edges, counts)
        report = StatsReport(session_id, worker_id, it, ep, ts, score,
                             itms, eps, tables[0], tables[1], hists, rss)
        if version >= 2:
            report.gradient_mean_magnitudes = unpack_table()
            report.update_ratios = unpack_table()
            (na,) = struct.unpack_from("<H", data, pos[0])
            pos[0] += 2
            for _ in range(na):
                k = unpack_str()
                m, s, d = struct.unpack_from("<ddd", data, pos[0])
                pos[0] += 24
                report.activation_stats[k] = (m, s, d)
            (report.watchdog_nonfinite,) = struct.unpack_from(
                "<q", data, pos[0])
            pos[0] += 8
        return report


class StatsListener:
    """Reference `StatsListener` — collect + route to a StatsStorage.

    `update_frequency`: collect every N iterations (reference
    listenerFrequency). Histograms are optional (more device→host
    traffic).

    Data sources, in order of preference:
    - the diagnostics aux (``info["diagnostics"]`` /
      ``model._last_diagnostics``): TRUE per-param gradient/update
      magnitudes, update:param ratios, activation stats, watchdog
      count, and (when the diagnostics config enables them) in-graph
      parameter histograms — zero extra transfers beyond the
      diagnostics readback the fit loop already performed;
    - the model's params, fetched in ONE batched transfer
      (`diagnostics.batched_host_tree`) — used for param magnitudes
      without a diagnostics seam, for host-side histograms, and for
      the param-delta update fallback.
    """

    def __init__(self, storage, session_id: str = "default",
                 worker_id: str = "worker0", update_frequency: int = 1,
                 collect_histograms: bool = False, histogram_bins: int = 20):
        self.storage = storage
        self.session_id = session_id
        self.worker_id = worker_id
        self.update_frequency = max(1, update_frequency)
        self.collect_histograms = collect_histograms
        self.histogram_bins = histogram_bins
        self._last_time = None
        self._prev_params: Dict[str, np.ndarray] = {}

    # TrainingListener protocol
    def on_fit_start(self, model):
        self._last_time = time.perf_counter()

    def iteration_done(self, model, iteration, epoch, score, **info):
        if iteration % self.update_frequency != 0:
            return
        now = time.perf_counter()
        dt_ms = 0.0 if self._last_time is None else (now - self._last_time) * 1e3
        self._last_time = now
        batch = info.get("batch_size", 0)
        report = StatsReport(
            session_id=self.session_id, worker_id=self.worker_id,
            iteration=iteration, epoch=epoch, timestamp=time.time(),
            score=float(score), iteration_time_ms=dt_ms,
            examples_per_sec=(batch / (dt_ms / 1e3) if dt_ms > 0 and batch else 0.0),
            memory_rss_mb=_rss_mb(),
        )
        # on-cadence fit loops pass the fresh readback in the callback;
        # an EXPLICIT None means "off-cadence this step" — fall back to
        # the param-delta path rather than relabeling the model's stale
        # last readback with the current iteration number. The model
        # attribute is only consulted when the caller never passed the
        # key at all (listeners driven outside the fit loops).
        diag = (info["diagnostics"] if "diagnostics" in info
                else getattr(model, "_last_diagnostics", None))
        diag_params = (diag or {}).get("params") or {}
        diag_hists = (diag or {}).get("hists") or {}
        # host params are needed only when something below reads raw
        # arrays: no diagnostics seam, or host-side histograms
        need_host = (not diag_params
                     or (self.collect_histograms and not diag_hists))
        host_params = None
        if need_host:
            from deeplearning4j_tpu.monitor.diagnostics import (
                batched_host_tree)
            host_params = batched_host_tree(model.params)
        for lk, lparams in model.params.items():
            for pn in lparams:
                key = f"{lk}_{pn}"
                d = diag_params.get(key)
                if d is not None:
                    report.param_mean_magnitudes[key] = float(d["param_mm"])
                    # TRUE update magnitude from the fused step's aux —
                    # not a param-delta approximation
                    report.update_mean_magnitudes[key] = float(d["upd_mm"])
                    report.update_ratios[key] = float(d["ratio"])
                    if "grad_mm" in d:
                        report.gradient_mean_magnitudes[key] = \
                            float(d["grad_mm"])
                else:
                    a = np.asarray(host_params[lk][pn])
                    report.param_mean_magnitudes[key] = \
                        float(np.mean(np.abs(a)))
                    prev = self._prev_params.get(key)
                    if prev is not None and prev.shape == a.shape:
                        # fallback: |param delta| since last report
                        # (reference BaseStatsListener update stats)
                        report.update_mean_magnitudes[key] = float(
                            np.mean(np.abs(a - prev)))
                    self._prev_params[key] = a
                if self.collect_histograms:
                    hv = diag_hists.get(key)
                    if hv is not None and diag is not None:
                        # fixed-bin in-graph histogram from the aux
                        md = getattr(model, "_diag", None)
                        r = (md.config.histogram_range
                             if md is not None else 1.0)
                        edges = np.linspace(-r, r, len(hv) + 1)
                        report.param_histograms[key] = (
                            edges.tolist(),
                            np.asarray(hv, np.int64).tolist())
                    else:
                        a = np.asarray(host_params[lk][pn])
                        counts, edges = np.histogram(
                            a, bins=self.histogram_bins)
                        report.param_histograms[key] = (edges.tolist(),
                                                        counts.tolist())
        if diag is not None:
            for lk, st in (diag.get("activations") or {}).items():
                report.activation_stats[lk] = (
                    float(st["mean"]), float(st["std"]), float(st["dead"]))
            md = getattr(model, "_diag", None)
            if md is not None:
                report.watchdog_nonfinite = int(md.nonfinite_total)
        self.storage.put_report(report)

    def on_epoch_start(self, model, epoch):
        pass

    def on_epoch_end(self, model, epoch):
        pass

    def on_fit_end(self, model):
        pass
