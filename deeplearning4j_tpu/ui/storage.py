"""StatsStorage backends.

Reference: `api/storage/StatsStorage.java` (listener/router API),
`storage/mapdb/MapDBStatsStorage.java` and
`storage/sqlite/J7FileStatsStorage.java` (persistent), plus
`RemoteUIStatsStorageRouter` (HTTP POST to a UI on another process).
Here: in-memory, sqlite3 (stdlib), and an HTTP router posting the
binary-encoded reports to a UIServer's /remote endpoint.
"""

from __future__ import annotations

import sqlite3
import threading
from typing import Dict, List, Optional

from deeplearning4j_tpu.ui.stats import StatsReport


class StatsStorage:
    def put_report(self, report: StatsReport) -> None:
        raise NotImplementedError

    def list_session_ids(self) -> List[str]:
        raise NotImplementedError

    def get_reports(self, session_id: str) -> List[StatsReport]:
        raise NotImplementedError

    def latest_report(self, session_id: str) -> Optional[StatsReport]:
        reports = self.get_reports(session_id)
        return reports[-1] if reports else None


class InMemoryStatsStorage(StatsStorage):
    def __init__(self):
        self._data: Dict[str, List[StatsReport]] = {}
        self._lock = threading.Lock()

    def put_report(self, report):
        with self._lock:
            self._data.setdefault(report.session_id, []).append(report)

    def list_session_ids(self):
        with self._lock:
            return list(self._data)

    def get_reports(self, session_id):
        with self._lock:
            return list(self._data.get(session_id, []))


class FileStatsStorage(StatsStorage):
    """sqlite-backed persistent storage (reference
    `J7FileStatsStorage.java` role). Reports are stored in the compact
    binary wire format."""

    def __init__(self, path):
        self.path = str(path)
        self._lock = threading.Lock()
        with self._conn() as c:
            c.execute("""CREATE TABLE IF NOT EXISTS reports (
                session_id TEXT, iteration INTEGER, payload BLOB,
                PRIMARY KEY (session_id, iteration))""")

    def _conn(self):
        return sqlite3.connect(self.path)

    def put_report(self, report):
        with self._lock, self._conn() as c:
            c.execute("INSERT OR REPLACE INTO reports VALUES (?, ?, ?)",
                      (report.session_id, report.iteration, report.encode()))

    def list_session_ids(self):
        with self._lock, self._conn() as c:
            rows = c.execute("SELECT DISTINCT session_id FROM reports").fetchall()
        return [r[0] for r in rows]

    def get_reports(self, session_id):
        with self._lock, self._conn() as c:
            rows = c.execute(
                "SELECT payload FROM reports WHERE session_id=? ORDER BY iteration",
                (session_id,)).fetchall()
        return [StatsReport.decode(r[0]) for r in rows]


class RemoteUIStatsStorageRouter(StatsStorage):
    """Train here, view there (reference
    `RemoteUIStatsStorageRouter.java`): POST binary reports to a
    UIServer's /remote endpoint."""

    def __init__(self, url: str):
        self.url = url.rstrip("/") + "/remote"

    def put_report(self, report):
        from urllib import request as urlrequest
        req = urlrequest.Request(
            self.url, data=report.encode(),
            headers={"Content-Type": "application/octet-stream"})
        urlrequest.urlopen(req).read()  # noqa: S310 — user-configured UI host

    def list_session_ids(self):
        return []

    def get_reports(self, session_id):
        return []
