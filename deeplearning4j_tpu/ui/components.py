"""Declarative UI component library — charts/tables/text serialized to
JSON, plus self-contained SVG/HTML renderers.

Reference: `deeplearning4j-ui-components` (`components/chart/Chart.java`
and subclasses ChartLine/ChartHistogram/ChartScatter/ChartStackedArea,
`components/table/ComponentTable.java`, `components/text/ComponentText.java`,
`components/component/ComponentDiv.java`, `api/Style.java`): components
are data (JSON) decoupled from rendering. The reference renders with
JS/D3 in the browser; here each component also knows how to render
itself to inline SVG/HTML so the dashboard needs no external assets.
"""

from __future__ import annotations

import dataclasses
import html as _html
import json
from typing import Any, Dict, List, Optional, Sequence, Tuple

_COMPONENT_REGISTRY: Dict[str, type] = {}


def register_component(cls):
    _COMPONENT_REGISTRY[cls.component_type] = cls
    return cls


@dataclasses.dataclass
class ChartStyle:
    """Subset of the reference `StyleChart` knobs."""

    width: int = 640
    height: int = 240
    stroke_width: float = 1.5
    series_colors: Sequence[str] = ("#2a6fdb", "#db2a2a", "#2adb7c",
                                    "#db9b2a", "#8b2adb", "#2adbd3")
    background: str = "#fafafa"

    def __post_init__(self):
        self.series_colors = list(self.series_colors)  # JSON-stable form

    def to_dict(self):
        return dataclasses.asdict(self)

    @staticmethod
    def from_dict(d):
        return ChartStyle(**d) if d else ChartStyle()


class Component:
    component_type = "component"

    def to_dict(self) -> dict:
        raise NotImplementedError

    def to_json(self, **kw) -> str:
        return json.dumps(self.to_dict(), **kw)

    def render(self) -> str:
        """Self-contained HTML/SVG fragment."""
        raise NotImplementedError


def component_from_dict(d: dict) -> Component:
    cls = _COMPONENT_REGISTRY[d["type"]]
    return cls._from_dict(d)


def component_from_json(s: str) -> Component:
    return component_from_dict(json.loads(s))


# ------------------------------------------------------------------ charts
class _BaseChart(Component):
    def __init__(self, title: str = "", style: Optional[ChartStyle] = None):
        self.title = title
        self.style = style or ChartStyle()

    def _frame(self, inner: str) -> str:
        s = self.style
        title = (f'<text x="45" y="16" font-size="12" font-weight="bold">'
                 f'{_html.escape(self.title)}</text>') if self.title else ""
        return (f'<svg width="{s.width}" height="{s.height}" '
                f'xmlns="http://www.w3.org/2000/svg">'
                f'<rect width="{s.width}" height="{s.height}" '
                f'fill="{s.background}"/>{title}{inner}</svg>')

    def _xy_transform(self, all_x, all_y):
        s = self.style
        xmin, xmax = min(all_x), max(all_x)
        ymin, ymax = min(all_y), max(all_y)
        if xmax == xmin:
            xmax = xmin + 1
        if ymax == ymin:
            ymax = ymin + 1

        def tx(x):
            return 45 + (x - xmin) / (xmax - xmin) * (s.width - 65)

        def ty(y):
            return s.height - 28 - (y - ymin) / (ymax - ymin) * (s.height - 52)

        axes = (f'<text x="45" y="{s.height - 10}" font-size="10">'
                f'{xmin:.4g}</text>'
                f'<text x="{s.width - 60}" y="{s.height - 10}" font-size="10">'
                f'{xmax:.4g}</text>'
                f'<text x="4" y="{s.height - 28}" font-size="10">{ymin:.4g}</text>'
                f'<text x="4" y="30" font-size="10">{ymax:.4g}</text>')
        return tx, ty, axes


@register_component
class ChartLine(_BaseChart):
    """Multi-series line chart (reference `ChartLine.java`)."""

    component_type = "chart_line"

    def __init__(self, title: str = "", style: Optional[ChartStyle] = None):
        super().__init__(title, style)
        self.series: List[Tuple[str, List[float], List[float]]] = []

    def add_series(self, name: str, x: Sequence[float], y: Sequence[float]):
        if len(x) != len(y):
            raise ValueError(f"series {name}: len(x) {len(x)} != len(y) {len(y)}")
        self.series.append((name, [float(v) for v in x], [float(v) for v in y]))
        return self

    def to_dict(self):
        return {"type": self.component_type, "title": self.title,
                "style": self.style.to_dict(),
                "series": [{"name": n, "x": x, "y": y}
                           for n, x, y in self.series]}

    @classmethod
    def _from_dict(cls, d):
        c = cls(d.get("title", ""), ChartStyle.from_dict(d.get("style")))
        for s in d.get("series", []):
            c.add_series(s["name"], s["x"], s["y"])
        return c

    def render(self):
        if not any(x for _, x, _ in self.series):
            return self._frame("")
        all_x = [v for _, x, _ in self.series for v in x]
        all_y = [v for _, _, y in self.series for v in y]
        tx, ty, axes = self._xy_transform(all_x, all_y)
        parts = [axes]
        for i, (name, x, y) in enumerate(self.series):
            if not y:
                continue
            color = self.style.series_colors[i % len(self.style.series_colors)]
            pts = " ".join(f"{tx(a):.1f},{ty(b):.1f}" for a, b in zip(x, y))
            parts.append(f'<polyline fill="none" stroke="{color}" '
                         f'stroke-width="{self.style.stroke_width}" '
                         f'points="{pts}"/>')
            parts.append(f'<text x="{self.style.width - 120}" y="{30 + 14 * i}" '
                         f'font-size="11" fill="{color}">{_html.escape(name)}'
                         f' ({y[-1]:.5g})</text>')
        return self._frame("".join(parts))


@register_component
class ChartHistogram(_BaseChart):
    """Histogram of pre-binned values (reference `ChartHistogram.java`:
    addBin(lower, upper, yValue))."""

    component_type = "chart_histogram"

    def __init__(self, title: str = "", style: Optional[ChartStyle] = None):
        super().__init__(title, style)
        self.bins: List[Tuple[float, float, float]] = []  # (low, high, y)

    def add_bin(self, lower: float, upper: float, y: float):
        self.bins.append((float(lower), float(upper), float(y)))
        return self

    def to_dict(self):
        return {"type": self.component_type, "title": self.title,
                "style": self.style.to_dict(),
                "bins": [{"lower": l, "upper": u, "y": y}
                         for l, u, y in self.bins]}

    @classmethod
    def _from_dict(cls, d):
        c = cls(d.get("title", ""), ChartStyle.from_dict(d.get("style")))
        for b in d.get("bins", []):
            c.add_bin(b["lower"], b["upper"], b["y"])
        return c

    def render(self):
        if not self.bins:
            return self._frame("")
        tx, ty, axes = self._xy_transform(
            [b[0] for b in self.bins] + [b[1] for b in self.bins],
            [0.0] + [b[2] for b in self.bins])
        y0 = ty(0.0)
        color = self.style.series_colors[0]
        parts = [axes]
        for low, high, y in self.bins:
            x1, x2 = tx(low), tx(high)
            yy = ty(y)
            parts.append(f'<rect x="{x1:.1f}" y="{min(yy, y0):.1f}" '
                         f'width="{max(x2 - x1 - 1, 1):.1f}" '
                         f'height="{abs(y0 - yy):.1f}" fill="{color}" '
                         f'fill-opacity="0.7"/>')
        return self._frame("".join(parts))


@register_component
class ChartScatter(_BaseChart):
    """Scatter plot (reference `ChartScatter.java`); the t-SNE module's
    workhorse."""

    component_type = "chart_scatter"

    def __init__(self, title: str = "", style: Optional[ChartStyle] = None):
        super().__init__(title, style)
        self.series: List[Tuple[str, List[float], List[float], List[str]]] = []

    def add_series(self, name: str, x: Sequence[float], y: Sequence[float],
                   labels: Optional[Sequence[str]] = None):
        if len(x) != len(y):
            raise ValueError(f"series {name}: len(x) != len(y)")
        labels = [str(l) for l in labels] if labels is not None else []
        self.series.append((name, [float(v) for v in x],
                            [float(v) for v in y], labels))
        return self

    def to_dict(self):
        return {"type": self.component_type, "title": self.title,
                "style": self.style.to_dict(),
                "series": [{"name": n, "x": x, "y": y, "labels": ls}
                           for n, x, y, ls in self.series]}

    @classmethod
    def _from_dict(cls, d):
        c = cls(d.get("title", ""), ChartStyle.from_dict(d.get("style")))
        for s in d.get("series", []):
            c.add_series(s["name"], s["x"], s["y"], s.get("labels") or None)
        return c

    def render(self):
        if not any(x for _, x, _, _ in self.series):
            return self._frame("")
        all_x = [v for _, x, _, _ in self.series for v in x]
        all_y = [v for _, _, y, _ in self.series for v in y]
        tx, ty, axes = self._xy_transform(all_x, all_y)
        parts = [axes]
        for i, (name, x, y, labels) in enumerate(self.series):
            color = self.style.series_colors[i % len(self.style.series_colors)]
            for j, (a, b) in enumerate(zip(x, y)):
                parts.append(f'<circle cx="{tx(a):.1f}" cy="{ty(b):.1f}" '
                             f'r="2.5" fill="{color}"/>')
                if j < len(labels):
                    parts.append(f'<text x="{tx(a) + 4:.1f}" '
                                 f'y="{ty(b) - 3:.1f}" font-size="9">'
                                 f'{_html.escape(labels[j])}</text>')
        return self._frame("".join(parts))


# ------------------------------------------------------------- table/text
@register_component
class ComponentTable(Component):
    """Reference `ComponentTable.java`."""

    component_type = "table"

    def __init__(self, header: Sequence[str], rows: Sequence[Sequence[Any]],
                 title: str = ""):
        self.title = title
        self.header = [str(h) for h in header]
        self.rows = [[str(c) for c in row] for row in rows]

    def to_dict(self):
        return {"type": self.component_type, "title": self.title,
                "header": self.header, "rows": self.rows}

    @classmethod
    def _from_dict(cls, d):
        return cls(d["header"], d["rows"], d.get("title", ""))

    def render(self):
        head = "".join(f"<th>{_html.escape(h)}</th>" for h in self.header)
        body = "".join(
            "<tr>" + "".join(f"<td>{_html.escape(c)}</td>" for c in row)
            + "</tr>" for row in self.rows)
        title = f"<h4>{_html.escape(self.title)}</h4>" if self.title else ""
        return (f'{title}<table border="1" cellpadding="4" '
                f'style="border-collapse:collapse">'
                f"<tr>{head}</tr>{body}</table>")


@register_component
class ComponentText(Component):
    component_type = "text"

    def __init__(self, text: str):
        self.text = text

    def to_dict(self):
        return {"type": self.component_type, "text": self.text}

    @classmethod
    def _from_dict(cls, d):
        return cls(d["text"])

    def render(self):
        return f"<p>{_html.escape(self.text)}</p>"


@register_component
class ComponentDiv(Component):
    """Container (reference `ComponentDiv.java`)."""

    component_type = "div"

    def __init__(self, *children: Component):
        self.children = list(children)

    def add(self, c: Component):
        self.children.append(c)
        return self

    def to_dict(self):
        return {"type": self.component_type,
                "children": [c.to_dict() for c in self.children]}

    @classmethod
    def _from_dict(cls, d):
        return cls(*[component_from_dict(c) for c in d.get("children", [])])

    def render(self):
        return "<div>" + "".join(c.render() for c in self.children) + "</div>"
