"""ConvolutionalIterationListener — render conv activations as image
grids.

Reference: `ui/ConvolutionalIterationListener.java` (621 LoC): every N
iterations, run the current minibatch's first example through the
network and save each convolutional layer's activation channels as one
tiled grayscale image.
"""

from __future__ import annotations

from pathlib import Path

import numpy as np

from deeplearning4j_tpu.optimize.listeners import TrainingListener


def activations_to_grid(act: np.ndarray, pad: int = 1) -> np.ndarray:
    """[H, W, C] activations → one tiled uint8 grayscale image."""
    h, w, c = act.shape
    cols = int(np.ceil(np.sqrt(c)))
    rows = int(np.ceil(c / cols))
    grid = np.zeros((rows * (h + pad) - pad, cols * (w + pad) - pad),
                    np.float32)
    for i in range(c):
        r, col = divmod(i, cols)
        ch = act[:, :, i]
        lo, hi = float(ch.min()), float(ch.max())
        norm = (ch - lo) / (hi - lo) if hi > lo else np.zeros_like(ch)
        grid[r * (h + pad):r * (h + pad) + h,
             col * (w + pad):col * (w + pad) + w] = norm
    return (grid * 255).astype(np.uint8)


class ConvolutionalIterationListener(TrainingListener):
    def __init__(self, output_dir=None, frequency: int = 10, ui_server=None):
        """`output_dir`: save tiled grids as PNG files; `ui_server`:
        also feed the UIServer's /activations module (reference play
        `module/convolutional/`). At least one sink must be given."""
        if output_dir is None and ui_server is None:
            raise ValueError("need output_dir and/or ui_server")
        self.output_dir = None if output_dir is None else Path(output_dir)
        if self.output_dir is not None:
            self.output_dir.mkdir(parents=True, exist_ok=True)
        self.ui_server = ui_server
        self.frequency = max(1, frequency)

    def iteration_done(self, model, iteration, epoch, score, **info):
        if iteration % self.frequency != 0:
            return
        batch = info.get("batch")
        if batch is None:
            return
        x = np.asarray(batch[0])[:1]  # first example only
        try:
            h, _, _, acts, _ = model._forward_core(
                model.params, model.net_state, x, train=False, rng=None,
                collect=True)
        except Exception:
            return
        for li, act in enumerate(acts):
            a = np.asarray(act)
            if a.ndim != 4:  # NHWC conv activations only
                continue
            grid = activations_to_grid(a[0])
            if self.output_dir is not None:
                from PIL import Image
                Image.fromarray(grid).save(
                    self.output_dir / f"iter{iteration:06d}_layer{li}.png")
            if self.ui_server is not None:
                self.ui_server.post_activation_grid(f"layer{li}", grid)
