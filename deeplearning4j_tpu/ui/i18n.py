"""Dashboard i18n (reference role: the Play UI's i18n resource bundles,
`deeplearning4j-ui-parent/deeplearning4j-play` i18n/ dir). Pages take a
`?lang=` query parameter; unknown languages and missing keys fall back
to English."""

from __future__ import annotations

_MESSAGES = {
    "en": {
        "overview": "Overview", "model": "Model", "system": "System",
        "tsne": "t-SNE", "activations": "Activations",
        "title.overview": "Training Overview", "title.model": "Model",
        "title.system": "System", "title.tsne": "t-SNE",
        "title.activations": "Activations",
        "session": "Session", "score": "score", "throughput": "throughput",
        "examples_per_sec": "examples/sec", "memory": "memory",
        "iteration_time": "iteration time",
        "mean_param": "mean |param|",
        "update_ratio": "log10 update : param ratio",
        "distribution": "distribution",
        "latest_magnitudes": "latest parameter magnitudes",
        "param": "param", "mean_value": "mean |value|",
        "no_sessions": "No training sessions attached yet.",
        "no_model_stats": "No model stats yet.",
        "profile": "Profile", "title.profile": "AOT Cost / Profile",
        "profile.summary": "cost summary",
        "profile.top_ops": "top ops by FLOPs",
        "health": "training health",
        "grad_norm": "mean |grad|",
        "watchdog": "watchdog non-finite steps",
        "act_stats": "activation stats",
        "act_layer": "layer", "act_mean": "mean", "act_std": "std",
        "act_dead": "dead fraction",
    },
    "ja": {
        "overview": "概要", "model": "モデル", "system": "システム",
        "tsne": "t-SNE", "activations": "活性化",
        "title.overview": "学習の概要", "title.model": "モデル",
        "title.system": "システム", "title.tsne": "t-SNE",
        "title.activations": "活性化",
        "session": "セッション", "score": "スコア",
        "throughput": "スループット", "examples_per_sec": "サンプル/秒",
        "memory": "メモリ", "iteration_time": "イテレーション時間",
        "mean_param": "平均 |パラメータ|",
        "update_ratio": "log10 更新:パラメータ比",
        "distribution": "分布",
        "latest_magnitudes": "最新のパラメータ値",
        "param": "パラメータ", "mean_value": "平均 |値|",
        "no_sessions": "学習セッションがまだ接続されていません。",
        "no_model_stats": "モデル統計はまだありません。",
        "profile": "プロファイル", "title.profile": "AOTコスト / プロファイル",
        "profile.summary": "コスト概要",
        "profile.top_ops": "FLOPs上位オペレーション",
        "health": "学習ヘルス",
        "grad_norm": "平均 |勾配|",
        "watchdog": "ウォッチドッグ非有限ステップ数",
        "act_stats": "活性化統計",
        "act_layer": "レイヤー", "act_mean": "平均", "act_std": "標準偏差",
        "act_dead": "デッド率",
    },
    "zh": {
        "overview": "概览", "model": "模型", "system": "系统",
        "tsne": "t-SNE", "activations": "激活",
        "title.overview": "训练概览", "title.model": "模型",
        "title.system": "系统", "title.tsne": "t-SNE",
        "title.activations": "激活",
        "session": "会话", "score": "得分", "throughput": "吞吐量",
        "examples_per_sec": "样本/秒", "memory": "内存",
        "iteration_time": "迭代时间",
        "mean_param": "平均 |参数|",
        "update_ratio": "log10 更新:参数比",
        "distribution": "分布",
        "latest_magnitudes": "最新参数值",
        "param": "参数", "mean_value": "平均 |值|",
        "no_sessions": "尚未连接任何训练会话。",
        "no_model_stats": "尚无模型统计。",
        "profile": "性能分析", "title.profile": "AOT成本 / 性能分析",
        "profile.summary": "成本摘要",
        "profile.top_ops": "按FLOPs排序的算子",
        "health": "训练健康",
        "grad_norm": "平均 |梯度|",
        "watchdog": "看门狗非有限步数",
        "act_stats": "激活统计",
        "act_layer": "层", "act_mean": "均值", "act_std": "标准差",
        "act_dead": "死亡比例",
    },
}

LANGUAGES = tuple(_MESSAGES)


def tr(lang: str, key: str) -> str:
    table = _MESSAGES.get(lang) or _MESSAGES["en"]
    return table.get(key) or _MESSAGES["en"].get(key, key)
