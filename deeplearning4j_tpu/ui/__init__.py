"""Observability / training UI (reference: deeplearning4j-ui-parent,
SURVEY §2.10): StatsListener → StatsStorage → web dashboard."""

from deeplearning4j_tpu.ui.stats import StatsListener, StatsReport
from deeplearning4j_tpu.ui.storage import (
    FileStatsStorage,
    InMemoryStatsStorage,
    RemoteUIStatsStorageRouter,
    StatsStorage,
)
from deeplearning4j_tpu.ui.server import UIServer
from deeplearning4j_tpu.ui.convolutional import ConvolutionalIterationListener
from deeplearning4j_tpu.ui.components import (
    ChartHistogram,
    ChartLine,
    ChartScatter,
    ChartStyle,
    ComponentDiv,
    ComponentTable,
    ComponentText,
    component_from_dict,
    component_from_json,
)
