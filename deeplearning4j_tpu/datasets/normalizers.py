"""Data normalizers: fit statistics on a dataset/iterator, then
transform (and revert) minibatches.

Reference: ND4J's `org.nd4j.linalg.dataset.api.preprocessor` family —
`NormalizerStandardize` (zero-mean/unit-variance), `NormalizerMinMaxScaler`
(rescale to [min, max]), `ImagePreProcessingScaler` (pixel [0, 255] →
[a, b]) — consumed throughout the reference via
`DataSetIterator.setPreProcessor` and persisted beside models by
`ModelSerializer.addNormalizerToModel` / `restoreNormalizerFromFile`
(`util/ModelSerializer.java`), with the `ModelGuesser.loadNormalizer`
facade (`deeplearning4j-core/util/ModelGuesser.java:29-40`).

TPU-first notes: statistics are accumulated on host in float64 via a
streaming one-pass sum/sum-of-squares (iterators may not fit in
memory); `transform` is plain elementwise numpy on the host side of
the input pipeline — on the device path the same affine fold is
cheaper fused into the jitted prolog (see the uint8-normalize prolog
in `bench.py`), so these classes deliberately stay host-side.
Feature-axis statistics reduce over every non-feature axis (batch,
time, spatial), matching the reference's per-feature semantics for
2-d, 3-d (masked time series) and 4-d (image) inputs.
"""

from __future__ import annotations

import json
from typing import Optional

import numpy as np

_REGISTRY = {}


def register_normalizer(cls):
    _REGISTRY[cls.kind] = cls
    return cls


def normalizer_from_meta(meta: dict, arrays: dict) -> "Normalizer":
    cls = _REGISTRY.get(meta.get("kind"))
    if cls is None:
        # the online-learning normalizers register on import of their
        # module; a checkpoint written by an OnlineTrainer must restore
        # through plain fault.resume() without the caller having
        # imported online/ first
        import importlib
        importlib.import_module("deeplearning4j_tpu.online.normalizer")
        cls = _REGISTRY.get(meta.get("kind"))
    if cls is None:
        raise ValueError(f"Unknown normalizer kind: {meta.get('kind')!r}")
    return cls._from_state(meta, arrays)


def _float_dtype(x: np.ndarray):
    """Normalized output is always floating point — casting a
    standardized batch back to the input's uint8 would truncate/wrap
    it into garbage (the reference normalizers yield float too)."""
    return x.dtype if np.issubdtype(x.dtype, np.floating) else np.float32


def _reduce_axes(x: np.ndarray):
    """All axes except the feature axis. Convention: rank-2 [B, F] and
    rank-3 [B, T, F] are feature-last (this repo's NHWC/[B,T,F]
    layouts); rank-4 images are NHWC with channels last."""
    return tuple(i for i in range(x.ndim) if i != x.ndim - 1)


class Normalizer:
    """fit / transform / revert protocol (reference
    `DataNormalization`). Subclasses hold per-feature state arrays."""

    kind = "abstract"
    fits_labels = False

    def fit(self, data) -> "Normalizer":
        """Accept a DataSet or any iterable of DataSets. A
        `features_mask` ([B, T], 1 = real timestep) excludes padded
        timesteps from the statistics — matching ND4J's masked-aware
        accumulation (`NormalizerStandardize` + `DataSetUtil`
        masked-columns path): padding zeros must not drag the mean
        toward 0 or deflate the variance of a padded corpus."""
        from deeplearning4j_tpu.datasets.dataset import DataSet
        batches = [data] if isinstance(data, DataSet) else data
        self._begin()
        n = 0
        for ds in batches:
            mask = getattr(ds, "features_mask", None)
            self._accumulate(np.asarray(ds.features),
                             None if mask is None else np.asarray(mask))
            n += 1
        if n == 0:
            raise ValueError("fit() saw no data")
        self._finish()
        if hasattr(data, "reset"):
            data.reset()
        return self

    def pre_process(self, ds):
        """In-place DataSet hook (reference `preProcess(DataSet)`) —
        the iterator-side entry point."""
        ds.features = self.transform(ds.features)
        return ds

    def transform(self, features):
        raise NotImplementedError

    def revert(self, features):
        raise NotImplementedError

    # ------------------------------------------------------- persistence
    def state(self):
        """(meta dict, arrays dict) for persistence."""
        raise NotImplementedError

    def _begin(self):
        raise NotImplementedError

    def _accumulate(self, x, mask=None):
        raise NotImplementedError

    def _finish(self):
        pass


def _mask_weights(x: np.ndarray, mask) -> Optional[np.ndarray]:
    """Broadcastable 0/1 weights for a [B, T] features_mask against
    [B, T, F] features (None when the mask doesn't apply)."""
    if mask is None or x.ndim != 3:
        return None
    w = np.asarray(mask, np.float64)
    if w.shape != x.shape[:2]:
        return None
    return w[:, :, None]


@register_normalizer
class NormalizerStandardize(Normalizer):
    """Per-feature zero-mean/unit-variance (reference
    `NormalizerStandardize`): one-pass streaming sum / sum-of-squares
    in float64 so iterator-sized corpora never need a second pass."""

    kind = "standardize"

    def __init__(self):
        self.mean: Optional[np.ndarray] = None
        self.std: Optional[np.ndarray] = None

    def _begin(self):
        self._n = 0.0
        self._sum = None
        self._sumsq = None

    def _accumulate(self, x, mask=None):
        x = np.asarray(x, np.float64)
        axes = _reduce_axes(x)
        w = _mask_weights(x, mask)
        if w is not None:
            cnt = float(w.sum())  # per-feature count — same for every F
            s = (x * w).sum(axis=axes)
            sq = (x * x * w).sum(axis=axes)
        else:
            cnt = float(np.prod([x.shape[a] for a in axes])) if axes else 1.0
            s = x.sum(axis=axes)
            sq = (x * x).sum(axis=axes)
        if self._sum is None:
            self._sum, self._sumsq = s, sq
        else:
            self._sum = self._sum + s
            self._sumsq = self._sumsq + sq
        self._n += cnt

    def _finish(self):
        if not self._n:
            # every timestep masked out (upstream filtering bug): a
            # silent 0/0 would make mean/std NaN and poison every
            # later transform with no pointer back here
            raise ValueError(
                "fit() saw no unmasked timesteps — the features_mask "
                "excluded every value; check the mask polarity "
                "(1 = real timestep)")
        self.mean = self._sum / self._n
        var = self._sumsq / self._n - self.mean ** 2
        self.std = np.sqrt(np.clip(var, 1e-12, None))

    def transform(self, features):
        x = np.asarray(features)
        return ((x - self.mean) / self.std).astype(_float_dtype(x))

    def revert(self, features):
        x = np.asarray(features)
        return (x * self.std + self.mean).astype(_float_dtype(x))

    def state(self):
        return {"kind": self.kind}, {"mean": self.mean, "std": self.std}

    @classmethod
    def _from_state(cls, meta, arrays):
        out = cls()
        out.mean = arrays["mean"]
        out.std = arrays["std"]
        return out


@register_normalizer
class NormalizerMinMaxScaler(Normalizer):
    """Per-feature rescale to [min_range, max_range] (reference
    `NormalizerMinMaxScaler`)."""

    kind = "minmax"

    def __init__(self, min_range: float = 0.0, max_range: float = 1.0):
        self.min_range = float(min_range)
        self.max_range = float(max_range)
        self.data_min: Optional[np.ndarray] = None
        self.data_max: Optional[np.ndarray] = None

    def _begin(self):
        self.data_min = None
        self.data_max = None

    def _accumulate(self, x, mask=None):
        x = np.asarray(x, np.float64)
        axes = _reduce_axes(x)
        w = _mask_weights(x, mask)
        if w is not None:
            keep = w > 0
            lo = np.where(keep, x, np.inf).min(axis=axes)
            hi = np.where(keep, x, -np.inf).max(axis=axes)
            if not np.isfinite(lo).all():  # batch fully padded
                return
        else:
            lo = x.min(axis=axes)
            hi = x.max(axis=axes)
        if self.data_min is None:
            self.data_min, self.data_max = lo, hi
        else:
            self.data_min = np.minimum(self.data_min, lo)
            self.data_max = np.maximum(self.data_max, hi)

    def _finish(self):
        if self.data_min is None:
            # every batch was fully masked — same loud failure as the
            # standardizer, instead of a later None-arithmetic crash
            raise ValueError(
                "fit() saw no unmasked timesteps — the features_mask "
                "excluded every value; check the mask polarity "
                "(1 = real timestep)")

    def _span(self):
        return np.clip(self.data_max - self.data_min, 1e-12, None)

    def transform(self, features):
        x = np.asarray(features)
        unit = (x - self.data_min) / self._span()
        out = unit * (self.max_range - self.min_range) + self.min_range
        return out.astype(_float_dtype(x))

    def revert(self, features):
        x = np.asarray(features)
        unit = (x - self.min_range) / (self.max_range - self.min_range)
        return (unit * self._span() + self.data_min).astype(_float_dtype(x))

    def state(self):
        return ({"kind": self.kind, "min_range": self.min_range,
                 "max_range": self.max_range},
                {"data_min": self.data_min, "data_max": self.data_max})

    @classmethod
    def _from_state(cls, meta, arrays):
        out = cls(meta.get("min_range", 0.0), meta.get("max_range", 1.0))
        out.data_min = arrays["data_min"]
        out.data_max = arrays["data_max"]
        return out


@register_normalizer
class ImagePreProcessingScaler(Normalizer):
    """Pixel-range scaler (reference `ImagePreProcessingScaler`):
    [0, 2^bits - 1] → [a, b] with no fitting required."""

    kind = "image_scaler"

    def __init__(self, a: float = 0.0, b: float = 1.0, bits: int = 8):
        self.a = float(a)
        self.b = float(b)
        self.bits = int(bits)

    @property
    def _max_pixel(self):
        return float(2 ** self.bits - 1)

    def fit(self, data):  # stateless — fit is a no-op like the reference
        return self

    def transform(self, features):
        x = np.asarray(features, np.float32)
        return x / self._max_pixel * (self.b - self.a) + self.a

    def revert(self, features):
        x = np.asarray(features, np.float32)
        return (x - self.a) / (self.b - self.a) * self._max_pixel

    def state(self):
        return ({"kind": self.kind, "a": self.a, "b": self.b,
                 "bits": self.bits}, {})

    @classmethod
    def _from_state(cls, meta, arrays):
        return cls(meta.get("a", 0.0), meta.get("b", 1.0),
                   meta.get("bits", 8))


def normalizer_to_json(norm: Normalizer) -> str:
    meta, _ = norm.state()
    return json.dumps(meta)
