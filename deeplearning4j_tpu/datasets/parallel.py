"""Parallel data iterators + device-transfer overlap.

Reference: `datasets/iterator/parallel/` — `BaseParallelDataSetIterator`
(round-robin over N producers with `InequalityHandling` when they
deplete unevenly), `JointParallelDataSetIterator.java` (N independent
iterators, each async-buffered), `FileSplitParallelDataSetIterator.java`
(files under a root matching a pattern, split across N virtual
producers, each file turned into a DataSet by a callback).

`DevicePrefetchIterator` is the TPU-side half the reference implements
with its per-device `MagicQueue`: JAX transfers are asynchronous, so
issuing `device_put` for the next batches while the consumer computes
on the current one overlaps H2D DMA with device compute — `fit()`
consumes device-resident DataSets transparently (jnp.asarray on a
committed device array is a no-op).
"""

from __future__ import annotations

import fnmatch
import os
from collections import deque
from enum import Enum
from typing import Callable, List, Optional, Sequence

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterator import (
    AsyncDataSetIterator,
    DataSetIterator,
)


class InequalityHandling(str, Enum):
    """What to do when producers deplete unevenly (reference
    `nd4j ...iterator.enums.InequalityHandling`)."""

    STOP_EVERYONE = "stop_everyone"   # first depleted producer ends it all
    RELOCATE = "relocate"             # skip depleted, drain the rest
    RESET = "reset"                   # restart depleted until all have wrapped
    PASS_NULL = "pass_null"           # yield None for depleted producers


class BaseParallelDataSetIterator(DataSetIterator):
    """Round-robin over N producers with inequality handling
    (reference `BaseParallelDataSetIterator.java` hasNext switch)."""

    def __init__(self, producers: Sequence[DataSetIterator],
                 inequality_handling: InequalityHandling =
                 InequalityHandling.STOP_EVERYONE,
                 prefetch: int = 2):
        if not producers:
            raise ValueError("need at least one producer iterator")
        self.producers = list(producers)
        self.inequality_handling = InequalityHandling(inequality_handling)
        self.prefetch = prefetch

    def _wrapped(self) -> List[DataSetIterator]:
        if self.prefetch > 0:
            return [AsyncDataSetIterator(p, prefetch=self.prefetch)
                    for p in self.producers]
        return list(self.producers)

    def __iter__(self):
        mode = self.inequality_handling
        its = [iter(p) for p in self._wrapped()]
        n = len(its)
        active = [True] * n
        wrapped_once = [False] * n   # RESET: stop after every producer wrapped

        def pull(i):
            try:
                return next(its[i]), True
            except StopIteration:
                return None, False

        i = 0
        while any(active):
            if active[i]:
                ds, ok = pull(i)
                if ok:
                    yield ds
                    i = (i + 1) % n
                    continue
                # producer i just depleted
                if mode == InequalityHandling.STOP_EVERYONE:
                    return
                if mode == InequalityHandling.RESET:
                    wrapped_once[i] = True
                    if all(wrapped_once):
                        return
                    self.producers[i].reset()
                    its[i] = iter(AsyncDataSetIterator(
                        self.producers[i], prefetch=self.prefetch)
                        if self.prefetch > 0 else self.producers[i])
                    ds, ok = pull(i)       # retry the producer ONCE
                    if ok:
                        yield ds
                        i = (i + 1) % n
                    else:
                        # empty even after reset: drop it or a zero-batch
                        # producer would busy-loop forever
                        active[i] = False
                        i = (i + 1) % n
                    continue
                active[i] = False          # RELOCATE / PASS_NULL
                if mode == InequalityHandling.PASS_NULL:
                    if not any(active):
                        return
                    yield None
                    i = (i + 1) % n
                    continue
            else:
                if mode == InequalityHandling.PASS_NULL:
                    yield None
                i = (i + 1) % n

    def reset(self):
        for p in self.producers:
            p.reset()


class JointParallelDataSetIterator(BaseParallelDataSetIterator):
    """N independent source iterators interleaved round-robin, each with
    its own async prefetch buffer (reference
    `JointParallelDataSetIterator.java`)."""


class FileSplitParallelDataSetIterator(BaseParallelDataSetIterator):
    """Files under `root` matching `pattern`, dealt round-robin across
    `num_producers` file lists; `callback(path) -> DataSet` loads one
    file per batch (reference `FileSplitParallelDataSetIterator.java`
    with its `FileCallback`)."""

    def __init__(self, root: str, pattern: str,
                 callback: Callable[[str], DataSet],
                 num_producers: int = 2,
                 inequality_handling: InequalityHandling =
                 InequalityHandling.STOP_EVERYONE,
                 prefetch: int = 2):
        paths: List[str] = []
        for dirpath, _, files in sorted(os.walk(root)):
            for f in sorted(files):
                if fnmatch.fnmatch(f, pattern):
                    paths.append(os.path.join(dirpath, f))
        if not paths:
            raise ValueError(f"no files under {root} match {pattern!r}")
        self.paths = paths
        num_producers = max(1, min(num_producers, len(paths)))
        splits = [paths[i::num_producers] for i in range(num_producers)]
        producers = [_FileListIterator(split, callback) for split in splits]
        super().__init__(producers, inequality_handling, prefetch)


class _FileListIterator(DataSetIterator):
    def __init__(self, paths: List[str], callback: Callable[[str], DataSet]):
        self.paths = paths
        self.callback = callback

    def __iter__(self):
        for p in self.paths:
            yield self.callback(p)

    def reset(self):
        pass


class DevicePrefetchIterator(DataSetIterator):
    """Keeps `depth` batches in flight to the device: `device_put` is
    async, so the next batches' H2D transfers run while the consumer
    computes on the current batch. Pass a `sharding`
    (e.g. NamedSharding(mesh, P("data"))) to land batches pre-sharded
    for a ParallelTrainer."""

    def __init__(self, base: DataSetIterator, depth: int = 2, sharding=None):
        self.base = base
        self.depth = max(1, depth)
        self.sharding = sharding

    def _put(self, ds: DataSet) -> DataSet:
        import jax

        def dev(a):
            if a is None:
                return None
            if self.sharding is not None:
                return jax.device_put(np.asarray(a), self.sharding)
            return jax.device_put(np.asarray(a))

        return DataSet(dev(ds.features), dev(ds.labels),
                       dev(ds.features_mask), dev(ds.labels_mask),
                       ds.example_metadata)

    def __iter__(self):
        buf: deque = deque()
        for ds in self.base:
            buf.append(self._put(ds))
            if len(buf) >= self.depth:
                yield buf.popleft()
        while buf:
            yield buf.popleft()

    def reset(self):
        self.base.reset()

    def batch_size(self):
        return self.base.batch_size()
