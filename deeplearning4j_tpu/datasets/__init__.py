"""Data pipeline: DataSet container, iterator protocol, fetchers,
async prefetch.

Reference: ND4J `DataSet`/`DataSetIterator` + deeplearning4j `datasets/`
(AsyncDataSetIterator, wrappers, fetchers).
"""

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterator import (
    DataSetIterator,
    ListDataSetIterator,
    ArrayDataSetIterator,
    AsyncDataSetIterator,
    MultipleEpochsIterator,
    EarlyTerminationDataSetIterator,
    SamplingDataSetIterator,
    BenchmarkDataSetIterator,
)
from deeplearning4j_tpu.datasets.fetchers import (
    IrisDataSetIterator,
    MnistDataSetIterator,
)
from deeplearning4j_tpu.datasets.parallel import (
    BaseParallelDataSetIterator,
    DevicePrefetchIterator,
    FileSplitParallelDataSetIterator,
    InequalityHandling,
    JointParallelDataSetIterator,
)
