"""DataSet: one minibatch of features + labels (+ masks).

Reference: ND4J `org.nd4j.linalg.dataset.DataSet` (features, labels,
featuresMask, labelsMask) — the currency every iterator yields and
`fit()` consumes.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np


@dataclasses.dataclass
class DataSet:
    features: np.ndarray
    labels: Optional[np.ndarray] = None
    features_mask: Optional[np.ndarray] = None
    labels_mask: Optional[np.ndarray] = None
    # per-example record metadata (reference RecordMetaData carried by
    # DataSet.getExampleMetaData) — list of len == num_examples, or None
    example_metadata: Optional[list] = None

    def num_examples(self) -> int:
        return int(np.shape(self.features)[0])

    def split_test_and_train(self, num_train: int):
        md = self.example_metadata
        train = DataSet(
            self.features[:num_train],
            None if self.labels is None else self.labels[:num_train],
            None if self.features_mask is None else self.features_mask[:num_train],
            None if self.labels_mask is None else self.labels_mask[:num_train],
            None if md is None else md[:num_train],
        )
        test = DataSet(
            self.features[num_train:],
            None if self.labels is None else self.labels[num_train:],
            None if self.features_mask is None else self.features_mask[num_train:],
            None if self.labels_mask is None else self.labels_mask[num_train:],
            None if md is None else md[num_train:],
        )
        return train, test

    def shuffle(self, seed: Optional[int] = None):
        rng = np.random.default_rng(seed)
        perm = rng.permutation(self.num_examples())
        self.features = self.features[perm]
        if self.labels is not None:
            self.labels = self.labels[perm]
        if self.features_mask is not None:
            self.features_mask = self.features_mask[perm]
        if self.labels_mask is not None:
            self.labels_mask = self.labels_mask[perm]
        if self.example_metadata is not None:
            self.example_metadata = [self.example_metadata[i] for i in perm]
        return self

    def batch_by(self, batch_size: int):
        n = self.num_examples()
        out = []
        md = self.example_metadata
        for i in range(0, n, batch_size):
            out.append(DataSet(
                self.features[i:i + batch_size],
                None if self.labels is None else self.labels[i:i + batch_size],
                None if self.features_mask is None else self.features_mask[i:i + batch_size],
                None if self.labels_mask is None else self.labels_mask[i:i + batch_size],
                None if md is None else md[i:i + batch_size],
            ))
        return out

    @staticmethod
    def merge(datasets):
        def cat(xs):
            if any(x is None for x in xs):
                return None
            return np.concatenate(xs, axis=0)
        return DataSet(
            cat([d.features for d in datasets]),
            cat([d.labels for d in datasets]),
            cat([d.features_mask for d in datasets]),
            cat([d.labels_mask for d in datasets]),
        )
