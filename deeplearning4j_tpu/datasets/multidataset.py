"""MultiDataSet: minibatch with multiple feature/label arrays for
ComputationGraph (reference: ND4J `MultiDataSet` +
`MultiDataSetIterator`)."""

from __future__ import annotations

import dataclasses
from typing import List, Optional

import numpy as np


@dataclasses.dataclass
class MultiDataSet:
    features: List[np.ndarray]
    labels: List[np.ndarray]
    features_masks: Optional[List[Optional[np.ndarray]]] = None
    labels_masks: Optional[List[Optional[np.ndarray]]] = None

    def num_examples(self) -> int:
        return int(np.shape(self.features[0])[0])


class MultiDataSetIterator:
    """Resettable iterable of MultiDataSets."""

    def __init__(self, datasets: List[MultiDataSet]):
        self._datasets = list(datasets)

    def __iter__(self):
        return iter(self._datasets)

    def reset(self):
        pass
