"""Record readers + record→DataSet iterators (the DataVec seam).

Reference: DataVec's `RecordReader` protocol consumed by
`deeplearning4j-core`'s `RecordReaderDataSetIterator.java` (441 LoC),
`SequenceRecordReaderDataSetIterator.java` (478) and
`RecordReaderMultiDataSetIterator.java` (898): records (lists of
writable values) are assembled into minibatch feature/label arrays,
with one-hot label columns for classification and masking for
variable-length sequences.
"""

from __future__ import annotations

import csv
from pathlib import Path
from typing import Iterable, List, Optional, Sequence

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterator import DataSetIterator


class RecordReader:
    """One record = list of values (DataVec `RecordReader`)."""

    def __iter__(self):
        self.reset()
        while self.has_next():
            yield self.next_record()

    def has_next(self) -> bool:
        raise NotImplementedError

    def next_record(self) -> List:
        raise NotImplementedError

    def reset(self) -> None:
        raise NotImplementedError


class CollectionRecordReader(RecordReader):
    """In-memory records (DataVec `CollectionRecordReader`)."""

    def __init__(self, records: Iterable[Sequence]):
        self._records = [list(r) for r in records]
        self._pos = 0

    def has_next(self):
        return self._pos < len(self._records)

    def next_record(self):
        r = self._records[self._pos]
        self._pos += 1
        return r

    def reset(self):
        self._pos = 0


class CSVRecordReader(CollectionRecordReader):
    """CSV file → records of floats/strings (DataVec `CSVRecordReader`)."""

    def __init__(self, path, skip_lines: int = 0, delimiter: str = ","):
        records = []
        with open(path, newline="") as f:
            for i, row in enumerate(csv.reader(f, delimiter=delimiter)):
                if i < skip_lines or not row:
                    continue
                records.append([self._maybe_num(v) for v in row])
        super().__init__(records)

    @staticmethod
    def _maybe_num(v: str):
        try:
            return float(v)
        except ValueError:
            return v


class CSVSequenceRecordReader:
    """One CSV file per sequence (DataVec `CSVSequenceRecordReader`):
    `next_sequence()` → list of records (timesteps)."""

    def __init__(self, paths: Sequence, skip_lines: int = 0,
                 delimiter: str = ","):
        self.paths = [Path(p) for p in paths]
        self.skip_lines = skip_lines
        self.delimiter = delimiter
        self._pos = 0

    def __iter__(self):
        self.reset()
        while self.has_next():
            yield self.next_sequence()

    def has_next(self):
        return self._pos < len(self.paths)

    def next_sequence(self) -> List[List]:
        reader = CSVRecordReader(self.paths[self._pos],
                                 skip_lines=self.skip_lines,
                                 delimiter=self.delimiter)
        self._pos += 1
        return [r for r in reader]

    def reset(self):
        self._pos = 0


class ImageRecordReader(RecordReader):
    """Image files → [H*W*C...] pixel records + optional label from the
    parent directory name (DataVec `ImageRecordReader`)."""

    def __init__(self, height: int, width: int, channels: int = 3,
                 paths: Optional[Sequence] = None, label_from_dir: bool = True):
        self.height, self.width, self.channels = height, width, channels
        self.paths = [Path(p) for p in (paths or [])]
        self.label_from_dir = label_from_dir
        self.labels = sorted({p.parent.name for p in self.paths}) \
            if label_from_dir else []
        self._pos = 0

    def has_next(self):
        return self._pos < len(self.paths)

    def next_record(self):
        from PIL import Image
        p = self.paths[self._pos]
        self._pos += 1
        img = Image.open(p).resize((self.width, self.height))
        if self.channels == 1:
            img = img.convert("L")
        else:
            img = img.convert("RGB")
        arr = np.asarray(img, np.float32)
        if arr.ndim == 2:
            arr = arr[..., None]
        rec = list(arr.reshape(-1))
        if self.label_from_dir:
            rec.append(float(self.labels.index(p.parent.name)))
        return rec

    def reset(self):
        self._pos = 0


# ---------------------------------------------------------------- iterators
class RecordReaderDataSetIterator(DataSetIterator):
    """records → minibatches (reference
    `RecordReaderDataSetIterator.java`): `label_index` column becomes a
    one-hot label (classification, `num_classes` given) or a raw
    regression target."""

    def __init__(self, reader: RecordReader, batch_size: int,
                 label_index: Optional[int] = None,
                 num_classes: Optional[int] = None,
                 regression: bool = False):
        self.reader = reader
        self.batch_size = batch_size
        self.label_index = label_index
        self.num_classes = num_classes
        self.regression = regression
        if label_index is not None and not regression and not num_classes:
            raise ValueError("classification mode needs num_classes "
                             "(or set regression=True)")
        self.reader.reset()

    def reset(self):
        self.reader.reset()

    def has_next(self):
        return self.reader.has_next()

    def next(self) -> DataSet:
        feats, labels = [], []
        while self.reader.has_next() and len(feats) < self.batch_size:
            rec = self.reader.next_record()
            if self.label_index is None:
                feats.append([float(v) for v in rec])
                continue
            li = self.label_index if self.label_index >= 0 else len(rec) - 1
            label = rec[li]
            feat = [float(v) for i, v in enumerate(rec) if i != li]
            feats.append(feat)
            if self.regression:
                labels.append([float(label)])
            else:
                one_hot = np.zeros(self.num_classes, np.float32)
                one_hot[int(label)] = 1.0
                labels.append(one_hot)
        x = np.asarray(feats, np.float32)
        y = np.asarray(labels, np.float32) if labels else None
        return DataSet(x, y)

    def __iter__(self):
        self.reset()
        while self.has_next():
            yield self.next()


class SequenceRecordReaderDataSetIterator(DataSetIterator):
    """Aligned feature/label sequence readers → padded+masked RNN
    minibatches [B, T, F] (reference
    `SequenceRecordReaderDataSetIterator.java` ALIGN_END semantics).

    `bucket_boundaries` (TPU-first knob, SURVEY §7 "dynamic shapes"):
    per-batch max-length padding gives every distinct T its own XLA
    compile; with boundaries, T pads UP to the smallest bucket ≥ the
    batch max (last bucket = hard cap, longer sequences truncated), so
    the number of compiled programs is bounded by len(boundaries). The
    masks already make the extra padding a numeric no-op."""

    def __init__(self, feature_reader: CSVSequenceRecordReader,
                 label_reader: Optional[CSVSequenceRecordReader],
                 batch_size: int, num_classes: Optional[int] = None,
                 regression: bool = False, label_index: int = -1,
                 bucket_boundaries: Optional[Sequence[int]] = None):
        self.feature_reader = feature_reader
        self.label_reader = label_reader
        self.batch_size = batch_size
        self.num_classes = num_classes
        self.regression = regression
        self.label_index = label_index
        if bucket_boundaries and any(b <= 0 for b in bucket_boundaries):
            raise ValueError(
                f"bucket_boundaries must be positive, got {bucket_boundaries}")
        self.bucket_boundaries = (sorted(bucket_boundaries)
                                  if bucket_boundaries else None)
        self._truncated_count = 0
        self._warned_truncation = False
        self.reset()

    @property
    def truncated_count(self) -> int:
        """#sequences tail-truncated by the last bucket boundary."""
        return self._truncated_count

    def _bucket_len(self, T: int) -> int:
        if self.bucket_boundaries is None:
            return T
        for b in self.bucket_boundaries:
            if T <= b:
                return b
        return self.bucket_boundaries[-1]     # hard cap: truncate

    def reset(self):
        self.feature_reader.reset()
        if self.label_reader is not None:
            self.label_reader.reset()

    def has_next(self):
        return self.feature_reader.has_next()

    def next(self) -> DataSet:
        seqs, label_seqs = [], []
        while self.feature_reader.has_next() and len(seqs) < self.batch_size:
            fseq = self.feature_reader.next_sequence()
            if self.label_reader is not None:
                lseq = self.label_reader.next_sequence()
            else:  # label column inside the feature records
                li = self.label_index
                lseq = [[r[li if li >= 0 else len(r) - 1]] for r in fseq]
                fseq = [[v for i, v in enumerate(r)
                         if i != (li if li >= 0 else len(r) - 1)] for r in fseq]
            seqs.append(np.asarray(fseq, np.float32))
            label_seqs.append(np.asarray(lseq, np.float32))
        B = len(seqs)
        T = self._bucket_len(max(s.shape[0] for s in seqs))
        F = seqs[0].shape[1]
        if self.regression or self.num_classes is None:
            L = label_seqs[0].shape[1]
        else:
            L = self.num_classes
        x = np.zeros((B, T, F), np.float32)
        y = np.zeros((B, T, L), np.float32)
        mask = np.zeros((B, T), np.float32)
        for i, (s, l) in enumerate(zip(seqs, label_seqs)):
            t = s.shape[0]
            if t > T:
                # hard-cap truncation (bucketing only) keeps the TAIL:
                # ALIGN_END semantics put the informative final steps
                # (and sequence-classification targets) at the end
                self._truncated_count += 1
                if not self._warned_truncation:
                    self._warned_truncation = True
                    import logging
                    logging.getLogger(__name__).warning(
                        "sequence of length %d exceeds the last bucket "
                        "boundary %d and was TAIL-truncated (keeping the "
                        "final %d steps); further truncations are counted "
                        "silently — see .truncated_count. Raise the last "
                        "bucket_boundaries entry to keep full sequences",
                        s.shape[0], T, T)
                t = T
                s, l = s[-T:], l[-T:]
            # (a label sequence misaligned with its features still
            # raises below — truncation never masks corrupted data)
            x[i, :t] = s
            if self.regression or self.num_classes is None:
                y[i, :t] = l
            else:
                for ti in range(t):
                    y[i, ti, int(l[ti, 0])] = 1.0
            mask[i, :t] = 1.0
        return DataSet(x, y, features_mask=mask, labels_mask=mask)

    def __iter__(self):
        self.reset()
        while self.has_next():
            yield self.next()
