"""Dataset fetchers: MNIST / EMNIST / Iris iterators.

Reference: `deeplearning4j-core` `base/MnistFetcher.java`,
`datasets/fetchers/MnistDataFetcher.java`, iterator impls under
`datasets/iterator/impl/` (MnistDataSetIterator, IrisDataSetIterator).

Network policy: fetchers first look for cached copies under
``~/.deeplearning4j_tpu/datasets`` (same idea as the reference's
``~/.deeplearning4j`` cache), then attempt download, and finally fall
back to a clearly-flagged DETERMINISTIC SYNTHETIC surrogate with the
same shapes/classes so training code and benchmarks run in air-gapped
environments. `is_synthetic` reports which path was taken.
"""

from __future__ import annotations

import gzip
import os
import struct
import urllib.request
from pathlib import Path

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterator import ArrayDataSetIterator

CACHE_DIR = Path(os.environ.get("DL4J_TPU_DATA_DIR", "~/.deeplearning4j_tpu/datasets")).expanduser()

_MNIST_URLS = {
    "train_images": "https://storage.googleapis.com/cvdf-datasets/mnist/train-images-idx3-ubyte.gz",
    "train_labels": "https://storage.googleapis.com/cvdf-datasets/mnist/train-labels-idx1-ubyte.gz",
    "test_images": "https://storage.googleapis.com/cvdf-datasets/mnist/t10k-images-idx3-ubyte.gz",
    "test_labels": "https://storage.googleapis.com/cvdf-datasets/mnist/t10k-labels-idx1-ubyte.gz",
}


def _read_idx(path: Path) -> np.ndarray:
    with gzip.open(path, "rb") as f:
        magic = struct.unpack(">HBB", f.read(4))
        ndim = magic[2]
        shape = struct.unpack(">" + "I" * ndim, f.read(4 * ndim))
        return np.frombuffer(f.read(), dtype=np.uint8).reshape(shape)


def _try_download(url: str, dest: Path) -> bool:
    try:
        dest.parent.mkdir(parents=True, exist_ok=True)
        urllib.request.urlretrieve(url, dest)  # noqa: S310
        return True
    except Exception:
        return False


def _synthetic_templates(num: int, num_classes: int, seed: int, *,
                         side: int = 28, tpl_seed: int, freq_hi: float):
    """Shared surrogate generator: each class is a fixed low-frequency
    sinusoid template + per-example noise; linearly separable enough
    that small CNNs reach high accuracy, hard enough that accuracy is
    meaningful."""
    rng = np.random.default_rng(seed)
    templates = []
    tpl_rng = np.random.default_rng(tpl_seed)
    yy, xx = np.mgrid[0:side, 0:side].astype(np.float32) / side
    for _ in range(num_classes):
        fx, fy = tpl_rng.uniform(1, freq_hi, 2)
        px, py = tpl_rng.uniform(0, 2 * np.pi, 2)
        tpl = 0.5 + 0.5 * np.sin(2 * np.pi * fx * xx + px) * np.cos(2 * np.pi * fy * yy + py)
        templates.append(tpl.astype(np.float32))
    labels = rng.integers(0, num_classes, size=num)
    images = np.stack([templates[c] for c in labels])
    noise = rng.standard_normal(images.shape, dtype=np.float32)
    images = np.clip(images + 0.25 * noise, 0, 1)
    onehot = np.eye(num_classes, dtype=np.float32)[labels]
    return images.reshape(num, side * side).astype(np.float32), onehot


def _synthetic_digits(num: int, seed: int, side: int = 28):
    """MNIST surrogate (template seed kept stable across refactors)."""
    return _synthetic_templates(num, 10, seed, side=side,
                                tpl_seed=20260729, freq_hi=4)


def load_mnist(train: bool = True, num_examples: int | None = None):
    """Returns (features [N, 784] float32 in [0,1], labels [N,10] one-hot,
    synthetic_flag)."""
    split = "train" if train else "test"
    img_p = CACHE_DIR / "mnist" / f"{split}_images.gz"
    lab_p = CACHE_DIR / "mnist" / f"{split}_labels.gz"
    if not img_p.exists():
        _try_download(_MNIST_URLS[f"{split}_images"], img_p)
        _try_download(_MNIST_URLS[f"{split}_labels"], lab_p)
    if img_p.exists() and lab_p.exists():
        try:
            images = _read_idx(img_p).astype(np.float32) / 255.0
            labels = _read_idx(lab_p)
            n = images.shape[0]
            feats = images.reshape(n, -1)
            onehot = np.eye(10, dtype=np.float32)[labels]
            if num_examples:
                feats, onehot = feats[:num_examples], onehot[:num_examples]
            return feats, onehot, False
        except Exception:
            pass
    n = num_examples or (60000 if train else 10000)
    feats, onehot = _synthetic_digits(n, seed=1 if train else 2)
    return feats, onehot, True


class MnistDataSetIterator(ArrayDataSetIterator):
    """Reference `MnistDataSetIterator(batch, train, seed)` — yields
    flattened [batch, 784] features + one-hot labels."""

    def __init__(self, batch_size: int, train: bool = True, seed: int = 123,
                 num_examples: int | None = None, shuffle: bool | None = None,
                 flatten: bool = True):
        """`flatten=False` yields NHWC [B,28,28,1] for conv nets whose
        config declares InputType.convolutional (the reference pairs
        flat output with convolutionalFlat + an auto preprocessor)."""
        feats, labels, synthetic = load_mnist(train, num_examples)
        if not flatten:
            feats = feats.reshape(-1, 28, 28, 1)
        self.is_synthetic = synthetic
        super().__init__(feats, labels, batch_size=batch_size,
                         shuffle=train if shuffle is None else shuffle, seed=seed)


# Fisher's Iris — the real 150-sample dataset is tiny; generated
# surrogate keeps class structure (3 Gaussian clusters in 4-d, one pair
# overlapping like versicolor/virginica).
def load_iris(seed: int = 7):
    rng = np.random.default_rng(seed)
    means = np.array([[5.0, 3.4, 1.5, 0.2], [5.9, 2.8, 4.3, 1.3], [6.6, 3.0, 5.6, 2.0]])
    stds = np.array([[0.35, 0.38, 0.17, 0.10], [0.52, 0.31, 0.47, 0.20], [0.64, 0.32, 0.55, 0.27]])
    feats, labels = [], []
    for c in range(3):
        feats.append(means[c] + stds[c] * rng.standard_normal((50, 4)))
        labels.extend([c] * 50)
    x = np.concatenate(feats).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[np.array(labels)]
    perm = rng.permutation(150)
    return x[perm], y[perm]


class IrisDataSetIterator(ArrayDataSetIterator):
    def __init__(self, batch_size: int = 150, num_examples: int = 150, seed: int = 7):
        x, y = load_iris(seed)
        super().__init__(x[:num_examples], y[:num_examples], batch_size=batch_size)


# ---------------------------------------------------------------- EMNIST
# Reference `EmnistFetcher`/`EmnistDataSetIterator` — EMNIST splits
# extend MNIST with letters. Downloads use the NIST mirrors; offline the
# surrogate generalizes _synthetic_digits to `num_classes` templates.
_EMNIST_CLASSES = {"letters": 26, "digits": 10, "balanced": 47,
                   "byclass": 62, "bymerge": 47, "mnist": 10}


def _synthetic_classes(num: int, num_classes: int, seed: int, side: int = 28):
    return _synthetic_templates(num, num_classes, seed, side=side,
                                tpl_seed=20260730 + num_classes, freq_hi=5)


def load_emnist(split: str = "balanced", train: bool = True,
                num_examples: int | None = None):
    """(features [N,784], one-hot labels, synthetic_flag)."""
    if split not in _EMNIST_CLASSES:
        raise ValueError(f"Unknown EMNIST split {split!r}: {sorted(_EMNIST_CLASSES)}")
    nc = _EMNIST_CLASSES[split]
    n = num_examples or (10000 if train else 2000)
    feats, onehot = _synthetic_classes(n, nc, seed=11 if train else 12)
    return feats, onehot, True


class EmnistDataSetIterator(ArrayDataSetIterator):
    """Reference `EmnistDataSetIterator(dataset, batch, train)`."""

    def __init__(self, split: str = "balanced", batch_size: int = 32,
                 train: bool = True, num_examples: int | None = None,
                 seed: int = 123):
        feats, labels, synthetic = load_emnist(split, train, num_examples)
        self.is_synthetic = synthetic
        self.num_classes = _EMNIST_CLASSES[split]
        super().__init__(feats, labels, batch_size=batch_size,
                         shuffle=train, seed=seed)


# ---------------------------------------------------------------- CIFAR-10
def load_cifar10(train: bool = True, num_examples: int | None = None):
    """(features [N,32,32,3] in [0,1] NHWC, one-hot labels,
    synthetic_flag). Surrogate: per-class color+texture templates."""
    n = num_examples or (50000 if train else 10000)
    rng = np.random.default_rng(21 if train else 22)
    tpl_rng = np.random.default_rng(20260731)
    yy, xx = np.mgrid[0:32, 0:32].astype(np.float32) / 32
    templates = []
    for _ in range(10):
        chans = []
        for _c in range(3):
            fx, fy = tpl_rng.uniform(0.5, 4, 2)
            px, py = tpl_rng.uniform(0, 2 * np.pi, 2)
            chans.append(0.5 + 0.5 * np.sin(2 * np.pi * fx * xx + px) *
                         np.cos(2 * np.pi * fy * yy + py))
        templates.append(np.stack(chans, -1).astype(np.float32))
    labels = rng.integers(0, 10, size=n)
    images = np.stack([templates[c] for c in labels])
    noise = rng.standard_normal(images.shape, dtype=np.float32)
    images = np.clip(images + 0.2 * noise, 0, 1)
    return images, np.eye(10, dtype=np.float32)[labels], True


class Cifar10DataSetIterator(ArrayDataSetIterator):
    """Reference `CifarDataSetIterator` — NHWC [B,32,32,3] batches."""

    def __init__(self, batch_size: int = 32, train: bool = True,
                 num_examples: int | None = None, seed: int = 123):
        feats, labels, synthetic = load_cifar10(train, num_examples)
        self.is_synthetic = synthetic
        super().__init__(feats, labels, batch_size=batch_size,
                         shuffle=train, seed=seed)


def load_lfw(num_examples: int | None = None, num_labels: int = 5749,
             use_subset: bool = True, image_size: int = 64):
    """LFW faces (reference `LFWDataSetIterator.java` / `LFWFetcher`:
    13,233 images, 5,749 people; `use_subset` = the "lfw-a" subset).
    Returns ([N, S, S, 3] float32 NHWC, one-hot labels, synthetic_flag).
    Surrogate when offline: per-identity face-like templates (oval +
    eye/mouth blobs at identity-specific offsets) + noise."""
    n_ids = min(num_labels, 40 if use_subset else 5749)
    n = num_examples or (1054 if use_subset else 13233)
    rng = np.random.default_rng(31)
    tpl_rng = np.random.default_rng(20260801)
    s = image_size
    yy, xx = np.mgrid[0:s, 0:s].astype(np.float32) / s
    templates = []
    for _ in range(n_ids):
        cx, cy = tpl_rng.uniform(0.4, 0.6, 2)
        rx, ry = tpl_rng.uniform(0.22, 0.3, 2)
        face = np.clip(1.2 - (((xx - cx) / rx) ** 2 + ((yy - cy) / ry) ** 2), 0, 1)
        for bx, by in ((cx - rx / 2, cy - ry / 3), (cx + rx / 2, cy - ry / 3),
                       (cx, cy + ry / 2)):
            face -= 0.5 * np.exp(-(((xx - bx) ** 2 + (yy - by) ** 2)
                                   / tpl_rng.uniform(0.001, 0.004)))
        tone = tpl_rng.uniform(0.5, 1.0, 3).astype(np.float32)
        templates.append(np.clip(face[..., None] * tone, 0, 1).astype(np.float32))
    labels = rng.integers(0, n_ids, size=n)
    images = np.stack([templates[c] for c in labels])
    noise = rng.standard_normal(images.shape, dtype=np.float32)
    images = np.clip(images + 0.1 * noise, 0, 1)
    return images, np.eye(n_ids, dtype=np.float32)[labels], True


class LFWDataSetIterator(ArrayDataSetIterator):
    """Reference `datasets/iterator/impl/LFWDataSetIterator.java`."""

    def __init__(self, batch_size: int = 32, num_examples: int | None = None,
                 num_labels: int = 5749, use_subset: bool = True,
                 image_size: int = 64, train: bool = True, seed: int = 123):
        feats, labels, synthetic = load_lfw(num_examples, num_labels,
                                            use_subset, image_size)
        self.is_synthetic = synthetic
        super().__init__(feats, labels, batch_size=batch_size,
                         shuffle=train, seed=seed)
