"""DataSetIterator protocol + framework-level wrappers.

Reference: ND4J `DataSetIterator` + deeplearning4j `datasets/iterator/`
(AsyncDataSetIterator with background prefetch, MultipleEpochsIterator,
EarlyTerminationDataSetIterator, SamplingDataSetIterator,
BenchmarkDataSetIterator, ExistingDataSetIterator…).

The protocol is a resettable Python iterable of `DataSet`s; `fit()`
accepts any of these. `AsyncDataSetIterator` reproduces the reference's
ETL/compute overlap (background prefetch thread feeding a bounded
queue, `datasets/iterator/AsyncDataSetIterator.java`) — on TPU this
overlaps host-side batch assembly with device steps.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Iterable, Iterator, List, Optional

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet


class DataSetIterator:
    """Base: iterable of DataSet minibatches with reset().

    Checkpointable iterators additionally implement the position
    contract `cursor()`/`seek(cursor)` (fault/ runtime): `cursor()`
    returns a json-safe dict pinning the ingest position — epoch index,
    batches CONSUMED within it, and the shuffle seed — and
    `seek(cursor)` repositions a fresh iterator there so a resumed run
    replays no consumed batch and sees the exact same remaining batch
    sequence (shuffle permutations are re-derived from the seed, not
    stored). The base returns None / raises: not every source is
    seekable."""

    def __iter__(self) -> Iterator[DataSet]:
        raise NotImplementedError

    def reset(self) -> None:
        pass

    def cursor(self) -> Optional[dict]:
        return None

    def seek(self, cursor: dict) -> None:
        raise NotImplementedError(
            f"{type(self).__name__} does not implement the "
            f"cursor()/seek() position contract")

    def batch_size(self) -> Optional[int]:
        return None

    def total_outcomes(self) -> Optional[int]:
        return None

    def input_columns(self) -> Optional[int]:
        return None


class ListDataSetIterator(DataSetIterator):
    """Iterate a pre-built list of DataSets (reference
    `ListDataSetIterator.java`)."""

    def __init__(self, datasets: List[DataSet], batch_size: Optional[int] = None):
        if batch_size is not None:
            merged = DataSet.merge(datasets)
            datasets = merged.batch_by(batch_size)
        self._datasets = datasets
        self._batch = batch_size

    def __iter__(self):
        return iter(self._datasets)

    def batch_size(self):
        return self._batch


class ArrayDataSetIterator(DataSetIterator):
    """Minibatches over (features, labels) arrays, optional shuffle each
    epoch."""

    def __init__(self, features, labels=None, batch_size: int = 32,
                 shuffle: bool = False, seed: int = 123,
                 features_mask=None, labels_mask=None, drop_last: bool = False):
        self.features = np.asarray(features)
        self.labels = None if labels is None else np.asarray(labels)
        self.features_mask = None if features_mask is None else np.asarray(features_mask)
        self.labels_mask = None if labels_mask is None else np.asarray(labels_mask)
        self._batch = batch_size
        self._shuffle = shuffle
        self._seed = seed
        self._rng = np.random.default_rng(seed)
        self._drop_last = drop_last
        # cursor()/seek() position contract (fault/ checkpointing):
        # epoch = passes started, yielded = batches consumed this pass,
        # skip = batches to silently skip at the next pass start
        self._epochs_started = 0
        self._yielded = 0
        self._skip = 0

    def __iter__(self):
        n = self.features.shape[0]
        idx = np.arange(n)
        if self._shuffle:
            self._rng.shuffle(idx)
        self._epochs_started += 1
        skip, self._skip = self._skip, 0
        self._yielded = skip
        stop = n - (n % self._batch) if self._drop_last else n
        for bi, i in enumerate(range(0, stop, self._batch)):
            sel = idx[i:i + self._batch]
            if self._drop_last and len(sel) < self._batch:
                break
            if bi < skip:        # seek(): consumed by the interrupted run
                continue
            # count BEFORE yielding: code after a yield only runs at the
            # NEXT pull, so a cursor() taken while the consumer holds
            # this batch must already include it
            self._yielded += 1
            yield DataSet(
                self.features[sel],
                None if self.labels is None else self.labels[sel],
                None if self.features_mask is None else self.features_mask[sel],
                None if self.labels_mask is None else self.labels_mask[sel],
            )

    def cursor(self):
        """Position contract: epoch (0-based pass index), batch
        (consumed within the pass), and the shuffle seed the
        permutation stream derives from. Valid mid-pass."""
        return {"epoch": max(0, self._epochs_started - 1),
                "batch": int(self._yielded),
                "seed": int(self._seed),
                "shuffle": bool(self._shuffle)}

    def seek(self, cursor: dict):
        """Reposition to `cursor` without replaying consumed batches:
        the shuffle rng is rebuilt from the seed and fast-forwarded by
        replaying the prior passes' permutation draws (a Generator's
        shuffle consumes state by LENGTH only), so the resumed pass
        draws the identical permutation the interrupted run was
        consuming — and the next pass continues the same stream."""
        epoch = int(cursor["epoch"])
        self._seed = int(cursor.get("seed", self._seed))
        self._rng = np.random.default_rng(self._seed)
        if self._shuffle:
            n = self.features.shape[0]
            scratch = np.arange(n)
            for _ in range(epoch):
                self._rng.shuffle(scratch)
        self._epochs_started = epoch
        self._skip = int(cursor["batch"])
        self._yielded = 0

    def batch_size(self):
        return self._batch

    def total_outcomes(self):
        return None if self.labels is None else self.labels.shape[-1]


class AsyncDataSetIterator(DataSetIterator):
    """Background-thread prefetch wrapper (reference
    `AsyncDataSetIterator.java`: bounded queue + worker thread so ETL
    overlaps device compute).

    Early-abandon safe: a consumer that `break`s out (or otherwise
    closes the generator) must not leave the worker blocked forever on
    the bounded `q.put` — the generator's finally clause signals the
    stop event, drains the queue so any in-flight put completes, and
    joins the worker, so no daemon thread (or its grip on the base
    iterator) outlives the consumer.

    Unbounded bases (online/iterator.py): the worker may be blocked
    INSIDE the base's `next()` — a streaming iterator's watermark wait,
    not the bounded put — where the stop event is invisible. Bases
    expose an ``abandon()`` hook for exactly this; the teardown calls
    it (when present) before joining, so the prefetch thread unblocks
    within one poll slice instead of hanging until the watermark
    timeout or the next record. The hook aborts only the CURRENT pass;
    re-iterating starts fresh."""

    _SENTINEL = object()

    def __init__(self, base: DataSetIterator, prefetch: int = 4):
        self.base = base
        self.prefetch = prefetch
        # batches handed to the CONSUMER this pass — the prefetch queue
        # means the base iterator runs AHEAD of consumption, so the
        # checkpointable position is counted here, not in the base
        self._consumed = 0
        self._seek_offset = 0

    def __iter__(self):
        q: "queue.Queue" = queue.Queue(maxsize=self.prefetch)
        stop = threading.Event()
        err: list = []

        def worker():
            try:
                for ds in self.base:
                    # bounded put with a stop check: a full queue whose
                    # consumer has gone away must not block forever
                    while not stop.is_set():
                        try:
                            q.put(ds, timeout=0.05)
                            break
                        except queue.Full:
                            continue
                    if stop.is_set():
                        return
            except BaseException as e:  # propagate into consumer
                err.append(e)
            finally:
                # the sentinel must REACH a live consumer (it blocks in
                # q.get until one arrives) but must not block forever
                # for an abandoned one — same stop-aware bounded put
                while not stop.is_set():
                    try:
                        q.put(self._SENTINEL, timeout=0.05)
                        break
                    except queue.Full:
                        continue

        t = threading.Thread(target=worker, daemon=True)
        t.start()
        # a seek() positions the base mid-pass; consumption resumes
        # from that absolute batch index, not from zero
        self._consumed, self._seek_offset = self._seek_offset, 0
        try:
            while True:
                item = q.get()
                if item is self._SENTINEL:
                    if err:
                        raise err[0]
                    return
                # count BEFORE yielding (a cursor() taken while the
                # consumer holds this batch must already include it)
                self._consumed += 1
                yield item
        finally:
            # GeneratorExit (consumer break/close) and normal exhaustion
            # both land here: stop the worker, unblock any pending put,
            # and reap the thread. An unbounded base's blocking read is
            # interrupted through its abandon() hook — the stop event
            # only covers the put side.
            stop.set()
            abandon = getattr(self.base, "abandon", None)
            if abandon is not None:
                abandon()
            while True:
                try:
                    q.get_nowait()
                except queue.Empty:
                    break
            t.join(timeout=5)

    def reset(self):
        self.base.reset()

    def cursor(self):
        """Position contract: the base's cursor with the batch index
        replaced by what the CONSUMER has actually taken — prefetched-
        but-unconsumed batches must be replayed after a restore, not
        skipped (they never reached the training loop)."""
        cur = self.base.cursor()
        if cur is None:
            return None
        cur = dict(cur)
        cur["batch"] = int(self._consumed)
        return cur

    def seek(self, cursor: dict):
        self.base.seek(cursor)
        self._seek_offset = int(cursor.get("batch", 0))

    def batch_size(self):
        return self.base.batch_size()


class MultipleEpochsIterator(DataSetIterator):
    """Replays the base iterator N times (reference
    `MultipleEpochsIterator.java`)."""

    def __init__(self, epochs: int, base: DataSetIterator):
        self.epochs = epochs
        self.base = base

    def __iter__(self):
        for _ in range(self.epochs):
            self.base.reset()
            yield from self.base


class EarlyTerminationDataSetIterator(DataSetIterator):
    """Caps the number of minibatches (reference
    `EarlyTerminationDataSetIterator.java`)."""

    def __init__(self, base: DataSetIterator, max_batches: int):
        self.base = base
        self.max_batches = max_batches

    def __iter__(self):
        for i, ds in enumerate(self.base):
            if i >= self.max_batches:
                return
            yield ds

    def reset(self):
        self.base.reset()


class SamplingDataSetIterator(DataSetIterator):
    """Samples random minibatches with replacement from one DataSet
    (reference `SamplingDataSetIterator.java`)."""

    def __init__(self, dataset: DataSet, batch_size: int, total_batches: int, seed: int = 123):
        self.dataset = dataset
        self._batch = batch_size
        self.total_batches = total_batches
        self._rng = np.random.default_rng(seed)

    def __iter__(self):
        n = self.dataset.num_examples()
        for _ in range(self.total_batches):
            sel = self._rng.integers(0, n, size=self._batch)
            d = self.dataset
            yield DataSet(
                d.features[sel],
                None if d.labels is None else d.labels[sel],
                None if d.features_mask is None else d.features_mask[sel],
                None if d.labels_mask is None else d.labels_mask[sel],
            )

    def batch_size(self):
        return self._batch


class BenchmarkDataSetIterator(DataSetIterator):
    """Synthetic fixed-shape batches to isolate compute from ETL
    (reference `BenchmarkDataSetIterator.java`)."""

    def __init__(self, feature_shape, num_classes: int, total_batches: int, seed: int = 42,
                 label_shape=None):
        rng = np.random.default_rng(seed)
        self.features = rng.standard_normal(feature_shape).astype(np.float32)
        batch = feature_shape[0]
        if label_shape is not None:
            self.labels = rng.standard_normal(label_shape).astype(np.float32)
        else:
            idx = rng.integers(0, num_classes, size=batch)
            self.labels = np.eye(num_classes, dtype=np.float32)[idx]
        self.total_batches = total_batches

    def __iter__(self):
        for _ in range(self.total_batches):
            yield DataSet(self.features, self.labels)

    def batch_size(self):
        return self.features.shape[0]


class TimedDataSetIterator(DataSetIterator):
    """Times each batch's assembly (the ETL cost: shuffle, slice, disk,
    decode — whatever the wrapped iterator does to produce a DataSet)
    and publishes it as `last_etl_ms` / `total_etl_ms`.

    The fit loops wrap their iterator with this and pass `last_etl_ms`
    into the listener bus's `etl_ms` info key (what PerformanceListener
    reports) — so ETL attribution comes from the iterator itself, not
    from loop-side clock bookkeeping. When the monitor substrate is
    enabled, each batch also lands in the `training_etl_seconds`
    histogram via MonitorListener; this wrapper itself keeps zero
    monitor coupling (two `perf_counter` reads per batch)."""

    def __init__(self, base: DataSetIterator):
        self.base = base
        self.last_etl_ms = 0.0
        self.total_etl_ms = 0.0
        self.batches = 0

    def __iter__(self):
        it = iter(self.base)
        while True:
            t0 = time.perf_counter()
            try:
                ds = next(it)
            except StopIteration:
                return
            self.last_etl_ms = (time.perf_counter() - t0) * 1e3
            self.total_etl_ms += self.last_etl_ms
            self.batches += 1
            yield ds

    def reset(self):
        self.base.reset()

    def cursor(self):
        return self.base.cursor()

    def seek(self, cursor):
        self.base.seek(cursor)

    def batch_size(self):
        return self.base.batch_size()

    def total_outcomes(self):
        return self.base.total_outcomes()

    def input_columns(self):
        return self.base.input_columns()


def as_iterator(data, labels=None, batch_size: int = 32, **kw) -> DataSetIterator:
    """Coerce fit()-style inputs into a DataSetIterator."""
    if isinstance(data, DataSetIterator):
        return data
    if isinstance(data, DataSet):
        return ListDataSetIterator(data.batch_by(batch_size))
    if isinstance(data, (list, tuple)) and data and isinstance(data[0], DataSet):
        return ListDataSetIterator(list(data))
    return ArrayDataSetIterator(data, labels, batch_size=batch_size, **kw)
