"""Native CSV loader — ctypes binding over native/csv/dl4j_csv.cpp.

Reference parity: DataVec's `CSVRecordReader` feeding
`RecordReaderDataSetIterator` runs on the JVM with native-speed IO; the
TPU framework's bulk-numeric path is the C++ single-pass parser
(compiled on first use, like the HDF5 shim), with a NumPy fallback when
no toolchain is available. Returns float32 matrices ready for
`DataSet`/device upload; non-numeric fields parse as NaN so the caller
chooses a policy.
"""

from __future__ import annotations

import ctypes
from pathlib import Path
from typing import Optional, Tuple

import numpy as np

from deeplearning4j_tpu.util.native_build import NATIVE_ROOT, build

_SRC = NATIVE_ROOT / "csv" / "dl4j_csv.cpp"

_lib = None
_lib_failed = False


def _load_lib():
    global _lib, _lib_failed
    if _lib is not None or _lib_failed:
        return _lib
    try:
        so = build(_SRC, "libdl4j_csv.so", extra_flags=["-O3"])
        lib = ctypes.CDLL(str(so))
        lib.dl4j_csv_shape.argtypes = [
            ctypes.c_char_p, ctypes.c_char, ctypes.c_long,
            ctypes.POINTER(ctypes.c_long), ctypes.POINTER(ctypes.c_long)]
        lib.dl4j_csv_shape.restype = ctypes.c_int
        lib.dl4j_csv_parse.argtypes = [
            ctypes.c_char_p, ctypes.c_char, ctypes.c_long,
            ctypes.POINTER(ctypes.c_float), ctypes.c_long, ctypes.c_long]
        lib.dl4j_csv_parse.restype = ctypes.c_long
        _lib = lib
    except Exception:
        _lib_failed = True
        _lib = None
    return _lib


def native_available() -> bool:
    return _load_lib() is not None


def load_csv_matrix(path: str, *, delimiter: str = ",",
                    skip_header: int = 0) -> np.ndarray:
    """Parse a numeric CSV file into a float32 [rows, cols] matrix.
    Unparseable fields become NaN."""
    lib = _load_lib()
    if lib is None:
        return _numpy_fallback(path, delimiter, skip_header)
    rows = ctypes.c_long()
    cols = ctypes.c_long()
    rc = lib.dl4j_csv_shape(str(path).encode(), delimiter.encode(),
                            skip_header, ctypes.byref(rows),
                            ctypes.byref(cols))
    if rc != 0:
        raise FileNotFoundError(path)
    out = np.empty((rows.value, cols.value), np.float32)
    got = lib.dl4j_csv_parse(
        str(path).encode(), delimiter.encode(), skip_header,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        rows.value, cols.value)
    if got < 0:
        raise IOError(f"native CSV parse failed for {path}")
    return out[:got]


def _split_quoted(line: str, delimiter: str):
    """Quote-aware field split (same rule as the native parser)."""
    fields, cur, quoted = [], [], False
    for ch in line:
        if ch == '"':
            quoted = not quoted
        elif ch == delimiter and not quoted:
            fields.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    fields.append("".join(cur))
    return fields


def _numpy_fallback(path, delimiter, skip_header) -> np.ndarray:
    """Pure-Python fallback with the native parser's semantics: comment
    (#) and blank lines are dropped BEFORE skip_header counts, fields
    split quote-aware, unparseable fields become NaN, ragged rows are
    NaN-padded/truncated to the first data row's column count."""
    rows = []
    cols = None
    with open(path) as f:
        data_line = 0
        for line in f:
            line = line.rstrip("\r\n")
            if not line or line.startswith("#"):
                continue
            if data_line >= skip_header:
                row = []
                for field in _split_quoted(line, delimiter):
                    try:
                        row.append(float(field.strip()))
                    except ValueError:
                        row.append(float("nan"))
                if cols is None:
                    cols = len(row)
                row = (row + [float("nan")] * cols)[:cols]
                rows.append(row)
            data_line += 1
    if not rows:
        return np.zeros((0, 0), np.float32)
    return np.asarray(rows, np.float32)


def load_csv_dataset(path: str, *, label_index: int = -1,
                     num_classes: Optional[int] = None,
                     delimiter: str = ",", skip_header: int = 0,
                     regression: bool = False):
    """CSV file → DataSet (the `CSVRecordReader` +
    `RecordReaderDataSetIterator(label_index, num_classes)` composition).
    Classification labels one-hot by default."""
    from deeplearning4j_tpu.datasets.dataset import DataSet

    m = load_csv_matrix(path, delimiter=delimiter, skip_header=skip_header)
    if label_index < 0:
        label_index = m.shape[1] + label_index
    features = np.delete(m, label_index, axis=1)
    raw = m[:, label_index]
    if regression:
        labels = raw[:, None].astype(np.float32)
    else:
        if len(raw) and not np.all(np.isfinite(raw)):
            bad = np.nonzero(~np.isfinite(raw))[0][:5].tolist()
            raise ValueError(
                f"non-numeric class labels at rows {bad} in {path}")
        idx = np.rint(raw).astype(np.int64)
        if len(idx) and (idx.min() < 0
                         or np.abs(raw - idx).max() > 1e-6):
            raise ValueError(
                f"class labels in {path} must be non-negative integers")
        n = num_classes or (int(idx.max()) + 1 if len(idx) else 0)
        if len(idx) and idx.max() >= n:
            raise ValueError(
                f"label {int(idx.max())} >= num_classes {n} in {path}")
        labels = np.eye(n, dtype=np.float32)[idx]
    return DataSet(features.astype(np.float32), labels)
