"""Fault-runtime exception types (dependency-free so every layer —
util serializers, checkpointer, drills — can raise/catch them without
import cycles)."""

from __future__ import annotations


class CheckpointCorruptError(RuntimeError):
    """A checkpoint failed integrity verification (checksum mismatch,
    truncated shard, unreadable container). Raised instead of the raw
    numpy/zip traceback so callers can implement previous-checkpoint
    fallback."""


class ElasticMembershipError(RuntimeError):
    """The elastic membership control plane is unreachable or rejected
    a request after bounded retries (parallel/elastic.py). Distinct
    from training/runtime failures so callers can decide whether to
    keep training on the last known topology or abort."""


class ElasticReconfiguration(Exception):
    """Control-flow signal of the elastic runtime: the membership
    generation changed and every process agreed (via the in-band drain
    sync) to leave the fit at the SAME step boundary, after a drain
    checkpoint committed. Raised by the drain listener inside fit;
    caught by `ElasticTrainer`, which tears the distributed runtime
    down and re-forms the mesh for the new generation. Not an error."""

    def __init__(self, generation: int, step: int = -1):
        super().__init__(
            f"elastic reconfiguration to generation {generation} "
            f"(drained at step {step})")
        self.generation = generation
        self.step = step


class SimulatedPreemption(BaseException):
    """Raised by the fault-injection drill at the scripted step.

    Derives from BaseException (like KeyboardInterrupt) so ordinary
    `except Exception` recovery blocks inside training code cannot
    swallow the simulated kill — a real SIGTERM would not be
    catchable there either."""

    def __init__(self, step: int):
        super().__init__(f"simulated preemption at step {step}")
        self.step = step
