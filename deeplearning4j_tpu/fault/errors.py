"""Fault-runtime exception types (dependency-free so every layer —
util serializers, checkpointer, drills — can raise/catch them without
import cycles)."""

from __future__ import annotations


class CheckpointCorruptError(RuntimeError):
    """A checkpoint failed integrity verification (checksum mismatch,
    truncated shard, unreadable container). Raised instead of the raw
    numpy/zip traceback so callers can implement previous-checkpoint
    fallback."""


class SimulatedPreemption(BaseException):
    """Raised by the fault-injection drill at the scripted step.

    Derives from BaseException (like KeyboardInterrupt) so ordinary
    `except Exception` recovery blocks inside training code cannot
    swallow the simulated kill — a real SIGTERM would not be
    catchable there either."""

    def __init__(self, step: int):
        super().__init__(f"simulated preemption at step {step}")
        self.step = step
