"""Async, atomic, per-host-sharded full-state checkpointer.

Write protocol (preemption cannot tear a checkpoint):

1. `save(state, step)` — the device→host snapshot already happened in
   `capture_training_state` (on the training thread, at a step
   boundary, before the next dispatch can donate the buffers); save()
   only enqueues the host trees and returns. Training never waits on
   the filesystem.
2. a single background writer thread serializes the snapshot into
   ``<dir>/.tmp-ckpt-<step>/``: one ``shard-<process>.npz`` with every
   array (flat ``\\x1f``-path keys), one ``manifest-<process>.json``
   with per-array crc32 checksums, then the merged ``MANIFEST.json``.
   Every file is flushed + fsync'd, the tmp dir fsync'd, then atomically
   renamed to ``ckpt-<step>`` and the parent dir fsync'd. A kill at any
   instant leaves either a complete committed checkpoint or an ignored
   ``.tmp-*`` orphan (GC'd on the next commit) — never a half-readable
   one.
3. retention: keep the newest `keep_last` checkpoints plus every
   checkpoint whose step is a multiple of `keep_every` (the
   reference CheckpointListener's keepLast/keepEvery semantics);
   everything else is deleted after the commit.

Multi-process: capture requires fully-addressable leaves (replicated /
data-parallel state — every host already holds the complete trees;
TP-sharded multi-host state goes through ShardedCheckpoint/Orbax), so
process 0 writes the single array shard and every other process
contributes a barrier ``manifest-<p>.json``; process 0 waits for all of
them, merges ``MANIFEST.json`` and performs the commit rename — a
commit therefore certifies every process reached the same step.

If a newer snapshot arrives while the writer is busy, the older pending
(uncommitted) snapshot is dropped — checkpointing is latest-wins, the
backlog never grows, and training never stalls behind a slow disk.

Health observability (monitor registry → /metrics, when enabled):
``checkpoint_write_seconds`` (timer), ``checkpoint_bytes_total``,
``checkpoint_total``, ``checkpoint_failures_total`` (counters),
``checkpoint_last_age_seconds`` / ``checkpoint_last_step`` (gauges).
"""

from __future__ import annotations

import json
import logging
import os
import shutil
import threading
import time
from pathlib import Path
from typing import Any, Dict, List, Optional

import numpy as np

from deeplearning4j_tpu.fault import state as fstate
from deeplearning4j_tpu.fault.errors import CheckpointCorruptError

log = logging.getLogger("deeplearning4j_tpu.fault")

MANIFEST_NAME = "MANIFEST.json"
_CKPT_PREFIX = "ckpt-"
_TMP_PREFIX = ".tmp-"


def _fsync_file(path: Path):
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _fsync_dir(path: Path):
    try:
        fd = os.open(path, os.O_RDONLY | getattr(os, "O_DIRECTORY", 0))
    except OSError:
        return
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _ckpt_dirname(step: int) -> str:
    return f"{_CKPT_PREFIX}{step:08d}"


def list_checkpoints(directory) -> List[int]:
    """Committed checkpoint steps (ascending). Only directories with a
    merged MANIFEST.json count — a torn tmp dir is invisible here."""
    directory = Path(directory)
    steps = []
    if not directory.is_dir():
        return steps
    for entry in directory.iterdir():
        if (entry.name.startswith(_CKPT_PREFIX) and entry.is_dir()
                and (entry / MANIFEST_NAME).is_file()):
            try:
                steps.append(int(entry.name[len(_CKPT_PREFIX):]))
            except ValueError:
                continue
    return sorted(steps)


def load_checkpoint(directory, step: int) -> Dict[str, Any]:
    """Read + integrity-verify one committed checkpoint. Returns the
    `capture_training_state` structure. Raises `CheckpointCorruptError`
    on any checksum/container damage."""
    cdir = Path(directory) / _ckpt_dirname(step)
    mpath = cdir / MANIFEST_NAME
    try:
        with open(mpath) as f:
            manifest = json.load(f)
    except (OSError, ValueError) as e:
        raise CheckpointCorruptError(
            f"{mpath}: unreadable manifest ({e})") from e
    flat: Dict[str, np.ndarray] = {}
    for shard in manifest.get("shards", []):
        spath = cdir / shard
        try:
            with np.load(spath, allow_pickle=False) as data:
                for k in data.files:
                    flat[k] = data[k]
        except Exception as e:  # truncated/garbled npz → typed error
            raise CheckpointCorruptError(
                f"{spath}: unreadable shard ({e})") from e
    fstate.verify_checksums(flat, {k: int(v) for k, v in
                                   manifest.get("checksums", {}).items()},
                            context=str(cdir))
    return {"arrays": fstate.unflatten_arrays(flat),
            "meta": manifest["meta"]}


class AsyncCheckpointer:
    def __init__(self, directory, *, keep_last: int = 3,
                 keep_every: Optional[int] = None, async_write: bool = True,
                 merge_timeout_s: float = 120.0):
        self.directory = Path(directory)
        self.directory.mkdir(parents=True, exist_ok=True)
        if keep_last < 1:
            raise ValueError(f"keep_last must be >= 1, got {keep_last}")
        if keep_every is not None and keep_every < 1:
            raise ValueError(f"keep_every must be >= 1, got {keep_every}")
        self.keep_last = keep_last
        self.keep_every = keep_every
        self.async_write = async_write
        self.merge_timeout_s = merge_timeout_s
        self._lock = threading.Lock()
        self._pending: Optional[tuple] = None      # (step, state) latest-wins
        self._wake = threading.Condition(self._lock)
        self._busy = False
        self._closed = False
        self._thread: Optional[threading.Thread] = None
        self._last_error: Optional[BaseException] = None
        self._last_commit_ts: Optional[float] = None
        self._age_gauge_bound = False

    # ------------------------------------------------------------- public
    def save(self, state: Dict[str, Any], step: int, *,
             blocking: bool = False) -> int:
        """Enqueue one snapshot for durable write (or write inline when
        `blocking` or the checkpointer was built with
        async_write=False). Re-raises the writer thread's last error so
        persistent disk failures surface on the training thread instead
        of looping silently."""
        self._raise_pending_error()
        if self._closed:
            raise RuntimeError("checkpointer is closed")
        if blocking or not self.async_write:
            self._write(step, state)
            return step
        with self._lock:
            if self._pending is not None:
                log.warning(
                    "checkpoint writer busy: dropping queued snapshot for "
                    "step %d in favor of step %d", self._pending[0], step)
            self._pending = (step, state)
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._worker, name="dl4j-checkpoint-writer",
                    daemon=True)
                self._thread.start()
            self._wake.notify_all()
        return step

    def wait(self):
        """Block until every enqueued snapshot is committed (end of
        fit / tests / drills), then surface any writer error."""
        with self._lock:
            while self._pending is not None or self._busy:
                self._wake.wait(timeout=0.1)
        self._raise_pending_error()

    def close(self):
        self.wait()
        with self._lock:
            self._closed = True
            self._wake.notify_all()

    def steps(self) -> List[int]:
        return list_checkpoints(self.directory)

    def load(self, step: Optional[int] = None) -> Dict[str, Any]:
        """Load a committed checkpoint (latest when step is None)."""
        steps = self.steps()
        if not steps:
            raise FileNotFoundError(
                f"no committed checkpoints under {self.directory}")
        return load_checkpoint(self.directory,
                               steps[-1] if step is None else step)

    # ------------------------------------------------------------- worker
    def _raise_pending_error(self):
        err, self._last_error = self._last_error, None
        if err is not None:
            raise err

    def _worker(self):
        while True:
            with self._lock:
                while self._pending is None and not self._closed:
                    self._wake.wait(timeout=0.5)
                if self._pending is None and self._closed:
                    return
                step, state = self._pending
                self._pending = None
                self._busy = True
            try:
                self._write(step, state)
            except BaseException as e:  # surfaced on next save()/wait()
                log.warning("async checkpoint write for step %d failed: %s",
                            step, e)
                self._last_error = e
                self._record_failure()
            finally:
                with self._lock:
                    self._busy = False
                    self._wake.notify_all()

    # -------------------------------------------------------------- write
    def _write(self, step: int, state: Dict[str, Any]):
        import jax

        t0 = time.perf_counter()
        proc = jax.process_index()
        nprocs = jax.process_count()
        tmp = self.directory / f"{_TMP_PREFIX}{_ckpt_dirname(step)}"
        final = self.directory / _ckpt_dirname(step)
        tmp.mkdir(parents=True, exist_ok=True)

        # capture requires fully-addressable leaves (fault/state.py), so
        # every process holds the COMPLETE state (replicated / DP
        # regime); process 0 writes the arrays once and the other
        # processes contribute only a barrier manifest — duplicate
        # shards would collide key-wise at merge/load. (TP-sharded
        # multi-host state goes through ShardedCheckpoint/Orbax.)
        nbytes = 0
        if proc == 0:
            flat = fstate.flatten_arrays(state["arrays"])
            checksums = fstate.checksum_flat(flat)
            shard_name = f"shard-{proc:05d}.npz"
            spath = tmp / shard_name
            with open(spath, "wb") as f:
                np.savez(f, **flat)
                f.flush()
                os.fsync(f.fileno())
            nbytes = spath.stat().st_size
        else:
            shard_name, checksums = None, {}
        pmanifest = {"process": proc, "shard": shard_name,
                     "checksums": checksums, "meta": state["meta"]}
        ppath = tmp / f"manifest-{proc:05d}.json"
        with open(ppath, "w") as f:
            json.dump(pmanifest, f)
            f.flush()
            os.fsync(f.fileno())

        if proc == 0:
            self._merge_and_commit(step, tmp, final, nprocs)
            self._gc()
            self._last_commit_ts = time.time()
            self._record_write(time.perf_counter() - t0, nbytes, step)
        # non-zero processes are done once their shard is durable

    def _merge_and_commit(self, step: int, tmp: Path, final: Path,
                          nprocs: int):
        # the barrier is a RANK SET, not a file count: an elastic
        # re-checkpoint of the same step at a smaller process count can
        # find stale higher-rank manifests from an aborted wider-world
        # attempt in the same tmp dir — those must neither satisfy nor
        # pollute the commit
        want = set(range(nprocs))
        deadline = time.time() + self.merge_timeout_s
        while True:
            # parse inside the wait loop: a manifest observed mid-write
            # (another rank's fsync not landed) or yanked away (a
            # concurrent committer renamed tmp — elastic world handoff)
            # counts as "not arrived yet", not corruption
            have = {}
            for mp in sorted(tmp.glob("manifest-*.json")):
                try:
                    rank = int(mp.name[len("manifest-"):-len(".json")])
                except ValueError:
                    continue
                if rank not in want:
                    continue
                try:
                    with open(mp) as f:
                        have[rank] = json.load(f)
                except (OSError, json.JSONDecodeError):
                    continue
            if set(have) == want:
                break
            if not tmp.exists() and final.exists():
                # a concurrent committer of the SAME step renamed our
                # shared tmp into place; its checkpoint stands
                return
            if time.time() > deadline:
                raise CheckpointCorruptError(
                    f"checkpoint step {step}: only ranks "
                    f"{sorted(have)} of {nprocs} arrived within "
                    f"{self.merge_timeout_s}s")
            time.sleep(0.05)
        merged: Dict[str, Any] = {
            "format_version": fstate.STATE_FORMAT_VERSION,
            "step": step, "process_count": nprocs,
            "shards": [], "checksums": {}, "meta": None}
        for _, pm in sorted(have.items()):
            if pm.get("shard"):
                merged["shards"].append(pm["shard"])
                merged["checksums"].update(pm["checksums"])
            if pm["process"] == 0:
                merged["meta"] = pm["meta"]
        mpath = tmp / MANIFEST_NAME
        with open(mpath, "w") as f:
            json.dump(merged, f)
            f.flush()
            os.fsync(f.fileno())
        _fsync_dir(tmp)
        self._replace_commit(step, tmp, final)
        _fsync_dir(self.directory)

    def _replace_commit(self, step: int, tmp: Path, final: Path):
        """Rename tmp into place, replacing an existing commit of the
        same step — tolerant of a CONCURRENT committer (during an
        elastic world handoff the draining world's rank 0 and the new
        world's rank 0 can both re-checkpoint the same step; both hold
        equivalent state, so whichever rename wins is a valid commit)."""
        if final.exists():
            trash = final.parent / f"{_TMP_PREFIX}trash-{os.getpid()}-" \
                                   f"{final.name}"
            try:
                os.rename(final, trash)
            except FileNotFoundError:
                pass                # the other committer replaced it first
            shutil.rmtree(trash, ignore_errors=True)
        try:
            os.rename(tmp, final)
        except OSError as e:
            if final.exists():
                log.warning("checkpoint step %d: concurrent commit won "
                            "the replace race (%s); dropping this "
                            "attempt's tmp", step, e)
                shutil.rmtree(tmp, ignore_errors=True)
            else:
                raise

    # ----------------------------------------------------------- retention
    def _retained(self, steps: List[int]) -> set:
        keep = set(steps[-self.keep_last:])
        if self.keep_every:
            keep.update(s for s in steps if s % self.keep_every == 0)
        return keep

    def _gc(self):
        steps = list_checkpoints(self.directory)
        keep = self._retained(steps)
        for s in steps:
            if s not in keep:
                shutil.rmtree(self.directory / _ckpt_dirname(s),
                              ignore_errors=True)
        # orphaned tmp dirs from a crashed writer: only reap attempts at
        # or below the newest COMMITTED step — a tmp another process is
        # still writing (for a newer step) must not be swept from under it
        newest = steps[-1] if steps else -1
        for entry in self.directory.glob(f"{_TMP_PREFIX}{_CKPT_PREFIX}*"):
            try:
                tstep = int(entry.name[len(_TMP_PREFIX) + len(_CKPT_PREFIX):])
            except ValueError:
                continue
            if tstep <= newest:
                shutil.rmtree(entry, ignore_errors=True)

    # ------------------------------------------------------------- metrics
    def _record_write(self, seconds: float, nbytes: int, step: int):
        from deeplearning4j_tpu import monitor
        if not monitor.is_enabled():
            return
        reg = monitor.registry()
        reg.timer("checkpoint_write_seconds",
                  help="durable full-state checkpoint write latency"
                  ).observe(seconds)
        reg.counter("checkpoint_bytes_total",
                    help="bytes written by the fault checkpointer"
                    ).inc(float(nbytes))
        reg.counter("checkpoint_total",
                    help="committed checkpoints").inc()
        reg.gauge("checkpoint_last_step",
                  help="step of the newest committed checkpoint").set(step)
        if not self._age_gauge_bound:
            reg.gauge("checkpoint_last_age_seconds",
                      help="seconds since the newest committed checkpoint"
                      ).set_function(
                lambda: (time.time() - self._last_commit_ts)
                if self._last_commit_ts else float("nan"))
            self._age_gauge_bound = True

    def _record_failure(self):
        from deeplearning4j_tpu import monitor
        if monitor.is_enabled():
            monitor.registry().counter(
                "checkpoint_failures_total",
                help="checkpoint writes that failed").inc()
