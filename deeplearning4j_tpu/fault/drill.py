"""Deterministic fault-injection drills.

The resilience counterpart of a fire drill: prove — on schedule, not
during an outage — that a training job kill-at-step-k resumes
bit-exactly, that a corrupted newest shard degrades to the previous
checkpoint, and that a checkpoint written at one replica count restarts
at another. `scripts/fault_drill.py` drives these as real subprocess
kills; the in-process pieces here are importable for tests.

Injection points:
- `PreemptionListener`: scripted preemption at step k from inside the
  listener bus — mode="exception" raises `SimulatedPreemption`
  (BaseException, uncatchable by ordinary recovery code), mode="sigterm"
  delivers a real SIGTERM to the process (default disposition: die
  immediately, mid-whatever-was-happening — the honest preemption).
- `corrupt_checkpoint`: truncate or bit-flip a committed shard (or its
  manifest) so restore-side verification and fallback can be drilled.
- `auto_resume`: the in-process restart driver — run `attempt_fn`,
  catching `SimulatedPreemption` and rerunning until it completes.
"""

from __future__ import annotations

import json
import logging
import os
import signal
from pathlib import Path
from typing import Callable, Optional

from deeplearning4j_tpu.fault.checkpointer import (
    MANIFEST_NAME,
    _ckpt_dirname,
    list_checkpoints,
)
from deeplearning4j_tpu.fault.errors import SimulatedPreemption
from deeplearning4j_tpu.optimize.listeners import TrainingListener

log = logging.getLogger("deeplearning4j_tpu.fault")


class PreemptionListener(TrainingListener):
    """Kill the training run at the first step boundary >= `kill_at_step`
    completed steps (exact at `kill_at_step` when the fit runs
    per-step; fused groups die at their boundary, like a real SIGTERM
    landing between dispatches)."""

    def __init__(self, kill_at_step: int, *, mode: str = "exception",
                 wait_for_checkpointer=None):
        if mode not in ("exception", "sigterm", "sigkill"):
            raise ValueError(
                f"mode must be exception|sigterm|sigkill, got {mode}")
        self.kill_at_step = int(kill_at_step)
        self.mode = mode
        # optional: drain this AsyncCheckpointer before dying — drills
        # the "preemption notice" path (SIGTERM + grace period) as
        # opposed to the default hard-kill path
        self.wait_for_checkpointer = wait_for_checkpointer
        self.fired = False

    def iteration_done(self, model, iteration, epoch, score, **info):
        if self.fired or not info.get("step_boundary", True):
            return
        if iteration + 1 < self.kill_at_step:
            return
        self.fired = True
        if self.wait_for_checkpointer is not None:
            self.wait_for_checkpointer.wait()
        log.warning("injecting preemption at step %d (%s)", iteration + 1,
                    self.mode)
        if self.mode == "sigterm":
            os.kill(os.getpid(), signal.SIGTERM)
        elif self.mode == "sigkill":
            # the elastic shrink drill: an instant, ungraceful death the
            # process cannot observe — no drain, no final checkpoint;
            # survivors must detect it and re-form the mesh without us
            os.kill(os.getpid(), signal.SIGKILL)
        raise SimulatedPreemption(iteration + 1)


def corrupt_checkpoint(directory, *, step: Optional[int] = None,
                       mode: str = "flip", target: str = "shard") -> Path:
    """Damage a committed checkpoint in place (newest when step=None).

    mode="flip" xors one byte mid-file (silent bit rot — caught only by
    checksums); mode="truncate" halves the file (torn write past the
    atomic-rename protocol, e.g. disk-level damage). target="shard"
    hits the array payload, target="manifest" the merged manifest.
    Returns the damaged path."""
    steps = list_checkpoints(directory)
    if not steps:
        raise FileNotFoundError(f"no checkpoints under {directory}")
    step = steps[-1] if step is None else step
    cdir = Path(directory) / _ckpt_dirname(step)
    if target == "manifest":
        path = cdir / MANIFEST_NAME
    else:
        shards = sorted(cdir.glob("shard-*.npz"))
        if not shards:
            raise FileNotFoundError(f"no shards in {cdir}")
        path = shards[0]
    size = path.stat().st_size
    if mode == "truncate":
        with open(path, "r+b") as f:
            f.truncate(max(1, size // 2))
    elif mode == "flip":
        with open(path, "r+b") as f:
            f.seek(size // 2)
            b = f.read(1)
            f.seek(size // 2)
            f.write(bytes([b[0] ^ 0xFF]))
    else:
        raise ValueError(f"mode must be flip|truncate, got {mode}")
    log.warning("injected %s corruption into %s", mode, path)
    return path


def auto_resume(attempt_fn: Callable[[int], object], *,
                max_restarts: int = 5):
    """In-process restart driver: call `attempt_fn(attempt)` until it
    returns (instead of dying to `SimulatedPreemption`). `attempt_fn`
    sees attempt=0 for the cold start and is expected to resume from
    the checkpoint directory on attempt >= 1. Returns
    (result, restarts)."""
    for attempt in range(max_restarts + 1):
        try:
            return attempt_fn(attempt), attempt
        except SimulatedPreemption as e:
            log.warning("attempt %d preempted at step %d; restarting",
                        attempt, e.step)
    raise RuntimeError(
        f"training did not complete within {max_restarts} restarts")


def checkpoint_meta(directory, step: Optional[int] = None) -> dict:
    """The merged manifest's meta block (no array IO — drill/tooling
    introspection)."""
    steps = list_checkpoints(directory)
    step = steps[-1] if step is None else step
    with open(Path(directory) / _ckpt_dirname(step) / MANIFEST_NAME) as f:
        return json.load(f)["meta"]
