"""`resume(dir)` — exact-restart entry point with corrupt-checkpoint
fallback.

Walks committed checkpoints newest-first; the first one that passes
integrity verification wins, and every corrupt newer one degrades with
a logged warning instead of a crash (the acceptance contract: a
truncated/bit-flipped newest shard falls back to the previous
checkpoint). Restores the model (built from the stored configuration
when none is passed), the trainer's residual/τ/per-replica state, the
iterator position, and bumps ``restore_total`` on the monitor registry.
"""

from __future__ import annotations

import logging
from typing import Any, Dict, Optional, Tuple

from deeplearning4j_tpu.fault import state as fstate
from deeplearning4j_tpu.fault.checkpointer import (
    list_checkpoints,
    load_checkpoint,
)
from deeplearning4j_tpu.fault.errors import CheckpointCorruptError

log = logging.getLogger("deeplearning4j_tpu.fault")


def load_latest_valid(directory, *, max_step: Optional[int] = None
                      ) -> Tuple[Dict[str, Any], int]:
    """(state, step) of the newest checkpoint that verifies; corrupt
    ones are skipped with a warning. Raises FileNotFoundError when the
    directory has no committed checkpoints at all, and
    CheckpointCorruptError when every committed checkpoint is damaged."""
    steps = list_checkpoints(directory)
    if max_step is not None:
        steps = [s for s in steps if s <= max_step]
    if not steps:
        raise FileNotFoundError(
            f"no committed checkpoints under {directory}")
    tried = []
    for step in reversed(steps):
        try:
            return load_checkpoint(directory, step), step
        except CheckpointCorruptError as e:
            log.warning(
                "checkpoint step %d under %s is corrupt (%s); falling "
                "back to the previous checkpoint", step, directory, e)
            tried.append((step, e))
    # name EVERY candidate tried — an elastic resume that lands here has
    # no recovery path left, and the operator needs the full damage
    # report, not just the newest failure
    detail = "; ".join(f"step {s}: {e}" for s, e in tried)
    raise CheckpointCorruptError(
        f"every committed checkpoint under {directory} failed "
        f"verification ({len(tried)} candidates tried) — {detail}")


def resume(directory, model=None, *, trainer=None, iterator=None,
           max_step: Optional[int] = None):
    """Restore the newest valid checkpoint. Returns ``(model, meta)``.

    `model=None` rebuilds the container from the stored configuration.
    `trainer` (ParallelTrainer / ShardedParallelTrainer /
    PipelineParallelTrainer) additionally restores gradient-sharing
    residual + τ and per-replica updater state — including the elastic
    re-shard when the current replica count differs from the one the
    checkpoint was written with. `iterator` is seeked to the stored
    ingest cursor so no consumed batch replays."""
    state, step = load_latest_valid(directory, max_step=max_step)
    meta = state["meta"]
    if model is None:
        model = fstate.build_model(meta)
    fstate.restore_training_state(model, state, trainer=trainer,
                                  iterator=iterator)
    from deeplearning4j_tpu import monitor
    if monitor.is_enabled():
        monitor.registry().counter(
            "restore_total",
            help="successful training-state restores").inc()
        monitor.registry().gauge(
            "restore_last_step",
            help="step of the last restored checkpoint").set(step)
    log.info("resumed training state from step %d under %s", step,
             directory)
    return model, meta
