"""Preemption-tolerant training runtime.

TPU fleets preempt: the TPU-generations survey (arXiv:2606.15870)
treats checkpoint/restore cadence matched to MTBF as a first-class
design axis at pod scale, and TensorFlow (arXiv:1605.08695) built its
fault-tolerance story on periodic checkpointing. This package makes a
kill at step k a non-event:

- `AsyncCheckpointer` — versioned, checksummed, per-host-sharded,
  atomically-committed (tmp+fsync+rename) full-state checkpoints
  written by a background thread with keep-last-N / keep-every-K
  retention (fault/checkpointer.py);
- `capture_training_state` / `restore_training_state` — the complete
  state schema: params, per-layer updater state, gradient-sharing
  residual + τ, layer running stats, iteration/epoch counters (which
  pin the per-step rng fold), iterator cursor, normalizer stats
  (fault/state.py);
- `CheckpointListener` — the fit-loop wiring via the ordinary listener
  bus, honoring fused multi-step boundaries (fault/listener.py);
- `resume(dir)` — exact restart from the newest VALID checkpoint, with
  corrupt-shard fallback, trainer residual/τ restore and elastic
  replica-count re-sharding (fault/resume.py);
- fault-injection drills: scripted preemption, shard corruption,
  auto-resume driving (fault/drill.py + scripts/fault_drill.py).

Interrupt + resume reproduces the uninterrupted run's params and
updater state bit-identically on CPU (tests/test_fault_runtime.py);
docs/FAULT_TOLERANCE.md documents the state schema, manifest format
and drill recipes.
"""

from deeplearning4j_tpu.fault.checkpointer import (
    AsyncCheckpointer,
    list_checkpoints,
    load_checkpoint,
)
from deeplearning4j_tpu.fault.drill import (
    PreemptionListener,
    auto_resume,
    checkpoint_meta,
    corrupt_checkpoint,
)
from deeplearning4j_tpu.fault.errors import (
    CheckpointCorruptError,
    ElasticMembershipError,
    ElasticReconfiguration,
    SimulatedPreemption,
)
from deeplearning4j_tpu.fault.listener import CheckpointListener
from deeplearning4j_tpu.fault.resume import load_latest_valid, resume
from deeplearning4j_tpu.fault.state import (
    capture_training_state,
    reshard_replica_stack,
    restore_normalizer,
    restore_training_state,
)

__all__ = [
    "AsyncCheckpointer", "CheckpointListener", "CheckpointCorruptError",
    "ElasticMembershipError", "ElasticReconfiguration",
    "SimulatedPreemption", "PreemptionListener",
    "capture_training_state", "restore_training_state",
    "restore_normalizer", "reshard_replica_stack",
    "resume", "load_latest_valid", "list_checkpoints", "load_checkpoint",
    "auto_resume", "corrupt_checkpoint", "checkpoint_meta",
]
