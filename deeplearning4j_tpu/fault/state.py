"""Complete-training-state capture/restore.

The zip `ModelSerializer` persists *model weights*; surviving a
preemption needs the whole optimization process: params + per-layer
updater state, gradient-sharing residual and τ, layer running stats,
iteration/epoch counters (the per-step rng key is `fold_in(PRNGKey(
seed+1), iteration_count)` in every fit loop, so restoring the counters
restores the rng stream bit-exactly), dataset-iterator cursor and
normalizer statistics. This module defines that state schema and the
pure capture/restore halves the checkpointer and `resume()` build on.

Capture sources:
- outside a trainer, the model's own attribute trees are the live state
  (`fit()` writes params/updater_state back every step);
- inside `ParallelTrainer` / `ShardedParallelTrainer` /
  `PipelineParallelTrainer`, the live state is held in fit-local device
  arrays, NOT on the model — those fits publish a
  `model._live_state_provider` callable for the duration of the fit and
  the capture goes through it (including per-replica updater state and
  the threshold residual/τ, which never exist on the model at all).

Iterator cursors come in two families under one contract: finite
iterators pin ``{epoch, batch, seed}`` (shuffle permutations re-derived
by replaying Generator draws), and UNBOUNDED streaming iterators
(`online/iterator.py`) pin the transport offset — ``batch`` counts
batches CONSUMED by the training loop, ``offset = batch * batch_size``
is the first unconsumed record, and `seek()` is replay-from-offset
over a retained log (records held back for a ragged tail, or
prefetched but unconsumed by `AsyncDataSetIterator`, sit past the
cursor by construction and replay). Both are json-safe dicts captured
in ``meta["iterator"]``.

Trees are flattened to npz-friendly flat dicts with `\\x1f`-joined path
keys (the ASCII unit separator cannot appear in layer indices or graph
node names) and carry a crc32 per array so restore can detect silent
shard corruption (`CheckpointCorruptError`) instead of loading garbage.
The ``stacked::`` run packing of nn/scan_stack.py exists only inside
jitted step programs — every tree here is per-layer-keyed by contract,
so checkpoints are independent of the scan/pack configuration that
wrote them.

Sharding-related invariants of the trainer state kinds:

- ``threshold`` / ``threshold_rs``: the error-feedback residual is a
  per-replica stack (leading replica axis) — elastic restore re-shards
  it sum-preserving (`reshard_replica_stack(kind="residual")`); τ is
  either one scalar (PR-4 single-barrier checkpoints) or a per-bucket
  ``{layer_key: scalar}`` tree (bucketed exchange) — both restore
  as written and coerce at the next fit.
- ``sync_dense_rs`` / ``threshold_rs``: the ZeRO modes hold updater
  state SHARDED over the data axis during fit, but checkpoints always
  carry the reassembled FULL per-layer tree (the trainers'
  `_rs_full_state_fn` gathers at capture) — so data-axis-sharded
  updater state is replica-count independent on disk and an elastic
  resume just re-slices at the next fit, with the shard plan
  re-derived for the new replica count.
"""

from __future__ import annotations

import zlib
from typing import Any, Dict, Optional, Tuple

import numpy as np

from deeplearning4j_tpu.fault.errors import CheckpointCorruptError

STATE_FORMAT_VERSION = 1

# path separator inside flattened array keys; ASCII unit separator —
# cannot collide with layer indices ("0", "1", ...) or sane node names
SEP = "\x1f"


# --------------------------------------------------------------- flattening
def flatten_arrays(tree, prefix: str = "") -> Dict[str, np.ndarray]:
    """Nested str-keyed dicts of array leaves → flat {path: np.ndarray}.
    Leaves are materialized on host (device→host copy happens HERE, at
    the step boundary, before any donation can invalidate them)."""
    out: Dict[str, np.ndarray] = {}
    if isinstance(tree, dict):
        for k, v in tree.items():
            k = str(k)
            if SEP in k:
                raise ValueError(
                    f"tree key {k!r} contains the reserved path "
                    f"separator U+001F")
            out.update(flatten_arrays(v, f"{prefix}{k}{SEP}"))
        return out
    if (not getattr(tree, "is_fully_addressable", True)
            and not getattr(tree, "is_fully_replicated", False)):
        # fully-REPLICATED multi-process arrays are fine: every process
        # holds a complete local copy, np.asarray reads it without any
        # cross-host traffic (the elastic multi-process capture path)
        raise ValueError(
            f"array at {prefix[:-1]!r} spans processes this host cannot "
            f"address (multi-host tensor-sharded state); the fault "
            f"checkpointer covers replicated/data-parallel state — "
            f"checkpoint TP-sharded multi-host models through "
            f"util.sharded_checkpoint.ShardedCheckpoint (Orbax)")
    out[prefix[:-1]] = np.asarray(tree)
    return out


def unflatten_arrays(flat: Dict[str, np.ndarray]) -> Dict:
    """Inverse of `flatten_arrays`."""
    out: Dict = {}
    for path, arr in flat.items():
        parts = path.split(SEP)
        node = out
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = arr
    return out


def checksum_array(arr: np.ndarray) -> int:
    return zlib.crc32(np.ascontiguousarray(arr).tobytes()) & 0xFFFFFFFF


def checksum_flat(flat: Dict[str, np.ndarray]) -> Dict[str, int]:
    return {k: checksum_array(v) for k, v in flat.items()}


def verify_checksums(flat: Dict[str, np.ndarray],
                     expected: Dict[str, int], *, context: str = ""):
    """Raise `CheckpointCorruptError` naming every mismatching/missing
    array — the caller's cue to fall back to an older checkpoint."""
    bad = []
    for key, crc in expected.items():
        if key not in flat:
            bad.append(f"{key!r} missing")
        elif checksum_array(flat[key]) != crc:
            bad.append(f"{key!r} checksum mismatch")
    if bad:
        raise CheckpointCorruptError(
            f"{context or 'checkpoint'} failed integrity verification: "
            + "; ".join(bad[:8])
            + (f" (+{len(bad) - 8} more)" if len(bad) > 8 else ""))


# ------------------------------------------------------------------ capture
def capture_training_state(model, *, iterator=None, normalizer=None,
                           step: Optional[int] = None,
                           epoch: Optional[int] = None,
                           extra_meta: Optional[Dict] = None
                           ) -> Dict[str, Any]:
    """Snapshot the COMPLETE training state to host memory.

    Returns ``{"arrays": {section: nested tree of np arrays},
    "meta": {...json-safe...}}``. `step`/`epoch` override the model's
    counters (the CheckpointListener fires before the fit loop
    increments them); `iterator` contributes its `cursor()` when it has
    one; `normalizer` contributes its fitted statistics.
    """
    provider = getattr(model, "_live_state_provider", None)
    if provider is not None:
        src = provider()
    else:
        src = {"params": model.params, "net_state": model.net_state,
               "updater_state": model.updater_state}
    host = lambda t: unflatten_arrays(flatten_arrays(t)) if t else {}
    arrays: Dict[str, Any] = {
        "params": host(src["params"]),
        "net_state": host(src.get("net_state")),
        "updater_state": host(src.get("updater_state")),
    }
    meta: Dict[str, Any] = {
        "format_version": STATE_FORMAT_VERSION,
        "model_type": type(model).__name__,
        "configuration": model.conf.to_dict(),
        # the ACTIVE dtype policy (which may come from a constructor
        # arg or env override, not the conf) — resume must rebuild the
        # same mixed-precision program or bit-parity breaks
        "dtype_policy": model.dtype.to_dict(),
        # the ACTIVE diagnostics config, same rationale: an arg/env-
        # selected watchdog (monitor/diagnostics.py) must survive
        # resume — under the `skip` policy it is trajectory-bearing
        "diagnostics": (None if getattr(model, "diagnostics", None) is None
                        else model.diagnostics.to_dict()),
        "iteration_count": int(model.iteration_count if step is None
                               else step),
        "epoch_count": int(model.epoch_count if epoch is None else epoch),
        "score": float(getattr(model, "score_value", float("nan"))),
    }
    if src.get("trainer_arrays"):
        arrays["trainer"] = host(src["trainer_arrays"])
    if src.get("trainer_meta"):
        meta["trainer"] = dict(src["trainer_meta"])
    if iterator is not None:
        cur = getattr(iterator, "cursor", lambda: None)()
        if cur is not None:
            meta["iterator"] = dict(cur)
    if normalizer is not None:
        nmeta, narrays = normalizer.state()
        meta["normalizer"] = nmeta
        arrays["normalizer"] = dict(narrays)
    if extra_meta:
        meta.update(extra_meta)
    return {"arrays": arrays, "meta": meta}


# ------------------------------------------------------------------ restore
def build_model(meta: Dict[str, Any]):
    """Reconstruct an uninitialized container from checkpoint meta
    (same two-phase conf→init restore `ModelSerializer` uses). The
    checkpoint's recorded dtype policy is passed explicitly so a run
    trained under `mixed_bf16()` (via arg or env) resumes into the
    same mixed-precision program — bit-parity depends on it. The
    `DL4J_DTYPE_POLICY` env override still wins (resolution order)."""
    policy = None
    if meta.get("dtype_policy") is not None:
        from deeplearning4j_tpu.nd.dtype import as_policy
        policy = as_policy(meta["dtype_policy"])
    diagnostics = None
    if meta.get("diagnostics") is not None:
        # the ACTIVE diagnostics config (arg/env-selected watchdogs
        # included) — DL4J_DIAGNOSTICS still wins at resolution time
        from deeplearning4j_tpu.monitor.diagnostics import as_diagnostics
        diagnostics = as_diagnostics(meta["diagnostics"])
    if meta["model_type"] == "ComputationGraph":
        from deeplearning4j_tpu.nn.graph import (
            ComputationGraph, ComputationGraphConfiguration)
        return ComputationGraph(
            ComputationGraphConfiguration.from_dict(meta["configuration"]),
            dtype_policy=policy, diagnostics=diagnostics)
    from deeplearning4j_tpu.nn.conf.builder import MultiLayerConfiguration
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    return MultiLayerNetwork(
        MultiLayerConfiguration.from_dict(meta["configuration"]),
        dtype_policy=policy, diagnostics=diagnostics)


def _deep_merge(base, overlay):
    """Overlay leaves replace base leaves, dicts merge recursively.
    Restore goes through a freshly-initialized tree merged with the
    checkpoint because flat npz keys cannot represent EMPTY dicts —
    e.g. a stateless Sgd updater's `{}` slots — and replacing the whole
    tree would silently drop that structure (breaking
    `_apply_updates`'s `upd_state[lk][pk]` lookups on resume)."""
    if not isinstance(base, dict) or not isinstance(overlay, dict):
        return overlay
    out = dict(base)
    for k, v in overlay.items():
        out[k] = _deep_merge(base[k], v) if k in base else v
    return out


def restore_training_state(model, state: Dict[str, Any], *,
                           trainer=None, iterator=None):
    """Load a captured/loaded state into `model` (and optionally a
    trainer's residual/τ/per-replica state + an iterator's position).
    Returns `model`. Bit-exact resume contract: counters are restored
    so the per-step rng fold and updater step counts continue exactly
    where the interrupted run stopped."""
    import jax
    import jax.numpy as jnp

    arrays, meta = state["arrays"], state["meta"]
    as_dev = lambda t: {} if not t else jax.tree_util.tree_map(jnp.asarray, t)
    if not getattr(model, "_initialized", False):
        model.init()
    model.params = as_dev(_deep_merge(model.params,
                                      arrays.get("params") or {}))
    model.net_state = as_dev(_deep_merge(model.net_state,
                                         arrays.get("net_state") or {}))
    model.updater_state = as_dev(_deep_merge(model.updater_state,
                                             arrays.get("updater_state")
                                             or {}))
    model.iteration_count = int(meta.get("iteration_count", 0))
    model.epoch_count = int(meta.get("epoch_count", 0))
    if "score" in meta:
        model.score_value = float(meta["score"])
    model._initialized = True
    if trainer is not None and hasattr(trainer, "_restore_fault_state"):
        trainer._restore_fault_state(arrays.get("trainer") or {},
                                     meta.get("trainer") or {})
    if iterator is not None and meta.get("iterator") is not None:
        try:
            # the DataSetIterator base defines seek() as raising, so a
            # hasattr check can never distinguish support — probe by
            # calling and translate into the actionable error
            iterator.seek(meta["iterator"])
        except NotImplementedError as e:
            raise ValueError(
                f"checkpoint carries an iterator cursor but "
                f"{type(iterator).__name__} does not implement the "
                f"cursor()/seek() position contract "
                f"(ArrayDataSetIterator, AsyncDataSetIterator and "
                f"StreamingDataSetIterator do)"
            ) from e
    return model


def restore_normalizer(state: Dict[str, Any]):
    """The fitted normalizer stored in a checkpoint, or None."""
    meta = state["meta"].get("normalizer")
    if meta is None:
        return None
    from deeplearning4j_tpu.datasets.normalizers import normalizer_from_meta
    return normalizer_from_meta(meta, state["arrays"].get("normalizer", {}))


# ------------------------------------------------------- elastic resharding
def reshard_replica_stack(tree, new_n: int, *, kind: str = "state"):
    """Re-shard a per-replica stacked tree (leading replica axis) to a
    different replica count — the elastic-resume path when a job comes
    back on more/fewer chips than it checkpointed with.

    kind="residual": the error-feedback residual is un-sent update
    MASS; the decode applies τ·Σ_r enc_r / N, so what must be preserved
    across a replica-count change is the SUM over replicas — each new
    replica gets sum/new_n and Σ residual is bit-for-bit conserved.

    kind="state": per-replica updater state drifts like independent
    workers; on an elastic restart every new replica starts from the
    replica MEAN for float leaves (the same averaging rule the
    param-averaging mode applies to updater state) and replica 0's
    value for integer/step-count leaves.
    """
    def one(a):
        a = np.asarray(a)
        if a.ndim == 0:
            return a
        old_n = a.shape[0]
        if old_n == new_n:
            return a
        if kind == "residual":
            total = a.sum(axis=0, dtype=np.float64)
            return np.broadcast_to(
                (total / new_n).astype(a.dtype), (new_n,) + a.shape[1:]
            ).copy()
        if np.issubdtype(a.dtype, np.floating):
            m = a.mean(axis=0)
        else:
            m = a[0]
        return np.broadcast_to(m, (new_n,) + a.shape[1:]).copy()

    import jax
    return jax.tree_util.tree_map(one, tree)


def stacked_replica_count(tree) -> Optional[int]:
    """Leading replica-axis extent of a per-replica stacked tree (None
    for an empty tree)."""
    import jax
    leaves = jax.tree_util.tree_leaves(tree)
    return int(np.shape(leaves[0])[0]) if leaves else None
