"""CheckpointListener — the fit-loop wiring of the fault runtime.

Reference: `optimize/listeners/checkpoint/CheckpointListener.java` —
periodic full checkpoints from inside the training loop, with
keepLast/keepEvery retention (retention lives on the AsyncCheckpointer
here). Attach with `model.add_listener(...)`; every fit loop
(MultiLayerNetwork, ComputationGraph, and all three parallel trainers,
whose fits publish a `_live_state_provider` for the duration) feeds it
through the ordinary listener bus.

Fused-dispatch correctness: with `steps_per_execution > 1` the loops
update params once per GROUP, then replay listener callbacks for each
fused iteration — mid-group callbacks see post-group params with a
mid-group iteration count, a combination that must never be
checkpointed (resume would double-apply steps). The loops mark the
group's last callback with ``step_boundary=True``; this listener only
captures there, and the cadence check is "`frequency` steps elapsed
since the last save" rather than a modulo so boundaries that don't
align with the cadence still checkpoint at the next legal boundary.
"""

from __future__ import annotations

from typing import Optional

from deeplearning4j_tpu.fault.checkpointer import AsyncCheckpointer
from deeplearning4j_tpu.fault.state import capture_training_state
from deeplearning4j_tpu.optimize.listeners import TrainingListener


class CheckpointListener(TrainingListener):
    def __init__(self, checkpointer, *, frequency: int = 10,
                 epoch_frequency: Optional[int] = None,
                 iterator=None, normalizer=None,
                 save_at_fit_end: bool = False):
        """`checkpointer`: an AsyncCheckpointer or a directory path.
        `frequency`: checkpoint every N completed steps (at the nearest
        step boundary); `epoch_frequency`: additionally at every Nth
        epoch end; `iterator`: the training DataSetIterator whose
        `cursor()` should ride along (pass the SAME object given to
        fit); `normalizer`: fitted DataNormalization to persist."""
        if not isinstance(checkpointer, AsyncCheckpointer):
            checkpointer = AsyncCheckpointer(checkpointer)
        self.checkpointer = checkpointer
        self.frequency = max(1, int(frequency))
        self.epoch_frequency = epoch_frequency
        self.iterator = iterator
        self.normalizer = normalizer
        self.save_at_fit_end = save_at_fit_end
        self._last_saved_step = 0

    # ------------------------------------------------------------ capture
    def _save(self, model, step: int, epoch: int, *,
              epoch_complete: bool = False):
        state = capture_training_state(
            model, iterator=self.iterator, normalizer=self.normalizer,
            step=step, epoch=epoch)
        if epoch_complete and state["meta"].get("iterator") is not None:
            # epoch-end save: epoch_count records the completed epoch,
            # so the cursor must point at the NEXT pass's start — kept
            # as {epoch: e, batch: <full>} it would pair with the
            # incremented epoch_count and double-count the completed
            # epoch (resume would train one epoch short)
            cur = state["meta"]["iterator"]
            state["meta"]["iterator"] = {**cur, "epoch": epoch, "batch": 0}
        self.checkpointer.save(state, step)
        self._last_saved_step = step

    def save_now(self, model, step: int, epoch: int):
        """Out-of-cadence checkpoint at an externally-chosen STEP
        BOUNDARY — the elastic runtime's drain checkpoint (every
        process calls this at the same agreed step, so the
        multi-process commit barrier lines up). The cadence clock
        advances so the next periodic save counts from here."""
        self._save(model, int(step), int(epoch))

    def iteration_done(self, model, iteration, epoch, score, **info):
        if not info.get("step_boundary", True):
            return
        step = iteration + 1          # completed steps
        if step - self._last_saved_step < self.frequency:
            return
        self._save(model, step, epoch)

    def on_epoch_end(self, model, epoch):
        if (self.epoch_frequency
                and (epoch + 1) % self.epoch_frequency == 0):
            self._save(model, int(model.iteration_count), epoch + 1,
                       epoch_complete=True)

    def on_fit_end(self, model):
        if self.save_at_fit_end and \
                int(model.iteration_count) > self._last_saved_step:
            self._save(model, int(model.iteration_count),
                       int(model.epoch_count))
        # a checkpoint enqueued on the last step must be durable before
        # the process exits fit() believing it is protected
        self.checkpointer.wait()
