"""Finite-difference gradient checker — the framework's correctness
oracle.

Reference: `gradientcheck/GradientCheckUtil.java:112,207-222`: perturb
each parameter ±ε in float64, compare (f(θ+ε)−f(θ−ε))/2ε against the
analytic gradient with a max-relative-error threshold. The reference
runs this over every layer/loss/vertex combination
(`deeplearning4j-core/src/test/java/org/deeplearning4j/gradientcheck/`).

Here the analytic gradient is jax autodiff; the checker still earns its
keep by validating every layer's forward math end-to-end (a wrong
forward gives a consistent-but-wrong gradient; a non-differentiable /
numerically unstable forward shows up as mismatch). Runs in float64 on
CPU via the `jax.enable_x64` context.
"""

from __future__ import annotations

from typing import Callable, Dict

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.parallel.compat import enable_x64


def check_gradients_fn(
    loss_fn: Callable[[Dict], jnp.ndarray],
    params: Dict,
    epsilon: float = 1e-6,
    max_rel_error: float = 1e-5,
    min_abs_error: float = 1e-8,
    max_params_per_array: int = 64,
    seed: int = 0,
    verbose: bool = False,
):
    """Check autodiff gradients of `loss_fn(params)` against central
    finite differences.

    Samples up to `max_params_per_array` coordinates per param tensor
    (the reference checks all; sampling keeps test time sane for big
    tensors while covering every tensor).

    Returns (ok, max_rel_err, failures).
    """
    with enable_x64(True):
        params64 = jax.tree_util.tree_map(
            lambda a: jnp.asarray(np.asarray(a), jnp.float64), params)
        loss64 = jax.jit(lambda p: jnp.asarray(loss_fn(p), jnp.float64))
        grads = jax.jit(jax.grad(lambda p: loss64(p)))(params64)
        flat_params, treedef = jax.tree_util.tree_flatten(params64)
        flat_grads = jax.tree_util.tree_leaves(grads)
        rng = np.random.default_rng(seed)
        failures = []
        worst = 0.0
        for ti, (arr, g) in enumerate(zip(flat_params, flat_grads)):
            size = int(np.prod(arr.shape)) if arr.shape else 1
            n_check = min(size, max_params_per_array)
            idxs = rng.choice(size, size=n_check, replace=False)
            host = np.asarray(arr, dtype=np.float64)
            for flat_idx in idxs:
                idx = np.unravel_index(int(flat_idx), arr.shape) if arr.shape else ()
                orig = host[idx] if arr.shape else float(host)

                def eval_at(v):
                    pert = host.copy()
                    pert[idx] = v
                    new_flat = list(flat_params)
                    new_flat[ti] = jnp.asarray(pert)
                    return float(loss64(jax.tree_util.tree_unflatten(treedef, new_flat)))

                plus = eval_at(orig + epsilon)
                minus = eval_at(orig - epsilon)
                numeric = (plus - minus) / (2 * epsilon)
                analytic = float(np.asarray(g)[idx] if arr.shape else float(g))
                denom = max(abs(numeric), abs(analytic))
                abs_err = abs(numeric - analytic)
                rel = abs_err / denom if denom > 0 else 0.0
                if abs_err > min_abs_error and rel > max_rel_error:
                    failures.append((ti, idx, analytic, numeric, rel))
                worst = max(worst, rel if abs_err > min_abs_error else 0.0)
                if verbose:
                    print(f"tensor {ti} idx {idx}: analytic {analytic:.3e} "
                          f"numeric {numeric:.3e} rel {rel:.3e}")
        return len(failures) == 0, worst, failures


def check_model_gradients(
    model,
    features,
    labels,
    epsilon: float = 1e-6,
    max_rel_error: float = 1e-4,
    max_params_per_array: int = 32,
    features_mask=None,
    labels_mask=None,
    seed: int = 0,
):
    """Gradient-check a MultiLayerNetwork on one minibatch (reference
    `GradientCheckUtil.checkGradients(mln, ...)`).

    Dropout must be disabled in the config (the reference asserts this
    too — stochastic forward breaks finite differences)."""
    for layer in model.layers:
        d = layer.dropout
        if d is not None and (not isinstance(d, (int, float)) or d < 1.0):
            raise ValueError("Gradient checks require dropout disabled "
                             "(reference GradientCheckUtil precondition)")
    if not model._initialized:
        model.init()
    x = np.asarray(features, dtype=np.float64)
    y = np.asarray(labels, dtype=np.float64)
    fm = None if features_mask is None else jnp.asarray(np.asarray(features_mask))
    lm = None if labels_mask is None else jnp.asarray(np.asarray(labels_mask))

    from deeplearning4j_tpu.nd.dtype import DataTypePolicy

    saved_policy = model.dtype
    model.dtype = DataTypePolicy(param_dtype=jnp.float64, compute_dtype=jnp.float64,
                                 output_dtype=jnp.float64)
    saved_state = model.net_state
    model.net_state = jax.tree_util.tree_map(
        lambda a: np.asarray(a, dtype=np.float64), model.net_state)

    def loss_fn(p):
        loss, _ = model._loss_fn(p, model.net_state, jnp.asarray(x), jnp.asarray(y),
                                 None, fm, lm, train=False)
        return loss

    try:
        return check_gradients_fn(loss_fn, model.params, epsilon=epsilon,
                                  max_rel_error=max_rel_error,
                                  max_params_per_array=max_params_per_array, seed=seed)
    finally:
        model.dtype = saved_policy
        model.net_state = saved_state


def check_graph_gradients(
    model,
    inputs,
    labels,
    epsilon: float = 1e-6,
    max_rel_error: float = 1e-4,
    max_params_per_array: int = 32,
    seed: int = 0,
):
    """Gradient-check a ComputationGraph on one minibatch (reference
    `GradientCheckUtil.checkGradients(graph, ...)` overload)."""
    if not isinstance(inputs, (list, tuple)):
        inputs = [inputs]
    if not isinstance(labels, (list, tuple)):
        labels = [labels]
    for name, node in model.conf.nodes.items():
        layer = getattr(node, "layer", None)
        if layer is None:
            continue
        d = layer.dropout
        if d is not None and (not isinstance(d, (int, float)) or d < 1.0):
            raise ValueError("Gradient checks require dropout disabled")
    if not model._initialized:
        model.init()
    xs = [np.asarray(x, dtype=np.float64) for x in inputs]
    ys = [np.asarray(y, dtype=np.float64) for y in labels]

    from deeplearning4j_tpu.nd.dtype import DataTypePolicy

    saved_policy = model.dtype
    model.dtype = DataTypePolicy(param_dtype=jnp.float64,
                                 compute_dtype=jnp.float64,
                                 output_dtype=jnp.float64)
    saved_state = model.net_state
    model.net_state = jax.tree_util.tree_map(
        lambda a: np.asarray(a, dtype=np.float64), model.net_state)

    def loss_fn(p):
        loss, _ = model._loss_fn(p, model.net_state,
                                 [jnp.asarray(x) for x in xs],
                                 [jnp.asarray(y) for y in ys],
                                 None, None, None, train=False)
        return loss

    try:
        return check_gradients_fn(loss_fn, model.params, epsilon=epsilon,
                                  max_rel_error=max_rel_error,
                                  max_params_per_array=max_params_per_array,
                                  seed=seed)
    finally:
        model.dtype = saved_policy
        model.net_state = saved_state
