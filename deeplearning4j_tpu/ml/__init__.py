"""ML-pipeline adapters (the dl4j-spark-ml role, SURVEY §2.4): sklearn-
style Estimator/Transformer wrappers around networks so they slot into
sklearn Pipelines and model-selection tooling."""

from deeplearning4j_tpu.ml.estimator import AutoEncoderEstimator, NetworkEstimator
