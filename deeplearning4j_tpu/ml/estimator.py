"""Estimator/Transformer ML-pipeline adapters (sklearn-style).

Reference: `dl4j-spark-ml` — `SparkDl4jNetwork.scala` (a Spark-ML
`Predictor` whose `train()` drives the distributed trainer and returns
a `SparkDl4jModel` Transformer) and `AutoEncoder.scala` (an estimator
whose model transforms rows into reconstructions/codes). The pipeline
framework of this ecosystem is scikit-learn, not Spark-ML, so the
adapters implement the sklearn contract (`fit` / `predict` /
`transform` / `get_params` / `set_params`) and slot into
`sklearn.pipeline.Pipeline`, `GridSearchCV`, etc. Distribution comes
from passing a `TrainingMaster` (mesh-parallel fit), mirroring how the
reference estimator carries its `TrainingMaster` parameter.
"""

from __future__ import annotations

from typing import Callable, Optional

import numpy as np


try:
    # real sklearn base when available: brings get_params/set_params,
    # __sklearn_tags__, clone support — full Pipeline/GridSearchCV compat
    from sklearn.base import BaseEstimator as _SklearnBase

    class _BaseEstimator(_SklearnBase):
        _param_names = ()

except ImportError:
    class _BaseEstimator:
        """Duck-typed parameter plumbing when sklearn is absent."""

        _param_names = ()

        def get_params(self, deep: bool = True):
            return {k: getattr(self, k) for k in self._param_names}

        def set_params(self, **params):
            for k, v in params.items():
                if k not in self._param_names:
                    raise ValueError(
                        f"Invalid parameter {k!r} for {type(self).__name__}")
                setattr(self, k, v)
            return self


class NetworkEstimator(_BaseEstimator):
    """`SparkDl4jNetwork` equivalent: estimator around a network
    configuration; `fit(X, y)` trains (optionally through a
    TrainingMaster over a mesh) and returns a fitted estimator whose
    `predict`/`predict_proba`/`transform` run batched inference.

    `conf_factory`: () -> MultiLayerConfiguration | ComputationGraph
    configuration — a factory, not an instance, so each `fit` starts
    from fresh init (the sklearn clone contract).
    """

    _param_names = ("conf_factory", "epochs", "batch_size",
                    "training_master", "num_classes", "steps_per_execution")

    def __init__(self, conf_factory: Callable, *, epochs: int = 10,
                 batch_size: int = 32, training_master=None,
                 num_classes: Optional[int] = None,
                 steps_per_execution: int = 1):
        self.conf_factory = conf_factory
        self.epochs = epochs
        self.batch_size = batch_size
        self.training_master = training_master
        self.num_classes = num_classes
        self.steps_per_execution = steps_per_execution
        self.model_ = None

    # ------------------------------------------------------------- fitting
    def _one_hot(self, y):
        y = np.asarray(y)
        if y.ndim == 1 or (y.ndim == 2 and y.shape[1] == 1):
            y = y.reshape(-1).astype(int)
            n = self.num_classes or int(y.max()) + 1
            self.classes_ = np.arange(n)
            return np.eye(n, dtype=np.float32)[y]
        self.classes_ = np.arange(y.shape[1])
        return y.astype(np.float32)

    def fit(self, X, y):
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        conf = self.conf_factory()
        net = conf if hasattr(conf, "fit") else MultiLayerNetwork(conf)
        net.init()
        X = np.asarray(X, np.float32)
        y1h = self._one_hot(y)
        if self.training_master is not None:
            self.training_master.execute_training(net, (X, y1h),
                                                  epochs=self.epochs)
        else:
            net.fit(X, y1h, epochs=self.epochs, batch_size=self.batch_size,
                    steps_per_execution=self.steps_per_execution)
        self.model_ = net
        return self

    # ----------------------------------------------------------- inference
    def _check_fitted(self):
        if self.model_ is None:
            raise RuntimeError("Estimator is not fitted; call fit(X, y) first")

    def predict_proba(self, X):
        self._check_fitted()
        return np.asarray(self.model_.output(np.asarray(X, np.float32)))

    def predict(self, X):
        return self.predict_proba(X).argmax(axis=-1)

    def transform(self, X):
        """Transformer view: the output activations (reference
        `SparkDl4jModel.transform` output column)."""
        return self.predict_proba(X)

    def score(self, X, y):
        """Mean accuracy (sklearn classifier contract)."""
        y = np.asarray(y)
        if y.ndim > 1:
            y = y.argmax(axis=-1)
        return float((self.predict(X) == y).mean())


class AutoEncoderEstimator(_BaseEstimator):
    """`dl4j-spark-ml AutoEncoder.scala` equivalent: unsupervised
    estimator; `fit(X)` pretrains an AutoEncoder layer and `transform`
    emits the hidden code (or the reconstruction)."""

    _param_names = ("n_hidden", "epochs", "batch_size", "learning_rate",
                    "corruption_level", "output")

    def __init__(self, n_hidden: int, *, epochs: int = 10,
                 batch_size: int = 32, learning_rate: float = 1e-2,
                 corruption_level: float = 0.0, output: str = "code"):
        if output not in ("code", "reconstruction"):
            raise ValueError("output must be 'code' or 'reconstruction'")
        self.n_hidden = n_hidden
        self.epochs = epochs
        self.batch_size = batch_size
        self.learning_rate = learning_rate
        self.corruption_level = corruption_level
        self.output = output
        self.model_ = None

    def fit(self, X, y=None):
        from deeplearning4j_tpu.common.updaters import Adam
        from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
        from deeplearning4j_tpu.nn.layers import AutoEncoder, OutputLayer
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        X = np.asarray(X, np.float32)
        n_in = X.shape[-1]
        conf = (NeuralNetConfiguration.builder()
                .seed(12).updater(Adam(self.learning_rate))
                .list()
                .layer(AutoEncoder(n_in=n_in, n_out=self.n_hidden,
                                   corruption_level=self.corruption_level,
                                   activation="sigmoid"))
                .layer(OutputLayer(n_in=self.n_hidden, n_out=n_in,
                                   activation="identity", loss="mse"))
                .set_input_type(InputType.feed_forward(n_in))
                .build())
        net = MultiLayerNetwork(conf).init()
        net.pretrain(X, epochs=self.epochs, batch_size=self.batch_size)
        self.model_ = net
        self._layer = net.layers[0]
        return self

    def transform(self, X):
        if self.model_ is None:
            raise RuntimeError("Estimator is not fitted; call fit(X) first")
        import jax.numpy as jnp
        X = jnp.asarray(np.asarray(X, np.float32))
        params = self.model_.params["0"]
        code = self._layer.encode(params, X)
        if self.output == "code":
            return np.asarray(code)
        return np.asarray(self._layer.decode(params, code))

    def fit_transform(self, X, y=None):
        return self.fit(X).transform(X)
