"""Cloud storage/provisioning adapters (reference: deeplearning4j-aws —
EC2 provisioning + S3 up/down, `aws/s3/uploader/S3Uploader.java`,
`BaseS3DataSetIterator`).

boto3 is not bundled in this image; the classes gate on it with a clear
error, and `S3DataSetIterator` accepts any fsspec-style fetch function
so the iterator logic is testable without AWS.
"""

from deeplearning4j_tpu.aws.s3 import S3DataSetIterator, S3Downloader, S3Uploader
