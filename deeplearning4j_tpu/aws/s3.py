"""S3 upload/download + S3-backed DataSet iteration.

Reference: `aws/s3/uploader/S3Uploader.java`, `aws/s3/reader/`,
`BaseS3DataSetIterator.java`. Requires boto3 (optional dependency).
"""

from __future__ import annotations

from pathlib import Path
from typing import Callable, List, Optional

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet


def _boto3():
    try:
        import boto3
        return boto3
    except ImportError as e:
        raise ImportError(
            "AWS adapters need the boto3 package (not bundled in this "
            "environment); install boto3 to use S3Uploader/S3Downloader") from e


class S3Uploader:
    def __init__(self, bucket: str, client=None):
        self.bucket = bucket
        self._client = client or _boto3().client("s3")

    def upload(self, local_path, key: Optional[str] = None):
        local_path = Path(local_path)
        self._client.upload_file(str(local_path), self.bucket,
                                 key or local_path.name)


class S3Downloader:
    def __init__(self, bucket: str, client=None):
        self.bucket = bucket
        self._client = client or _boto3().client("s3")

    def download(self, key: str, dest):
        self._client.download_file(self.bucket, key, str(dest))

    def list_keys(self, prefix: str = "") -> List[str]:
        resp = self._client.list_objects_v2(Bucket=self.bucket, Prefix=prefix)
        return [o["Key"] for o in resp.get("Contents", [])]


class S3DataSetIterator:
    """Iterate DataSets stored as .npz objects under an S3 prefix
    (reference `BaseS3DataSetIterator`). `fetch_fn(key) -> bytes` is
    injectable so the iterator works against any object store."""

    def __init__(self, keys: List[str], fetch_fn: Callable[[str], bytes]):
        self.keys = list(keys)
        self.fetch_fn = fetch_fn
        self._pos = 0

    @staticmethod
    def from_bucket(bucket: str, prefix: str = "", client=None):
        dl = S3Downloader(bucket, client)

        def fetch(key):
            import io
            buf = io.BytesIO()
            dl._client.download_fileobj(bucket, key, buf)
            return buf.getvalue()

        return S3DataSetIterator(dl.list_keys(prefix), fetch)

    def reset(self):
        self._pos = 0

    def has_next(self):
        return self._pos < len(self.keys)

    def next(self) -> DataSet:
        import io
        data = self.fetch_fn(self.keys[self._pos])
        self._pos += 1
        npz = np.load(io.BytesIO(data))
        return DataSet(npz["features"],
                       npz["labels"] if "labels" in npz else None)

    def __iter__(self):
        self.reset()
        while self.has_next():
            yield self.next()
