"""Updaters (optimizer update rules).

Mirrors ND4J's `IUpdater` family as configured per layer in the
reference (`nn/conf/layers/BaseLayer.java:52-53` holds an IUpdater;
`nn/updater/BaseMultiLayerUpdater.java` partitions the flat gradient
into blocks sharing updater state): Sgd, Adam, AdaMax, Nadam, Nesterovs,
AdaGrad, AdaDelta, RmsProp, NoOp.

TPU-first design: each updater is a pure (grad, state, step) → (update,
state) transform over a *single tensor*; the container maps it across
the param pytree (jax.tree_util), so the whole optimizer step fuses into
the jitted train step. Updater state is a dict of arrays shaped like the
param — flattening it for checkpoints reproduces the reference's
"updater state is one flat vector" invariant
(`util/ModelSerializer.java:79-120`).

Learning rates may be scalars or `Schedule`s of the iteration counter.
Defaults follow the nd4j learning configs (Adam 1e-3/0.9/0.999/1e-8,
Nesterovs 0.1/0.9, AdaGrad 0.1/1e-6, RmsProp 0.1/0.95/1e-8,
AdaDelta rho 0.95/1e-6).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict

import jax.numpy as jnp

from deeplearning4j_tpu.common.schedules import Schedule, as_schedule, schedule_from_dict


def _lr(lr, step):
    if isinstance(lr, Schedule):
        return lr.value_at(step)
    return lr


class Updater:
    """Base updater config. Subclasses are dataclasses (serializable)."""

    name = "base"

    def init_state(self, param) -> Dict[str, Any]:
        return {}

    def apply(self, grad, state, step):
        """Return (update_to_subtract, new_state)."""
        raise NotImplementedError

    def with_lr(self, lr):
        """Copy of this updater with a replaced learning rate (used by
        transfer-learning fine-tune overrides)."""
        if hasattr(self, "learning_rate"):
            return dataclasses.replace(self, learning_rate=lr)
        return self

    def to_dict(self):
        d = {"updater": self.name}
        for f in dataclasses.fields(self):
            v = getattr(self, f.name)
            if isinstance(v, Schedule):
                v = v.to_dict()
            d[f.name] = v
        return d

    def __eq__(self, other):
        return type(self) is type(other) and self.to_dict() == other.to_dict()


@dataclasses.dataclass(eq=False)
class Sgd(Updater):
    learning_rate: Any = 1e-3
    name = "sgd"

    def apply(self, grad, state, step):
        return _lr(self.learning_rate, step) * grad, state


@dataclasses.dataclass(eq=False)
class NoOp(Updater):
    name = "noop"

    def apply(self, grad, state, step):
        return jnp.zeros_like(grad), state


@dataclasses.dataclass(eq=False)
class Adam(Updater):
    learning_rate: Any = 1e-3
    beta1: float = 0.9
    beta2: float = 0.999
    epsilon: float = 1e-8
    name = "adam"

    def init_state(self, param):
        return {"m": jnp.zeros_like(param), "v": jnp.zeros_like(param)}

    def apply(self, grad, state, step):
        t = jnp.asarray(step, jnp.float32) + 1.0
        m = self.beta1 * state["m"] + (1 - self.beta1) * grad
        v = self.beta2 * state["v"] + (1 - self.beta2) * grad * grad
        mhat = m / (1 - self.beta1 ** t)
        vhat = v / (1 - self.beta2 ** t)
        upd = _lr(self.learning_rate, step) * mhat / (jnp.sqrt(vhat) + self.epsilon)
        return upd, {"m": m, "v": v}


@dataclasses.dataclass(eq=False)
class AdaMax(Updater):
    learning_rate: Any = 1e-3
    beta1: float = 0.9
    beta2: float = 0.999
    epsilon: float = 1e-8
    name = "adamax"

    def init_state(self, param):
        return {"m": jnp.zeros_like(param), "u": jnp.zeros_like(param)}

    def apply(self, grad, state, step):
        t = jnp.asarray(step, jnp.float32) + 1.0
        m = self.beta1 * state["m"] + (1 - self.beta1) * grad
        u = jnp.maximum(self.beta2 * state["u"], jnp.abs(grad))
        upd = _lr(self.learning_rate, step) / (1 - self.beta1 ** t) * m / (u + self.epsilon)
        return upd, {"m": m, "u": u}


@dataclasses.dataclass(eq=False)
class Nadam(Updater):
    learning_rate: Any = 1e-3
    beta1: float = 0.9
    beta2: float = 0.999
    epsilon: float = 1e-8
    name = "nadam"

    def init_state(self, param):
        return {"m": jnp.zeros_like(param), "v": jnp.zeros_like(param)}

    def apply(self, grad, state, step):
        t = jnp.asarray(step, jnp.float32) + 1.0
        m = self.beta1 * state["m"] + (1 - self.beta1) * grad
        v = self.beta2 * state["v"] + (1 - self.beta2) * grad * grad
        mhat = m / (1 - self.beta1 ** t)
        vhat = v / (1 - self.beta2 ** t)
        nesterov_m = self.beta1 * mhat + (1 - self.beta1) * grad / (1 - self.beta1 ** t)
        upd = _lr(self.learning_rate, step) * nesterov_m / (jnp.sqrt(vhat) + self.epsilon)
        return upd, {"m": m, "v": v}


@dataclasses.dataclass(eq=False)
class Nesterovs(Updater):
    learning_rate: Any = 0.1
    momentum: float = 0.9
    name = "nesterovs"

    def init_state(self, param):
        return {"v": jnp.zeros_like(param)}

    def apply(self, grad, state, step):
        # Matches nd4j NesterovsUpdater: vPrev = v; v = mu*v - lr*g;
        # update = -(mu*vPrev - (1+mu)*v)  (applied as param -= update)
        lr = _lr(self.learning_rate, step)
        v_prev = state["v"]
        v = self.momentum * v_prev - lr * grad
        upd = -(self.momentum * v_prev - (1 + self.momentum) * v)
        return -upd, {"v": v}


@dataclasses.dataclass(eq=False)
class AdaGrad(Updater):
    learning_rate: Any = 0.1
    epsilon: float = 1e-6
    name = "adagrad"

    def init_state(self, param):
        return {"h": jnp.zeros_like(param)}

    def apply(self, grad, state, step):
        h = state["h"] + grad * grad
        upd = _lr(self.learning_rate, step) * grad / (jnp.sqrt(h) + self.epsilon)
        return upd, {"h": h}


@dataclasses.dataclass(eq=False)
class AdaDelta(Updater):
    rho: float = 0.95
    epsilon: float = 1e-6
    name = "adadelta"

    def init_state(self, param):
        return {"msg": jnp.zeros_like(param), "msdx": jnp.zeros_like(param)}

    def apply(self, grad, state, step):
        msg = self.rho * state["msg"] + (1 - self.rho) * grad * grad
        dx = jnp.sqrt(state["msdx"] + self.epsilon) / jnp.sqrt(msg + self.epsilon) * grad
        msdx = self.rho * state["msdx"] + (1 - self.rho) * dx * dx
        return dx, {"msg": msg, "msdx": msdx}


@dataclasses.dataclass(eq=False)
class RmsProp(Updater):
    learning_rate: Any = 0.1
    rms_decay: float = 0.95
    epsilon: float = 1e-8
    name = "rmsprop"

    def init_state(self, param):
        return {"g2": jnp.zeros_like(param)}

    def apply(self, grad, state, step):
        g2 = self.rms_decay * state["g2"] + (1 - self.rms_decay) * grad * grad
        upd = _lr(self.learning_rate, step) * grad / (jnp.sqrt(g2 + self.epsilon))
        return upd, {"g2": g2}


_UPDATERS = {
    "sgd": Sgd, "noop": NoOp, "adam": Adam, "adamax": AdaMax, "nadam": Nadam,
    "nesterovs": Nesterovs, "adagrad": AdaGrad, "adadelta": AdaDelta, "rmsprop": RmsProp,
}


def get_updater(u) -> Updater:
    if isinstance(u, Updater):
        return u
    if isinstance(u, str):
        key = u.lower()
        if key not in _UPDATERS:
            raise ValueError(f"Unknown updater {u!r}. Known: {sorted(_UPDATERS)}")
        return _UPDATERS[key]()
    raise TypeError(f"Cannot interpret {u!r} as an updater")


def updater_from_dict(d: dict) -> Updater:
    d = dict(d)
    name = d.pop("updater")
    cls = _UPDATERS[name]
    kwargs = {}
    for f in dataclasses.fields(cls):
        if f.name in d:
            v = d[f.name]
            if f.name == "learning_rate" and isinstance(v, dict):
                v = schedule_from_dict(v)
            kwargs[f.name] = v
    return cls(**kwargs)
