"""Weight-init distributions.

Mirrors `nn/conf/distribution/` in the reference: Normal/Gaussian,
Uniform, Binomial, Constant, LogNormal, Orthogonal, TruncatedNormal
(+ JSON serde).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp


class Distribution:
    name = "base"

    def sample(self, rng, shape, dtype=jnp.float32):
        raise NotImplementedError

    def to_dict(self):
        d = {"distribution": self.name}
        d.update(dataclasses.asdict(self))
        return d

    def __eq__(self, other):
        return type(self) is type(other) and self.__dict__ == other.__dict__


@dataclasses.dataclass(eq=False)
class NormalDistribution(Distribution):
    mean: float = 0.0
    std: float = 1.0
    name = "normal"

    def sample(self, rng, shape, dtype=jnp.float32):
        return self.mean + self.std * jax.random.normal(rng, shape, dtype)


@dataclasses.dataclass(eq=False)
class UniformDistribution(Distribution):
    lower: float = -1.0
    upper: float = 1.0
    name = "uniform"

    def sample(self, rng, shape, dtype=jnp.float32):
        return jax.random.uniform(rng, shape, dtype, self.lower, self.upper)


@dataclasses.dataclass(eq=False)
class BinomialDistribution(Distribution):
    trials: int = 1
    probability: float = 0.5
    name = "binomial"

    def sample(self, rng, shape, dtype=jnp.float32):
        draws = jax.random.bernoulli(rng, self.probability, (self.trials,) + tuple(shape))
        return jnp.sum(draws, axis=0).astype(dtype)


@dataclasses.dataclass(eq=False)
class ConstantDistribution(Distribution):
    value: float = 0.0
    name = "constant"

    def sample(self, rng, shape, dtype=jnp.float32):
        return jnp.full(shape, self.value, dtype)


@dataclasses.dataclass(eq=False)
class LogNormalDistribution(Distribution):
    mean: float = 0.0
    std: float = 1.0
    name = "lognormal"

    def sample(self, rng, shape, dtype=jnp.float32):
        return jnp.exp(self.mean + self.std * jax.random.normal(rng, shape, dtype))


@dataclasses.dataclass(eq=False)
class TruncatedNormalDistribution(Distribution):
    mean: float = 0.0
    std: float = 1.0
    name = "truncated_normal"

    def sample(self, rng, shape, dtype=jnp.float32):
        return self.mean + self.std * jax.random.truncated_normal(rng, -2.0, 2.0, shape, dtype)


@dataclasses.dataclass(eq=False)
class OrthogonalDistribution(Distribution):
    gain: float = 1.0
    name = "orthogonal"

    def sample(self, rng, shape, dtype=jnp.float32):
        return self.gain * jax.nn.initializers.orthogonal()(rng, shape, dtype)


_DISTS = {
    "normal": NormalDistribution,
    "gaussian": NormalDistribution,  # reference treats Gaussian == Normal
    "uniform": UniformDistribution,
    "binomial": BinomialDistribution,
    "constant": ConstantDistribution,
    "lognormal": LogNormalDistribution,
    "truncated_normal": TruncatedNormalDistribution,
    "orthogonal": OrthogonalDistribution,
}


def distribution_from_dict(d: dict) -> Distribution:
    d = dict(d)
    name = d.pop("distribution")
    return _DISTS[name](**d)
