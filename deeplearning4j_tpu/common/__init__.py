"""Common numeric building blocks: activations, losses, updaters,
learning-rate schedules, weight initializers and distributions.

These correspond to ND4J's `IActivation`, `ILossFunction`, `IUpdater`
surfaces plus deeplearning4j-nn's `nn/weights` and `nn/conf/distribution`
packages — re-expressed as serializable configs + pure JAX functions.
"""

from deeplearning4j_tpu.common.activations import Activation, get_activation
from deeplearning4j_tpu.common.losses import LossFunction, get_loss
from deeplearning4j_tpu.common.updaters import (
    Updater,
    Sgd,
    Adam,
    AdaMax,
    Nadam,
    Nesterovs,
    AdaGrad,
    AdaDelta,
    RmsProp,
    NoOp,
    updater_from_dict,
)
from deeplearning4j_tpu.common.schedules import Schedule, schedule_from_dict
from deeplearning4j_tpu.common.weights import WeightInit, init_weights
from deeplearning4j_tpu.common.distributions import Distribution, distribution_from_dict
