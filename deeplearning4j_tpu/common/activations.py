"""Activation functions.

Mirrors ND4J's `IActivation` catalog as consumed by the reference
(`nn/conf/layers/BaseLayer.java` activation field; enum set in
nd4j `Activation`): CUBE, ELU, HARDSIGMOID, HARDTANH, IDENTITY,
LEAKYRELU, RATIONALTANH, RELU, RRELU, SIGMOID, SOFTMAX, SOFTPLUS,
SOFTSIGN, TANH, RECTIFIEDTANH, SELU, SWISH — plus GELU/RELU6/MISH which
later model families need.

Each activation is a pure JAX function; names are the serialization
surface (stored in layer-config JSON).
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp

ActivationFn = Callable[[jnp.ndarray], jnp.ndarray]


def _identity(x):
    return x


def _cube(x):
    return x ** 3


def _hardsigmoid(x):
    return jnp.clip(0.2 * x + 0.5, 0.0, 1.0)


def _hardtanh(x):
    return jnp.clip(x, -1.0, 1.0)


def _leakyrelu(x, alpha=0.01):
    return jnp.where(x >= 0, x, alpha * x)


def _rationaltanh(x):
    # 1.7159 * tanh(2x/3) approximated rationally (matches nd4j
    # ActivationRationalTanh semantics: a cheap tanh surrogate).
    a = jnp.abs(2.0 * x / 3.0)
    approx = 1.0 - 1.0 / (1.0 + a + a * a + 1.41645 * a ** 4)
    return 1.7159 * jnp.sign(x) * approx


def _rectifiedtanh(x):
    return jnp.maximum(0.0, jnp.tanh(x))


def _softmax(x):
    return jax.nn.softmax(x, axis=-1)


def _softsign(x):
    return x / (1.0 + jnp.abs(x))


def _swish(x):
    return x * jax.nn.sigmoid(x)


def _mish(x):
    return x * jnp.tanh(jax.nn.softplus(x))


def _relu6(x):
    return jnp.clip(x, 0.0, 6.0)


ACTIVATIONS: dict[str, ActivationFn] = {
    "identity": _identity,
    "cube": _cube,
    "elu": jax.nn.elu,
    "gelu": jax.nn.gelu,
    "hardsigmoid": _hardsigmoid,
    "hardtanh": _hardtanh,
    "leakyrelu": _leakyrelu,
    "mish": _mish,
    "rationaltanh": _rationaltanh,
    "rectifiedtanh": _rectifiedtanh,
    "relu": jax.nn.relu,
    "relu6": _relu6,
    "rrelu": _leakyrelu,  # deterministic (test-mode) RReLU == leaky with mean slope
    "selu": jax.nn.selu,
    "sigmoid": jax.nn.sigmoid,
    "softmax": _softmax,
    "softplus": jax.nn.softplus,
    "softsign": _softsign,
    "swish": _swish,
    "tanh": jnp.tanh,
}


class Activation:
    """String-keyed activation, serializable into layer-config JSON."""

    def __init__(self, name: str):
        name = name.lower()
        # parameterized form "name:value" (e.g. "leakyrelu:0.3")
        base, _, param = name.partition(":")
        if base not in ACTIVATIONS:
            raise ValueError(f"Unknown activation: {name!r}. Known: {sorted(ACTIVATIONS)}")
        self.name = name
        if param and base == "leakyrelu":
            alpha = float(param)
            self.fn = lambda x: _leakyrelu(x, alpha)
        elif param:
            raise ValueError(f"Activation {base!r} takes no parameter")
        else:
            self.fn = ACTIVATIONS[base]

    def __call__(self, x):
        return self.fn(x)

    def __repr__(self):
        return f"Activation({self.name})"

    def __eq__(self, other):
        return isinstance(other, Activation) and other.name == self.name

    def __hash__(self):
        return hash(("Activation", self.name))


def get_activation(act) -> Activation:
    if isinstance(act, Activation):
        return act
    if isinstance(act, str):
        return Activation(act)
    raise TypeError(f"Cannot interpret {act!r} as an activation")
