"""Learning-rate (and generally value) schedules.

Mirrors the reference's `LearningRatePolicy` / nd4j `ISchedule` family
(Fixed, Exponential, Inverse, Poly, Sigmoid, Step, Schedule-map —
consumed in `BaseOptimizer`/updater `preApply` paths), plus warmup and
cosine schedules that modern TPU training recipes expect.

All schedules are pure functions of the iteration counter so they can be
traced inside a jitted train step (the counter is a traced scalar).
"""

from __future__ import annotations

import dataclasses
from typing import Dict

import jax.numpy as jnp


class Schedule:
    name = "base"

    def value_at(self, step):
        raise NotImplementedError

    def __call__(self, step):
        return self.value_at(step)

    def to_dict(self):
        d = {"schedule": self.name}
        d.update(dataclasses.asdict(self))
        return d

    def __eq__(self, other):
        return type(self) is type(other) and self.__dict__ == other.__dict__


@dataclasses.dataclass(eq=False)
class FixedSchedule(Schedule):
    value: float
    name = "fixed"

    def value_at(self, step):
        return self.value


@dataclasses.dataclass(eq=False)
class ExponentialSchedule(Schedule):
    initial_value: float
    gamma: float
    name = "exponential"

    def value_at(self, step):
        return self.initial_value * self.gamma ** jnp.asarray(step, jnp.float32)


@dataclasses.dataclass(eq=False)
class InverseSchedule(Schedule):
    initial_value: float
    gamma: float
    power: float
    name = "inverse"

    def value_at(self, step):
        return self.initial_value / (1.0 + self.gamma * jnp.asarray(step, jnp.float32)) ** self.power


@dataclasses.dataclass(eq=False)
class PolySchedule(Schedule):
    initial_value: float
    power: float
    max_iter: int
    name = "poly"

    def value_at(self, step):
        frac = jnp.clip(jnp.asarray(step, jnp.float32) / self.max_iter, 0.0, 1.0)
        return self.initial_value * (1.0 - frac) ** self.power


@dataclasses.dataclass(eq=False)
class SigmoidSchedule(Schedule):
    initial_value: float
    gamma: float
    step_size: int
    name = "sigmoid"

    def value_at(self, step):
        s = jnp.asarray(step, jnp.float32)
        return self.initial_value / (1.0 + jnp.exp(self.gamma * (s - self.step_size)))


@dataclasses.dataclass(eq=False)
class StepSchedule(Schedule):
    initial_value: float
    decay_rate: float
    step_size: int
    name = "step"

    def value_at(self, step):
        s = jnp.asarray(step, jnp.float32)
        return self.initial_value * self.decay_rate ** jnp.floor(s / self.step_size)


@dataclasses.dataclass(eq=False)
class MapSchedule(Schedule):
    """Piecewise-constant schedule keyed by iteration, like nd4j MapSchedule.

    Implemented branchlessly so it traces under jit.
    """

    values: Dict[int, float]
    name = "map"

    def value_at(self, step):
        keys = sorted(self.values)
        s = jnp.asarray(step, jnp.int32)
        out = jnp.asarray(self.values[keys[0]], jnp.float32)
        for k in keys[1:]:
            out = jnp.where(s >= k, self.values[k], out)
        return out

    def to_dict(self):
        return {"schedule": self.name, "values": {str(k): v for k, v in self.values.items()}}


@dataclasses.dataclass(eq=False)
class WarmupCosineSchedule(Schedule):
    """Linear warmup then cosine decay — the standard TPU LR recipe."""

    peak_value: float
    warmup_steps: int
    total_steps: int
    end_value: float = 0.0
    name = "warmup_cosine"

    def value_at(self, step):
        s = jnp.asarray(step, jnp.float32)
        warm = self.peak_value * s / jnp.maximum(self.warmup_steps, 1)
        frac = jnp.clip(
            (s - self.warmup_steps) / jnp.maximum(self.total_steps - self.warmup_steps, 1),
            0.0, 1.0,
        )
        cos = self.end_value + 0.5 * (self.peak_value - self.end_value) * (1 + jnp.cos(jnp.pi * frac))
        return jnp.where(s < self.warmup_steps, warm, cos)


_SCHEDULES = {
    "fixed": FixedSchedule,
    "exponential": ExponentialSchedule,
    "inverse": InverseSchedule,
    "poly": PolySchedule,
    "sigmoid": SigmoidSchedule,
    "step": StepSchedule,
    "map": MapSchedule,
    "warmup_cosine": WarmupCosineSchedule,
}


def schedule_from_dict(d) -> Schedule:
    if isinstance(d, (int, float)):
        return FixedSchedule(float(d))
    d = dict(d)
    name = d.pop("schedule")
    cls = _SCHEDULES[name]
    if cls is MapSchedule:
        return MapSchedule({int(k): float(v) for k, v in d["values"].items()})
    return cls(**d)


def as_schedule(value) -> Schedule:
    if isinstance(value, Schedule):
        return value
    return FixedSchedule(float(value))
