"""Loss functions.

Mirrors ND4J's `ILossFunction` catalog referenced by the output layers
(`nn/conf/layers/LossLayer.java`, `OutputLayer`): MSE, L1, L2, MAE,
XENT (binary cross-entropy), MCXENT (multi-class cross-entropy),
NEGATIVELOGLIKELIHOOD, HINGE, SQUARED_HINGE, KL_DIVERGENCE, POISSON,
COSINE_PROXIMITY, MSLE, plus weighted variants via `weights`.

Design difference from the reference: DL4J losses implement analytic
`computeGradient` w.r.t. pre-output; here gradients come from JAX
autodiff, so a loss only needs a forward `score`. Numerically-fused
paths (softmax+MCXENT via log-softmax, sigmoid+XENT via logits form)
are special-cased for stability — the same motivation as DL4J's fused
`LossMCXENT` + softmax backward shortcut.

Signature: ``score_array(labels, preoutput, activation, mask, weights)``
returns per-example scores (shape [batch] or [batch, time]); the
container reduces (sum over output dims, mean over examples — matching
`BaseOutputLayer.computeScore` semantics).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.common.activations import Activation, get_activation

_EPS = 1e-7


def _apply_activation(preout, activation: Activation):
    return activation(preout)


def _finish(per_elem, mask, weights):
    """Apply per-output weights + mask; sum over the feature axis."""
    if weights is not None:
        per_elem = per_elem * jnp.asarray(weights, per_elem.dtype)
    score = jnp.sum(per_elem, axis=-1)
    if mask is not None:
        score = score * mask
    return score


class LossFunction:
    name: str = "base"

    def score_array(self, labels, preout, activation: Activation, mask=None, weights=None):
        raise NotImplementedError

    def __call__(self, labels, preout, activation, mask=None, weights=None):
        """Mean score over examples (and masked timesteps)."""
        sa = self.score_array(labels, preout, activation, mask, weights)
        if mask is not None:
            denom = jnp.maximum(jnp.sum(mask), 1.0)
            return jnp.sum(sa) / denom
        return jnp.mean(sa)

    def to_dict(self):
        return {"loss": self.name}

    def __eq__(self, other):
        return type(self) is type(other) and self.__dict__ == other.__dict__

    def __repr__(self):
        return f"{type(self).__name__}()"


class LossMSE(LossFunction):
    name = "mse"

    def score_array(self, labels, preout, activation, mask=None, weights=None):
        out = _apply_activation(preout, activation)
        per = (out - labels) ** 2 / labels.shape[-1]
        return _finish(per, mask, weights)


class LossL2(LossFunction):
    """Sum of squared errors (MSE without the 1/n)."""

    name = "l2"

    def score_array(self, labels, preout, activation, mask=None, weights=None):
        out = _apply_activation(preout, activation)
        return _finish((out - labels) ** 2, mask, weights)


class LossMAE(LossFunction):
    name = "mae"

    def score_array(self, labels, preout, activation, mask=None, weights=None):
        out = _apply_activation(preout, activation)
        per = jnp.abs(out - labels) / labels.shape[-1]
        return _finish(per, mask, weights)


class LossL1(LossFunction):
    name = "l1"

    def score_array(self, labels, preout, activation, mask=None, weights=None):
        out = _apply_activation(preout, activation)
        return _finish(jnp.abs(out - labels), mask, weights)


class LossMSLE(LossFunction):
    name = "msle"

    def score_array(self, labels, preout, activation, mask=None, weights=None):
        out = _apply_activation(preout, activation)
        per = (jnp.log1p(jnp.maximum(out, -1 + _EPS)) - jnp.log1p(labels)) ** 2 / labels.shape[-1]
        return _finish(per, mask, weights)


class LossBinaryXENT(LossFunction):
    """Binary cross-entropy. Fused stable path when activation == sigmoid."""

    name = "xent"

    def __init__(self, clip_eps: float = _EPS):
        self.clip_eps = clip_eps

    def score_array(self, labels, preout, activation, mask=None, weights=None):
        if activation.name == "sigmoid":
            # logits form: max(x,0) - x*z + log1p(exp(-|x|))
            x, z = preout, labels
            per = jnp.maximum(x, 0) - x * z + jnp.log1p(jnp.exp(-jnp.abs(x)))
        else:
            out = _apply_activation(preout, activation)
            out = jnp.clip(out, self.clip_eps, 1.0 - self.clip_eps)
            per = -(labels * jnp.log(out) + (1 - labels) * jnp.log(1 - out))
        return _finish(per, mask, weights)


class LossMCXENT(LossFunction):
    """Multi-class cross-entropy. Fused log-softmax path when activation==softmax."""

    name = "mcxent"

    def __init__(self, soft_label_clip: float = _EPS):
        self.soft_label_clip = soft_label_clip

    def score_array(self, labels, preout, activation, mask=None, weights=None):
        if activation.name == "softmax":
            logp = jax.nn.log_softmax(preout, axis=-1)
            per = -labels * logp
        else:
            out = _apply_activation(preout, activation)
            per = -labels * jnp.log(jnp.clip(out, self.soft_label_clip, 1.0))
        return _finish(per, mask, weights)


class LossNegativeLogLikelihood(LossMCXENT):
    """Alias of MCXENT in the reference (LossNegativeLogLikelihood extends LossMCXENT)."""

    name = "negativeloglikelihood"


class LossHinge(LossFunction):
    name = "hinge"

    def score_array(self, labels, preout, activation, mask=None, weights=None):
        out = _apply_activation(preout, activation)
        y = 2.0 * labels - 1.0  # {0,1} -> {-1,1}
        return _finish(jnp.maximum(0.0, 1.0 - y * out), mask, weights)


class LossSquaredHinge(LossFunction):
    name = "squaredhinge"

    def score_array(self, labels, preout, activation, mask=None, weights=None):
        out = _apply_activation(preout, activation)
        y = 2.0 * labels - 1.0
        return _finish(jnp.maximum(0.0, 1.0 - y * out) ** 2, mask, weights)


class LossKLD(LossFunction):
    name = "kl_divergence"

    def score_array(self, labels, preout, activation, mask=None, weights=None):
        out = _apply_activation(preout, activation)
        out = jnp.clip(out, _EPS, 1.0)
        lab = jnp.clip(labels, _EPS, 1.0)
        return _finish(lab * (jnp.log(lab) - jnp.log(out)), mask, weights)


class LossPoisson(LossFunction):
    name = "poisson"

    def score_array(self, labels, preout, activation, mask=None, weights=None):
        out = _apply_activation(preout, activation)
        return _finish(out - labels * jnp.log(jnp.maximum(out, _EPS)), mask, weights)


class LossCosineProximity(LossFunction):
    name = "cosine_proximity"

    def score_array(self, labels, preout, activation, mask=None, weights=None):
        out = _apply_activation(preout, activation)
        ln = jnp.linalg.norm(labels, axis=-1, keepdims=True)
        on = jnp.linalg.norm(out, axis=-1, keepdims=True)
        cos = jnp.sum(labels * out, axis=-1, keepdims=True) / jnp.maximum(ln * on, _EPS)
        return _finish(-cos, mask, weights)


_LOSSES = {
    cls().name if cls not in (LossBinaryXENT, LossMCXENT, LossNegativeLogLikelihood) else cls.name: cls
    for cls in [
        LossMSE, LossL2, LossMAE, LossL1, LossMSLE, LossBinaryXENT, LossMCXENT,
        LossNegativeLogLikelihood, LossHinge, LossSquaredHinge, LossKLD,
        LossPoisson, LossCosineProximity,
    ]
}


def get_loss(loss) -> LossFunction:
    if isinstance(loss, LossFunction):
        return loss
    if isinstance(loss, str):
        key = loss.lower()
        if key not in _LOSSES:
            raise ValueError(f"Unknown loss {loss!r}. Known: {sorted(_LOSSES)}")
        return _LOSSES[key]()
    raise TypeError(f"Cannot interpret {loss!r} as a loss function")


def loss_from_dict(d: dict) -> LossFunction:
    return get_loss(d["loss"])
