"""Weight initialization schemes.

Mirrors `nn/weights/WeightInit.java` + `WeightInitUtil.java` in the
reference (21 schemes): XAVIER family, RELU family, LECUN, SIGMOID_UNIFORM,
UNIFORM, VAR_SCALING family, ZERO, ONES, IDENTITY, DISTRIBUTION.

`fan_in`/`fan_out` follow the reference convention: for a dense [nIn,
nOut] kernel fan_in=nIn, fan_out=nOut; for conv kernels fan_in =
in_channels * prod(kernel), fan_out = out_channels * prod(kernel)
(WeightInitUtil computes these from the param shape the same way).
"""

from __future__ import annotations

import math
from enum import Enum

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.common.distributions import Distribution


class WeightInit(str, Enum):
    ZERO = "zero"
    ONES = "ones"
    IDENTITY = "identity"
    DISTRIBUTION = "distribution"
    SIGMOID_UNIFORM = "sigmoid_uniform"
    UNIFORM = "uniform"
    LECUN_NORMAL = "lecun_normal"
    LECUN_UNIFORM = "lecun_uniform"
    NORMAL = "normal"
    XAVIER = "xavier"
    XAVIER_UNIFORM = "xavier_uniform"
    XAVIER_FAN_IN = "xavier_fan_in"
    XAVIER_LEGACY = "xavier_legacy"
    RELU = "relu"
    RELU_UNIFORM = "relu_uniform"
    SELU = "selu"  # == lecun normal, kept for config parity
    VAR_SCALING_NORMAL_FAN_IN = "var_scaling_normal_fan_in"
    VAR_SCALING_NORMAL_FAN_OUT = "var_scaling_normal_fan_out"
    VAR_SCALING_NORMAL_FAN_AVG = "var_scaling_normal_fan_avg"
    VAR_SCALING_UNIFORM_FAN_IN = "var_scaling_uniform_fan_in"
    VAR_SCALING_UNIFORM_FAN_OUT = "var_scaling_uniform_fan_out"
    VAR_SCALING_UNIFORM_FAN_AVG = "var_scaling_uniform_fan_avg"


def init_weights(
    rng,
    shape,
    weight_init: WeightInit | str,
    fan_in: float,
    fan_out: float,
    distribution: Distribution | None = None,
    dtype=jnp.float32,
):
    wi = WeightInit(weight_init) if not isinstance(weight_init, WeightInit) else weight_init
    shape = tuple(shape)

    def normal(std):
        return std * jax.random.normal(rng, shape, dtype)

    def uniform(a):
        return jax.random.uniform(rng, shape, dtype, -a, a)

    if wi == WeightInit.ZERO:
        return jnp.zeros(shape, dtype)
    if wi == WeightInit.ONES:
        return jnp.ones(shape, dtype)
    if wi == WeightInit.IDENTITY:
        if len(shape) != 2 or shape[0] != shape[1]:
            raise ValueError("IDENTITY init requires a square 2d shape")
        return jnp.eye(shape[0], dtype=dtype)
    if wi == WeightInit.DISTRIBUTION:
        if distribution is None:
            raise ValueError("WeightInit.DISTRIBUTION requires a distribution")
        return distribution.sample(rng, shape, dtype)
    if wi == WeightInit.SIGMOID_UNIFORM:
        return uniform(4.0 * math.sqrt(6.0 / (fan_in + fan_out)))
    if wi == WeightInit.UNIFORM:
        return uniform(1.0 / math.sqrt(fan_in))
    if wi in (WeightInit.LECUN_NORMAL, WeightInit.SELU):
        return normal(math.sqrt(1.0 / fan_in))
    if wi == WeightInit.LECUN_UNIFORM:
        return uniform(math.sqrt(3.0 / fan_in))
    if wi == WeightInit.NORMAL:
        return normal(math.sqrt(1.0 / fan_in))
    if wi == WeightInit.XAVIER:
        return normal(math.sqrt(2.0 / (fan_in + fan_out)))
    if wi == WeightInit.XAVIER_UNIFORM:
        return uniform(math.sqrt(6.0 / (fan_in + fan_out)))
    if wi == WeightInit.XAVIER_FAN_IN:
        return normal(math.sqrt(1.0 / fan_in))
    if wi == WeightInit.XAVIER_LEGACY:
        return normal(math.sqrt(1.0 / (fan_in + fan_out)))
    if wi == WeightInit.RELU:
        return normal(math.sqrt(2.0 / fan_in))
    if wi == WeightInit.RELU_UNIFORM:
        return uniform(math.sqrt(6.0 / fan_in))
    if wi == WeightInit.VAR_SCALING_NORMAL_FAN_IN:
        return normal(math.sqrt(1.0 / fan_in))
    if wi == WeightInit.VAR_SCALING_NORMAL_FAN_OUT:
        return normal(math.sqrt(1.0 / fan_out))
    if wi == WeightInit.VAR_SCALING_NORMAL_FAN_AVG:
        return normal(math.sqrt(2.0 / (fan_in + fan_out)))
    if wi == WeightInit.VAR_SCALING_UNIFORM_FAN_IN:
        return uniform(math.sqrt(3.0 / fan_in))
    if wi == WeightInit.VAR_SCALING_UNIFORM_FAN_OUT:
        return uniform(math.sqrt(3.0 / fan_out))
    if wi == WeightInit.VAR_SCALING_UNIFORM_FAN_AVG:
        return uniform(math.sqrt(6.0 / (fan_in + fan_out)))
    raise ValueError(f"Unhandled weight init {wi}")
