"""Fleet telemetry federation: many worker registries, one /metrics view.

The push/pull seam between per-process `MetricsRegistry` instances and a
fleet-level scrape endpoint (the streamz-federation role in TensorFlow's
fleet instrumentation, arXiv:1605.08695 §5; the fleet-health aggregation
TPU pods depend on, arXiv:2606.15870):

- `export_snapshot(registry, worker)` — one worker's labeled snapshot
  (a JSON-friendly dict; histograms carry their bucket layout so the
  merge is a true bucket merge, not a lossy sum/count).
- `MetricsAggregator` — ingests worker exports (last snapshot per worker
  wins) and renders ONE exposition: every series re-labeled with
  `worker=<name>`, plus cross-worker merged series (counters summed,
  gauges last-write by snapshot time, histograms bucket-merged when the
  layouts match) without the worker label.
- `FederationPublisher` / `FederationCollector` — the push pipe over any
  `streaming.Transport` (local queue in tests, Kafka in a real fleet):
  publisher serializes exports onto a topic, collector drains them into
  an aggregator.
- elastic integration: training workers ride the heartbeat info channel
  (`ElasticClient.federate_metrics()`), and `ingest_elastic_status`
  lifts member info out of a coordinator `status()` into an aggregator.

Transports are duck-typed (`send`/`receive` of bytes) so this module
stays stdlib-only.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Dict, List, Optional

from .registry import (MetricsRegistry, _escape_help, _fmt_labels,
                       _fmt_value, _label_key)


def export_snapshot(registry: MetricsRegistry, worker: str) -> Dict:
    """One worker's federation payload."""
    return {"worker": str(worker), "ts": time.time(),
            "metrics": registry.snapshot()}


def _exposition_lines(name: str, kind: str, entry: Dict,
                      extra_labels: Dict[str, str]) -> List[str]:
    """Render one snapshot entry (a single labeled child) as exposition
    lines, with `extra_labels` merged in."""
    labels = dict(entry.get("labels") or {})
    labels.update(extra_labels)
    key = _label_key(labels)
    lines: List[str] = []
    if kind in ("histogram", "timer"):
        buckets = entry.get("buckets")
        counts = entry.get("bucket_counts")
        if buckets is not None and counts is not None:
            acc = 0
            for b, c in zip(buckets, counts):
                acc += c
                bkey = key + (("le", _fmt_value(b)),)
                lines.append(f"{name}_bucket{_fmt_labels(bkey)} {acc}")
            acc += counts[-1] if len(counts) > len(buckets) else 0
            ikey = key + (("le", "+Inf"),)
            lines.append(f"{name}_bucket{_fmt_labels(ikey)} {acc}")
        lines.append(f"{name}_sum{_fmt_labels(key)} "
                     f"{_fmt_value(entry.get('sum', 0.0))}")
        lines.append(f"{name}_count{_fmt_labels(key)} "
                     f"{int(entry.get('count', 0))}")
    else:
        lines.append(f"{name}{_fmt_labels(key)} "
                     f"{_fmt_value(entry.get('value', 0.0))}")
    return lines


class MetricsAggregator:
    """Merge worker snapshots into one exposition.

    Duck-compatible with the slice of `MetricsRegistry` the UIServer's
    `/metrics` route needs (`exposition()`, `snapshot()`), so it can be
    attached via `UIServer.attach_registry(aggregator)` directly.
    """

    def __init__(self):
        self._lock = threading.Lock()
        # worker -> {"ts": float, "metrics": snapshot-dict}
        self._exports: Dict[str, Dict] = {}

    # ------------------------------------------------------------- ingest
    def ingest(self, export: Dict) -> str:
        """Absorb one `export_snapshot` payload (dict or JSON str/bytes).
        Last snapshot per worker wins (by export ts). Returns the worker
        name."""
        if isinstance(export, (bytes, bytearray)):
            export = export.decode("utf-8")
        if isinstance(export, str):
            export = json.loads(export)
        worker = str(export["worker"])
        ts = float(export.get("ts", time.time()))
        with self._lock:
            prev = self._exports.get(worker)
            if prev is None or ts >= prev["ts"]:
                self._exports[worker] = {"ts": ts,
                                         "metrics": export["metrics"]}
        return worker

    def ingest_registry(self, registry: MetricsRegistry, worker: str) -> str:
        return self.ingest(export_snapshot(registry, worker))

    def workers(self) -> List[str]:
        with self._lock:
            return sorted(self._exports)

    def export_ages(self, now: Optional[float] = None) -> Dict[str, float]:
        """Seconds since each worker's last ingested export (by the
        export's own `ts`, a `time.time()` stamp) — the scrape-side
        liveness signal the worker-vanished alert rule evaluates."""
        now = time.time() if now is None else float(now)
        with self._lock:
            return {w: max(0.0, now - e["ts"])
                    for w, e in self._exports.items()}

    def drop_worker(self, worker: str) -> bool:
        """Forget one worker's export (deliberate decommission — its
        series leave `/metrics` instead of going stale)."""
        with self._lock:
            return self._exports.pop(str(worker), None) is not None

    def clear(self):
        with self._lock:
            self._exports.clear()

    # -------------------------------------------------------------- merge
    def _merged_families(self) -> Dict[str, Dict]:
        """family name -> {"type", "help", "per_worker": [(worker, entry)],
        "merged": [entry]} — merged entries keyed by the original label
        set: counters summed, gauges last-write (newest snapshot wins),
        histograms bucket-merged when layouts match (sum/count-only
        entry otherwise)."""
        with self._lock:
            exports = {w: dict(e) for w, e in self._exports.items()}
        fams: Dict[str, Dict] = {}
        for worker in sorted(exports):
            ts = exports[worker]["ts"]
            for name, fam in exports[worker]["metrics"].items():
                slot = fams.setdefault(
                    name, {"type": fam.get("type", "gauge"),
                           "help": fam.get("help", ""),
                           "per_worker": [], "_merge": {}})
                if fam.get("help") and not slot["help"]:
                    slot["help"] = fam["help"]
                for entry in fam.get("values", ()):
                    slot["per_worker"].append((worker, entry))
                    lkey = _label_key(entry.get("labels") or {})
                    m = slot["_merge"].get(lkey)
                    kind = slot["type"]
                    if kind == "counter":
                        if m is None:
                            m = {"labels": dict(entry.get("labels") or {}),
                                 "value": 0.0}
                            slot["_merge"][lkey] = m
                        m["value"] += float(entry.get("value", 0.0))
                    elif kind in ("histogram", "timer"):
                        if m is None:
                            m = {"labels": dict(entry.get("labels") or {}),
                                 "sum": 0.0, "count": 0,
                                 "buckets": entry.get("buckets"),
                                 "bucket_counts":
                                     (list(entry["bucket_counts"])
                                      if entry.get("bucket_counts")
                                      else None)}
                            slot["_merge"][lkey] = m
                        elif (m.get("buckets") is not None
                                and entry.get("buckets") == m["buckets"]
                                and entry.get("bucket_counts")):
                            m["bucket_counts"] = [
                                a + b for a, b in
                                zip(m["bucket_counts"],
                                    entry["bucket_counts"])]
                        else:
                            # layout mismatch: degrade to sum/count
                            m["buckets"] = None
                            m["bucket_counts"] = None
                        m["sum"] += float(entry.get("sum", 0.0))
                        m["count"] += int(entry.get("count", 0))
                    else:  # gauge: last write wins, newest snapshot ts
                        if m is None or ts >= m["_ts"]:
                            slot["_merge"][lkey] = {
                                "labels": dict(entry.get("labels") or {}),
                                "value": entry.get("value", 0.0),
                                "_ts": ts}
        for slot in fams.values():
            merged = []
            for lkey in sorted(slot["_merge"]):
                e = dict(slot["_merge"][lkey])
                e.pop("_ts", None)
                if e.get("buckets") is None:
                    e.pop("buckets", None)
                    e.pop("bucket_counts", None)
                merged.append(e)
            slot["merged"] = merged
            del slot["_merge"]
        return fams

    # ------------------------------------------------------------- export
    def exposition(self) -> str:
        """Prometheus text 0.0.4: per-worker series carry `worker=`
        labels; merged cross-worker series carry none."""
        lines: List[str] = []
        fams = self._merged_families()
        for name in sorted(fams):
            slot = fams[name]
            ptype = "histogram" if slot["type"] == "timer" else slot["type"]
            if slot["help"]:
                lines.append(f"# HELP {name} {_escape_help(slot['help'])}")
            lines.append(f"# TYPE {name} {ptype}")
            for worker, entry in slot["per_worker"]:
                lines.extend(_exposition_lines(
                    name, slot["type"], entry, {"worker": worker}))
            for entry in slot["merged"]:
                lines.extend(_exposition_lines(name, slot["type"],
                                               entry, {}))
        return "\n".join(lines) + "\n"

    def snapshot(self) -> Dict:
        """Merged cross-worker view in `MetricsRegistry.snapshot()`
        shape (what the UI JSON routes consume)."""
        out: Dict = {}
        for name, slot in self._merged_families().items():
            out[name] = {"type": slot["type"], "help": slot["help"],
                         "values": [dict(e) for e in slot["merged"]]}
        return out


# =====================================================================
# transport pipe
# =====================================================================
class FederationPublisher:
    """Push side: serialize this process's registry onto a transport
    topic, once or on a daemon interval."""

    def __init__(self, transport, topic: str, worker: str,
                 registry: Optional[MetricsRegistry] = None,
                 interval_s: float = 1.0):
        self.transport = transport
        self.topic = topic
        self.worker = str(worker)
        self._registry = registry
        self.interval_s = float(interval_s)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.published_total = 0

    def _resolve_registry(self) -> MetricsRegistry:
        if self._registry is not None:
            return self._registry
        from deeplearning4j_tpu import monitor
        return monitor.registry()

    def publish_once(self):
        payload = json.dumps(
            export_snapshot(self._resolve_registry(), self.worker),
            default=str).encode("utf-8")
        self.transport.send(self.topic, payload)
        self.published_total += 1

    def start(self) -> "FederationPublisher":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, daemon=True,
                name=f"fed-pub-{self.worker}")
            self._thread.start()
        return self

    def _loop(self):
        while not self._stop.wait(self.interval_s):
            try:
                self.publish_once()
            except Exception:  # noqa: BLE001 — telemetry must not crash
                pass

    def stop(self):
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=2 * self.interval_s + 1)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False


class FederationCollector:
    """Pull side: drain exports off the topic into an aggregator."""

    def __init__(self, transport, topic: str,
                 aggregator: Optional[MetricsAggregator] = None):
        self.transport = transport
        self.topic = topic
        self.aggregator = aggregator or MetricsAggregator()
        self.ingested_total = 0

    def poll(self, timeout: float = 0.05, max_msgs: int = 1000) -> int:
        """Ingest up to `max_msgs` waiting exports; returns how many."""
        n = 0
        for _ in range(int(max_msgs)):
            try:
                payload = self.transport.receive(self.topic, timeout)
            except Exception:  # queue.Empty / TimeoutError — drained
                break
            self.aggregator.ingest(payload)
            self.ingested_total += 1
            n += 1
        return n


# =====================================================================
# elastic heartbeat integration
# =====================================================================
def ingest_elastic_status(status: Dict,
                          aggregator: MetricsAggregator) -> int:
    """Lift federated metrics out of an `ElasticCoordinator.status()`
    view: any member whose heartbeat info carries a `"metrics"` export
    (see `ElasticClient.federate_metrics`) is ingested. Returns how many
    members contributed."""
    n = 0
    for token, member in (status.get("members") or {}).items():
        export = (member.get("info") or {}).get("metrics")
        if export:
            aggregator.ingest(export)
            n += 1
    return n
