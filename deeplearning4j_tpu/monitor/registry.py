"""Process-global metrics registry: counters, gauges, histograms, timers.

The role TensorFlow's runtime counters/streamz play in its fleet
instrumentation (arXiv:1605.08695 §5): one named, labeled metric space
every layer writes into, with a single exporter per format. Pure
stdlib — no JAX imports — so the registry can serve `/metrics` from a
UI-only process that never touches a device.

Thread safety: one registry-level RLock guards family creation; each
child metric guards its own mutation with the same lock object (metric
writes are a few ns of float math — a shared lock is cheaper than
per-child locks and keeps `exposition()` consistent).
"""

from __future__ import annotations

import json
import math
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

_LabelKey = Tuple[Tuple[str, str], ...]


def _label_key(labels: Dict[str, str]) -> _LabelKey:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _escape_label_value(v: str) -> str:
    return v.replace("\\", r"\\").replace('"', r'\"').replace("\n", r"\n")


def _escape_help(v: str) -> str:
    # exposition-format 0.0.4 HELP escaping: backslash and newline only
    # (no quote escaping — HELP text is not quoted)
    return v.replace("\\", r"\\").replace("\n", r"\n")


def _unescape_label_value(v: str) -> str:
    out, i = [], 0
    while i < len(v):
        c = v[i]
        if c == "\\" and i + 1 < len(v):
            nxt = v[i + 1]
            if nxt == "n":
                out.append("\n")
                i += 2
                continue
            if nxt in ("\\", '"'):
                out.append(nxt)
                i += 2
                continue
        out.append(c)
        i += 1
    return "".join(out)


def _fmt_labels(key: _LabelKey) -> str:
    if not key:
        return ""
    inner = ",".join(f'{k}="{_escape_label_value(v)}"' for k, v in key)
    return "{" + inner + "}"


def _fmt_value(v: float) -> str:
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if math.isnan(v):
        return "NaN"
    return repr(float(v))


class Counter:
    """Monotonically increasing value (one labeled child)."""

    def __init__(self, lock: threading.RLock):
        self._lock = lock
        self._value = 0.0

    def inc(self, amount: float = 1.0):
        if amount < 0:
            raise ValueError(f"counters only go up (inc {amount})")
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        return self._value


class Gauge:
    """Point-in-time value; may also be backed by a callback evaluated
    lazily at exposition time (device-memory style collectors)."""

    def __init__(self, lock: threading.RLock):
        self._lock = lock
        self._value = 0.0
        self._fn: Optional[Callable[[], float]] = None

    def set(self, value: float):
        with self._lock:
            self._fn = None
            self._value = float(value)

    def inc(self, amount: float = 1.0):
        with self._lock:
            self._fn = None
            self._value += amount

    def dec(self, amount: float = 1.0):
        self.inc(-amount)

    def set_function(self, fn: Callable[[], float]):
        with self._lock:
            self._fn = fn

    @property
    def value(self) -> float:
        fn = self._fn
        if fn is not None:
            try:
                return float(fn())
            except Exception:  # noqa: BLE001 — exporter must never die
                return float("nan")
        return self._value


# default buckets: 0.1ms .. ~100s in roughly 4x steps — wide enough for
# both a fused TPU step (sub-ms) and an XLA compile (tens of seconds)
DEFAULT_BUCKETS = (0.0001, 0.0005, 0.001, 0.005, 0.01, 0.05,
                   0.1, 0.5, 1.0, 5.0, 10.0, 30.0, 60.0, 120.0)


class Histogram:
    """Cumulative-bucket histogram (Prometheus semantics)."""

    def __init__(self, lock: threading.RLock,
                 buckets: Sequence[float] = DEFAULT_BUCKETS):
        self._lock = lock
        self.buckets = tuple(sorted(float(b) for b in buckets))
        self.bucket_counts = [0] * (len(self.buckets) + 1)  # +Inf last
        self.sum = 0.0
        self.count = 0

    def observe(self, value: float):
        v = float(value)
        with self._lock:
            self.sum += v
            self.count += 1
            for i, b in enumerate(self.buckets):
                if v <= b:
                    self.bucket_counts[i] += 1
                    return
            self.bucket_counts[-1] += 1

    def cumulative_counts(self) -> List[int]:
        out, acc = [], 0
        for c in self.bucket_counts:
            acc += c
            out.append(acc)
        return out


class Timer(Histogram):
    """Histogram observed in seconds, with a `time()` context manager."""

    class _Ctx:
        __slots__ = ("_timer", "_t0")

        def __init__(self, timer):
            self._timer = timer

        def __enter__(self):
            self._t0 = time.perf_counter()
            return self

        def __exit__(self, *exc):
            self._timer.observe(time.perf_counter() - self._t0)
            return False

    def time(self) -> "Timer._Ctx":
        return self._Ctx(self)


class _Family:
    def __init__(self, name: str, kind: str, help_text: str,
                 lock: threading.RLock, **kwargs):
        self.name = name
        self.kind = kind          # counter | gauge | histogram
        self.help = help_text
        self.kwargs = kwargs
        self._lock = lock
        self.children: Dict[_LabelKey, object] = {}

    def child(self, labels: Dict[str, str]):
        key = _label_key(labels)
        with self._lock:
            c = self.children.get(key)
            if c is None:
                if self.kind == "counter":
                    c = Counter(self._lock)
                elif self.kind == "gauge":
                    c = Gauge(self._lock)
                elif self.kind == "histogram":
                    c = Histogram(self._lock, **self.kwargs)
                else:  # timer
                    c = Timer(self._lock, **self.kwargs)
                self.children[key] = c
            return c


class MetricsRegistry:
    """Named metric families with label support + Prometheus/JSON export.

    `registry.counter("training_iterations_total", phase="fit").inc()`
    creates the family on first use and returns the labeled child; the
    same (name, labels) always maps to the same child object.
    """

    def __init__(self):
        self._lock = threading.RLock()
        self._families: Dict[str, _Family] = {}

    # ------------------------------------------------------------ factories
    def _family(self, name: str, kind: str, help_text: str, **kwargs):
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = _Family(name, kind, help_text, self._lock, **kwargs)
                self._families[name] = fam
            elif fam.kind != kind:
                raise ValueError(
                    f"metric {name!r} already registered as {fam.kind}, "
                    f"requested {kind}")
            return fam

    def counter(self, name: str, help: str = "", **labels) -> Counter:
        return self._family(name, "counter", help).child(labels)

    def gauge(self, name: str, help: str = "", **labels) -> Gauge:
        return self._family(name, "gauge", help).child(labels)

    def histogram(self, name: str, help: str = "",
                  buckets: Sequence[float] = DEFAULT_BUCKETS,
                  **labels) -> Histogram:
        return self._family(name, "histogram", help,
                            buckets=buckets).child(labels)

    def timer(self, name: str, help: str = "",
              buckets: Sequence[float] = DEFAULT_BUCKETS, **labels) -> Timer:
        return self._family(name, "timer", help, buckets=buckets).child(labels)

    # -------------------------------------------------------------- export
    def exposition(self) -> str:
        """Prometheus text exposition format 0.0.4."""
        lines: List[str] = []
        with self._lock:
            families = sorted(self._families.values(), key=lambda f: f.name)
            for fam in families:
                ptype = "histogram" if fam.kind == "timer" else fam.kind
                if fam.help:
                    lines.append(f"# HELP {fam.name} {_escape_help(fam.help)}")
                lines.append(f"# TYPE {fam.name} {ptype}")
                for key, child in sorted(fam.children.items()):
                    if isinstance(child, Histogram):
                        cum = child.cumulative_counts()
                        for b, c in zip(child.buckets, cum):
                            bkey = key + (("le", _fmt_value(b)),)
                            lines.append(f"{fam.name}_bucket"
                                         f"{_fmt_labels(bkey)} {c}")
                        ikey = key + (("le", "+Inf"),)
                        lines.append(f"{fam.name}_bucket{_fmt_labels(ikey)} "
                                     f"{cum[-1]}")
                        lines.append(f"{fam.name}_sum{_fmt_labels(key)} "
                                     f"{_fmt_value(child.sum)}")
                        lines.append(f"{fam.name}_count{_fmt_labels(key)} "
                                     f"{child.count}")
                    else:
                        lines.append(f"{fam.name}{_fmt_labels(key)} "
                                     f"{_fmt_value(child.value)}")
        return "\n".join(lines) + "\n"

    def snapshot(self) -> Dict:
        """JSON-friendly dump (the JSONL sink's payload)."""
        out: Dict = {}
        with self._lock:
            for name, fam in self._families.items():
                entries = []
                for key, child in fam.children.items():
                    labels = dict(key)
                    if isinstance(child, Histogram):
                        # bucket layout rides along so a federation
                        # aggregator can bucket-merge, not just sum/count
                        entries.append({"labels": labels, "sum": child.sum,
                                        "count": child.count,
                                        "buckets": list(child.buckets),
                                        "bucket_counts":
                                            list(child.bucket_counts)})
                    else:
                        entries.append({"labels": labels,
                                        "value": child.value})
                out[name] = {"type": fam.kind, "help": fam.help,
                             "values": entries}
        return out

    def dump_jsonl(self, path: str, **meta):
        """Append one snapshot line to a JSONL event log."""
        rec = {"ts": time.time(), "kind": "metrics",
               "metrics": self.snapshot(), **meta}
        with open(path, "a") as f:
            f.write(json.dumps(rec, default=str) + "\n")
        return path

    def clear(self):
        with self._lock:
            self._families.clear()


# the process-global registry (`streamz` role); swap per-test via
# monitor.enable(registry=MetricsRegistry())
GLOBAL_REGISTRY = MetricsRegistry()
